"""NRI-mode runtime hooks: a containerd NRI plugin adapter.

Analog of reference `pkg/koordlet/runtimehooks/nri/server.go` (the third
runtimehooks mode next to the CRI proxy and the standalone reconciler).
Topology matches NRI's defining shape: the PLUGIN dials the runtime's
socket (`/var/run/nri/nri.sock` analog; start fails fast when the socket
does not exist — Options.Validate, server.go:50-58), registers itself
(plugin name `koordlet_nri`, index `00` — server.go:68-70), answers the
runtime's Configure with its subscribed-event mask, then serves
RunPodSandbox / CreateContainer / UpdateContainer requests arriving on the
SAME dialed connection (reverse RPC, as ttrpc does for NRI).

Wire format: length-prefixed protobuf frames (koordlet/nri.proto mirrors
the NRI v0.3.0 API surface; the upstream ttrpc schema is not vendored in
the reference checkout). Frame header: `!IHI` = payload length, method id
(response bit 0x8000, error bit 0x4000), request id.

Hook dispatch mirrors server.go:
  * RunPodSandbox  -> PreRunPodSandbox hooks; pod-level cgroup writes are
    applied locally through the executor (podCtx.NriDone), nothing returns
    to the runtime (server.go:151-166);
  * CreateContainer -> PreCreateContainer hooks; env + the NRI-expressible
    cgroup writes (cpuset, cfs quota, memory limit) return as a
    ContainerAdjustment; inexpressible writes (bvt, core-sched cookies)
    apply locally via the executor (containerCtx.NriDone split);
  * UpdateContainer -> PreUpdateContainerResources hooks; returns a
    ContainerUpdate (server.go:190-213).
FailurePolicy: FAIL returns the hook error to the runtime; IGNORE logs
and answers success (server.go:154-160).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
from koordinator_tpu.koordlet import nri_pb2
from koordinator_tpu.koordlet.runtimehooks import ContainerContext, RuntimeHooks
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.runtimeproxy.server import FailurePolicy

PLUGIN_NAME = "koordlet_nri"
PLUGIN_IDX = "00"
DEFAULT_EVENTS = ("RunPodSandbox", "CreateContainer", "UpdateContainer")

# method ids on the wire
M_REGISTER = 1
M_CONFIGURE = 2
M_SYNCHRONIZE = 3
M_RUN_POD_SANDBOX = 4
M_CREATE_CONTAINER = 5
M_UPDATE_CONTAINER = 6
M_SHUTDOWN = 7
RESPONSE_BIT = 0x8000
ERROR_BIT = 0x4000

_EVENT_BITS = {
    "RunPodSandbox": 1 << 0,
    "StopPodSandbox": 1 << 1,
    "RemovePodSandbox": 1 << 2,
    "CreateContainer": 1 << 3,
    "StartContainer": 1 << 4,
    "UpdateContainer": 1 << 5,
    "StopContainer": 1 << 6,
    "RemoveContainer": 1 << 7,
}

_HDR = struct.Struct("!IHI")


def event_mask(names) -> int:
    mask = 0
    for n in names:
        bit = _EVENT_BITS.get(str(n).strip())
        if bit is None:
            raise ValueError(f"unknown NRI event {n!r}")
        mask |= bit
    return mask


def send_frame(sock: socket.socket, method: int, req_id: int,
               payload: bytes) -> None:
    sock.sendall(_HDR.pack(len(payload), method, req_id) + payload)


def recv_frame(sock: socket.socket) -> Optional[Tuple[int, int, bytes]]:
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    length, method, req_id = _HDR.unpack(hdr)
    payload = _recv_exact(sock, length) if length else b""
    if length and payload is None:
        return None
    return method, req_id, payload or b""


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def pod_from_sandbox(sb: nri_pb2.PodSandbox) -> Pod:
    """protocol.PodContext.FromNri: rebuild the pod view the hooks consume."""
    return Pod(
        meta=ObjectMeta(
            name=sb.name,
            namespace=sb.namespace,
            uid=sb.uid,
            labels=dict(sb.labels),
            annotations=dict(sb.annotations),
        ),
        spec=PodSpec(),
    )


class NriPlugin:
    """The koordlet-side NRI plugin (NriServer analog)."""

    def __init__(self, socket_path: str, hooks: RuntimeHooks,
                 failure_policy: FailurePolicy = FailurePolicy.IGNORE,
                 events=DEFAULT_EVENTS):
        self.socket_path = socket_path
        self.hooks = hooks
        self.failure_policy = failure_policy
        self.mask = event_mask(events)
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        # handled/errors are written by the serve thread and read by the
        # owner (tests, daemon status) — guard both behind one lock
        self._state_lock = threading.Lock()
        self.handled: Dict[str, int] = {}
        self.errors: List[str] = []

    def _count(self, method: str) -> None:
        with self._state_lock:
            self.handled[method] = self.handled.get(method, 0) + 1

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        """Validate + dial + register + serve (NewNriServer then Start).
        Raises FileNotFoundError when the NRI socket does not exist — the
        fast support check of Options.Validate."""
        if not os.path.exists(self.socket_path):
            raise FileNotFoundError(
                f"nri socket path {self.socket_path!r} does not exist")
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(self.socket_path)
        reg = nri_pb2.RegisterPlugin(
            plugin_name=PLUGIN_NAME, plugin_idx=PLUGIN_IDX)
        send_frame(self._sock, M_REGISTER, 0, reg.SerializeToString())
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()
            self._sock = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # -- serving -------------------------------------------------------
    def _serve(self) -> None:
        while True:
            # capture locally: stop() nulls self._sock concurrently; a
            # vanished or closed socket is a clean shutdown, not a crash
            sock = self._sock
            if sock is None:
                return
            frame = recv_frame(sock)
            if frame is None:
                return
            method, req_id, payload = frame
            if method == M_SHUTDOWN:
                return
            try:
                resp = self._dispatch(method, payload)
                send_frame(sock, method | RESPONSE_BIT, req_id,
                           resp.SerializeToString())
            except OSError:
                return  # peer went away mid-response
            except Exception as exc:  # noqa: BLE001 — relayed to the runtime
                err = nri_pb2.Error(message=str(exc))
                try:
                    send_frame(sock, method | RESPONSE_BIT | ERROR_BIT,
                               req_id, err.SerializeToString())
                except OSError:
                    return

    def _dispatch(self, method: int, payload: bytes):
        if method == M_CONFIGURE:
            return self._configure(
                nri_pb2.ConfigureRequest.FromString(payload))
        if method == M_SYNCHRONIZE:
            nri_pb2.SynchronizeRequest.FromString(payload)
            # todo-parity: the reference's Synchronize is a no-op too
            # (server.go:146-149)
            return nri_pb2.SynchronizeResponse()
        if method == M_RUN_POD_SANDBOX:
            return self._run_pod_sandbox(
                nri_pb2.RunPodSandboxRequest.FromString(payload))
        if method == M_CREATE_CONTAINER:
            return self._create_container(
                nri_pb2.CreateContainerRequest.FromString(payload))
        if method == M_UPDATE_CONTAINER:
            return self._update_container(
                nri_pb2.UpdateContainerRequest.FromString(payload))
        raise ValueError(f"unknown NRI method {method}")

    def _configure(self, req: nri_pb2.ConfigureRequest):
        self._count("Configure")
        if req.config:
            cfg = json.loads(req.config)
            self.mask = event_mask(cfg.get("events") or [])
        return nri_pb2.ConfigureResponse(events=self.mask)

    def _run_hooks(self, ctx: ContainerContext, stage: str) -> None:
        try:
            self.hooks.run_hooks(ctx)
        except Exception as exc:  # noqa: BLE001
            with self._state_lock:
                self.errors.append(f"{stage}: {exc}")
            if self.failure_policy is FailurePolicy.FAIL:
                raise
            # IGNORE: the runtime proceeds unmodified

    def _run_pod_sandbox(self, req: nri_pb2.RunPodSandboxRequest):
        self._count("RunPodSandbox")
        pod = pod_from_sandbox(req.pod)
        ctx = ContainerContext(
            pod=pod, cgroup_parent=req.pod.cgroup_parent)
        self._run_hooks(ctx, "RunPodSandbox")
        # podCtx.NriDone: pod-level writes go straight through the executor
        if ctx.cgroup_writes:
            self.hooks.executor.leveled_update_batch(
                list(ctx.cgroup_writes), increase=True)
        return nri_pb2.Empty()

    def _adjustment(self, ctx: ContainerContext) -> nri_pb2.ContainerAdjustment:
        """containerCtx.NriDone split: NRI-expressible writes become
        adjustment resources, the rest applies locally via the executor."""
        adjust = nri_pb2.ContainerAdjustment()
        for k, v in ctx.env.items():
            adjust.env.add(key=k, value=v)
        local = []
        for w in ctx.cgroup_writes:
            if w.resource == sysutil.CPUSET_CPUS:
                adjust.resources.cpuset_cpus = w.value
            elif w.resource == sysutil.CPU_CFS_QUOTA:
                adjust.resources.cpu_quota = int(w.value)
            elif w.resource == sysutil.MEMORY_LIMIT:
                adjust.resources.memory_limit_in_bytes = int(w.value)
            else:
                local.append(w)
        if local:
            self.hooks.executor.leveled_update_batch(local, increase=True)
        return adjust

    def _create_container(self, req: nri_pb2.CreateContainerRequest):
        self._count("CreateContainer")
        pod = pod_from_sandbox(req.pod)
        ctx = ContainerContext(
            pod=pod,
            cgroup_parent=req.container.cgroup_parent
            or req.pod.cgroup_parent,
            env={},
        )
        self._run_hooks(ctx, "CreateContainer")
        return nri_pb2.CreateContainerResponse(adjust=self._adjustment(ctx))

    def _update_container(self, req: nri_pb2.UpdateContainerRequest):
        self._count("UpdateContainer")
        pod = pod_from_sandbox(req.pod)
        ctx = ContainerContext(
            pod=pod,
            cgroup_parent=req.container.cgroup_parent
            or req.pod.cgroup_parent,
            env={},
        )
        self._run_hooks(ctx, "UpdateContainer")
        adjust = self._adjustment(ctx)
        update = nri_pb2.ContainerUpdate(
            container_id=req.container.id, resources=adjust.resources)
        return nri_pb2.UpdateContainerResponse(updates=[update])


class FakeContainerdNri:
    """Test-side runtime: binds the NRI socket, accepts one plugin, drives
    the Configure handshake and lifecycle events (the fake-backend
    discipline of tests/test_criserver.py and tests/test_dockerproxy.py)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(socket_path)
        self._listener.listen(1)
        self._conn: Optional[socket.socket] = None
        self._req_id = 0
        self.registered: Optional[nri_pb2.RegisterPlugin] = None

    def accept_plugin(self, timeout: float = 5.0) -> nri_pb2.RegisterPlugin:
        self._listener.settimeout(timeout)
        self._conn, _ = self._listener.accept()
        self._conn.settimeout(timeout)
        frame = recv_frame(self._conn)
        assert frame is not None and frame[0] == M_REGISTER
        self.registered = nri_pb2.RegisterPlugin.FromString(frame[2])
        return self.registered

    def call(self, method: int, request) -> Tuple[bool, bytes]:
        """(ok, payload): send one request, wait for its response frame."""
        assert self._conn is not None
        self._req_id += 1
        send_frame(self._conn, method, self._req_id,
                   request.SerializeToString())
        frame = recv_frame(self._conn)
        assert frame is not None, "plugin hung up"
        rmethod, rid, payload = frame
        assert rid == self._req_id, "response id mismatch"
        assert rmethod & RESPONSE_BIT, "expected a response frame"
        assert (rmethod & ~(RESPONSE_BIT | ERROR_BIT)) == method
        return not (rmethod & ERROR_BIT), payload

    def configure(self, config: str = "", runtime: str = "fake-containerd",
                  version: str = "v2.0") -> nri_pb2.ConfigureResponse:
        ok, payload = self.call(M_CONFIGURE, nri_pb2.ConfigureRequest(
            config=config, runtime_name=runtime, runtime_version=version))
        assert ok, nri_pb2.Error.FromString(payload).message
        return nri_pb2.ConfigureResponse.FromString(payload)

    def close(self) -> None:
        if self._conn is not None:
            try:
                send_frame(self._conn, M_SHUTDOWN, 0, b"")
                self._conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._conn.close()
        self._listener.close()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
