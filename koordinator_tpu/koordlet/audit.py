"""Audit: ring buffer of node mutations with token-paged queries.

Analog of reference `pkg/koordlet/audit/auditor.go:38-247`: every cgroup/resctrl
write is recorded (wired through the resource executor); consumers page through
events with an opaque token.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class AuditEvent:
    seq: int
    timestamp: float
    level: str
    group: str          # e.g. "node", "pod/<uid>"
    operation: str      # e.g. "cgroup_write"
    detail: Dict[str, str] = field(default_factory=dict)


class Auditor:
    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._buf: List[AuditEvent] = []
        self._capacity = capacity
        self._seq = 0

    def record(self, level: str, group: str, operation: str, **detail: str) -> None:
        with self._lock:
            self._seq += 1
            self._buf.append(
                AuditEvent(self._seq, time.time(), level, group, operation,
                           {k: str(v) for k, v in detail.items()})
            )
            if len(self._buf) > self._capacity:
                self._buf = self._buf[-self._capacity:]

    def query(self, token: Optional[int] = None, limit: int = 100) -> Tuple[List[AuditEvent], int]:
        """Events with seq > token (oldest first); returns (events, next_token)."""
        with self._lock:
            start = token or 0
            out = [e for e in self._buf if e.seq > start][:limit]
            next_token = out[-1].seq if out else start
            return out, next_token

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)
