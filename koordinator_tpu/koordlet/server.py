"""Koordlet node API server: audit query + metrics + health.

Analog of reference `pkg/koordlet/audit/auditor.go:130-246` (HTTP query with
opaque-token paging, ?size= page control) plus the agent's metrics/healthz
endpoints. Routing core is `handle(path, query)` so tests drive it without
sockets; `serve()` wraps it in a ThreadingHTTPServer.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from koordinator_tpu.koordlet.audit import Auditor


class KoordletServer:
    def __init__(self, auditor: Auditor, metrics_registry=None):
        self.auditor = auditor
        self.metrics_registry = metrics_registry

    # -- routing core ---------------------------------------------------
    def handle(self, path: str, query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, str, str]:
        """(status, content_type, body)."""
        query = query or {}
        parts = [p for p in path.split("/") if p]
        if parts == ["healthz"]:
            return 200, "text/plain", "ok"
        if parts == ["apis", "v1", "audit"]:
            return self._audit(query)
        if parts == ["metrics"] and self.metrics_registry is not None:
            return 200, "text/plain; version=0.0.4", self.metrics_registry.expose()
        return 404, "text/plain", f"unknown path {path!r}"

    def _audit(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        """Token-paged audit events (auditor.go:130-246): ?token=&size=.
        The response carries next_token; an empty page returns the same token
        so pollers can resume."""
        try:
            token = int(query.get("token", "0") or "0")
            size = max(0, min(int(query.get("size", "100") or "100"), 1000))
        except ValueError:
            return 400, "text/plain", "token/size must be integers"
        events, next_token = self.auditor.query(token=token, limit=size)
        body = json.dumps({
            "events": [
                {
                    "seq": e.seq,
                    "timestamp": e.timestamp,
                    "level": e.level,
                    "group": e.group,
                    "operation": e.operation,
                    "detail": e.detail,
                }
                for e in events
            ],
            "next_token": next_token,
        })
        return 200, "application/json", body

    # -- live server ----------------------------------------------------
    def serve(self, port: int = 0):
        """Start the HTTP server; returns (server, thread)."""
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                url = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(url.query).items()}
                status, ctype, body = outer.handle(url.path, q)
                payload = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, fmt, *args):  # silence
                pass

        server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server, thread
