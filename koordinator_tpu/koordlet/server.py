"""Koordlet node API server: audit query + metrics + health.

Analog of reference `pkg/koordlet/audit/auditor.go:130-246` (HTTP query with
opaque-token paging, ?size= page control) plus the agent's metrics/healthz
endpoints. Routing core is `handle(path, query)` so tests drive it without
sockets; `serve()` wraps it in a ThreadingHTTPServer.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.obs.server import ObsServer


class KoordletServer:
    def __init__(self, auditor: Auditor, metrics_registry=None, tracer=None):
        self.auditor = auditor
        # /metrics and /traces live on the shared observability routing
        # core (single copy of the registry/tracer state — it already
        # 404s routes whose backend is absent), so all binaries expose
        # the identical formats
        self.obs = ObsServer(metrics_registry, tracer)

    # -- routing core ---------------------------------------------------
    def handle(self, path: str, query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, str, str]:
        """(status, content_type, body)."""
        query = query or {}
        parts = [p for p in path.split("/") if p]
        if parts == ["healthz"]:
            return 200, "text/plain", "ok"
        if parts == ["apis", "v1", "audit"]:
            return self._audit(query)
        if parts == ["metrics"] or parts == ["traces"]:
            return self.obs.handle(path, query)
        return 404, "text/plain", f"unknown path {path!r}"

    def _audit(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        """Token-paged audit events (auditor.go:130-246): ?token=&size=.
        The response carries next_token; an empty page returns the same token
        so pollers can resume."""
        try:
            token = int(query.get("token", "0") or "0")
            size = max(0, min(int(query.get("size", "100") or "100"), 1000))
        except ValueError:
            return 400, "text/plain", "token/size must be integers"
        events, next_token = self.auditor.query(token=token, limit=size)
        body = json.dumps({
            "events": [
                {
                    "seq": e.seq,
                    "timestamp": e.timestamp,
                    "level": e.level,
                    "group": e.group,
                    "operation": e.operation,
                    "detail": e.detail,
                }
                for e in events
            ],
            "next_token": next_token,
        })
        return 200, "application/json", body

    # -- live server ----------------------------------------------------
    def serve(self, port: int = 0):
        """Start the HTTP server; returns (server, thread)."""
        from koordinator_tpu.obs.server import serve_handler

        return serve_handler(self.handle, port)
