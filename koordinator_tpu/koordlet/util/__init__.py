"""koordlet kernel-interface utilities (reference `pkg/koordlet/util/`)."""
