"""resctrl (Intel RDT / AMD QoS) filesystem interface.

Analog of reference `pkg/koordlet/util/system/resctrl*.go`:
  * schemata parsing/formatting — `L3:<dom>=<hexmask>` cache-allocation lines
    and `MB:<dom>=<percent>` memory-bandwidth lines
  * control-group management (LS/LSR/BE group dirs, tasks file)
  * percent-range -> contiguous way bitmask calculation
    (resctrl.go CalculateCatL3MaskValue semantics: masks must be contiguous;
    a QoS class gets the ways covering [start%, end%] of the cache)

All paths resolve through a `SystemConfig` so the whole module runs against a
`FakeFS` tree in tests.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.koordlet.util import system as sysutil

# well-known resctrl group names (resctrl.go LSRResctrlGroup etc.)
ROOT_GROUP = ""
LSR_GROUP = "LSR"
LS_GROUP = "LS"
BE_GROUP = "BE"
STANDARD_GROUPS = (LSR_GROUP, LS_GROUP, BE_GROUP)

SCHEMATA_FILE = "schemata"
TASKS_FILE = "tasks"
CPUS_FILE = "cpus"

_L3_LINE = re.compile(r"^\s*L3:(.*)$")
_MB_LINE = re.compile(r"^\s*MB:(.*)$")


@dataclass
class Schemata:
    """Parsed schemata: per-domain L3 way masks and MB percents."""

    l3_masks: Dict[int, int] = field(default_factory=dict)
    mb_percents: Dict[int, int] = field(default_factory=dict)
    l3_num_ways: int = 0  # inferred from root-group mask width when parsed

    def format(self) -> str:
        lines: List[str] = []
        if self.l3_masks:
            doms = ";".join(
                f"{d}={m:x}" for d, m in sorted(self.l3_masks.items()))
            lines.append(f"L3:{doms}")
        if self.mb_percents:
            doms = ";".join(
                f"{d}={p}" for d, p in sorted(self.mb_percents.items()))
            lines.append(f"MB:{doms}")
        return "\n".join(lines) + "\n"


def parse_schemata(content: str) -> Schemata:
    out = Schemata()
    for line in content.splitlines():
        m = _L3_LINE.match(line)
        if m:
            for part in m.group(1).split(";"):
                if "=" not in part:
                    continue
                dom, mask = part.split("=", 1)
                out.l3_masks[int(dom)] = int(mask.strip(), 16)
            continue
        m = _MB_LINE.match(line)
        if m:
            for part in m.group(1).split(";"):
                if "=" not in part:
                    continue
                dom, pct = part.split("=", 1)
                out.mb_percents[int(dom)] = int(pct.strip())
    if out.l3_masks:
        out.l3_num_ways = max(m.bit_length() for m in out.l3_masks.values())
    return out


def calculate_l3_mask(num_ways: int, start_percent: int, end_percent: int) -> int:
    """Contiguous way mask covering [start%, end%] of an L3 with num_ways ways.

    Matches the reference's semantics (resctrl.go CalculateCatL3MaskValue):
    the mask must be contiguous and non-empty; the BE class typically gets
    [0, llcPercent], LS/LSR get [0, 100].
    """
    if num_ways <= 0:
        raise ValueError("num_ways must be positive")
    if not (0 <= start_percent < end_percent <= 100):
        raise ValueError(f"invalid percent range [{start_percent},{end_percent}]")
    lo = num_ways * start_percent // 100
    hi = max(lo + 1, (num_ways * end_percent + 99) // 100)  # ceil, >=1 way
    hi = min(hi, num_ways)
    width = hi - lo
    return ((1 << width) - 1) << lo


class ResctrlInterface:
    """Group + schemata management against the resctrl fs root."""

    def __init__(self, config: Optional[sysutil.SystemConfig] = None):
        self.config = config or sysutil.CONFIG

    def group_dir(self, group: str) -> str:
        root = self.config.resctrl_root()
        return root if group == ROOT_GROUP else os.path.join(root, group)

    def available(self) -> bool:
        """resctrl mounted (root schemata readable)?"""
        return sysutil.read_file(
            os.path.join(self.config.resctrl_root(), SCHEMATA_FILE)) is not None

    def read_schemata(self, group: str = ROOT_GROUP) -> Optional[Schemata]:
        raw = sysutil.read_file(os.path.join(self.group_dir(group), SCHEMATA_FILE))
        return parse_schemata(raw) if raw is not None else None

    def num_l3_ways(self) -> int:
        root = self.read_schemata(ROOT_GROUP)
        return root.l3_num_ways if root else 0

    def ensure_group(self, group: str) -> bool:
        try:
            os.makedirs(self.group_dir(group), exist_ok=True)
            return True
        except OSError:
            return False

    def write_schemata(self, group: str, schemata: Schemata) -> bool:
        self.ensure_group(group)
        return sysutil.write_file(
            os.path.join(self.group_dir(group), SCHEMATA_FILE), schemata.format())

    def add_tasks(self, group: str, pids: List[int]) -> bool:
        """Move tasks into a control group. One pid per write(2): the kernel
        rejects multi-pid writes, and rewriting existing members would fail
        with ESRCH if any has exited. Failures for individual pids (task died)
        don't abort the rest."""
        path = os.path.join(self.group_dir(group), TASKS_FILE)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
        except OSError:
            return False
        ok = True
        for pid in pids:
            try:
                with open(path, "a") as f:
                    f.write(f"{pid}\n")
            except OSError:
                ok = False
        return ok

    def read_tasks(self, group: str) -> List[int]:
        raw = sysutil.read_file(os.path.join(self.group_dir(group), TASKS_FILE))
        if not raw:
            return []
        return [int(x) for x in raw.split() if x.isdigit()]
