"""Machine topology discovery from /sys + /proc.

Analog of reference `pkg/koordlet/util/system`'s lscpu/NUMA parsing
(machine info feeding the nodeTopo statesinformer, which reports the
NodeResourceTopology CR the NodeNUMAResource scheduler plugin consumes):

  * per-cpu topology from /sys/devices/system/cpu/cpu<i>/topology/
    {core_id, physical_package_id}
  * NUMA membership from /sys/devices/system/node/node<j>/cpulist
  * online cpu list from /sys/devices/system/cpu/online
  * per-NUMA memory from /sys/devices/system/node/node<j>/meminfo

Everything resolves through a SystemConfig so FakeFS trees work.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import CPUInfo
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.scheduler.cpu_topology import CPUTopology
from koordinator_tpu.utils.cpuset import CPUSet


@dataclass
class NUMAMemInfo:
    numa_id: int
    total_bytes: int = 0
    free_bytes: int = 0


@dataclass
class MachineInfo:
    topology: CPUTopology
    numa_mem: Dict[int, NUMAMemInfo] = field(default_factory=dict)

    @property
    def num_cpus(self) -> int:
        return self.topology.num_cpus


def _sys_path(config: sysutil.SystemConfig, *parts: str) -> str:
    return os.path.join(config.sys_root_dir, *parts)


def read_online_cpus(config: Optional[sysutil.SystemConfig] = None) -> CPUSet:
    cfg = config or sysutil.CONFIG
    raw = sysutil.read_file(_sys_path(cfg, "devices/system/cpu/online"))
    return CPUSet.parse(raw) if raw else CPUSet()


def read_numa_cpulists(config: Optional[sysutil.SystemConfig] = None) -> Dict[int, CPUSet]:
    cfg = config or sysutil.CONFIG
    node_root = _sys_path(cfg, "devices/system/node")
    out: Dict[int, CPUSet] = {}
    try:
        entries = os.listdir(node_root)
    except OSError:
        return out
    for name in sorted(entries):
        m = re.fullmatch(r"node(\d+)", name)
        if not m:
            continue
        raw = sysutil.read_file(os.path.join(node_root, name, "cpulist"))
        if raw:
            out[int(m.group(1))] = CPUSet.parse(raw)
    return out


_MEMINFO_LINE = re.compile(r"Node \d+ (\w+):\s+(\d+)(?:\s+kB)?")


def read_numa_meminfo(numa_id: int,
                      config: Optional[sysutil.SystemConfig] = None) -> Optional[NUMAMemInfo]:
    cfg = config or sysutil.CONFIG
    raw = sysutil.read_file(
        _sys_path(cfg, "devices/system/node", f"node{numa_id}", "meminfo"))
    if raw is None:
        return None
    info = NUMAMemInfo(numa_id=numa_id)
    for line in raw.splitlines():
        m = _MEMINFO_LINE.search(line)
        if not m:
            continue
        key, val = m.group(1), int(m.group(2)) * 1024
        if key == "MemTotal":
            info.total_bytes = val
        elif key == "MemFree":
            info.free_bytes = val
    return info


def discover(config: Optional[sysutil.SystemConfig] = None) -> Optional[MachineInfo]:
    """Build MachineInfo from the /sys tree; None if topology files absent."""
    cfg = config or sysutil.CONFIG
    online = read_online_cpus(cfg)
    if len(online) == 0:
        return None
    numa_of_cpu: Dict[int, int] = {}
    for numa_id, cpus in read_numa_cpulists(cfg).items():
        for cpu in cpus.to_list():
            numa_of_cpu[cpu] = numa_id

    infos: List[CPUInfo] = []
    for cpu in online.to_list():
        topo_dir = _sys_path(cfg, "devices/system/cpu", f"cpu{cpu}", "topology")
        core_raw = sysutil.read_file(os.path.join(topo_dir, "core_id"))
        pkg_raw = sysutil.read_file(os.path.join(topo_dir, "physical_package_id"))
        if core_raw is None or pkg_raw is None:
            return None
        socket_id = int(pkg_raw)
        # core_id is only unique within a package; globalize like lscpu does
        core_id = socket_id * 10_000 + int(core_raw)
        infos.append(CPUInfo(
            cpu_id=cpu, core_id=core_id, socket_id=socket_id,
            numa_node_id=numa_of_cpu.get(cpu, socket_id)))

    mem = {}
    for numa_id in sorted({c.numa_node_id for c in infos}):
        mi = read_numa_meminfo(numa_id, cfg)
        if mi is not None:
            mem[numa_id] = mi
    return MachineInfo(topology=CPUTopology(cpus=infos), numa_mem=mem)


def write_fake_machine(fs, num_sockets: int = 1, nodes_per_socket: int = 2,
                       cores_per_node: int = 4, threads_per_core: int = 2,
                       mem_per_numa_gb: int = 32) -> None:
    """Populate a FakeFS with a regular machine's /sys topology tree."""
    topo = CPUTopology.build(num_sockets, nodes_per_socket, cores_per_node,
                             threads_per_core)
    all_cpus = sorted(c.cpu_id for c in topo.cpus)
    fs.set_file(os.path.join(
        "sys", "devices/system/cpu/online"), CPUSet(all_cpus).format())
    by_numa: Dict[int, List[int]] = {}
    for c in topo.cpus:
        by_numa.setdefault(c.numa_node_id, []).append(c.cpu_id)
        base = os.path.join("sys", "devices/system/cpu", f"cpu{c.cpu_id}",
                            "topology")
        fs.set_file(os.path.join(base, "core_id"), str(c.core_id % 10_000))
        fs.set_file(os.path.join(base, "physical_package_id"), str(c.socket_id))
    for numa_id, cpus in by_numa.items():
        node_dir = os.path.join("sys", "devices/system/node", f"node{numa_id}")
        fs.set_file(os.path.join(node_dir, "cpulist"), CPUSet(cpus).format())
        kb = mem_per_numa_gb * 1024 * 1024
        fs.set_file(
            os.path.join(node_dir, "meminfo"),
            f"Node {numa_id} MemTotal:       {kb} kB\n"
            f"Node {numa_id} MemFree:        {kb * 3 // 4} kB\n")
