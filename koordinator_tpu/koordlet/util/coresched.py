"""Core-scheduling cookie interface (prctl PR_SCHED_CORE).

Analog of reference `pkg/koordlet/util/system/core_sched.go` +
`core_sched_linux.go`: assign SMT-core-scheduling cookies so tasks of
different trust domains (e.g. BE vs LS pods) never share a physical core's
hyperthreads simultaneously.

Two implementations behind one interface:
  * `SystemCoreSched` — real prctl(2) via ctypes (PR_SCHED_CORE=62), used on
    kernels >= 5.14 with CONFIG_SCHED_CORE
  * `FakeCoreSched` — in-memory cookie table for tests and non-Linux hosts

The runtimehooks `coresched` hook drives this: new BE container -> create a
cookie on its first task, share it to the rest of the pod's tasks.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Dict, List, Optional

# prctl constants (linux/prctl.h)
PR_SCHED_CORE = 62
PR_SCHED_CORE_GET = 0
PR_SCHED_CORE_CREATE = 1
PR_SCHED_CORE_SHARE_TO = 2
PR_SCHED_CORE_SHARE_FROM = 3

PIDTYPE_PID = 0
PIDTYPE_TGID = 1
PIDTYPE_PGID = 2


class CoreSchedInterface:
    def supported(self) -> bool:
        raise NotImplementedError

    def get_cookie(self, pid: int) -> Optional[int]:
        raise NotImplementedError

    def create_cookie(self, pid: int, pid_type: int = PIDTYPE_PID) -> bool:
        """Assign a fresh random cookie to pid (kernel generates the value)."""
        raise NotImplementedError

    def share_from(self, from_pid: int, to_pids: List[int]) -> List[int]:
        """Copy from_pid's cookie onto each of to_pids; returns pids that failed."""
        raise NotImplementedError

    def clear_cookie(self, pid: int) -> bool:
        raise NotImplementedError


class SystemCoreSched(CoreSchedInterface):
    """prctl(2)-backed cookies. Degrades to unsupported on any failure."""

    def __init__(self) -> None:
        self._libc = None
        try:
            libc = ctypes.CDLL(ctypes.util.find_library("c"), use_errno=True)
            libc.prctl  # symbol lookup raises on non-Linux libc
            self._libc = libc
        except (OSError, AttributeError, TypeError):
            self._libc = None

    def _prctl(self, op: int, pid: int, pid_type: int, arg: int) -> int:
        if self._libc is None:
            return -1
        return self._libc.prctl(
            PR_SCHED_CORE, ctypes.c_ulong(op), ctypes.c_ulong(pid),
            ctypes.c_ulong(pid_type), ctypes.c_ulong(arg))

    def supported(self) -> bool:
        if self._libc is None:
            return False
        # PR_SCHED_CORE_GET on self: ENOMEM/EINVAL on old kernels, 0 on new
        cookie = ctypes.c_ulong(0)
        try:
            rc = self._libc.prctl(
                PR_SCHED_CORE, PR_SCHED_CORE_GET, 0, PIDTYPE_PID,
                ctypes.byref(cookie))
        except (OSError, ctypes.ArgumentError):
            return False
        return rc == 0

    def get_cookie(self, pid: int) -> Optional[int]:
        if self._libc is None:
            return None
        cookie = ctypes.c_ulong(0)
        rc = self._libc.prctl(
            PR_SCHED_CORE, PR_SCHED_CORE_GET, pid, PIDTYPE_PID,
            ctypes.byref(cookie))
        return int(cookie.value) if rc == 0 else None

    def create_cookie(self, pid: int, pid_type: int = PIDTYPE_PID) -> bool:
        return self._prctl(PR_SCHED_CORE_CREATE, pid, pid_type, 0) == 0

    def share_from(self, from_pid: int, to_pids: List[int]) -> List[int]:
        """SHARE_TO pushes the *calling task's* cookie onto a target, so the
        copy must run on a helper task that first pulls from_pid's cookie via
        SHARE_FROM (the reference's dedicated-thread dance). Python threads
        are distinct kernel tasks, so a short-lived thread serves as the
        helper without disturbing the agent's own (zero) cookie."""
        import threading

        failed: List[int] = list(to_pids)

        def _dance() -> None:
            if self._prctl(PR_SCHED_CORE_SHARE_FROM, from_pid, PIDTYPE_PID, 0) != 0:
                return
            failed.clear()
            for pid in to_pids:
                if self._prctl(PR_SCHED_CORE_SHARE_TO, pid, PIDTYPE_PID, 0) != 0:
                    failed.append(pid)

        t = threading.Thread(target=_dance, name="coresched-share")
        t.start()
        t.join()
        return failed

    def clear_cookie(self, pid: int) -> bool:
        """Push the agent's own zero cookie onto pid (SHARE_TO from a
        clean task clears); the koordlet main thread never takes a cookie."""
        return self._prctl(PR_SCHED_CORE_SHARE_TO, pid, PIDTYPE_PID, 0) == 0


class FakeCoreSched(CoreSchedInterface):
    """Deterministic in-memory cookie table (test double)."""

    def __init__(self) -> None:
        self.cookies: Dict[int, int] = {}
        self._next = 1

    def supported(self) -> bool:
        return True

    def get_cookie(self, pid: int) -> Optional[int]:
        return self.cookies.get(pid, 0)

    def create_cookie(self, pid: int, pid_type: int = PIDTYPE_PID) -> bool:
        self.cookies[pid] = self._next
        self._next += 1
        return True

    def share_from(self, from_pid: int, to_pids: List[int]) -> List[int]:
        src = self.cookies.get(from_pid)
        if src is None:
            return list(to_pids)
        for pid in to_pids:
            self.cookies[pid] = src
        return []

    def clear_cookie(self, pid: int) -> bool:
        self.cookies[pid] = 0
        return True


def default_interface() -> CoreSchedInterface:
    """The real prctl interface. Callers must check supported() and degrade
    explicitly — substituting the in-memory fake here would report phantom
    isolation success on kernels without PR_SCHED_CORE. Tests use
    FakeCoreSched directly."""
    return SystemCoreSched()
