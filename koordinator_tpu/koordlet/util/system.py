"""Kernel interface layer: cgroups v1/v2, /proc, PSI, resctrl.

Analog of reference `pkg/koordlet/util/system/`:
  * resource file registry for both cgroup drivers (cgroup_resource.go)
  * path resolution per QoS class / pod / container (the koordinator cgroup
    hierarchy: kubepods/{besteffort|burstable}/pod<uid>/<container>)
  * PSI parsing (psi.go), /proc/stat + /proc/meminfo parsing
  * `SystemConfig` root-dir redirection + `FakeFS` builder — the testability
    seam (config.go:38-82, util_test_tool.go:56-69): every read/write goes
    through the config roots, so tests (and the whole qosmanager/runtimehooks
    stack) run against a temp tree without root privileges.
"""

from __future__ import annotations

import os
import re
import tempfile
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

# cgroup resource kinds (names match the reference's ResourceType strings)
CPU_SHARES = "cpu.shares"
CPU_CFS_QUOTA = "cpu.cfs_quota_us"
CPU_CFS_PERIOD = "cpu.cfs_period_us"
CPU_CFS_BURST = "cpu.cfs_burst_us"
CPU_MAX = "cpu.max"                      # v2: "<quota> <period>"
CPU_WEIGHT = "cpu.weight"
CPU_BVT_WARP_NS = "cpu.bvt_warp_ns"      # group identity (Anolis bvt)
CPU_IDLE = "cpu.idle"
CPUSET_CPUS = "cpuset.cpus"
CPUSET_CPUS_EFFECTIVE = "cpuset.cpus.effective"
MEMORY_LIMIT = "memory.limit_in_bytes"
MEMORY_MAX = "memory.max"                # v2
MEMORY_HIGH = "memory.high"
MEMORY_MIN = "memory.min"
MEMORY_LOW = "memory.low"
MEMORY_WMARK_RATIO = "memory.wmark_ratio"
MEMORY_USAGE = "memory.usage_in_bytes"
MEMORY_CURRENT = "memory.current"        # v2
MEMORY_STAT = "memory.stat"
CPU_STAT = "cpu.stat"
CPUACCT_USAGE = "cpuacct.usage"          # v1 ns counter
CGROUP_PROCS = "cgroup.procs"            # PIDs attached to the cgroup
CPU_PRESSURE = "cpu.pressure"
MEMORY_PRESSURE = "memory.pressure"
IO_PRESSURE = "io.pressure"
BLKIO_WEIGHT = "blkio.bfq.weight"
IO_WEIGHT = "io.weight"                  # v2
IO_MAX = "io.max"                        # v2 "<maj:min> rbps=N wbps=N riops=N wiops=N"

# v1 files live under a subsystem directory; v2 files under the unified dir
_V1_SUBSYSTEM = {
    CPU_SHARES: "cpu", CPU_CFS_QUOTA: "cpu", CPU_CFS_PERIOD: "cpu",
    CPU_CFS_BURST: "cpu", CPU_BVT_WARP_NS: "cpu", CPU_STAT: "cpu",
    CPU_IDLE: "cpu",
    CPUSET_CPUS: "cpuset", CPUSET_CPUS_EFFECTIVE: "cpuset",
    MEMORY_LIMIT: "memory", MEMORY_USAGE: "memory", MEMORY_STAT: "memory",
    MEMORY_WMARK_RATIO: "memory", MEMORY_MIN: "memory", MEMORY_LOW: "memory",
    MEMORY_HIGH: "memory",
    CPUACCT_USAGE: "cpuacct",
    CPU_PRESSURE: "cpu", MEMORY_PRESSURE: "memory", IO_PRESSURE: "io",
    BLKIO_WEIGHT: "blkio",
    CGROUP_PROCS: "cpu",  # v1: any subsystem lists the same tasks; use cpu
}

# v1 name <-> v2 name translations where they differ
V1_TO_V2 = {
    MEMORY_LIMIT: MEMORY_MAX,
    MEMORY_USAGE: MEMORY_CURRENT,
    CPUACCT_USAGE: CPU_STAT,  # usage_usec field
    CPU_CFS_QUOTA: CPU_MAX,
    CPU_CFS_PERIOD: CPU_MAX,
    CPU_SHARES: CPU_WEIGHT,
    BLKIO_WEIGHT: IO_WEIGHT,
}

QOS_BESTEFFORT = "besteffort"
QOS_BURSTABLE = "burstable"
QOS_GUARANTEED = ""  # guaranteed pods sit directly under kubepods

# cgroup drivers (cgroup_driver.go): kubelet either lays pods out as plain
# dirs (cgroupfs) or as systemd slices/scopes (systemd)
DRIVER_CGROUPFS = "cgroupfs"
DRIVER_SYSTEMD = "systemd"


@dataclass
class SystemConfig:
    """Root-dir redirection (reference system.Conf)."""

    cgroup_root_dir: str = "/sys/fs/cgroup"
    proc_root_dir: str = "/proc"
    sys_root_dir: str = "/sys"
    fs_root_dir: str = "/"  # root volume for storage usage metrics
    use_cgroup_v2: bool = True
    cgroup_kube_root: str = "kubepods"
    cgroup_driver: str = DRIVER_CGROUPFS

    def qos_relative_path(self, qos_class: str) -> str:
        """kubepods[.slice]/<qos> relative dir for a k8s QoS class."""
        if self.cgroup_driver == DRIVER_SYSTEMD:
            root = f"{self.cgroup_kube_root}.slice"
            if qos_class in ("", QOS_GUARANTEED):
                return root
            return os.path.join(
                root, f"{self.cgroup_kube_root}-{qos_class}.slice")
        if qos_class in ("", QOS_GUARANTEED):
            return self.cgroup_kube_root
        return os.path.join(self.cgroup_kube_root, qos_class)

    def pod_relative_path(self, qos_class: str, pod_uid: str) -> str:
        if self.cgroup_driver == DRIVER_SYSTEMD:
            uid = pod_uid.replace("-", "_")
            prefix = self.cgroup_kube_root
            if qos_class not in ("", QOS_GUARANTEED):
                prefix = f"{prefix}-{qos_class}"
            return os.path.join(
                self.qos_relative_path(qos_class), f"{prefix}-pod{uid}.slice")
        return os.path.join(self.qos_relative_path(qos_class), f"pod{pod_uid}")

    def container_relative_path(self, qos_class: str, pod_uid: str,
                                container_id: str) -> str:
        if self.cgroup_driver == DRIVER_SYSTEMD:
            return os.path.join(
                self.pod_relative_path(qos_class, pod_uid),
                f"cri-containerd-{container_id}.scope")
        return os.path.join(self.pod_relative_path(qos_class, pod_uid), container_id)

    def cgroup_file_path(self, relative_dir: str, resource: str) -> str:
        if self.use_cgroup_v2:
            name = V1_TO_V2.get(resource, resource)
            return os.path.join(self.cgroup_root_dir, relative_dir, name)
        subsystem = _V1_SUBSYSTEM.get(resource, "cpu")
        return os.path.join(self.cgroup_root_dir, subsystem, relative_dir, resource)

    def proc_path(self, *parts: str) -> str:
        return os.path.join(self.proc_root_dir, *parts)

    def resctrl_root(self) -> str:
        return os.path.join(self.sys_root_dir, "fs", "resctrl")


def detect_cgroup_driver(config: "SystemConfig") -> str:
    """Probe the cgroup tree for kubepods.slice vs kubepods
    (cgroup_driver.go GetCgroupDriver semantics: look at which layout the
    kubelet actually created)."""
    roots = ([config.cgroup_root_dir] if config.use_cgroup_v2 else
             [os.path.join(config.cgroup_root_dir, sub)
              for sub in ("cpu", "memory", "cpuset")])
    for root in roots:
        if os.path.isdir(os.path.join(root, f"{config.cgroup_kube_root}.slice")):
            return DRIVER_SYSTEMD
        if os.path.isdir(os.path.join(root, config.cgroup_kube_root)):
            return DRIVER_CGROUPFS
    return DRIVER_CGROUPFS


def detect_cgroup_version(config: "SystemConfig") -> bool:
    """True if the unified (v2) hierarchy is mounted at the cgroup root."""
    return os.path.isfile(os.path.join(config.cgroup_root_dir,
                                       "cgroup.controllers"))


# module-level active config (reference's system.Conf global)
CONFIG = SystemConfig()


def read_file(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def write_file(path: str, value: str) -> bool:
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(value)
        return True
    except OSError:
        return False


def read_cgroup(relative_dir: str, resource: str,
                config: Optional[SystemConfig] = None) -> Optional[str]:
    cfg = config or CONFIG
    return read_file(cfg.cgroup_file_path(relative_dir, resource))


def write_cgroup(relative_dir: str, resource: str, value: str,
                 config: Optional[SystemConfig] = None) -> bool:
    cfg = config or CONFIG
    return write_file(cfg.cgroup_file_path(relative_dir, resource), value)


def read_cpu_usage_ns(relative_dir: str, config: Optional[SystemConfig] = None) -> Optional[int]:
    """Cumulative cpu usage in nanoseconds (cpuacct.usage v1 / cpu.stat v2)."""
    cfg = config or CONFIG
    if cfg.use_cgroup_v2:
        raw = read_cgroup(relative_dir, CPU_STAT, cfg)
        if raw is None:
            return None
        m = re.search(r"usage_usec (\d+)", raw)
        return int(m.group(1)) * 1000 if m else None
    raw = read_cgroup(relative_dir, CPUACCT_USAGE, cfg)
    return int(raw) if raw and raw.isdigit() else None


def read_memory_usage_bytes(relative_dir: str, config: Optional[SystemConfig] = None) -> Optional[int]:
    raw = read_cgroup(relative_dir, MEMORY_USAGE, config)
    return int(raw) if raw and raw.isdigit() else None


# ---------------------------------------------------------------------------
# PSI (psi.go)
# ---------------------------------------------------------------------------


@dataclass
class PSIStats:
    some_avg10: float = 0.0
    some_avg60: float = 0.0
    some_avg300: float = 0.0
    some_total_us: int = 0
    full_avg10: float = 0.0
    full_avg60: float = 0.0
    full_avg300: float = 0.0
    full_total_us: int = 0


_PSI_LINE = re.compile(
    r"^(some|full) avg10=([\d.]+) avg60=([\d.]+) avg300=([\d.]+) total=(\d+)"
)


def parse_psi(content: str) -> PSIStats:
    out = PSIStats()
    for line in content.splitlines():
        m = _PSI_LINE.match(line.strip())
        if not m:
            continue
        kind, a10, a60, a300, total = m.groups()
        if kind == "some":
            out.some_avg10, out.some_avg60, out.some_avg300 = (
                float(a10), float(a60), float(a300))
            out.some_total_us = int(total)
        else:
            out.full_avg10, out.full_avg60, out.full_avg300 = (
                float(a10), float(a60), float(a300))
            out.full_total_us = int(total)
    return out


def read_psi(relative_dir: str, resource: str = CPU_PRESSURE,
             config: Optional[SystemConfig] = None) -> Optional[PSIStats]:
    raw = read_cgroup(relative_dir, resource, config)
    return parse_psi(raw) if raw is not None else None


# ---------------------------------------------------------------------------
# /proc parsing
# ---------------------------------------------------------------------------


def read_proc_stat_cpu(config: Optional[SystemConfig] = None) -> Optional[Tuple[int, int]]:
    """(total_jiffies, idle_jiffies) from /proc/stat's aggregate cpu line."""
    cfg = config or CONFIG
    raw = read_file(cfg.proc_path("stat"))
    if not raw:
        return None
    for line in raw.splitlines():
        if line.startswith("cpu "):
            fields = [int(x) for x in line.split()[1:]]
            total = sum(fields)
            idle = fields[3] + (fields[4] if len(fields) > 4 else 0)
            return total, idle
    return None


def read_meminfo(config: Optional[SystemConfig] = None) -> Dict[str, int]:
    """/proc/meminfo in bytes."""
    cfg = config or CONFIG
    raw = read_file(cfg.proc_path("meminfo"))
    out: Dict[str, int] = {}
    if not raw:
        return out
    for line in raw.splitlines():
        parts = line.split()
        if len(parts) >= 2 and parts[0].endswith(":"):
            val = int(parts[1])
            if len(parts) >= 3 and parts[2] == "kB":
                val *= 1024
            out[parts[0][:-1]] = val
    return out


# ---------------------------------------------------------------------------
# FakeFS (util_test_tool.go FileTestUtil)
# ---------------------------------------------------------------------------


class FakeFS:
    """Builds a temp /sys + /proc + cgroup tree and repoints a SystemConfig at
    it; all koordlet modules taking a config then run hermetically."""

    def __init__(self, use_cgroup_v2: bool = True):
        self.root = tempfile.mkdtemp(prefix="koordlet-fakefs-")
        self.config = SystemConfig(
            cgroup_root_dir=os.path.join(self.root, "cgroup"),
            proc_root_dir=os.path.join(self.root, "proc"),
            sys_root_dir=os.path.join(self.root, "sys"),
            fs_root_dir=self.root,
            use_cgroup_v2=use_cgroup_v2,
        )

    def set_cgroup(self, relative_dir: str, resource: str, value: str) -> str:
        path = self.config.cgroup_file_path(relative_dir, resource)
        assert write_file(path, value)
        return path

    def get_cgroup(self, relative_dir: str, resource: str) -> Optional[str]:
        return read_cgroup(relative_dir, resource, self.config)

    def set_proc(self, name: str, content: str) -> None:
        write_file(self.config.proc_path(name), content)

    def set_file(self, path: str, content: str) -> None:
        write_file(os.path.join(self.root, path), content)

    def cleanup(self) -> None:
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)
