"""kidled cold-memory accounting (Anolis kernel idle-page scanner).

Analog of reference `pkg/koordlet/util/system/kidled_util.go`: the kidled
kernel thread ages idle pages into exponential buckets; per-cgroup
`memory.idle_page_stats` reports bytes per (page kind x age bucket). The
coldmemoryresource collector sums buckets older than `coldBoundary` scan
periods to compute reclaimable "cold" memory, which feeds the batch-memory
calculation (cold pages are effectively free capacity).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.koordlet.util import system as sysutil

KIDLED_SCAN_PERIOD = "kernel/mm/kidled/scan_period_in_seconds"
KIDLED_USE_HIERARCHY = "kernel/mm/kidled/use_hierarchy"
IDLE_PAGE_STATS = "memory.idle_page_stats"

# idle_page_stats rows: csei/dsei/cfei/dfei/csui/dsui/cfui/dfui/csea/dsea/...
# (clean/dirty x swappable/file x evictable/unevictable x inactive/active);
# columns are age buckets [1,2,5,15,30,60,120,240] scan periods.
_STATS_ROW = re.compile(r"^\s*([a-z]{4})\s+((?:\d+\s*)+)$")
DEFAULT_BUCKETS = [1, 2, 5, 15, 30, 60, 120, 240]


@dataclass
class IdlePageStats:
    version: str = ""
    scans: int = 0
    scan_period_s: int = 0
    buckets: List[int] = field(default_factory=lambda: list(DEFAULT_BUCKETS))
    rows: Dict[str, List[int]] = field(default_factory=dict)

    def cold_bytes(self, cold_boundary_s: int) -> int:
        """Sum of all pages idle for >= cold_boundary_s seconds."""
        if not self.rows or self.scan_period_s <= 0:
            return 0
        start = 0
        for i, periods in enumerate(self.buckets):
            if periods * self.scan_period_s >= cold_boundary_s:
                start = i
                break
        else:
            return 0
        return sum(sum(vals[start:]) for vals in self.rows.values())


def parse_idle_page_stats(content: str) -> IdlePageStats:
    out = IdlePageStats()
    for line in content.splitlines():
        if line.startswith("# version:"):
            out.version = line.split(":", 1)[1].strip()
        elif line.startswith("# scans:"):
            out.scans = int(line.split(":", 1)[1])
        elif line.startswith("# scan_period_in_seconds:"):
            out.scan_period_s = int(line.split(":", 1)[1])
        elif line.startswith("# buckets:"):
            out.buckets = [int(x) for x in
                           line.split(":", 1)[1].replace(",", " ").split()]
        else:
            m = _STATS_ROW.match(line)
            if m:
                out.rows[m.group(1)] = [int(x) for x in m.group(2).split()]
    return out


class KidledInterface:
    def __init__(self, config: Optional[sysutil.SystemConfig] = None):
        self.config = config or sysutil.CONFIG

    def _sys(self, rel: str) -> str:
        return os.path.join(self.config.sys_root_dir, rel)

    def supported(self) -> bool:
        return sysutil.read_file(self._sys(KIDLED_SCAN_PERIOD)) is not None

    def scan_period_s(self) -> int:
        raw = sysutil.read_file(self._sys(KIDLED_SCAN_PERIOD))
        return int(raw) if raw and raw.lstrip("-").isdigit() else 0

    def enabled(self) -> bool:
        return self.scan_period_s() > 0

    def enable(self, scan_period_s: int = 120, use_hierarchy: bool = True) -> bool:
        ok = sysutil.write_file(self._sys(KIDLED_SCAN_PERIOD), str(scan_period_s))
        ok = sysutil.write_file(
            self._sys(KIDLED_USE_HIERARCHY), "1" if use_hierarchy else "0") and ok
        return ok

    def read_pod_stats(self, relative_dir: str) -> Optional[IdlePageStats]:
        raw = sysutil.read_cgroup(relative_dir, IDLE_PAGE_STATS, self.config)
        return parse_idle_page_stats(raw) if raw is not None else None

    def pod_cold_bytes(self, relative_dir: str, cold_boundary_s: int = 300) -> int:
        stats = self.read_pod_stats(relative_dir)
        return stats.cold_bytes(cold_boundary_s) if stats else 0
