"""States informer: a registry of node-state informer plugins.

Analog of reference `pkg/koordlet/statesinformer/` — the plugin registry in
`impl/registry.go:21-28` instantiates {nodeSLO, pvc, nodeTopo, node, pods,
nodeMetric} informers, plus the device reporter (`impl/states_device_linux.go`).
Mirrored here:

  * ``NodeInformer`` / ``NodeSLOInformer`` — local views of the store with
    callback fan-out to subscribers (api.go:94-108)
  * ``PodsInformer`` — pod map keyed by UID; when a :class:`KubeletStub` is
    attached it pulls `GET /pods` on an interval and PLEG pod-added events
    force an immediate resync (`impl/states_pods.go:91-126`), otherwise it
    mirrors the store
  * ``PVCInformer`` — pvc namespace/name -> bound volume name map
    (`impl/states_pvc.go:44-60`)
  * ``DeviceInformer`` — publishes the node's accelerator inventory as a
    Device CR (`impl/states_device_linux.go`); the default collector probes
    the local TPU chips via ``jax.devices()`` instead of NVML
  * ``NodeMetricInformer`` — aggregates the metric cache into the NodeMetric
    CR status on an interval (`impl/states_nodemetric.go:182-210`)
  * ``NodeTopoInformer`` — publishes NodeResourceTopology from machine info.

The outer :class:`StatesInformer` keeps the pre-registry surface (get_node,
get_all_pods, register_callback, ...) by delegating to the plugins, so every
koordlet module keeps working unchanged.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional

from koordinator_tpu.api.objects import (
    Device,
    DeviceInfo,
    Node,
    NodeMetric,
    NodeMetricInfo,
    NodeResourceTopology,
    NodeSLO,
    ObjectMeta,
    PersistentVolumeClaim,
    Pod,
    PodMetricInfo,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_DEVICE,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_SLO,
    KIND_NODE_TOPOLOGY,
    KIND_POD,
    KIND_PVC,
    EventType,
    ObjectStore,
)
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.kubeletstub import KubeletError, KubeletStub
from koordinator_tpu.koordlet.pleg import Pleg, PodLifecycleEvent

CALLBACK_NODE_SLO = "nodeslo"
CALLBACK_PODS = "pods"
CALLBACK_NODE = "node"


@dataclass
class PluginOption:
    """Construction-time wiring handed to every plugin's setup()
    (impl/states_informer.go PluginOption)."""

    store: ObjectStore
    node_name: str
    cache: mc.MetricCache
    report_interval: int = 60
    aggregate_windows: tuple = (300, 900, 1800)
    kubelet_stub: Optional[KubeletStub] = None
    kubelet_sync_interval: float = 30.0
    pleg: Optional[Pleg] = None
    device_collector: Optional[Callable[[], List[DeviceInfo]]] = None


class PluginState:
    """Shared inter-plugin state: the plugin map (for cross-plugin lookups the
    way podsInformer grabs nodeInformer in states_pods.go:79-86) and the
    callback runner."""

    def __init__(self) -> None:
        self.informer_plugins: Dict[str, "InformerPlugin"] = {}
        self._callbacks: Dict[str, List[Callable]] = {}

    def register_callback(self, kind: str, fn: Callable) -> None:
        self._callbacks.setdefault(kind, []).append(fn)

    def fire(self, kind: str, obj) -> None:
        for fn in self._callbacks.get(kind, []):
            fn(obj)


class InformerPlugin:
    """informerPlugin interface (impl/states_informer.go:60-66): Setup wires
    dependencies, sync() is one tick of the plugin's loop, HasSynced gates
    consumers that need a complete first view."""

    name: str = ""

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        raise NotImplementedError

    def sync(self, now: float) -> None:  # default: event-driven plugins no-op
        return None

    def has_synced(self) -> bool:
        return True


class NodeInformer(InformerPlugin):
    name = "nodeInformer"

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        self.opts, self.state = opts, state
        opts.store.subscribe(KIND_NODE, self._on_node)

    def get_node(self) -> Optional[Node]:
        return self.opts.store.get(KIND_NODE, f"/{self.opts.node_name}")

    def _on_node(self, ev: EventType, node: Node, old) -> None:
        if node.meta.name == self.opts.node_name:
            self.state.fire(CALLBACK_NODE, node)


class NodeSLOInformer(InformerPlugin):
    name = "nodeSLOInformer"

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        self.opts, self.state = opts, state
        opts.store.subscribe(KIND_NODE_SLO, self._on_nodeslo)

    def get_node_slo(self) -> NodeSLO:
        slo = self.opts.store.get(KIND_NODE_SLO, f"/{self.opts.node_name}")
        return slo if slo is not None else NodeSLO(
            meta=ObjectMeta(name=self.opts.node_name, namespace="")
        )

    def _on_nodeslo(self, ev: EventType, slo: NodeSLO, old) -> None:
        if slo.meta.name == self.opts.node_name:
            self.state.fire(CALLBACK_NODE_SLO, slo)


class PodsInformer(InformerPlugin):
    """Pod map for this node. Two sources, matching the reference:

    * apiserver mirror: store events keep the map fresh (the default; all
      in-process tests run this way)
    * kubelet: when ``opts.kubelet_stub`` is set, `GET /pods` is pulled every
      ``kubelet_sync_interval`` seconds and a PLEG pod-added event forces the
      next sync() to pull immediately (states_pods.go:102-126) — the kubelet
      list then *owns* the map (pods it no longer reports are dropped)."""

    name = "podsInformer"

    def __init__(self) -> None:
        self._pods_by_uid: Dict[str, Pod] = {}
        self._synced = False
        self._last_kubelet_sync = 0.0
        self._resync_requested = False

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        self.opts, self.state = opts, state
        opts.store.subscribe(KIND_POD, self._on_pod)
        if opts.pleg is not None:
            opts.pleg.add_handler(self._on_pleg_event)

    # -- views ---------------------------------------------------------------
    def get_all_pods(self) -> List[Pod]:
        if self.opts.kubelet_stub is not None:
            return [p for p in self._pods_by_uid.values() if not p.is_terminated]
        return [
            p
            for p in self.opts.store.list(KIND_POD)
            if p.spec.node_name == self.opts.node_name and not p.is_terminated
        ]

    def get_pod_by_uid(self, uid: str) -> Optional[Pod]:
        """O(1) lookup for the hook server's per-RPC critical path."""
        return self._pods_by_uid.get(uid)

    def has_synced(self) -> bool:
        return self.opts.kubelet_stub is None or self._synced

    # -- sources -------------------------------------------------------------
    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        if pod.spec.node_name != self.opts.node_name:
            return
        uid = pod.meta.uid
        if uid:
            if ev is EventType.DELETED:
                self._pods_by_uid.pop(uid, None)
            else:
                self._pods_by_uid[uid] = pod
        self.state.fire(CALLBACK_PODS, pod)

    def _on_pleg_event(self, ev: PodLifecycleEvent) -> None:
        # states_pods.go:102-112: only pod creation triggers an early resync,
        # and an already-pending request is not duplicated.
        if ev.event_type == "pod_added":
            self._resync_requested = True

    def request_resync(self) -> None:
        self._resync_requested = True

    def sync(self, now: float) -> None:
        stub = self.opts.kubelet_stub
        if stub is None:
            return
        due = now - self._last_kubelet_sync >= self.opts.kubelet_sync_interval
        if not (due or self._resync_requested):
            return
        try:
            pods = stub.get_all_pods()
        except KubeletError:
            # kubelet unreachable: keep the last good view (states_pods.go:148)
            return
        if not pods and self._pods_by_uid:
            # kubelet recovering from a crash may return empty; don't wipe
            return
        self._last_kubelet_sync = now
        self._resync_requested = False
        self._pods_by_uid = {p.meta.uid: p for p in pods if p.meta.uid}
        self._synced = True
        for pod in self._pods_by_uid.values():
            self.state.fire(CALLBACK_PODS, pod)


class PVCInformer(InformerPlugin):
    name = "pvcInformer"

    def __init__(self) -> None:
        self._volume_name: Dict[str, str] = {}

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        self.opts = opts
        opts.store.subscribe(KIND_PVC, self._on_pvc)

    def get_volume_name(self, namespace: str, name: str) -> str:
        """pvc namespace/name -> bound PV name (states_pvc.go:55-60); the
        blkio reconciler resolves device majmin through this."""
        return self._volume_name.get(f"{namespace}/{name}", "")

    def _on_pvc(self, ev: EventType, pvc: PersistentVolumeClaim, old) -> None:
        if ev is EventType.DELETED:
            self._volume_name.pop(pvc.meta.key, None)
        elif pvc.volume_name:
            self._volume_name[pvc.meta.key] = pvc.volume_name


_DEVICE_PROBE_LOGGED = set()  # log each failure stage once, count always
_DEVICE_PROBE_LOCK = threading.Lock()  # probes run from informer threads


def _device_probe_error(stage: str, exc: Exception) -> None:
    """An accelerator-probe failure is an EXPECTED degradation off-TPU
    but must never be invisible: count every occurrence
    (koord_koordlet_informer_errors_total) and log the first per stage —
    a silent `except Exception` here once hid real breakage behind an
    empty device inventory."""
    from koordinator_tpu.koordlet import metrics as koordlet_metrics

    koordlet_metrics.INFORMER_ERRORS_TOTAL.inc(
        informer="deviceInformer", stage=stage)
    with _DEVICE_PROBE_LOCK:
        first = stage not in _DEVICE_PROBE_LOGGED
        _DEVICE_PROBE_LOGGED.add(stage)
    if first:
        logging.getLogger(__name__).warning(
            "device probe %s failed (%s: %s); reporting no accelerators "
            "— counted in koord_koordlet_informer_errors_total",
            stage, type(exc).__name__, exc)


def collect_tpu_devices() -> List[DeviceInfo]:
    """Default device collector: probe local TPU chips through JAX (the
    tpu-native stand-in for the reference's NVML walk in
    states_device_linux.go buildGPUDevice). Reported under the generic
    accelerator resource axes so DeviceShare/gpudeviceresource consume them
    unchanged. Returns [] off-TPU (logged once + counted, never silent)."""
    try:
        import jax

        devices = [d for d in jax.devices() if d.platform == "tpu"]
    except Exception as exc:
        _device_probe_error("jax_devices", exc)
        return []
    out = []
    for d in devices:
        mem = 0
        stats = getattr(d, "memory_stats", None)
        if callable(stats):
            try:
                mem = int(stats().get("bytes_limit", 0))
            except Exception as exc:
                _device_probe_error("memory_stats", exc)
                mem = 0
        out.append(
            DeviceInfo(
                type="gpu",  # accelerator axis shared with the scheduler
                uuid=f"TPU-{getattr(d, 'id', 0)}",
                minor=int(getattr(d, "id", 0)),
                health=True,
                resources=ResourceList.of(
                    gpu_core=100, gpu_memory=mem, gpu_memory_ratio=100
                ),
                numa_node=int(getattr(d, "process_index", 0)),
            )
        )
    return out


class DeviceInformer(InformerPlugin):
    """Publish the node's device inventory as a Device CR for the scheduler's
    DeviceShare plugin and the gpudeviceresource node-resource plugin
    (states_device_linux.go reportDevice)."""

    name = "deviceInformer"

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        self.opts = opts
        self.collector = opts.device_collector or collect_tpu_devices

    def sync(self, now: float) -> None:
        devices = self.collector()
        if not devices:
            return
        # the CR owns its copies: a collector reusing DeviceInfo objects must
        # not mutate the stored view (nvml walk rebuilds each report too)
        devices = [replace(d) for d in devices]
        store, name = self.opts.store, self.opts.node_name
        existing: Optional[Device] = store.get(KIND_DEVICE, f"/{name}")
        if existing is None:
            store.add(KIND_DEVICE, Device(
                meta=ObjectMeta(name=name, namespace=""), devices=devices
            ))
        elif [
            (d.type, d.uuid, d.minor, d.health) for d in existing.devices
        ] != [(d.type, d.uuid, d.minor, d.health) for d in devices]:
            existing.devices = devices
            store.update(KIND_DEVICE, existing)


class NodeMetricInformer(InformerPlugin):
    """NodeMetric reporter (states_nodemetric.go:182-210): avg + percentile
    windows aggregated from the metric cache into the CR status."""

    name = "nodeMetricInformer"

    def __init__(self) -> None:
        self._last_report = 0.0

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        self.opts = opts
        self.pods = state.informer_plugins["podsInformer"]

    def sync(self, now: float) -> None:
        self.sync_node_metric(now)

    def sync_node_metric(self, now: Optional[float] = None) -> Optional[NodeMetric]:
        now = time.time() if now is None else now
        opts = self.opts
        if now - self._last_report < opts.report_interval:
            return None
        self._last_report = now
        cache = opts.cache

        def usage(window: Optional[float], agg: str) -> ResourceList:
            cpu = cache.query(mc.NODE_CPU_USAGE, agg, window, now)
            mem = cache.query(mc.NODE_MEMORY_USAGE, agg, window, now)
            return ResourceList.of(
                cpu=int((cpu or 0.0) * 1000), memory=int(mem or 0)
            )

        info = NodeMetricInfo(
            node_usage=usage(opts.report_interval * 2, "avg"),
            system_usage=ResourceList.of(
                cpu=int(
                    (cache.query(mc.SYS_CPU_USAGE, "avg",
                                 opts.report_interval * 2, now) or 0.0)
                    * 1000
                )
            ),
            aggregated_node_usages={
                w: {
                    agg: usage(float(w), agg)
                    for agg in ("avg", "p50", "p90", "p95", "p99")
                }
                for w in opts.aggregate_windows
            },
        )
        pods_metric = []
        for pod in self.pods.get_all_pods():
            cpu = cache.query(
                mc.POD_CPU_USAGE, "avg", opts.report_interval * 2, now,
                pod=pod.meta.key,
            )
            memv = cache.query(
                mc.POD_MEMORY_USAGE, "avg", opts.report_interval * 2, now,
                pod=pod.meta.key,
            )
            if cpu is None and memv is None:
                continue
            pods_metric.append(
                PodMetricInfo(
                    namespace=pod.meta.namespace,
                    name=pod.meta.name,
                    pod_usage=ResourceList.of(
                        cpu=int((cpu or 0.0) * 1000), memory=int(memv or 0)
                    ),
                    priority_class=pod.priority_class,
                )
            )
        nm = opts.store.get(KIND_NODE_METRIC, f"/{opts.node_name}")
        if nm is None:
            nm = NodeMetric(meta=ObjectMeta(name=opts.node_name, namespace=""))
            opts.store.add(KIND_NODE_METRIC, nm)
        nm.update_time = now
        nm.node_metric = info
        nm.pods_metric = pods_metric
        nm.report_interval_seconds = opts.report_interval
        nm.aggregate_durations = list(opts.aggregate_windows)
        opts.store.update(KIND_NODE_METRIC, nm)
        return nm


class NodeTopoInformer(InformerPlugin):
    name = "nodeTopoInformer"

    def setup(self, opts: PluginOption, state: PluginState) -> None:
        self.opts = opts

    def sync_node_topology(self, topo_cr: NodeResourceTopology) -> None:
        topo_cr.meta.name = self.opts.node_name
        topo_cr.meta.namespace = ""
        store = self.opts.store
        existing = store.get(KIND_NODE_TOPOLOGY, f"/{self.opts.node_name}")
        if existing is None:
            store.add(KIND_NODE_TOPOLOGY, topo_cr)
        else:
            store.update(KIND_NODE_TOPOLOGY, topo_cr)


# registry.go:21-28 (+ the linux device reporter, a method there, a plugin here)
DEFAULT_PLUGIN_REGISTRY: Dict[str, Callable[[], InformerPlugin]] = {
    "nodeSLOInformer": NodeSLOInformer,
    "pvcInformer": PVCInformer,
    "nodeTopoInformer": NodeTopoInformer,
    "nodeInformer": NodeInformer,
    "podsInformer": PodsInformer,
    "nodeMetricInformer": NodeMetricInformer,
    "deviceInformer": DeviceInformer,
}


class StatesInformer:
    """Facade over the plugin registry; keeps the original method surface."""

    def __init__(self, store: ObjectStore, node_name: str,
                 cache: mc.MetricCache,
                 report_interval_seconds: int = 60,
                 aggregate_windows=(300, 900, 1800),
                 kubelet_stub: Optional[KubeletStub] = None,
                 kubelet_sync_interval: float = 30.0,
                 pleg: Optional[Pleg] = None,
                 device_collector: Optional[Callable[[], List[DeviceInfo]]] = None,
                 registry: Optional[Dict[str, Callable[[], InformerPlugin]]] = None):
        self.store = store
        self.node_name = node_name
        self.cache = cache
        opts = PluginOption(
            store=store, node_name=node_name, cache=cache,
            report_interval=report_interval_seconds,
            aggregate_windows=tuple(aggregate_windows),
            kubelet_stub=kubelet_stub,
            kubelet_sync_interval=kubelet_sync_interval,
            pleg=pleg, device_collector=device_collector,
        )
        self.state = PluginState()
        self.plugins = self.state.informer_plugins
        # two-phase: instantiate all, then setup all, so plugins can resolve
        # each other through PluginState (states_pods.go:79-86)
        for name, factory in (registry or DEFAULT_PLUGIN_REGISTRY).items():
            plugin = factory()
            plugin.name = name
            self.plugins[name] = plugin
        for plugin in self.plugins.values():
            plugin.setup(opts, self.state)

    def sync(self, now: Optional[float] = None) -> None:
        """One tick of every plugin's loop (states_informer.go Run)."""
        now = time.time() if now is None else now
        for plugin in self.plugins.values():
            plugin.sync(now)

    def has_synced(self) -> bool:
        return all(p.has_synced() for p in self.plugins.values())

    # -- pre-registry surface, delegated -------------------------------------
    def get_node(self) -> Optional[Node]:
        return self.plugins["nodeInformer"].get_node()

    def get_node_slo(self) -> NodeSLO:
        return self.plugins["nodeSLOInformer"].get_node_slo()

    def get_all_pods(self) -> List[Pod]:
        return self.plugins["podsInformer"].get_all_pods()

    def get_pod_by_uid(self, uid: str) -> Optional[Pod]:
        return self.plugins["podsInformer"].get_pod_by_uid(uid)

    def get_volume_name(self, namespace: str, name: str) -> str:
        return self.plugins["pvcInformer"].get_volume_name(namespace, name)

    def register_callback(self, kind: str, fn: Callable) -> None:
        self.state.register_callback(kind, fn)

    def sync_node_metric(self, now: Optional[float] = None) -> Optional[NodeMetric]:
        return self.plugins["nodeMetricInformer"].sync_node_metric(now)

    def sync_node_topology(self, topo_cr: NodeResourceTopology) -> None:
        self.plugins["nodeTopoInformer"].sync_node_topology(topo_cr)
