"""States informer: node-local state plugins + NodeMetric/NodeTopo reporting.

Analog of reference `pkg/koordlet/statesinformer/` (registry impl/registry.go:21-28):
  * node/pods/nodeslo informers: local views of the store (the kubelet-stub +
    CRD informers of the reference), with callback fan-out to subscribers
    (api.go:94-108) on state changes
  * nodemetric reporter (impl/states_nodemetric.go:182-210): aggregates the
    metric cache into the NodeMetric CR status on an interval (avg + percentile
    windows)
  * nodetopo reporter: publishes NodeResourceTopology from machine info.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from koordinator_tpu.api.objects import (
    Node,
    NodeMetric,
    NodeMetricInfo,
    NodeResourceTopology,
    NodeSLO,
    ObjectMeta,
    Pod,
    PodMetricInfo,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_SLO,
    KIND_NODE_TOPOLOGY,
    KIND_POD,
    EventType,
    ObjectStore,
)
from koordinator_tpu.koordlet import metriccache as mc

CALLBACK_NODE_SLO = "nodeslo"
CALLBACK_PODS = "pods"
CALLBACK_NODE = "node"


class StatesInformer:
    def __init__(self, store: ObjectStore, node_name: str,
                 cache: mc.MetricCache,
                 report_interval_seconds: int = 60,
                 aggregate_windows=(300, 900, 1800)):
        self.store = store
        self.node_name = node_name
        self.cache = cache
        self.report_interval = report_interval_seconds
        self.aggregate_windows = tuple(aggregate_windows)
        self._callbacks: Dict[str, List[Callable]] = {}
        self._last_report = 0.0
        self._pods_by_uid: Dict[str, Pod] = {}
        store.subscribe(KIND_POD, self._on_pod)
        store.subscribe(KIND_NODE_SLO, self._on_nodeslo)
        store.subscribe(KIND_NODE, self._on_node)

    # -- local views ---------------------------------------------------------
    def get_node(self) -> Optional[Node]:
        return self.store.get(KIND_NODE, f"/{self.node_name}")

    def get_node_slo(self) -> NodeSLO:
        slo = self.store.get(KIND_NODE_SLO, f"/{self.node_name}")
        return slo if slo is not None else NodeSLO(
            meta=ObjectMeta(name=self.node_name, namespace="")
        )

    def get_all_pods(self) -> List[Pod]:
        return [
            p
            for p in self.store.list(KIND_POD)
            if p.spec.node_name == self.node_name and not p.is_terminated
        ]

    # -- callbacks (api.go RegisterCallbacks) --------------------------------
    def register_callback(self, kind: str, fn: Callable) -> None:
        self._callbacks.setdefault(kind, []).append(fn)

    def _fire(self, kind: str, obj) -> None:
        for fn in self._callbacks.get(kind, []):
            fn(obj)

    def get_pod_by_uid(self, uid: str) -> Optional[Pod]:
        """O(1) lookup for the hook server's per-RPC critical path."""
        return self._pods_by_uid.get(uid)

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        if pod.spec.node_name != self.node_name:
            return
        uid = pod.meta.uid
        if uid:
            if ev is EventType.DELETED:
                self._pods_by_uid.pop(uid, None)
            else:
                self._pods_by_uid[uid] = pod
        self._fire(CALLBACK_PODS, pod)

    def _on_nodeslo(self, ev: EventType, slo: NodeSLO, old) -> None:
        if slo.meta.name == self.node_name:
            self._fire(CALLBACK_NODE_SLO, slo)

    def _on_node(self, ev: EventType, node: Node, old) -> None:
        if node.meta.name == self.node_name:
            self._fire(CALLBACK_NODE, node)

    # -- NodeMetric reporter (states_nodemetric.go) --------------------------
    def sync_node_metric(self, now: Optional[float] = None) -> Optional[NodeMetric]:
        now = time.time() if now is None else now
        if now - self._last_report < self.report_interval:
            return None
        self._last_report = now

        def usage(window: Optional[float], agg: str) -> ResourceList:
            cpu = self.cache.query(mc.NODE_CPU_USAGE, agg, window, now)
            mem = self.cache.query(mc.NODE_MEMORY_USAGE, agg, window, now)
            return ResourceList.of(
                cpu=int((cpu or 0.0) * 1000), memory=int(mem or 0)
            )

        info = NodeMetricInfo(
            node_usage=usage(self.report_interval * 2, "avg"),
            system_usage=ResourceList.of(
                cpu=int(
                    (self.cache.query(mc.SYS_CPU_USAGE, "avg",
                                      self.report_interval * 2, now) or 0.0)
                    * 1000
                )
            ),
            aggregated_node_usages={
                w: {
                    agg: usage(float(w), agg)
                    for agg in ("avg", "p50", "p90", "p95", "p99")
                }
                for w in self.aggregate_windows
            },
        )
        pods_metric = []
        for pod in self.get_all_pods():
            cpu = self.cache.query(
                mc.POD_CPU_USAGE, "avg", self.report_interval * 2, now,
                pod=pod.meta.key,
            )
            memv = self.cache.query(
                mc.POD_MEMORY_USAGE, "avg", self.report_interval * 2, now,
                pod=pod.meta.key,
            )
            if cpu is None and memv is None:
                continue
            pods_metric.append(
                PodMetricInfo(
                    namespace=pod.meta.namespace,
                    name=pod.meta.name,
                    pod_usage=ResourceList.of(
                        cpu=int((cpu or 0.0) * 1000), memory=int(memv or 0)
                    ),
                    priority_class=pod.priority_class,
                )
            )
        nm = self.store.get(KIND_NODE_METRIC, f"/{self.node_name}")
        if nm is None:
            nm = NodeMetric(meta=ObjectMeta(name=self.node_name, namespace=""))
            self.store.add(KIND_NODE_METRIC, nm)
        nm.update_time = now
        nm.node_metric = info
        nm.pods_metric = pods_metric
        nm.report_interval_seconds = self.report_interval
        nm.aggregate_durations = list(self.aggregate_windows)
        self.store.update(KIND_NODE_METRIC, nm)
        return nm

    # -- NodeResourceTopology reporter (states_nodetopo) ---------------------
    def sync_node_topology(self, topo_cr: NodeResourceTopology) -> None:
        topo_cr.meta.name = self.node_name
        topo_cr.meta.namespace = ""
        existing = self.store.get(KIND_NODE_TOPOLOGY, f"/{self.node_name}")
        if existing is None:
            self.store.add(KIND_NODE_TOPOLOGY, topo_cr)
        else:
            self.store.update(KIND_NODE_TOPOLOGY, topo_cr)
