"""koordlet Daemon: module wiring + run loop.

Analog of reference `pkg/koordlet/koordlet.go:70-188`: NewDaemon builds
executor -> metriccache -> statesinformer -> metricsadvisor -> prediction ->
qosmanager -> runtimehooks; Run starts them in dependency order. `run_once(now)`
drives one tick of everything (tests and the driver call it directly; `run`
loops it on an interval)."""

from __future__ import annotations

import os
import time
from typing import Optional

from koordinator_tpu.client.store import ObjectStore
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.server import KoordletServer
from koordinator_tpu.koordlet.metricsadvisor import MetricsAdvisor
from koordinator_tpu.koordlet.pleg import Pleg
from koordinator_tpu.koordlet.prediction import PeakPredictServer
from koordinator_tpu.koordlet.qosmanager import QoSManager
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks import RuntimeHooks
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.koordlet import metriccache as mc


class Daemon:
    def __init__(self, store: ObjectStore, node_name: str,
                 config: Optional[sysutil.SystemConfig] = None,
                 checkpoint_dir: Optional[str] = None,
                 report_interval_seconds: int = 60,
                 autodetect_cgroups: bool = False,
                 kubelet_stub=None,
                 device_collector=None):
        self.config = config or sysutil.CONFIG
        if autodetect_cgroups:
            # probe the real node layout (koordlet.go does this at startup
            # via system.InitSupportConfigs); explicit configs (tests/FakeFS)
            # skip it
            self.config.use_cgroup_v2 = sysutil.detect_cgroup_version(self.config)
            self.config.cgroup_driver = sysutil.detect_cgroup_driver(self.config)
        self.auditor = Auditor()
        self.executor = ResourceUpdateExecutor(self.config, self.auditor)
        # metriccache persists next to the prediction checkpoints so the
        # NodeMetric aggregation window survives agent restarts
        # (tsdb_storage.go:32-46)
        metric_storage = (
            os.path.join(checkpoint_dir, "metriccache.pkl")
            if checkpoint_dir else None
        )
        from koordinator_tpu.koordlet.metrics import REGISTRY

        self.metric_cache = MetricCache(storage_path=metric_storage)
        self.api_server = KoordletServer(self.auditor,
                                         metrics_registry=REGISTRY)
        # PLEG feeds the pods informer (cgroup pod-added -> early kubelet
        # resync), so it is built first (koordlet.go wiring order)
        self.pleg = Pleg(self.config)
        self.states_informer = StatesInformer(
            store, node_name, self.metric_cache,
            report_interval_seconds=report_interval_seconds,
            kubelet_stub=kubelet_stub,
            pleg=self.pleg,
            device_collector=device_collector,
        )
        self.metrics_advisor = MetricsAdvisor(
            self.states_informer, self.metric_cache, self.config
        )
        from koordinator_tpu.utils.features import KOORDLET_GATES

        if KOORDLET_GATES.enabled("CPICollector"):
            from koordinator_tpu.native.perf import build_cgroup_perf_reader

            self.metrics_advisor.perf_reader = build_cgroup_perf_reader(self.config)
        self.prediction = PeakPredictServer(checkpoint_dir)
        self.qos_manager = QoSManager(
            store, self.states_informer, self.metric_cache, self.executor
        )
        self.runtime_hooks = RuntimeHooks(self.states_informer, self.executor)

    def run_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self.pleg.tick()
        self.metrics_advisor.collect_once(now)
        for pod in self.states_informer.get_all_pods():
            cpu = self.metric_cache.query(
                mc.POD_CPU_USAGE, "latest", now=now, pod=pod.meta.key
            )
            mem = self.metric_cache.query(
                mc.POD_MEMORY_USAGE, "latest", now=now, pod=pod.meta.key
            )
            if cpu is not None or mem is not None:
                self.prediction.update(
                    pod.meta.uid or pod.meta.key, cpu or 0.0, mem or 0.0, now
                )
        self.states_informer.sync(now)
        self.qos_manager.run_once(now)
        self.runtime_hooks.reconcile()
        self.metric_cache.maybe_flush(now)

    def run(self, interval_seconds: float = 10.0, max_ticks: Optional[int] = None) -> None:
        ticks = 0
        while max_ticks is None or ticks < max_ticks:
            self.run_once()
            self.prediction.checkpoint()
            ticks += 1
            time.sleep(interval_seconds)
