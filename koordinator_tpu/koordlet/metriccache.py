"""Metric cache: the node-local TSDB + static-info KV store.

Analog of reference `pkg/koordlet/metriccache/` (embedded Prometheus tsdb + gob
KV, metric_cache.go:56-79, tsdb_storage.go:32-46): time-series keyed by
(metric, labels) with windowed aggregate queries
(avg/p50/p90/p95/p99/latest/count), bounded retention. Numpy-backed percentile
math so the NodeMetric reporter's aggregated usages are consistent with the
scheduler's percentile semantics.

Persistence: the reference's TSDB lives on disk and survives agent restarts;
here an atomic pickle snapshot (tmp + rename) is written every
flush_interval_seconds and restored on construction, so the NodeMetric
aggregation window (and the static-info KV) carries across restarts.
"""

from __future__ import annotations

import bisect
import os
import pickle
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple

import numpy as np

# canonical metric names (metric_resources.go)
NODE_CPU_USAGE = "node_cpu_usage"            # cores
NODE_MEMORY_USAGE = "node_memory_usage"      # bytes
POD_CPU_USAGE = "pod_cpu_usage"
POD_MEMORY_USAGE = "pod_memory_usage"
CONTAINER_CPU_USAGE = "container_cpu_usage"
CONTAINER_MEMORY_USAGE = "container_memory_usage"
BE_CPU_USAGE = "be_cpu_usage"
SYS_CPU_USAGE = "sys_cpu_usage"
NODE_CPU_PSI_FULL_AVG10 = "node_cpu_psi_full_avg10"
NODE_MEM_PSI_FULL_AVG10 = "node_mem_psi_full_avg10"
POD_CPI = "pod_cpi"
HOST_APP_CPU_USAGE = "host_app_cpu_usage"
HOST_APP_MEMORY_USAGE = "host_app_memory_usage"
POD_PAGECACHE = "pod_pagecache"              # bytes of page cache per pod
POD_COLD_MEMORY = "pod_cold_memory"          # kidled cold bytes per pod
POD_CPU_THROTTLED_RATIO = "pod_cpu_throttled_ratio"  # nr_throttled/nr_periods
NODE_FS_USED_BYTES = "node_fs_used_bytes"
NODE_FS_TOTAL_BYTES = "node_fs_total_bytes"
NODE_DISK_IO_TICKS = "node_disk_io_ticks"    # per-device busy-ms counter delta
NODE_GPU_CORE_USAGE = "node_gpu_core_usage"  # per-accelerator compute %
NODE_GPU_MEM_USAGE = "node_gpu_mem_usage"    # per-accelerator HBM bytes in use

NODE_CPU_INFO_KEY = "node_cpu_info"
NODE_NUMA_INFO_KEY = "node_numa_info"
NODE_STORAGE_INFO_KEY = "node_storage_info"


@dataclass(frozen=True)
class SeriesKey:
    metric: str
    labels: Tuple[Tuple[str, str], ...] = ()

    @staticmethod
    def of(metric: str, **labels: str) -> "SeriesKey":
        return SeriesKey(metric, tuple(sorted(labels.items())))


class MetricCache:
    def __init__(self, retention_seconds: float = 1800.0,
                 storage_path: Optional[str] = None,
                 flush_interval_seconds: float = 60.0):
        self.retention = retention_seconds
        self.storage_path = storage_path
        self.flush_interval = flush_interval_seconds
        self._last_flush = 0.0
        self._lock = threading.RLock()
        self._series: Dict[SeriesKey, Deque[Tuple[float, float]]] = {}
        self._kv: Dict[str, Any] = {}
        if storage_path:
            self._restore()

    # -- persistence (tsdb_storage.go analog) --------------------------------
    def _restore(self) -> None:
        # a bad snapshot must never crash-loop agent startup: ANY failure
        # (unpickling, moved classes -> AttributeError, malformed keys ->
        # TypeError) degrades to an empty cache, as the reference does when
        # the TSDB dir is unusable
        try:
            with open(self.storage_path, "rb") as f:
                snap = pickle.load(f)
            series = snap.get("series", {})
            # retention anchored to the newest persisted sample, not wall
            # clock: keeps the window intact across clock skew and makes
            # restore deterministic for replayed timelines; add_sample prunes
            # from there
            latest = max(
                (pts[-1][0] for pts in series.values() if pts), default=0.0
            )
            cutoff = latest - self.retention
            restored = {}
            for key_parts, points in series.items():
                kept = [(ts, v) for ts, v in points if ts >= cutoff]
                if kept:
                    restored[SeriesKey(*key_parts)] = deque(kept)
            kv = dict(snap.get("kv", {}))
        except Exception:
            return
        with self._lock:
            self._series.update(restored)
            self._kv.update(kv)

    def flush(self, now: Optional[float] = None) -> bool:
        """Atomic snapshot to disk (tmp + rename): a crash mid-write never
        corrupts the previous snapshot. I/O failures (disk full, unwritable
        dir) are swallowed — persistence is best-effort and must never kill
        the agent loop; _last_flush still advances so a bad disk isn't
        retried every tick."""
        if not self.storage_path:
            return False
        now = time.time() if now is None else now
        with self._lock:
            snap = {
                "series": {
                    (k.metric, k.labels): list(q)
                    for k, q in self._series.items()
                },
                "kv": dict(self._kv),
            }
            self._last_flush = now
        tmp = self.storage_path + ".tmp"
        try:
            os.makedirs(os.path.dirname(self.storage_path) or ".", exist_ok=True)
            with open(tmp, "wb") as f:
                pickle.dump(snap, f)
            os.replace(tmp, self.storage_path)
        except OSError:
            return False
        return True

    def maybe_flush(self, now: Optional[float] = None) -> bool:
        """Periodic flush hook for the daemon loop."""
        now = time.time() if now is None else now
        if not self.storage_path or now - self._last_flush < self.flush_interval:
            return False
        self.flush(now)
        return True

    # -- samples -------------------------------------------------------------
    def add_sample(self, metric: str, value: float,
                   timestamp: Optional[float] = None, **labels: str) -> None:
        ts = time.time() if timestamp is None else timestamp
        key = SeriesKey.of(metric, **labels)
        with self._lock:
            q = self._series.setdefault(key, deque())
            q.append((ts, float(value)))
            cutoff = ts - self.retention
            while q and q[0][0] < cutoff:
                q.popleft()

    def _values(self, metric: str, window: Optional[float], now: Optional[float],
                **labels: str) -> List[float]:
        key = SeriesKey.of(metric, **labels)
        with self._lock:
            q = self._series.get(key)
            if not q:
                return []
            if window is None:
                return [v for _, v in q]
            now = time.time() if now is None else now
            cutoff = now - window
            return [v for ts, v in q if ts >= cutoff]

    def query(self, metric: str, agg: str = "latest",
              window: Optional[float] = None, now: Optional[float] = None,
              **labels: str) -> Optional[float]:
        vals = self._values(metric, window, now, **labels)
        if not vals:
            return None
        if agg == "latest":
            return vals[-1]
        if agg == "avg":
            return float(np.mean(vals))
        if agg == "count":
            return float(len(vals))
        if agg.startswith("p") and agg[1:].isdigit():
            return float(np.percentile(vals, int(agg[1:])))
        raise ValueError(f"unknown aggregation {agg!r}")

    def series_labels(self, metric: str) -> List[Dict[str, str]]:
        with self._lock:
            return [
                dict(k.labels) for k in self._series if k.metric == metric
            ]

    # -- KV (static info) ------------------------------------------------------
    def set_kv(self, key: str, value: Any) -> None:
        with self._lock:
            self._kv[key] = value

    def get_kv(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._kv.get(key)
