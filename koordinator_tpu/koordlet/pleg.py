"""PLEG: pod lifecycle event generator.

Analog of reference `pkg/koordlet/pleg/pleg.go:75-246`: the reference inotify-
watches cgroup directories; here a portable polling scan of the kubepods tree
diffs pod/container dirs between ticks and emits events to handlers (drives the
pod-informer resync)."""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Callable, List, Optional, Set

from koordinator_tpu.koordlet.util import system as sysutil

# pod cgroup dirs: "pod<uid>" (cgroupfs) or "kubepods[-<qos>]-pod<uid>.slice"
# (systemd driver)
_POD_DIR = re.compile(r"^(pod|kubepods(-[a-z]+)?-pod)")


@dataclass(frozen=True)
class PodLifecycleEvent:
    event_type: str  # "pod_added" | "pod_deleted"
    pod_dir: str


Handler = Callable[[PodLifecycleEvent], None]


class Pleg:
    def __init__(self, config: Optional[sysutil.SystemConfig] = None):
        self.config = config or sysutil.CONFIG
        self.handlers: List[Handler] = []
        self._known: Optional[Set[str]] = None

    def add_handler(self, handler: Handler) -> None:
        self.handlers.append(handler)

    def _scan(self) -> Set[str]:
        found: Set[str] = set()
        root = self.config.cgroup_root_dir
        if not self.config.use_cgroup_v2:
            root = os.path.join(root, "cpu")
        for qos in ("", sysutil.QOS_BESTEFFORT, sysutil.QOS_BURSTABLE):
            qos_dir = os.path.join(root, self.config.qos_relative_path(qos))
            try:
                for entry in os.listdir(qos_dir):
                    if _POD_DIR.match(entry):
                        found.add(os.path.join(self.config.qos_relative_path(qos), entry))
            except OSError:
                continue
        return found

    def tick(self) -> List[PodLifecycleEvent]:
        """Diff the cgroup tree; emit + return events."""
        current = self._scan()
        events: List[PodLifecycleEvent] = []
        if self._known is not None:
            for added in sorted(current - self._known):
                events.append(PodLifecycleEvent("pod_added", added))
            for removed in sorted(self._known - current):
                events.append(PodLifecycleEvent("pod_deleted", removed))
        self._known = current
        for ev in events:
            for h in self.handlers:
                h(ev)
        return events
