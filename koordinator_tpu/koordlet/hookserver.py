"""koordlet hook server: RuntimeHookService backed by the runtime hooks.

Analog of reference `pkg/koordlet/runtimehooks/proxyserver/`: translates the
proto context into a ContainerContext, runs the hook chain, and maps the writes
back to LinuxContainerResources / env in the response. Served over gRPC/UDS by
`runtimeproxy.hookclient.serve_hook_service`, or embedded in-process (NRI
mode)."""

from __future__ import annotations

from typing import Optional

from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
from koordinator_tpu.koordlet.runtimehooks import ContainerContext, RuntimeHooks
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.runtimeproxy import api_pb2


def _pod_from_meta(meta: api_pb2.PodSandboxMeta) -> Pod:
    return Pod(
        meta=ObjectMeta(
            name=meta.name,
            namespace=meta.namespace or "default",
            uid=meta.uid,
            labels=dict(meta.labels),
            annotations=dict(meta.annotations),
        ),
        spec=PodSpec(),
    )


class HookHandler:
    """One method per RPC (see runtimeproxy/api.proto)."""

    def __init__(self, runtime_hooks: RuntimeHooks):
        self.hooks = runtime_hooks

    # -- translation -----------------------------------------------------
    def _run(self, pod_meta: api_pb2.PodSandboxMeta) -> ContainerContext:
        # prefer the informer's full pod object (it has requests/limits);
        # O(1) uid lookup — this is the per-CRI-call critical path
        pod = None
        if pod_meta.uid:
            pod = self.hooks.informer.get_pod_by_uid(pod_meta.uid)
        if pod is None:
            pod = _pod_from_meta(pod_meta)
        ctx = ContainerContext(pod=pod, cgroup_parent=pod_meta.cgroup_parent)
        self.hooks.run_hooks(ctx)
        return ctx

    @staticmethod
    def _resources_from_ctx(ctx: ContainerContext) -> api_pb2.LinuxContainerResources:
        out = api_pb2.LinuxContainerResources()
        for w in ctx.cgroup_writes:
            if w.resource == sysutil.CPU_BVT_WARP_NS:
                out.cpu_bvt_warp_ns = int(w.value)
            elif w.resource == sysutil.CPU_CFS_QUOTA:
                out.cpu_quota = int(w.value)
            elif w.resource == sysutil.CPUSET_CPUS:
                out.cpuset_cpus = w.value
            elif w.resource == sysutil.MEMORY_LIMIT:
                out.memory_limit_bytes = int(w.value)
            elif w.resource == sysutil.CPU_SHARES:
                out.cpu_shares = int(w.value)
        return out

    # -- pod sandbox RPCs ------------------------------------------------
    def PreRunPodSandboxHook(self, request: api_pb2.PodSandboxHookRequest):
        ctx = self._run(request.pod_meta)
        return api_pb2.PodSandboxHookResponse(
            resources=self._resources_from_ctx(ctx),
            cgroup_parent=request.pod_meta.cgroup_parent,
        )

    def PostStopPodSandboxHook(self, request: api_pb2.PodSandboxHookRequest):
        return api_pb2.PodSandboxHookResponse()

    # -- container RPCs ---------------------------------------------------
    def _container_rpc(self, request: api_pb2.ContainerResourceHookRequest):
        ctx = self._run(request.pod_meta)
        res = api_pb2.ContainerResourceHookResponse(
            resources=self._resources_from_ctx(ctx)
        )
        for k, v in ctx.env.items():
            res.env[k] = v
        return res

    def PreCreateContainerHook(self, request):
        return self._container_rpc(request)

    def PreStartContainerHook(self, request):
        return self._container_rpc(request)

    def PostStartContainerHook(self, request):
        return self._container_rpc(request)

    def PreUpdateContainerResourcesHook(self, request):
        return self._container_rpc(request)

    def PostStopContainerHook(self, request):
        return api_pb2.ContainerResourceHookResponse()
