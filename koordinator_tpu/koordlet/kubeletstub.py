"""Kubelet stub: HTTP client for the kubelet's read-only endpoints.

Analog of reference `pkg/koordlet/statesinformer/impl/kubelet_stub.go:40-130`:
`GetAllPods` pulls `GET /pods/` (a k8s-style `PodList` JSON document) and
`GetKubeletConfiguration` pulls `GET /configz`. The pods informer uses this as
its pod source so the agent tracks what the *kubelet* is actually running, not
just what the apiserver mirror says. Tests stand up a plain `http.server`
fixture serving the same JSON shapes.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
from koordinator_tpu.api.resources import ResourceList, ResourceName, parse_quantity


class KubeletError(RuntimeError):
    pass


def _parse_resource_map(raw: Optional[Dict[str, Any]]) -> ResourceList:
    if not raw:
        return ResourceList()
    return ResourceList(
        {
            name: parse_quantity(value, cpu=(name == ResourceName.CPU))
            for name, value in raw.items()
        }
    )


def pod_from_k8s_json(doc: Dict[str, Any]) -> Pod:
    """Decode one k8s-wire pod object (the subset the agent consumes).

    Container requests/limits aggregate across containers the way the
    kubelet's resource accounting does (sum requests, sum limits)."""
    meta = doc.get("metadata") or {}
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}

    requests = ResourceList()
    limits = ResourceList()
    for container in spec.get("containers") or []:
        res = container.get("resources") or {}
        requests = requests.add(_parse_resource_map(res.get("requests")))
        limits = limits.add(_parse_resource_map(res.get("limits")))

    priority = spec.get("priority")
    return Pod(
        meta=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
        ),
        spec=PodSpec(
            node_name=spec.get("nodeName", ""),
            scheduler_name=spec.get("schedulerName", "koord-scheduler"),
            priority=int(priority) if priority is not None else None,
            priority_class_name=spec.get("priorityClassName", ""),
            requests=requests,
            limits=limits,
            node_selector=dict(spec.get("nodeSelector") or {}),
        ),
        phase=status.get("phase", "Pending"),
    )


class KubeletStub:
    """Minimal HTTP client for the kubelet read-only API."""

    def __init__(self, host: str = "127.0.0.1", port: int = 10255,
                 scheme: str = "http", timeout_seconds: float = 2.0):
        self.host = host
        self.port = port
        self.scheme = scheme
        self.timeout = timeout_seconds

    def _get_json(self, path: str) -> Any:
        url = f"{self.scheme}://{self.host}:{self.port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as rsp:
                if rsp.status != 200:
                    raise KubeletError(f"request {url} failed, code {rsp.status}")
                body = rsp.read()
        except (urllib.error.URLError, OSError) as exc:
            raise KubeletError(f"request {url} failed: {exc}") from exc
        try:
            return json.loads(body)
        except ValueError as exc:
            raise KubeletError(f"parse {path} response failed: {exc}") from exc

    def get_all_pods(self) -> List[Pod]:
        """GET /pods/ -> decoded pod list (kubelet_stub.go:72-103)."""
        doc = self._get_json("/pods/")
        items = doc.get("items") if isinstance(doc, dict) else None
        return [pod_from_k8s_json(item) for item in items or []]

    def get_kubelet_configuration(self) -> Dict[str, Any]:
        """GET /configz -> the `kubeletconfig` payload (kubelet_stub.go:105-130)."""
        doc = self._get_json("/configz")
        if isinstance(doc, dict) and "kubeletconfig" in doc:
            return doc["kubeletconfig"]
        raise KubeletError("configz response missing 'kubeletconfig'")
