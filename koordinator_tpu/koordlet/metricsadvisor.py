"""Metrics advisor: the collector framework.

Analog of reference `pkg/koordlet/metricsadvisor/` (framework/plugin.go:25-48 +
collectors): each collector owns a tick; `collect_once(now)` makes the whole
advisor drivable from tests and from the Daemon loop alike. Rate metrics (cpu)
are derived from cumulative counters between ticks, exactly like the cgroup
cpuacct/proc-stat based collectors in the reference.

Collectors: noderesource, podresource (+containers), beresource, sysresource,
psi, performance (CPI via the native perf binding when enabled).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.utils.features import KOORDLET_GATES


def pod_qos_dir(pod) -> str:
    """k8s cgroup QoS class dir for a pod (guaranteed pods sit under kubepods)."""
    qos = pod.qos_class
    if qos == QoSClass.BE:
        return sysutil.QOS_BESTEFFORT
    if not pod.spec.requests or pod.spec.requests != pod.spec.limits:
        return sysutil.QOS_BURSTABLE
    return sysutil.QOS_GUARANTEED


class MetricsAdvisor:
    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 config: Optional[sysutil.SystemConfig] = None):
        self.informer = informer
        self.cache = cache
        self.config = config or sysutil.CONFIG
        self._last_cpu: Dict[str, tuple] = {}  # key -> (ts, cumulative_ns)
        self._last_proc: Optional[tuple] = None  # (ts, total, idle)
        self.perf_reader = None  # set by Daemon when CPICollector enabled

    # -- helpers -------------------------------------------------------------
    def _cpu_rate(self, key: str, now: float, cumulative_ns: Optional[int]) -> Optional[float]:
        if cumulative_ns is None:
            return None
        prev = self._last_cpu.get(key)
        self._last_cpu[key] = (now, cumulative_ns)
        if prev is None or now <= prev[0]:
            return None
        return max(0.0, (cumulative_ns - prev[1]) / 1e9 / (now - prev[0]))

    # -- collectors ----------------------------------------------------------
    def collect_node_resource(self, now: float) -> None:
        stat = sysutil.read_proc_stat_cpu(self.config)
        if stat is not None:
            total, idle = stat
            prev = self._last_proc
            self._last_proc = (now, total, idle)
            if prev is not None and total > prev[1]:
                busy_frac = 1.0 - (idle - prev[2]) / (total - prev[1])
                node = self.informer.get_node()
                cores = (
                    node.allocatable.get("cpu", 0) / 1000.0 if node else 1.0
                ) or 1.0
                self.cache.add_sample(
                    mc.NODE_CPU_USAGE, busy_frac * cores, now
                )
        mem = sysutil.read_meminfo(self.config)
        if mem:
            total_b = mem.get("MemTotal", 0)
            avail = mem.get("MemAvailable", mem.get("MemFree", 0))
            if total_b:
                self.cache.add_sample(mc.NODE_MEMORY_USAGE, total_b - avail, now)

    def collect_pod_resource(self, now: float) -> None:
        for pod in self.informer.get_all_pods():
            rel = self.config.pod_relative_path(pod_qos_dir(pod), pod.meta.uid or pod.meta.name)
            cpu_ns = sysutil.read_cpu_usage_ns(rel, self.config)
            rate = self._cpu_rate(f"pod/{pod.meta.key}", now, cpu_ns)
            if rate is not None:
                self.cache.add_sample(mc.POD_CPU_USAGE, rate, now, pod=pod.meta.key)
            mem_b = sysutil.read_memory_usage_bytes(rel, self.config)
            if mem_b is not None:
                self.cache.add_sample(mc.POD_MEMORY_USAGE, mem_b, now, pod=pod.meta.key)

    def collect_be_resource(self, now: float) -> None:
        rel = self.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        cpu_ns = sysutil.read_cpu_usage_ns(rel, self.config)
        rate = self._cpu_rate("be_root", now, cpu_ns)
        if rate is not None:
            self.cache.add_sample(mc.BE_CPU_USAGE, rate, now)

    def collect_sys_resource(self, now: float) -> None:
        """system usage = node usage - sum(pod usage) (sysresource collector)."""
        node = self.cache.query(mc.NODE_CPU_USAGE, "latest", now=now)
        if node is None:
            return
        pod_sum = 0.0
        for labels in self.cache.series_labels(mc.POD_CPU_USAGE):
            v = self.cache.query(mc.POD_CPU_USAGE, "latest", now=now, **labels)
            pod_sum += v or 0.0
        self.cache.add_sample(mc.SYS_CPU_USAGE, max(0.0, node - pod_sum), now)

    def collect_psi(self, now: float) -> None:
        if not KOORDLET_GATES.enabled("PSICollector"):
            return
        psi = sysutil.read_psi("", sysutil.CPU_PRESSURE, self.config)
        if psi is not None:
            self.cache.add_sample(mc.NODE_CPU_PSI_FULL_AVG10, psi.full_avg10, now)
        psi = sysutil.read_psi("", sysutil.MEMORY_PRESSURE, self.config)
        if psi is not None:
            self.cache.add_sample(mc.NODE_MEM_PSI_FULL_AVG10, psi.full_avg10, now)

    def collect_performance(self, now: float) -> None:
        """CPI per pod via the native perf_event binding (performance collector,
        performance_collector_linux.go:46-101; gated like Libpfm4/CPICollector)."""
        if not KOORDLET_GATES.enabled("CPICollector") or self.perf_reader is None:
            return
        pods = self.informer.get_all_pods()
        for pod in pods:
            sample = self.perf_reader(pod)
            if sample is None:
                continue
            cycles, instructions = sample
            if instructions > 0:
                self.cache.add_sample(
                    mc.POD_CPI, cycles / instructions, now, pod=pod.meta.key
                )
        gc = getattr(self.perf_reader, "gc", None)
        if gc is not None:
            gc(p.meta.key for p in pods)

    def collect_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self.collect_node_resource(now)
        self.collect_pod_resource(now)
        self.collect_be_resource(now)
        self.collect_sys_resource(now)
        self.collect_psi(now)
        self.collect_performance(now)
