"""Metrics advisor: the collector framework.

Analog of reference `pkg/koordlet/metricsadvisor/` (framework/plugin.go:25-48 +
collectors, plugins_profile.go registry): each collector owns a tick;
`collect_once(now)` drives the registered profile in order, so the whole
advisor is drivable from tests and the Daemon loop alike. Rate metrics (cpu)
are derived from cumulative counters between ticks, exactly like the cgroup
cpuacct/proc-stat based collectors in the reference.

Collector profile (reference collectors in parens): noderesource, nodeinfo
(static CPU/NUMA -> KV), nodestorageinfo, podresource, beresource,
sysresource, pagecache, coldmemoryresource (kidled), hostapplication,
podthrottled, psi, performance (CPI via the native perf binding). Container
granularity is folded into the pod collectors (the pod model here carries no
container statuses; every consumer reads pod-level series).
"""

from __future__ import annotations

import os
import re
import time
from typing import Callable, Dict, List, Optional, Tuple

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import metrics as koordlet_metrics
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util import kidled as kidled_util
from koordinator_tpu.koordlet.util import machineinfo
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.utils.features import KOORDLET_GATES


def pod_qos_dir(pod) -> str:
    """k8s cgroup QoS class dir for a pod (guaranteed pods sit under kubepods)."""
    qos = pod.qos_class
    if qos == QoSClass.BE:
        return sysutil.QOS_BESTEFFORT
    if not pod.spec.requests or pod.spec.requests != pod.spec.limits:
        return sysutil.QOS_BURSTABLE
    return sysutil.QOS_GUARANTEED


class MetricsAdvisor:
    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 config: Optional[sysutil.SystemConfig] = None):
        self.informer = informer
        self.cache = cache
        self.config = config or sysutil.CONFIG
        self._last_cpu: Dict[str, tuple] = {}  # key -> (ts, cumulative_ns)
        self._last_proc: Optional[tuple] = None  # (ts, total, idle)
        self._last_throttled: Dict[str, Tuple[int, int]] = {}  # (periods, throttled)
        self.perf_reader = None  # set by Daemon when CPICollector enabled
        self.kidled = kidled_util.KidledInterface(self.config)
        self._node_info_collected = False
        # the collector profile (plugins_profile.go): (name, gate-or-None, fn);
        # gated entries are skipped when their feature gate is off
        self.profile: List[Tuple[str, Optional[str], Callable[[float], None]]] = [
            ("noderesource", None, self.collect_node_resource),
            ("nodeinfo", None, self.collect_node_info),
            ("nodestorageinfo", None, self.collect_node_storage_info),
            ("podresource", None, self.collect_pod_resource),
            ("beresource", None, self.collect_be_resource),
            ("sysresource", None, self.collect_sys_resource),
            ("pagecache", "PageCacheCollector", self.collect_pagecache),
            ("coldmemoryresource", "ColdPageCollector", self.collect_cold_memory),
            ("hostapplication", None, self.collect_host_application),
            ("podthrottled", None, self.collect_pod_throttled),
            ("psi", "PSICollector", self.collect_psi),
            ("performance", "CPICollector", self.collect_performance),
            # gated OFF by default: the default sampler touches jax.devices(),
            # and initializing the TPU runtime from the node agent would take
            # exclusive chip ownership away from workload pods
            ("gpudevice", "TPUDeviceCollector", self.collect_device_usage),
        ]
        # device sampler seam (reference devices/gpu NVML walk; here the local
        # TPU chips via JAX): () -> [{minor, uuid, core_pct, mem_bytes}]
        self.device_sampler = sample_tpu_devices

    # -- helpers -------------------------------------------------------------
    def _cpu_rate(self, key: str, now: float, cumulative_ns: Optional[int]) -> Optional[float]:
        if cumulative_ns is None:
            return None
        prev = self._last_cpu.get(key)
        self._last_cpu[key] = (now, cumulative_ns)
        if prev is None or now <= prev[0]:
            return None
        return max(0.0, (cumulative_ns - prev[1]) / 1e9 / (now - prev[0]))

    # -- collectors ----------------------------------------------------------
    def collect_node_resource(self, now: float) -> None:
        stat = sysutil.read_proc_stat_cpu(self.config)
        if stat is not None:
            total, idle = stat
            prev = self._last_proc
            self._last_proc = (now, total, idle)
            if prev is not None and total > prev[1]:
                busy_frac = 1.0 - (idle - prev[2]) / (total - prev[1])
                node = self.informer.get_node()
                cores = (
                    node.allocatable.get("cpu", 0) / 1000.0 if node else 1.0
                ) or 1.0
                self.cache.add_sample(
                    mc.NODE_CPU_USAGE, busy_frac * cores, now
                )
        mem = sysutil.read_meminfo(self.config)
        if mem:
            total_b = mem.get("MemTotal", 0)
            avail = mem.get("MemAvailable", mem.get("MemFree", 0))
            if total_b:
                self.cache.add_sample(mc.NODE_MEMORY_USAGE, total_b - avail, now)

    def collect_pod_resource(self, now: float) -> None:
        for pod in self.informer.get_all_pods():
            rel = self.config.pod_relative_path(pod_qos_dir(pod), pod.meta.uid or pod.meta.name)
            cpu_ns = sysutil.read_cpu_usage_ns(rel, self.config)
            rate = self._cpu_rate(f"pod/{pod.meta.key}", now, cpu_ns)
            if rate is not None:
                self.cache.add_sample(mc.POD_CPU_USAGE, rate, now, pod=pod.meta.key)
            mem_b = sysutil.read_memory_usage_bytes(rel, self.config)
            if mem_b is not None:
                self.cache.add_sample(mc.POD_MEMORY_USAGE, mem_b, now, pod=pod.meta.key)

    def collect_be_resource(self, now: float) -> None:
        rel = self.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        cpu_ns = sysutil.read_cpu_usage_ns(rel, self.config)
        rate = self._cpu_rate("be_root", now, cpu_ns)
        if rate is not None:
            self.cache.add_sample(mc.BE_CPU_USAGE, rate, now)

    def collect_sys_resource(self, now: float) -> None:
        """system usage = node usage - sum(pod usage) (sysresource collector)."""
        node = self.cache.query(mc.NODE_CPU_USAGE, "latest", now=now)
        if node is None:
            return
        pod_sum = 0.0
        for labels in self.cache.series_labels(mc.POD_CPU_USAGE):
            v = self.cache.query(mc.POD_CPU_USAGE, "latest", now=now, **labels)
            pod_sum += v or 0.0
        self.cache.add_sample(mc.SYS_CPU_USAGE, max(0.0, node - pod_sum), now)

    def collect_node_info(self, now: float) -> None:
        """Static CPU/NUMA topology -> KV store (nodeinfo collector; feeds the
        statesinformer nodeTopo reporter). Collected once — topology is
        immutable while the agent runs."""
        if self._node_info_collected:
            return
        info = machineinfo.discover(self.config)
        if info is None:
            return
        self.cache.set_kv(mc.NODE_CPU_INFO_KEY, info.topology)
        self.cache.set_kv(mc.NODE_NUMA_INFO_KEY, info.numa_mem)
        self._node_info_collected = True

    def collect_node_storage_info(self, now: float) -> None:
        """Filesystem usage of the root volume + disk busy-ticks from
        /proc/diskstats (nodestorageinfo collector)."""
        raw = sysutil.read_file(self.config.proc_path("diskstats"))
        if raw:
            devices = {}
            for line in raw.splitlines():
                f = line.split()
                # field 13 = ms spent doing I/O (io_ticks)
                if len(f) >= 13:
                    devices[f[2]] = int(f[12])
            for dev, ticks in devices.items():
                rate = self._cpu_rate(f"disk/{dev}", now, ticks * 10**6)
                if rate is not None:
                    self.cache.add_sample(
                        mc.NODE_DISK_IO_TICKS, rate, now, device=dev)
        try:
            st = os.statvfs(self.config.fs_root_dir)
            total = st.f_frsize * st.f_blocks
            used = total - st.f_frsize * st.f_bavail
            self.cache.add_sample(mc.NODE_FS_TOTAL_BYTES, total, now)
            self.cache.add_sample(mc.NODE_FS_USED_BYTES, used, now)
        except OSError:
            pass

    def collect_pagecache(self, now: float) -> None:
        """Per-pod page cache from memory.stat (pagecache collector): the
        'file' (v2) / 'cache' (v1) field — reclaimable, so the batch-memory
        calculation can credit it back."""
        field_name = "file" if self.config.use_cgroup_v2 else "cache"
        pat = re.compile(rf"^{field_name} (\d+)", re.M)
        for pod in self.informer.get_all_pods():
            rel = self.config.pod_relative_path(
                pod_qos_dir(pod), pod.meta.uid or pod.meta.name)
            raw = sysutil.read_cgroup(rel, sysutil.MEMORY_STAT, self.config)
            if raw is None:
                continue
            m = pat.search(raw)
            if m:
                self.cache.add_sample(
                    mc.POD_PAGECACHE, int(m.group(1)), now, pod=pod.meta.key)

    def collect_cold_memory(self, now: float) -> None:
        """Per-pod kidled cold bytes (coldmemoryresource collector)."""
        if not self.kidled.enabled():
            return
        for pod in self.informer.get_all_pods():
            rel = self.config.pod_relative_path(
                pod_qos_dir(pod), pod.meta.uid or pod.meta.name)
            stats = self.kidled.read_pod_stats(rel)
            if stats is not None:
                self.cache.add_sample(
                    mc.POD_COLD_MEMORY, stats.cold_bytes(300), now,
                    pod=pod.meta.key)

    def collect_host_application(self, now: float) -> None:
        """Usage of non-k8s host services declared in NodeSLO extensions
        (hostapplication collector): entries {name, cgroupPath} under the
        'hostApplications' extension key."""
        from koordinator_tpu.api.objects import host_applications

        for app in host_applications(self.informer.get_node_slo()):
            name, rel = app.get("name"), app.get("cgroupPath")
            if not name or not rel:
                continue
            cpu_ns = sysutil.read_cpu_usage_ns(rel, self.config)
            rate = self._cpu_rate(f"hostapp/{name}", now, cpu_ns)
            if rate is not None:
                self.cache.add_sample(mc.HOST_APP_CPU_USAGE, rate, now, app=name)
            mem_b = sysutil.read_memory_usage_bytes(rel, self.config)
            if mem_b is not None:
                self.cache.add_sample(
                    mc.HOST_APP_MEMORY_USAGE, mem_b, now, app=name)

    def collect_pod_throttled(self, now: float) -> None:
        """cfs throttling ratio per pod from cpu.stat (podthrottled collector):
        delta(nr_throttled)/delta(nr_periods) between ticks."""
        for pod in self.informer.get_all_pods():
            rel = self.config.pod_relative_path(
                pod_qos_dir(pod), pod.meta.uid or pod.meta.name)
            raw = sysutil.read_cgroup(rel, sysutil.CPU_STAT, self.config)
            if raw is None:
                continue
            periods = re.search(r"nr_periods (\d+)", raw)
            throttled = re.search(r"nr_throttled (\d+)", raw)
            if not periods or not throttled:
                continue
            cur = (int(periods.group(1)), int(throttled.group(1)))
            prev = self._last_throttled.get(pod.meta.key)
            self._last_throttled[pod.meta.key] = cur
            if prev is None:
                continue
            dp = cur[0] - prev[0]
            dt = cur[1] - prev[1]
            if dp > 0:
                self.cache.add_sample(
                    mc.POD_CPU_THROTTLED_RATIO, dt / dp, now, pod=pod.meta.key)

    def collect_psi(self, now: float) -> None:
        psi = sysutil.read_psi("", sysutil.CPU_PRESSURE, self.config)
        if psi is not None:
            self.cache.add_sample(mc.NODE_CPU_PSI_FULL_AVG10, psi.full_avg10, now)
            koordlet_metrics.NODE_CPU_PSI_FULL_AVG10.set(psi.full_avg10)
        psi = sysutil.read_psi("", sysutil.MEMORY_PRESSURE, self.config)
        if psi is not None:
            self.cache.add_sample(mc.NODE_MEM_PSI_FULL_AVG10, psi.full_avg10, now)
            koordlet_metrics.NODE_MEM_PSI_FULL_AVG10.set(psi.full_avg10)

    def collect_performance(self, now: float) -> None:
        """CPI per pod via the native perf_event binding (performance collector,
        performance_collector_linux.go:46-101; gated like Libpfm4/CPICollector)."""
        if self.perf_reader is None:
            return
        pods = self.informer.get_all_pods()
        for pod in pods:
            sample = self.perf_reader(pod)
            if sample is None:
                continue
            cycles, instructions = sample
            if instructions > 0:
                cpi = cycles / instructions
                self.cache.add_sample(mc.POD_CPI, cpi, now, pod=pod.meta.key)
                koordlet_metrics.CONTAINER_CPI.set(cpi, pod=pod.meta.key)
        gc = getattr(self.perf_reader, "gc", None)
        if gc is not None:
            gc(p.meta.key for p in pods)

    def collect_device_usage(self, now: float) -> None:
        """Per-accelerator utilization series (reference devices/gpu
        collector_gpu_linux.go:164-201 walks NVML; the TPU-native sampler
        reads per-chip HBM occupancy through JAX). Pod-level attribution is
        not collected: a TPU chip is held by one process, so node-level
        per-chip series carry the same information NVML per-PID walks do."""
        for dev in self.device_sampler():
            labels = {"minor": str(dev["minor"]), "uuid": dev["uuid"]}
            self.cache.add_sample(
                mc.NODE_GPU_CORE_USAGE, float(dev.get("core_pct", 0.0)), now,
                **labels,
            )
            self.cache.add_sample(
                mc.NODE_GPU_MEM_USAGE, float(dev.get("mem_bytes", 0)), now,
                **labels,
            )

    def collect_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for _name, gate, fn in self.profile:
            if gate is not None and not KOORDLET_GATES.enabled(gate):
                continue
            fn(now)


def sample_tpu_devices() -> List[Dict]:
    """Default device sampler: local TPU chips' HBM occupancy via JAX
    memory_stats (bytes_in_use / bytes_limit). Returns [] off-TPU."""
    try:
        import jax

        devices = [d for d in jax.devices() if d.platform == "tpu"]
    except Exception:
        return []
    out = []
    for d in devices:
        stats = getattr(d, "memory_stats", None)
        try:
            stats = stats() if callable(stats) else None
        except Exception:
            stats = None
        if not isinstance(stats, dict):
            stats = {}
        in_use = int(stats.get("bytes_in_use", 0))
        limit = int(stats.get("bytes_limit", 0))
        out.append({
            "minor": int(getattr(d, "id", 0)),
            "uuid": f"TPU-{getattr(d, 'id', 0)}",
            # unknown capacity -> no occupancy claim, not a nonsense ratio
            "core_pct": 100.0 * in_use / limit if limit > 0 else 0.0,
            "mem_bytes": in_use,
        })
    return out
