"""koordlet: the node agent (analog of reference `pkg/koordlet/`, SURVEY.md 2.3).

Module wiring follows `koordlet.go:70-188`: the Daemon builds resourceexecutor,
metriccache, statesinformer, metricsadvisor, prediction, qosmanager and
runtimehooks, then runs them in dependency order. All kernel interfaces go
through `util/system` with redirectable roots so everything runs hermetically
against a fake /sys + /proc + cgroupfs tree (the reference's FileTestUtil
pattern, util_test_tool.go:56-69).
"""

from koordinator_tpu.koordlet.daemon import Daemon  # noqa: F401
