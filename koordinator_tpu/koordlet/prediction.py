"""Peak-usage prediction: decayed histograms with checkpoints.

Analog of reference `pkg/koordlet/prediction/peak_predictor.go:34-141` +
`checkpoint.go:36-95`: per-UID decaying histograms of cpu/memory usage, a
safety-margin peak estimate (p95 * (1 + margin)), cold-start handling, and
periodic JSON checkpoints restored on start. Feeds the Mid-tier resource
calculation in the noderesource controller."""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

from koordinator_tpu.utils.histogram import DecayingHistogram, HistogramOptions

DEFAULT_SAFETY_MARGIN_PERCENT = 10
COLD_START_SECONDS = 15 * 60


class PeakPredictServer:
    def __init__(self, checkpoint_dir: Optional[str] = None,
                 half_life_seconds: float = 12 * 3600,
                 safety_margin_percent: int = DEFAULT_SAFETY_MARGIN_PERCENT):
        self.checkpoint_dir = checkpoint_dir
        self.safety_margin = safety_margin_percent
        self.half_life = half_life_seconds
        self._cpu_opts = HistogramOptions.exponential(1024.0, 0.025, 1.05)
        self._mem_opts = HistogramOptions.exponential(1 << 44, 1 << 24, 1.05)
        self.cpu: Dict[str, DecayingHistogram] = {}
        self.mem: Dict[str, DecayingHistogram] = {}
        self.first_seen: Dict[str, float] = {}
        if checkpoint_dir:
            self.restore()

    def _hist(self, cache: Dict[str, DecayingHistogram], opts, uid: str) -> DecayingHistogram:
        if uid not in cache:
            cache[uid] = DecayingHistogram(opts, self.half_life)
        return cache[uid]

    def update(self, uid: str, cpu_cores: float, memory_bytes: float,
               timestamp: Optional[float] = None) -> None:
        ts = time.time() if timestamp is None else timestamp
        self.first_seen.setdefault(uid, ts)
        self._hist(self.cpu, self._cpu_opts, uid).add_sample(cpu_cores, 1.0, ts)
        self._hist(self.mem, self._mem_opts, uid).add_sample(memory_bytes, 1.0, ts)

    def predict_peak(self, uid: str, now: Optional[float] = None
                     ) -> Optional[Tuple[float, float]]:
        """(cpu_cores, memory_bytes) p95 peak with safety margin; None during
        cold start or for unknown UIDs."""
        now = time.time() if now is None else now
        if uid not in self.cpu:
            return None
        if now - self.first_seen.get(uid, now) < COLD_START_SECONDS:
            return None
        factor = 1.0 + self.safety_margin / 100.0
        return (
            self.cpu[uid].percentile(0.95) * factor,
            self.mem[uid].percentile(0.95) * factor,
        )

    # -- checkpoints ---------------------------------------------------------
    def checkpoint(self) -> None:
        if not self.checkpoint_dir:
            return
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        data = {
            "first_seen": self.first_seen,
            "cpu": {uid: h.to_checkpoint() for uid, h in self.cpu.items()},
            "mem": {uid: h.to_checkpoint() for uid, h in self.mem.items()},
        }
        path = os.path.join(self.checkpoint_dir, "prediction.json")
        with open(path + ".tmp", "w") as f:
            json.dump(data, f)
        os.replace(path + ".tmp", path)

    def restore(self) -> bool:
        path = os.path.join(self.checkpoint_dir or "", "prediction.json")
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return False
        self.first_seen = {k: float(v) for k, v in data.get("first_seen", {}).items()}
        for uid, ckpt in data.get("cpu", {}).items():
            try:
                self.cpu[uid] = DecayingHistogram.from_checkpoint(self._cpu_opts, ckpt)
            except ValueError:
                continue
        for uid, ckpt in data.get("mem", {}).items():
            try:
                self.mem[uid] = DecayingHistogram.from_checkpoint(self._mem_opts, ckpt)
            except ValueError:
                continue
        return True
