"""Prometheus-style metrics registry for the node agent.

Analog of reference `pkg/koordlet/metrics/`: gauges/counters for QoS actions
(BE suppress level, evictions, CPI, PSI) labeled by node/pod, with a text
exposition format so any scraper (or test) can read the agent's state. The
control-plane components register their own metrics in the same registry
class (`pkg/scheduler/metrics/`, `pkg/descheduler/metrics/` analogs reuse
Registry instances).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _lk(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted(labels.items()))


def _escape_label(value: str) -> str:
    """Prometheus exposition: label values escape backslash, double-quote and
    line-feed (exposition_formats spec; client_golang expfmt.go)."""
    return (str(value).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and line-feed only (quote is label-only)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, help_text: str, kind: str):
        self.name = name
        self.help = help_text
        self.kind = kind
        self._lock = threading.Lock()
        self._values: Dict[_LabelKey, float] = {}

    def _set(self, labels: Dict[str, str], value: float) -> None:
        with self._lock:
            self._values[_lk(labels)] = value

    def _add(self, labels: Dict[str, str], delta: float) -> None:
        key = _lk(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def get(self, **labels: str) -> Optional[float]:
        with self._lock:
            return self._values.get(_lk(labels))

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]

    def clear(self, **labels: str) -> None:
        with self._lock:
            self._values.pop(_lk(labels), None)


class Gauge(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "gauge")

    def set(self, value: float, **labels: str) -> None:
        self._set(labels, value)


class Counter(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "counter")

    def inc(self, delta: float = 1.0, **labels: str) -> None:
        self._add(labels, delta)


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._register(Gauge(name, help_text))

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._register(Counter(name, help_text))

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if existing.kind != metric.kind:
                    raise ValueError(
                        f"metric {metric.name} re-registered as {metric.kind}, "
                        f"was {existing.kind}")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labels, value in m.samples():
                if labels:
                    body = ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items()))
                    lines.append(f"{m.name}{{{body}}} {value:g}")
                else:
                    lines.append(f"{m.name} {value:g}")
        return "\n".join(lines) + "\n"


# the agent-wide default registry and its well-known metrics
# (pkg/koordlet/metrics/{common,resource_summary,qos}.go)
REGISTRY = Registry()

BE_SUPPRESS_CPU_CORES = REGISTRY.gauge(
    "koordlet_be_suppress_cpu_cores",
    "CPU cores the BE tier is currently suppressed to")
POD_EVICTION_TOTAL = REGISTRY.counter(
    "koordlet_pod_eviction_total",
    "Pods evicted by qosmanager, labeled by reason")
CONTAINER_CPI = REGISTRY.gauge(
    "koordlet_container_cpi",
    "Cycles per instruction, labeled by pod")
NODE_CPU_PSI_FULL_AVG10 = REGISTRY.gauge(
    "koordlet_node_cpu_psi_full_avg10",
    "Node cpu full-stall pressure, 10s average")
NODE_MEM_PSI_FULL_AVG10 = REGISTRY.gauge(
    "koordlet_node_mem_psi_full_avg10",
    "Node memory full-stall pressure, 10s average")
NODE_RESOURCE_ALLOCATABLE = REGISTRY.gauge(
    "koordlet_node_resource_allocatable",
    "Node allocatable, labeled by resource")
CPU_BURST_TOTAL = REGISTRY.counter(
    "koordlet_cpu_burst_total",
    "cfs burst applications, labeled by pod")
RESCTRL_UPDATE_TOTAL = REGISTRY.counter(
    "koordlet_resctrl_update_total",
    "resctrl schemata updates, labeled by group")
