"""Prometheus-style metrics registry for the node agent.

Analog of reference `pkg/koordlet/metrics/`: gauges/counters for QoS actions
(BE suppress level, evictions, CPI, PSI) labeled by node/pod, with a text
exposition format so any scraper (or test) can read the agent's state. The
control-plane components register their own metrics in the same registry
class (`pkg/scheduler/metrics/`, `pkg/descheduler/metrics/` analogs reuse
Registry instances).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _lk(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted(labels.items()))


def _escape_label(value: str) -> str:
    """Prometheus exposition: label values escape backslash, double-quote and
    line-feed (exposition_formats spec; client_golang expfmt.go)."""
    return (str(value).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and line-feed only (quote is label-only)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(value: float) -> str:
    """Full-precision exposition value. %g keeps only 6 significant digits,
    which silently rounds ever-growing counters/bucket counts once they
    pass ~1e6 (increments smaller than the rounding granule vanish between
    scrapes); integral values render as exact integers instead. Non-finite
    values render as Prometheus' +Inf/-Inf/NaN spellings — one bad sample
    must never poison the whole exposition."""
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 2**53:
        return str(int(value))
    return repr(value)


def _render_sample(name: str, labels: Dict[str, str], value: float) -> str:
    """One exposition sample line with sorted, escaped labels."""
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


class _Metric:
    def __init__(self, name: str, help_text: str, kind: str):
        self.name = name
        self.help = help_text
        self.kind = kind
        self._lock = threading.Lock()
        self._values: Dict[_LabelKey, float] = {}

    def _set(self, labels: Dict[str, str], value: float) -> None:
        with self._lock:
            self._values[_lk(labels)] = value

    def _add(self, labels: Dict[str, str], delta: float) -> None:
        key = _lk(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def get(self, **labels: str) -> Optional[float]:
        with self._lock:
            return self._values.get(_lk(labels))

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]

    def clear(self, **labels: str) -> None:
        with self._lock:
            self._values.pop(_lk(labels), None)

    def sample_lines(self) -> List[str]:
        """Exposition body lines (after HELP/TYPE); kind-specific."""
        return [_render_sample(self.name, labels, value)
                for labels, value in self.samples()]


class Gauge(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "gauge")

    def set(self, value: float, **labels: str) -> None:
        self._set(labels, value)


class Counter(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "counter")

    def inc(self, delta: float = 1.0, **labels: str) -> None:
        self._add(labels, delta)


# latency-shaped default buckets (client_golang prometheus.DefBuckets):
# most cycle/stage latencies here land between 1ms and 10s
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt_le(bound: float) -> str:
    return f"{bound:g}"


class Histogram(_Metric):
    """Prometheus histogram: per label-set bucket counts + sum + count,
    exposed as cumulative `_bucket{le=...}` series ending in `le="+Inf"`.
    Storage is per-bucket (non-cumulative) under the shared `_Metric` lock
    discipline; cumulation happens at exposition time."""

    def __init__(self, name: str, help_text: str = "",
                 buckets: Optional[Tuple[float, ...]] = None):
        super().__init__(name, help_text, "histogram")
        # an explicit +Inf bound would duplicate the synthesized le="+Inf"
        # series and fail the whole scrape; strip it like client_golang
        upper = tuple(sorted({float(b) for b in (buckets or DEFAULT_BUCKETS)
                              if math.isfinite(float(b))}))
        if not upper:
            raise ValueError(
                f"histogram {name} needs at least one finite bucket")
        self._upper = upper
        # label-set -> [per-bucket counts..., sum, count]
        self._series: Dict[_LabelKey, List[float]] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = _lk(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = [0.0] * (len(self._upper) + 2)
            for i, bound in enumerate(self._upper):
                if value <= bound:
                    state[i] += 1.0
                    break
            state[-2] += value
            state[-1] += 1.0

    def snapshot(self, **labels: str):
        """(upper_bounds, cumulative_bucket_counts, sum, count) for one
        label set, or None if never observed. The cumulative counts align
        with `upper_bounds`; `count` is the implicit +Inf bucket."""
        with self._lock:
            state = self._series.get(_lk(labels))
            if state is None:
                return None
            state = list(state)
        cumulative: List[float] = []
        running = 0.0
        for c in state[:-2]:
            running += c
            cumulative.append(running)
        return self._upper, cumulative, state[-2], state[-1]

    def count(self, **labels: str) -> float:
        snap = self.snapshot(**labels)
        return snap[3] if snap is not None else 0.0

    def sum(self, **labels: str) -> float:
        snap = self.snapshot(**labels)
        return snap[2] if snap is not None else 0.0

    # the scalar `_Metric` API targets `_values`, which a histogram never
    # uses — rebind it to `_series` (get/clear) or refuse it (set/add), so
    # a caller following the gauge/counter idiom can't silently no-op
    def get(self, **labels: str) -> Optional[float]:
        """Observation count for the label set (None if never observed)."""
        with self._lock:
            state = self._series.get(_lk(labels))
            return state[-1] if state is not None else None

    def clear(self, **labels: str) -> None:
        with self._lock:
            self._series.pop(_lk(labels), None)

    def _set(self, labels: Dict[str, str], value: float) -> None:
        raise TypeError(f"histogram {self.name} only supports observe()")

    def _add(self, labels: Dict[str, str], delta: float) -> None:
        raise TypeError(f"histogram {self.name} only supports observe()")

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        """(labels, count) per series — the scalar view for generic
        consumers; the full bucket layout comes from sample_lines()."""
        with self._lock:
            return [(dict(k), v[-1]) for k, v in sorted(self._series.items())]

    def sample_lines(self) -> List[str]:
        with self._lock:
            series = [(dict(k), list(v))
                      for k, v in sorted(self._series.items())]
        lines: List[str] = []
        for labels, state in series:
            running = 0.0
            for bound, c in zip(self._upper, state[:-2]):
                running += c
                lines.append(_render_sample(
                    f"{self.name}_bucket",
                    {**labels, "le": _fmt_le(bound)}, running))
            lines.append(_render_sample(
                f"{self.name}_bucket", {**labels, "le": "+Inf"}, state[-1]))
            lines.append(_render_sample(f"{self.name}_sum", labels, state[-2]))
            lines.append(_render_sample(
                f"{self.name}_count", labels, state[-1]))
        return lines


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._register(Gauge(name, help_text))

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._register(Counter(name, help_text))

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._register(Histogram(name, help_text, buckets=buckets))

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if existing.kind != metric.kind:
                    raise ValueError(
                        f"metric {metric.name} re-registered as {metric.kind}, "
                        f"was {existing.kind}")
                # histograms carry per-metric config: silently handing back
                # an instance with DIFFERENT buckets would drop the
                # caller's spec and skew every quantile it computes
                if (getattr(existing, "_upper", None)
                        != getattr(metric, "_upper", None)):
                    raise ValueError(
                        f"histogram {metric.name} re-registered with "
                        f"different buckets")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.sample_lines())
        return "\n".join(lines) + "\n"


# the agent-wide default registry and its well-known metrics
# (pkg/koordlet/metrics/{common,resource_summary,qos}.go)
REGISTRY = Registry()

BE_SUPPRESS_CPU_CORES = REGISTRY.gauge(
    "koordlet_be_suppress_cpu_cores",
    "CPU cores the BE tier is currently suppressed to")
POD_EVICTION_TOTAL = REGISTRY.counter(
    "koordlet_pod_eviction_total",
    "Pods evicted by qosmanager, labeled by reason")
CONTAINER_CPI = REGISTRY.gauge(
    "koordlet_container_cpi",
    "Cycles per instruction, labeled by pod")
NODE_CPU_PSI_FULL_AVG10 = REGISTRY.gauge(
    "koordlet_node_cpu_psi_full_avg10",
    "Node cpu full-stall pressure, 10s average")
NODE_MEM_PSI_FULL_AVG10 = REGISTRY.gauge(
    "koordlet_node_mem_psi_full_avg10",
    "Node memory full-stall pressure, 10s average")
NODE_RESOURCE_ALLOCATABLE = REGISTRY.gauge(
    "koordlet_node_resource_allocatable",
    "Node allocatable, labeled by resource")
CPU_BURST_TOTAL = REGISTRY.counter(
    "koordlet_cpu_burst_total",
    "cfs burst applications, labeled by pod")
RESCTRL_UPDATE_TOTAL = REGISTRY.counter(
    "koordlet_resctrl_update_total",
    "resctrl schemata updates, labeled by group")
QOS_CYCLE_SECONDS = REGISTRY.histogram(
    "koordlet_qosmanager_cycle_seconds",
    "End-to-end qosmanager strategy-loop latency")
QOS_STRATEGY_RUN_TOTAL = REGISTRY.counter(
    "koordlet_qos_strategy_run_total",
    "QoS strategy executions, labeled by strategy")
INFORMER_ERRORS_TOTAL = REGISTRY.counter(
    "koord_koordlet_informer_errors_total",
    "Errors swallowed inside statesinformer plugins (device probe, "
    "kubelet pulls), labeled by informer and stage — a rising rate "
    "means an informer is silently degraded, not healthy")
