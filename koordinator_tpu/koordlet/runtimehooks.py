"""Runtime hooks: container-lifecycle resource injection.

Analog of reference `pkg/koordlet/runtimehooks/` (runtimehooks.go:35-77): a hook
registry applied in three modes —
  (a) proxy: invoked by the runtime-proxy gRPC interceptor per CRI call
      (runtimeproxy/ hands us a ContainerContext, we mutate it)
  (b) NRI: same hooks behind containerd's NRI (mode wiring only differs)
  (c) standalone reconciler (reconciler/reconciler.go): watch pods, write
      cgroups directly via the executor — always-on backstop.

Hooks (feature-gated, config.go:38-100):
  * groupidentity : bvt.warp_ns per QoS class (hooks/groupidentity)
  * cpuset        : apply the scheduler's resource-status annotation
  * batchresource : cfs quota + memory limits from batch-cpu/batch-memory
  * gpu           : device env injection (NVIDIA_VISIBLE_DEVICES)
  * cpunormalization: scale cfs quota by the node's cpu-normalization ratio
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import (
    ANNOTATION_DEVICE_ALLOCATED,
    ANNOTATION_RESOURCE_STATUS,
    Pod,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import ResourceName
from koordinator_tpu.koordlet.metricsadvisor import pod_qos_dir
from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdateExecutor,
    ResourceUpdater,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util import system as sysutil

ANNOTATION_CPU_NORMALIZATION_RATIO = "node.koordinator.sh/cpu-normalization-ratio"

# bvt.warp_ns values per QoS (groupidentity defaults: LS=2, BE=-1)
BVT_BY_QOS = {
    QoSClass.LSE: 2,
    QoSClass.LSR: 2,
    QoSClass.LS: 2,
    QoSClass.SYSTEM: 0,
    QoSClass.BE: -1,
    QoSClass.NONE: 0,
}


@dataclass
class ContainerContext:
    """Mutable view of a container's runtime config (protocol/ adapters)."""

    pod: Pod
    cgroup_parent: str
    env: Dict[str, str] = field(default_factory=dict)
    cgroup_writes: List[ResourceUpdater] = field(default_factory=list)

    def add_write(self, resource: str, value: str, level: int = 2) -> None:
        self.cgroup_writes.append(
            ResourceUpdater(self.cgroup_parent, resource, value, level)
        )


class Hook:
    name = "hook"

    def apply(self, ctx: ContainerContext) -> None:
        raise NotImplementedError


class GroupIdentityHook(Hook):
    name = "GroupIdentity"

    def apply(self, ctx: ContainerContext) -> None:
        bvt = BVT_BY_QOS.get(ctx.pod.qos_class, 0)
        ctx.add_write(sysutil.CPU_BVT_WARP_NS, str(bvt))


class CPUSetHook(Hook):
    name = "CPUSetAllocator"

    def apply(self, ctx: ContainerContext) -> None:
        raw = ctx.pod.meta.annotations.get(ANNOTATION_RESOURCE_STATUS)
        if not raw:
            return
        try:
            status = json.loads(raw)
        except (ValueError, TypeError):
            return
        cpuset = status.get("cpuset")
        if cpuset:
            ctx.add_write(sysutil.CPUSET_CPUS, cpuset)


class BatchResourceHook(Hook):
    name = "BatchResource"

    def apply(self, ctx: ContainerContext) -> None:
        req = ctx.pod.spec.requests
        limits = ctx.pod.spec.limits
        batch_cpu = limits.get(ResourceName.BATCH_CPU) or req.get(ResourceName.BATCH_CPU)
        batch_mem = limits.get(ResourceName.BATCH_MEMORY) or req.get(
            ResourceName.BATCH_MEMORY
        )
        if batch_cpu:
            period = 100000
            ctx.add_write(sysutil.CPU_CFS_QUOTA, str(int(batch_cpu / 1000 * period)))
        if batch_mem:
            ctx.add_write(sysutil.MEMORY_LIMIT, str(int(batch_mem)))


class GPUEnvHook(Hook):
    name = "GPUEnv"

    def apply(self, ctx: ContainerContext) -> None:
        raw = ctx.pod.meta.annotations.get(ANNOTATION_DEVICE_ALLOCATED)
        if not raw:
            return
        try:
            alloc = json.loads(raw)
        except (ValueError, TypeError):
            return
        gpus = alloc.get("gpu") or []
        if gpus:
            ctx.env["NVIDIA_VISIBLE_DEVICES"] = ",".join(
                str(g["minor"]) for g in gpus
            )
            core = sum(g.get("core", 0) for g in gpus)
            if core and core % 100 != 0:
                ctx.env["CUDA_MPS_ACTIVE_THREAD_PERCENTAGE"] = str(core)


class CPUNormalizationHook(Hook):
    name = "CPUNormalization"

    def __init__(self, informer: StatesInformer):
        self.informer = informer

    def apply(self, ctx: ContainerContext) -> None:
        node = self.informer.get_node()
        if node is None:
            return
        raw = node.meta.annotations.get(ANNOTATION_CPU_NORMALIZATION_RATIO)
        if not raw:
            return
        try:
            ratio = float(raw)
        except ValueError:
            return
        if ratio <= 0 or ratio == 1.0:
            return
        cpu_limit = ctx.pod.spec.limits.get(ResourceName.CPU)
        if cpu_limit:
            period = 100000
            quota = int(cpu_limit / 1000.0 * period / ratio)
            ctx.add_write(sysutil.CPU_CFS_QUOTA, str(quota))


DEFAULT_HOOKS = (GroupIdentityHook, CPUSetHook, BatchResourceHook, GPUEnvHook)


class RuntimeHooks:
    """Hook runner: proxy-mode entry (run_hooks) + standalone reconciler."""

    def __init__(self, informer: StatesInformer, executor: ResourceUpdateExecutor):
        self.informer = informer
        self.executor = executor
        self.hooks: List[Hook] = [cls() for cls in DEFAULT_HOOKS]
        self.hooks.append(CPUNormalizationHook(informer))

    def run_hooks(self, ctx: ContainerContext) -> ContainerContext:
        """Proxy/NRI-mode: mutate the container context; the caller (runtime
        proxy or NRI adapter) applies the response to the real runtime call."""
        for hook in self.hooks:
            hook.apply(ctx)
        return ctx

    def reconcile(self) -> int:
        """Standalone reconciler backstop (reconciler.go:144): apply hook output
        directly through the executor for every local pod; returns writes."""
        wrote = 0
        for pod in self.informer.get_all_pods():
            if not pod.is_assigned:
                continue
            rel = self.executor.config.pod_relative_path(
                pod_qos_dir(pod), pod.meta.uid or pod.meta.name
            )
            ctx = ContainerContext(pod=pod, cgroup_parent=rel)
            self.run_hooks(ctx)
            shrink = [u for u in ctx.cgroup_writes]
            wrote += self.executor.leveled_update_batch(shrink, increase=False)
        return wrote
