"""Runtime hooks: container-lifecycle resource injection.

Analog of reference `pkg/koordlet/runtimehooks/` (runtimehooks.go:35-77): a hook
registry applied in three modes —
  (a) proxy: invoked by the runtime-proxy gRPC interceptor per CRI call
      (runtimeproxy/ hands us a ContainerContext, we mutate it)
  (b) NRI: the koordlet/nri.py plugin dials containerd's NRI socket,
      registers, and serves RunPodSandbox/CreateContainer/UpdateContainer
      from the same hook chain (reference runtimehooks/nri/server.go;
      e2e against a fake containerd in tests/test_nri.py)
  (c) standalone reconciler (reconciler/reconciler.go): watch pods, write
      cgroups directly via the executor — always-on backstop.

Hooks (feature-gated, config.go:38-100):
  * groupidentity : bvt.warp_ns per QoS class (hooks/groupidentity)
  * cpuset        : apply the scheduler's resource-status annotation
  * batchresource : cfs quota + memory limits from batch-cpu/batch-memory
  * gpu           : device env injection (NVIDIA_VISIBLE_DEVICES)
  * cpunormalization: scale cfs quota by the node's cpu-normalization ratio
  * coresched     : SMT core-scheduling cookies per QoS group (hooks/coresched)
  * terwayqos     : network-QoS config files for the terway dataplane
                    (hooks/terwayqos)
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import (
    ANNOTATION_DEVICE_ALLOCATED,
    ANNOTATION_RESOURCE_STATUS,
    Pod,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import ResourceName
from koordinator_tpu.koordlet.metricsadvisor import pod_qos_dir
from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdateExecutor,
    ResourceUpdater,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util import system as sysutil

ANNOTATION_CPU_NORMALIZATION_RATIO = "node.koordinator.sh/cpu-normalization-ratio"

# bvt.warp_ns values per QoS (groupidentity defaults: LS=2, BE=-1)
BVT_BY_QOS = {
    QoSClass.LSE: 2,
    QoSClass.LSR: 2,
    QoSClass.LS: 2,
    QoSClass.SYSTEM: 0,
    QoSClass.BE: -1,
    QoSClass.NONE: 0,
}


@dataclass
class ContainerContext:
    """Mutable view of a container's runtime config (protocol/ adapters)."""

    pod: Pod
    cgroup_parent: str
    env: Dict[str, str] = field(default_factory=dict)
    cgroup_writes: List[ResourceUpdater] = field(default_factory=list)

    def add_write(self, resource: str, value: str, level: int = 2) -> None:
        self.cgroup_writes.append(
            ResourceUpdater(self.cgroup_parent, resource, value, level)
        )


class Hook:
    name = "hook"

    def apply(self, ctx: ContainerContext) -> None:
        raise NotImplementedError


class GroupIdentityHook(Hook):
    name = "GroupIdentity"

    def apply(self, ctx: ContainerContext) -> None:
        bvt = BVT_BY_QOS.get(ctx.pod.qos_class, 0)
        ctx.add_write(sysutil.CPU_BVT_WARP_NS, str(bvt))


class CPUSetHook(Hook):
    name = "CPUSetAllocator"

    def __init__(self, informer: Optional[StatesInformer] = None):
        self.informer = informer

    def apply(self, ctx: ContainerContext) -> None:
        # SYSTEM QoS pods run on the node's dedicated system cpuset
        # (hooks/cpuset/rule.go system-qos-resource path)
        if ctx.pod.qos_class == QoSClass.SYSTEM and self.informer is not None:
            node = self.informer.get_node()
            if node is not None:
                sys_cpus, _excl = node.system_qos_resource()
                if sys_cpus:
                    ctx.add_write(sysutil.CPUSET_CPUS, sys_cpus)
                    return
        raw = ctx.pod.meta.annotations.get(ANNOTATION_RESOURCE_STATUS)
        if not raw:
            return
        try:
            status = json.loads(raw)
        except (ValueError, TypeError):
            return
        cpuset = status.get("cpuset")
        if cpuset:
            ctx.add_write(sysutil.CPUSET_CPUS, cpuset)


class BatchResourceHook(Hook):
    name = "BatchResource"

    def apply(self, ctx: ContainerContext) -> None:
        req = ctx.pod.spec.requests
        limits = ctx.pod.spec.limits
        batch_cpu = limits.get(ResourceName.BATCH_CPU) or req.get(ResourceName.BATCH_CPU)
        batch_mem = limits.get(ResourceName.BATCH_MEMORY) or req.get(
            ResourceName.BATCH_MEMORY
        )
        if batch_cpu:
            period = 100000
            ctx.add_write(sysutil.CPU_CFS_QUOTA, str(int(batch_cpu / 1000 * period)))
        if batch_mem:
            ctx.add_write(sysutil.MEMORY_LIMIT, str(int(batch_mem)))


class GPUEnvHook(Hook):
    name = "GPUEnv"

    def apply(self, ctx: ContainerContext) -> None:
        raw = ctx.pod.meta.annotations.get(ANNOTATION_DEVICE_ALLOCATED)
        if not raw:
            return
        try:
            alloc = json.loads(raw)
        except (ValueError, TypeError):
            return
        gpus = alloc.get("gpu") or []
        if gpus:
            ctx.env["NVIDIA_VISIBLE_DEVICES"] = ",".join(
                str(g["minor"]) for g in gpus
            )
            core = sum(g.get("core", 0) for g in gpus)
            if core and core % 100 != 0:
                ctx.env["CUDA_MPS_ACTIVE_THREAD_PERCENTAGE"] = str(core)


class CPUNormalizationHook(Hook):
    name = "CPUNormalization"

    def __init__(self, informer: StatesInformer):
        self.informer = informer

    def apply(self, ctx: ContainerContext) -> None:
        node = self.informer.get_node()
        if node is None:
            return
        raw = node.meta.annotations.get(ANNOTATION_CPU_NORMALIZATION_RATIO)
        if not raw:
            return
        try:
            ratio = float(raw)
        except ValueError:
            return
        if ratio <= 0 or ratio == 1.0:
            return
        cpu_limit = ctx.pod.spec.limits.get(ResourceName.CPU)
        if cpu_limit:
            period = 100000
            quota = int(cpu_limit / 1000.0 * period / ratio)
            ctx.add_write(sysutil.CPU_CFS_QUOTA, str(quota))


class CoreSchedHook(Hook):
    """SMT core-scheduling cookies per QoS trust domain (hooks/coresched/
    core_sched.go): tasks of LS-tier pods share one "expeller" cookie, each
    BE pod group gets its own, so BE never co-runs on a hyperthread sibling
    of an LS task. Gated by NodeSLO resourceQOSStrategy.core_sched_enable and
    kernel support (util/coresched, prctl PR_SCHED_CORE; degrades to no-op)."""

    name = "CoreSched"

    # QoS tiers sharing the node-wide expeller cookie (ExpellerGroupSuffix)
    _EXPELLER = (QoSClass.LSE, QoSClass.LSR, QoSClass.LS, QoSClass.SYSTEM)

    def __init__(self, informer: StatesInformer,
                 executor: ResourceUpdateExecutor, cse=None):
        from koordinator_tpu.koordlet.util.coresched import default_interface

        self.informer = informer
        self.executor = executor
        self.cse = cse if cse is not None else default_interface()
        # core-sched-group-id -> (leader pid, cookie value) — the cookie value
        # guards against pid reuse: a recycled leader pid carries a DIFFERENT
        # cookie, so the entry is discarded instead of leaking a foreign
        # cookie into the group (cookie_cache.go expiry analog)
        self.groups: Dict[str, tuple] = {}
        # every pid a cookie was put on, for cleanup when the group (or the
        # whole feature) goes away
        self.group_pids: Dict[str, set] = {}

    def _group_id(self, pod: Pod) -> str:
        qos = pod.qos_class
        if qos in self._EXPELLER:
            return "ls-expeller"
        if qos is QoSClass.BE:
            return f"be/{pod.meta.uid or pod.meta.key}"
        return ""  # NONE: leave cookies alone

    def _pod_pids(self, relative_dir: str) -> List[int]:
        """Tasks of the pod: the pod dir's procs plus every child (container)
        cgroup's — on cgroup v2 the no-internal-process rule keeps all tasks
        in the leaf container cgroups, so the pod file alone is empty."""
        chunks = [self.executor.read(relative_dir, sysutil.CGROUP_PROCS) or ""]
        pod_file = self.executor.config.cgroup_file_path(
            relative_dir, sysutil.CGROUP_PROCS
        )
        pod_dir = os.path.dirname(pod_file)
        try:
            children = sorted(os.listdir(pod_dir))
        except OSError:
            children = []
        for child in children:
            child_procs = os.path.join(pod_dir, child, sysutil.CGROUP_PROCS)
            if os.path.isfile(child_procs):
                chunks.append(sysutil.read_file(child_procs) or "")
        pids: List[int] = []
        for chunk in chunks:
            pids.extend(int(p) for p in chunk.split() if p.strip().isdigit())
        return pids

    def apply(self, ctx: ContainerContext) -> None:
        if not self.informer.get_node_slo().resource_qos_strategy.core_sched_enable:
            return
        if not self.cse.supported():
            return
        group = self._group_id(ctx.pod)
        if not group:
            return
        pids = self._pod_pids(ctx.cgroup_parent)
        if not pids:
            return
        entry = self.groups.get(group)
        if entry is not None and self.cse.get_cookie(entry[0]) != entry[1]:
            entry = None  # leader died (or its pid was recycled)
        if entry is None:
            # first container of the group: mint a cookie on its first task
            if not self.cse.create_cookie(pids[0]):
                return
            cookie = self.cse.get_cookie(pids[0])
            if not cookie:
                return
            entry = (pids[0], cookie)
            self.groups[group] = entry
        leader, cookie = entry
        # idempotent: only tasks whose cookie diverges are re-shared, so a
        # steady-state reconcile tick issues zero prctls
        stale = [
            p for p in pids if p != leader and self.cse.get_cookie(p) != cookie
        ]
        if stale:
            self.cse.share_from(leader, stale)
        self.group_pids.setdefault(group, set()).update(pids)

    def _clear_group(self, group: str) -> None:
        for pid in self.group_pids.pop(group, ()):  # dead pids fail harmlessly
            self.cse.clear_cookie(pid)
        self.groups.pop(group, None)

    def reconcile_node(self) -> None:
        """Prune cookie groups whose pods are gone, and clear every cookie
        when the feature is switched off (the reference clears on disable —
        otherwise SMT siblings stay force-idled until every pod restarts)."""
        if not self.groups and not self.group_pids:
            return
        if not self.informer.get_node_slo().resource_qos_strategy.core_sched_enable:
            for group in list(self.group_pids) + list(self.groups):
                self._clear_group(group)
            return
        live = {"ls-expeller"}
        for pod in self.informer.get_all_pods():
            group = self._group_id(pod)
            if group:
                live.add(group)
        for group in list(self.groups):
            if group not in live:
                self._clear_group(group)


ANNOTATION_NET_QOS = "koordinator.sh/networkQOS"  # extension network qos


class TerwayQoSHook(Hook):
    """Network QoS config generator (hooks/terwayqos/terwayqos.go): when the
    NodeSLO netQoS policy is "terwayQos", render the node bandwidth ceilings
    to `var/lib/terway/qos/global_bps_config` and every local pod's priority +
    per-pod limits to `pod.json`; the terway dataplane consumes the files.
    Per-container apply() is a no-op — this is a node-level reconciler."""

    name = "TerwayQoS"

    # QoS class -> terway priority band (getPodPrio: LS tiers 0, mid 1, BE 2)
    _PRIO = {QoSClass.LSE: 0, QoSClass.LSR: 0, QoSClass.LS: 0,
             QoSClass.SYSTEM: 0, QoSClass.NONE: 1, QoSClass.BE: 2}

    def __init__(self, informer: StatesInformer,
                 executor: ResourceUpdateExecutor):
        self.informer = informer
        self.executor = executor
        self._written: Dict[str, str] = {}  # path -> last content on disk

    def _qos_dir(self) -> str:
        root = self.executor.config.fs_root_dir
        return os.path.join(root, "var/lib/terway/qos")

    def apply(self, ctx: ContainerContext) -> None:
        return None

    def reconcile_node(self) -> None:
        slo = self.informer.get_node_slo().resource_qos_strategy
        qos_dir = self._qos_dir()
        node_path = os.path.join(qos_dir, "global_bps_config")
        pod_path = os.path.join(qos_dir, "pod.json")
        if slo.net_qos_policy != "terwayQos":
            for path in (node_path, pod_path):
                if self._written.pop(path, None) is not None or os.path.exists(path):
                    try:
                        os.remove(path)
                    except OSError:
                        pass
            return
        os.makedirs(qos_dir, exist_ok=True)
        self._write_atomic(node_path, (
            f"hw_tx_bps_max {slo.net_hw_tx_bps}\n"
            f"hw_rx_bps_max {slo.net_hw_rx_bps}\n"
        ))
        pods = {}
        for pod in self.informer.get_all_pods():
            limits = {}
            raw = pod.meta.annotations.get(ANNOTATION_NET_QOS)
            if raw:
                try:
                    limits = json.loads(raw)
                except (ValueError, TypeError):
                    limits = {}
                if not isinstance(limits, dict):
                    limits = {}  # valid JSON but not an object
            pods[pod.meta.uid or pod.meta.key] = {
                "podName": pod.meta.name,
                "podNamespace": pod.meta.namespace,
                "podUID": pod.meta.uid,
                "prio": self._PRIO.get(pod.qos_class, 1),
                "ingressLimit": limits.get("ingressLimit", ""),
                "egressLimit": limits.get("egressLimit", ""),
            }
        self._write_atomic(pod_path, json.dumps(pods, sort_keys=True))

    def _write_atomic(self, path: str, content: str) -> None:
        # tmp + rename (the dataplane polls these files and must never read a
        # truncated document); unchanged content is not rewritten, so steady
        # state leaves mtime/inode alone and the poller skips re-parsing
        if self._written.get(path) == content:
            return
        tmp = path + ".tmp"
        if sysutil.write_file(tmp, content):
            try:
                os.replace(tmp, path)
            except OSError:
                return
            self._written[path] = content


DEFAULT_HOOKS = (GroupIdentityHook, BatchResourceHook, GPUEnvHook)


class HostApplicationHook(Hook):
    """Group identity for non-k8s host services: every NodeSLO
    `hostApplications` entry gets the bvt of its declared QoS written to its
    own cgroup dir (hooks/groupidentity/rule.go getHostQOSBvtValue +
    interceptor.go host-app path). Node-level only — host apps have no
    container lifecycle, so the standalone reconciler is the only mode."""

    name = "hostapplication"

    def __init__(self, informer: StatesInformer,
                 executor: ResourceUpdateExecutor):
        self.informer = informer
        self.executor = executor
        self._applied: Dict[str, int] = {}  # cgroup rel -> bvt written

    def apply(self, ctx: ContainerContext) -> None:  # no per-container work
        return

    def reconcile_node(self) -> None:
        from koordinator_tpu.api.objects import host_applications
        from koordinator_tpu.api.qos import qos_class_by_name

        want: Dict[str, int] = {}
        for app in host_applications(self.informer.get_node_slo()):
            rel = app.get("cgroupPath")
            if not rel:
                continue
            qos = qos_class_by_name(app.get("qos", ""))
            want[rel] = BVT_BY_QOS.get(qos, 0)
        # entries removed from NodeSLO (or whose path changed) get their
        # bvt reset — otherwise a deleted host app keeps preempting BE
        for rel in list(self._applied):
            if rel not in want:
                self.executor.update(
                    ResourceUpdater(rel, sysutil.CPU_BVT_WARP_NS, "0"))
                del self._applied[rel]
        for rel, bvt in want.items():
            self.executor.update(
                ResourceUpdater(rel, sysutil.CPU_BVT_WARP_NS, str(bvt)))
            self._applied[rel] = bvt


class RuntimeHooks:
    """Hook runner: proxy-mode entry (run_hooks) + standalone reconciler."""

    def __init__(self, informer: StatesInformer, executor: ResourceUpdateExecutor,
                 core_sched=None):
        self.informer = informer
        self.executor = executor
        self.hooks: List[Hook] = [cls() for cls in DEFAULT_HOOKS]
        self.hooks.append(CPUSetHook(informer))
        self.hooks.append(CPUNormalizationHook(informer))
        self.hooks.append(CoreSchedHook(informer, executor, cse=core_sched))
        self.hooks.append(TerwayQoSHook(informer, executor))
        self.hooks.append(HostApplicationHook(informer, executor))

    def run_hooks(self, ctx: ContainerContext) -> ContainerContext:
        """Proxy/NRI-mode: mutate the container context; the caller (runtime
        proxy or NRI adapter) applies the response to the real runtime call."""
        for hook in self.hooks:
            hook.apply(ctx)
        return ctx

    def reconcile(self) -> int:
        """Standalone reconciler backstop (reconciler.go:144): apply hook output
        directly through the executor for every local pod; returns writes."""
        wrote = 0
        for hook in self.hooks:
            node_level = getattr(hook, "reconcile_node", None)
            if node_level is not None:
                node_level()
        for pod in self.informer.get_all_pods():
            if not pod.is_assigned:
                continue
            rel = self.executor.config.pod_relative_path(
                pod_qos_dir(pod), pod.meta.uid or pod.meta.name
            )
            ctx = ContainerContext(pod=pod, cgroup_parent=rel)
            self.run_hooks(ctx)
            shrink = [u for u in ctx.cgroup_writes]
            wrote += self.executor.leveled_update_batch(shrink, increase=False)
        return wrote
