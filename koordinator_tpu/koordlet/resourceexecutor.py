"""Resource executor: serialized, cached, audited cgroup/resctrl writer.

Analog of reference `pkg/koordlet/resourceexecutor/`:
  * last-written-value cache suppresses redundant writes (executor.go:203-264)
  * leveled batch updates apply parent dirs before children for limit increases
    and children first for decreases (LeveledUpdateBatch, executor.go:114) —
    order matters for cgroup hierarchies (a child limit can't exceed its parent)
  * merge-update semantics for guarded files (e.g. cpuset shrink keeps union
    until children release cpus) are approximated by the cache comparison
  * every mutation lands in the audit ring.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.util import system as sysutil


@dataclass(frozen=True)
class ResourceUpdater:
    relative_dir: str
    resource: str
    value: str
    level: int = 0  # depth in the cgroup tree (0=qos root, 1=pod, 2=container)


class ResourceUpdateExecutor:
    def __init__(self, config: Optional[sysutil.SystemConfig] = None,
                 auditor: Optional[Auditor] = None):
        self.config = config if config is not None else sysutil.CONFIG
        # explicit None check: an empty Auditor is falsy via __len__, and `or`
        # would silently swap in a fresh one, detaching the daemon's audit ring
        self.auditor = auditor if auditor is not None else Auditor()
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[str, str], str] = {}

    def update(self, updater: ResourceUpdater, force: bool = False) -> bool:
        """Write unless cached value matches; returns whether a write happened."""
        key = (updater.relative_dir, updater.resource)
        with self._lock:
            if not force and self._cache.get(key) == updater.value:
                return False
            ok = sysutil.write_cgroup(
                updater.relative_dir, updater.resource, updater.value, self.config
            )
            if ok:
                self._cache[key] = updater.value
                self.auditor.record(
                    "info",
                    updater.relative_dir or "node",
                    "cgroup_write",
                    resource=updater.resource,
                    value=updater.value,
                )
            return ok

    def leveled_update_batch(self, updaters: List[ResourceUpdater],
                             increase: bool = True) -> int:
        """Apply a batch ordered by tree level: top-down when limits grow,
        bottom-up when they shrink (executor.go LeveledUpdateBatch)."""
        ordered = sorted(updaters, key=lambda u: u.level, reverse=not increase)
        wrote = 0
        for u in ordered:
            if self.update(u):
                wrote += 1
        return wrote

    def read(self, relative_dir: str, resource: str) -> Optional[str]:
        return sysutil.read_cgroup(relative_dir, resource, self.config)

    def cached_value(self, relative_dir: str, resource: str) -> Optional[str]:
        with self._lock:
            return self._cache.get((relative_dir, resource))
