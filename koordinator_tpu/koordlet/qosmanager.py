"""QoS manager: runtime enforcement strategy plugins.

Analog of reference `pkg/koordlet/qosmanager/` (registry plugins/register.go:36-46):
each strategy reads statesinformer + metriccache and enforces through the
resource executor. Implemented strategies:

  * cpusuppress  (plugins/cpusuppress/cpu_suppress.go:240-321, formula :138-164):
      suppress(BE) = capacity * thresholdPercent - podNonBEUsed - systemUsed
      applied as the BE root cpuset size (paired HT cores, spread over NUMA) or
      as cfs quota, with recovery when the policy flips.
  * cpuevict     (BE eviction when BE cpu satisfaction is below threshold)
  * memoryevict  (BE eviction when node memory utilization crosses threshold)
  * cpuburst     (cfs burst for LS containers, plugins/cpuburst/)
  * resctrl      (LLC ways / MBA percent per QoS class via resctrl fs)
  * cgreconcile  (cpu.shares / memory guarantees per QoS cgroup)

An `Evictor` mirrors the shared eviction helper (framework/context.go:42-90):
victims sorted BE-first by priority then usage.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import NodeSLO, Pod
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.client.store import KIND_POD, ObjectStore
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import metrics as koordlet_metrics
from koordinator_tpu.koordlet.metricsadvisor import pod_qos_dir
from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdateExecutor,
    ResourceUpdater,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util import resctrl as resctrl_util
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.utils.cpuset import CPUSet
from koordinator_tpu.utils.features import KOORDLET_GATES


class Evictor:
    """Shared BE eviction helper (qosmanager/framework/context.go:42-90)."""

    def __init__(self, store: ObjectStore, informer: StatesInformer,
                 cache: mc.MetricCache):
        self.store = store
        self.informer = informer
        self.cache = cache
        self.evicted: List[str] = []

    def be_victims_by_usage(self) -> List[Pod]:
        pods = [
            p for p in self.informer.get_all_pods()
            if p.qos_class == QoSClass.BE
        ]

        def usage(p: Pod) -> float:
            return self.cache.query(mc.POD_CPU_USAGE, "latest", pod=p.meta.key) or 0.0

        # lowest priority first, then highest usage (framework helper sort)
        return sorted(pods, key=lambda p: ((p.spec.priority or 0), -usage(p)))

    def evict(self, pod: Pod, reason: str) -> None:
        pod.phase = "Failed"
        pod.meta.annotations["koordinator.sh/evicted"] = reason
        self.store.update(KIND_POD, pod)
        self.evicted.append(pod.meta.key)
        koordlet_metrics.POD_EVICTION_TOTAL.inc(reason=reason)


@dataclass
class QOSStrategyContext:
    informer: StatesInformer
    cache: mc.MetricCache
    executor: ResourceUpdateExecutor
    evictor: Evictor
    metric_collect_interval: float = 60.0


class CPUSuppress:
    """BE cpu suppression (cpusuppress plugin)."""

    name = "cpusuppress"
    MIN_SUPPRESS_CPUS = 2  # reference beMinCPU

    def __init__(self, ctx: QOSStrategyContext):
        self.ctx = ctx
        self.policy_in_use: Optional[str] = None

    def _suppress_cpus(self, slo: NodeSLO, now: float) -> Optional[float]:
        node = self.ctx.informer.get_node()
        if node is None:
            return None
        threshold = slo.resource_used_threshold_with_be.cpu_suppress_threshold_percent
        capacity = node.allocatable.get("cpu", 0) / 1000.0
        node_usage = self.ctx.cache.query(
            mc.NODE_CPU_USAGE, "latest", self.ctx.metric_collect_interval, now
        )
        if node_usage is None:
            return None
        # podNonBEUsed + hostAppNonBEUsed + systemUsed = nodeUsage - BE usage.
        # Host applications declared BE in NodeSLO must not shrink the BE
        # share either (helpers/calculator.go:30-66 NonBEHostAppFilter +
        # cpu_suppress.go:139-161): their usage comes out of the non-BE side.
        be_usage = self.ctx.cache.query(
            mc.BE_CPU_USAGE, "latest", self.ctx.metric_collect_interval, now
        ) or 0.0
        from koordinator_tpu.api.objects import host_applications

        for app in host_applications(slo):
            if app.get("qos", "") != "BE" or not app.get("name"):
                continue
            be_usage += self.ctx.cache.query(
                mc.HOST_APP_CPU_USAGE, "latest",
                self.ctx.metric_collect_interval, now, app=app["name"],
            ) or 0.0
        non_be_used = max(0.0, node_usage - be_usage)
        suppress = capacity * threshold / 100.0 - non_be_used
        return max(suppress, float(self.MIN_SUPPRESS_CPUS))

    def run(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        slo = self.ctx.informer.get_node_slo()
        be_rel = self.ctx.executor.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        if not (KOORDLET_GATES.enabled("BECPUSuppress")
                and slo.resource_used_threshold_with_be.enable):
            self._recover(be_rel)
            return
        suppress = self._suppress_cpus(slo, now)
        if suppress is None:
            return
        node = self.ctx.informer.get_node()
        total_cpus = int((node.allocatable.get("cpu", 0)) // 1000) if node else 0
        if slo.resource_used_threshold_with_be.cpu_suppress_policy == "cfsQuota":
            period = 100000
            quota = max(int(suppress * period), period // 100)
            self.ctx.executor.update(
                ResourceUpdater(be_rel, sysutil.CPU_CFS_QUOTA, str(quota))
            )
            self.policy_in_use = "cfsQuota"
            koordlet_metrics.BE_SUPPRESS_CPU_CORES.set(quota / period)
        else:
            # cpuset policy: round up, at least 2, paired HT cores from the
            # top — skipping the node's exclusive SYSTEM-QoS cores
            # (cpu_suppress.go system-qos-resource path)
            excluded = self._system_qos_excluded(node)
            want = min(max(int(math.ceil(suppress)), self.MIN_SUPPRESS_CPUS),
                       max(total_cpus - len(excluded),
                           self.MIN_SUPPRESS_CPUS))
            # only real cpu ids: running past total_cpus would write a
            # cpuset the kernel rejects with EINVAL
            picked = [c for c in range(total_cpus) if c not in excluded]
            if len(picked) < self.MIN_SUPPRESS_CPUS:
                # the exclusion is unsatisfiable (system cores cover nearly
                # the whole node): top up with the least-bad excluded cores
                # — still only REAL cpu ids, never fabricated ones
                picked = picked + [c for c in sorted(excluded)
                                   if c < total_cpus]
            if not picked:
                return  # no real cpus known; writing any cpuset would EINVAL
            cpus = CPUSet(picked[:want])
            self.ctx.executor.update(
                ResourceUpdater(be_rel, sysutil.CPUSET_CPUS, cpus.format())
            )
            self.policy_in_use = "cpuset"
            koordlet_metrics.BE_SUPPRESS_CPU_CORES.set(float(len(cpus)))

    @staticmethod
    def _system_qos_excluded(node) -> set:
        """Exclusive SYSTEM-QoS cores are barred to BE under suppression
        AND recovery (cpu_suppress.go system-qos-resource path)."""
        if node is None:
            return set()
        sys_cpus, sys_exclusive = node.system_qos_resource()
        if sys_cpus and sys_exclusive:
            return set(CPUSet.parse(sys_cpus))
        return set()

    def _recover(self, be_rel: str) -> None:
        if self.policy_in_use == "cfsQuota":
            self.ctx.executor.update(
                ResourceUpdater(be_rel, sysutil.CPU_CFS_QUOTA, "-1")
            )
        elif self.policy_in_use == "cpuset":
            node = self.ctx.informer.get_node()
            if node is not None:
                total = int(node.allocatable.get("cpu", 0) // 1000)
                excluded = self._system_qos_excluded(node)
                restore = [c for c in range(total) if c not in excluded]
                if not restore:
                    restore = list(range(total))  # unsatisfiable exclusion
                if restore:
                    self.ctx.executor.update(
                        ResourceUpdater(
                            be_rel, sysutil.CPUSET_CPUS,
                            CPUSet(restore).format(),
                        )
                    )
        self.policy_in_use = None
        koordlet_metrics.BE_SUPPRESS_CPU_CORES.clear()


class CPUEvict:
    """Evict BE pods when BE cpu satisfaction is below threshold
    (plugins/cpuevict)."""

    name = "cpuevict"

    def __init__(self, ctx: QOSStrategyContext):
        self.ctx = ctx

    def run(self, now: Optional[float] = None) -> None:
        if not KOORDLET_GATES.enabled("BECPUEvict"):
            return
        now = time.time() if now is None else now
        slo = self.ctx.informer.get_node_slo()
        thr = slo.resource_used_threshold_with_be
        if not thr.enable:
            return
        be_usage = self.ctx.cache.query(mc.BE_CPU_USAGE, "avg", 300, now)
        node = self.ctx.informer.get_node()
        if be_usage is None or node is None:
            return
        capacity = node.allocatable.get("cpu", 0) / 1000.0
        if capacity and be_usage / capacity * 100 >= thr.cpu_evict_be_usage_threshold_percent:
            victims = self.ctx.evictor.be_victims_by_usage()
            if victims:
                self.ctx.evictor.evict(victims[0], "BECPUEvict")


class MemoryEvict:
    """Evict BE pods on node memory pressure (plugins/memoryevict)."""

    name = "memoryevict"

    def __init__(self, ctx: QOSStrategyContext):
        self.ctx = ctx

    def run(self, now: Optional[float] = None) -> None:
        if not KOORDLET_GATES.enabled("BEMemoryEvict"):
            return
        now = time.time() if now is None else now
        slo = self.ctx.informer.get_node_slo()
        thr = slo.resource_used_threshold_with_be
        if not thr.enable:
            return
        node = self.ctx.informer.get_node()
        mem_usage = self.ctx.cache.query(mc.NODE_MEMORY_USAGE, "latest", now=now)
        if node is None or mem_usage is None:
            return
        capacity = node.allocatable.get("memory", 0)
        if not capacity:
            return
        util = mem_usage / capacity * 100
        if util < thr.memory_evict_threshold_percent:
            return
        lower = thr.memory_evict_lower_percent or (thr.memory_evict_threshold_percent - 2)
        to_release = (util - lower) / 100.0 * capacity
        released = 0.0
        for victim in self.ctx.evictor.be_victims_by_usage():
            if released >= to_release:
                break
            released += self.ctx.cache.query(
                mc.POD_MEMORY_USAGE, "latest", pod=victim.meta.key
            ) or 0.0
            self.ctx.evictor.evict(victim, "BEMemoryEvict")


class CPUBurst:
    """cfs burst for LS pods (plugins/cpuburst)."""

    name = "cpuburst"

    def __init__(self, ctx: QOSStrategyContext):
        self.ctx = ctx

    def run(self, now: Optional[float] = None) -> None:
        if not KOORDLET_GATES.enabled("CPUBurst"):
            return
        slo = self.ctx.informer.get_node_slo()
        strategy = slo.cpu_burst_strategy
        if strategy.policy == "none":
            return
        for pod in self.ctx.informer.get_all_pods():
            if not pod.qos_class.is_latency_sensitive:
                continue
            limit_milli = pod.spec.limits.get("cpu", 0)
            if limit_milli <= 0:
                continue
            rel = self.ctx.executor.config.pod_relative_path(
                pod_qos_dir(pod), pod.meta.uid or pod.meta.name
            )
            if strategy.policy in ("cpuBurstOnly", "auto"):
                burst_us = int(
                    limit_milli / 1000.0 * 100000
                    * strategy.cpu_burst_percent / 100.0
                )
                self.ctx.executor.update(
                    ResourceUpdater(rel, sysutil.CPU_CFS_BURST, str(burst_us), level=1)
                )
                koordlet_metrics.CPU_BURST_TOTAL.inc(pod=pod.meta.key)


class ResctrlReconcile:
    """LLC / memory-bandwidth isolation via resctrl groups (plugins/resctrl).

    Creates BE/LS resctrl groups and writes schemata lines with the configured
    LLC way-percentage and MBA percent."""

    name = "resctrl"

    def __init__(self, ctx: QOSStrategyContext, cache_ways: int = 12):
        self.ctx = ctx
        # fallback way count when the root schemata isn't readable
        self.cache_ways = cache_ways
        self.iface = resctrl_util.ResctrlInterface(ctx.executor.config)

    def run(self, now: Optional[float] = None) -> None:
        if not KOORDLET_GATES.enabled("RdtResctrl"):
            return
        slo = self.ctx.informer.get_node_slo()
        qos = slo.resource_qos_strategy
        if not qos.be_enable:
            return
        num_ways = self.iface.num_l3_ways() or self.cache_ways
        # tolerate out-of-range config (mis-rendered sloconfig) rather than
        # crashing the whole strategy loop
        percent = min(100, max(1, qos.llc_be_percent))
        schemata = resctrl_util.Schemata(
            l3_masks={0: resctrl_util.calculate_l3_mask(num_ways, 0, percent)},
            mb_percents={0: qos.mba_be_percent},
        )
        self.iface.write_schemata(resctrl_util.BE_GROUP, schemata)
        koordlet_metrics.RESCTRL_UPDATE_TOTAL.inc(group="BE")
        self.ctx.executor.auditor.record(
            "info", "node", "resctrl_write", group="BE",
            schemata=schemata.format().strip()
        )


class CgroupReconcile:
    """Baseline per-QoS cgroup parameters (plugins/cgreconcile): cpu.shares and
    memory protection per QoS class."""

    name = "cgreconcile"
    CPU_SHARES_BY_QOS = {
        QoSClass.LSE: 4096, QoSClass.LSR: 4096, QoSClass.LS: 2048,
        QoSClass.BE: 2,
    }

    def __init__(self, ctx: QOSStrategyContext):
        self.ctx = ctx

    def run(self, now: Optional[float] = None) -> None:
        if not KOORDLET_GATES.enabled("CgroupReconcile"):
            return
        for pod in self.ctx.informer.get_all_pods():
            shares = self.CPU_SHARES_BY_QOS.get(pod.qos_class)
            if shares is None:
                continue
            rel = self.ctx.executor.config.pod_relative_path(
                pod_qos_dir(pod), pod.meta.uid or pod.meta.name
            )
            self.ctx.executor.update(
                ResourceUpdater(rel, sysutil.CPU_SHARES, str(shares), level=1)
            )


class BlkIOReconcile:
    """Per-QoS-tier block-IO weights (plugins/blkio): LS gets a high io.weight
    (v2; blkio.bfq.weight on v1 via the resource table translation), BE a low
    one, so BE IO yields under contention."""

    name = "blkio"

    def __init__(self, ctx: QOSStrategyContext):
        self.ctx = ctx

    def run(self, now: Optional[float] = None) -> None:
        if not KOORDLET_GATES.enabled("BlkIOReconcile"):
            return
        slo = self.ctx.informer.get_node_slo()
        qos = slo.resource_qos_strategy
        if not qos.blkio_enable:
            return
        # tier dirs first (besteffort/burstable; NOT the kubepods root —
        # guaranteed pods are its direct children and boosting the root
        # would change kubepods-vs-system weighting instead)
        for qos_dir, weight in (
            (sysutil.QOS_BURSTABLE, qos.ls_blkio_weight),
            (sysutil.QOS_BESTEFFORT, qos.be_blkio_weight),
        ):
            rel = self.ctx.executor.config.qos_relative_path(qos_dir)
            self.ctx.executor.update(
                ResourceUpdater(rel, sysutil.BLKIO_WEIGHT, str(weight))
            )
        # guaranteed pods get the LS weight on their own pod dirs
        for pod in self.ctx.informer.get_all_pods():
            if pod_qos_dir(pod) != sysutil.QOS_GUARANTEED:
                continue
            weight = (qos.be_blkio_weight
                      if pod.qos_class == QoSClass.BE else qos.ls_blkio_weight)
            rel = self.ctx.executor.config.pod_relative_path(
                sysutil.QOS_GUARANTEED, pod.meta.uid or pod.meta.name)
            self.ctx.executor.update(
                ResourceUpdater(rel, sysutil.BLKIO_WEIGHT, str(weight), level=1)
            )


class SystemReconcile:
    """Node-level memory watermark tuning (plugins/sysreconcile): writes
    /proc/sys/vm knobs from the NodeSLO system strategy so reclaim starts
    early enough to protect LS pods from BE memory bursts."""

    name = "sysreconcile"

    def __init__(self, ctx: QOSStrategyContext):
        self.ctx = ctx

    def run(self, now: Optional[float] = None) -> None:
        if not KOORDLET_GATES.enabled("SystemConfig"):
            return
        slo = self.ctx.informer.get_node_slo()
        strategy = slo.system_strategy
        cfg = self.ctx.executor.config
        mem = sysutil.read_meminfo(cfg)
        total_kb = mem.get("MemTotal", 0) // 1024
        if total_kb:
            # factor is per-ten-thousand of total memory
            min_free = total_kb * strategy.min_free_kbytes_factor // 10_000
            sysutil.write_file(
                cfg.proc_path("sys/vm/min_free_kbytes"), str(min_free))
        sysutil.write_file(
            cfg.proc_path("sys/vm/watermark_scale_factor"),
            str(strategy.watermark_scale_factor))
        self.ctx.executor.auditor.record(
            "info", "node", "sysreconcile",
            watermark_scale_factor=str(strategy.watermark_scale_factor))


class QoSManager:
    """Strategy loop (qosmanager framework)."""

    def __init__(self, store: ObjectStore, informer: StatesInformer,
                 cache: mc.MetricCache, executor: ResourceUpdateExecutor):
        self.evictor = Evictor(store, informer, cache)
        self.ctx = QOSStrategyContext(informer, cache, executor, self.evictor)
        self.strategies = [
            CPUSuppress(self.ctx),
            CPUEvict(self.ctx),
            MemoryEvict(self.ctx),
            CPUBurst(self.ctx),
            ResctrlReconcile(self.ctx),
            CgroupReconcile(self.ctx),
            BlkIOReconcile(self.ctx),
            SystemReconcile(self.ctx),
        ]

    def run_once(self, now: Optional[float] = None) -> None:
        t_start = time.perf_counter()
        for strategy in self.strategies:
            strategy.run(now)
            koordlet_metrics.QOS_STRATEGY_RUN_TOTAL.inc(
                strategy=type(strategy).__name__)
        koordlet_metrics.QOS_CYCLE_SECONDS.observe(
            time.perf_counter() - t_start)
