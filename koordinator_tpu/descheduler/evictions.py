"""Eviction machinery: controllerfinder, evictability filter, PDB-aware
evictor variants.

Analog of reference `pkg/descheduler/evictions/` +
`controllers/migration/evictor/` + `controllers/migration/controllerfinder/`:

  * ControllerFinder — resolve a pod's workload (owner kind/name) to its
    replica set: expected replicas (from the workload's pods themselves; the
    store carries no Deployment objects) and currently-healthy members.
  * is_evictable — defaultevictor filter semantics: DaemonSet pods, bare
    (ownerless) pods, and system-critical-priority pods are non-evictable
    unless force-annotated; an explicit opt-out annotation always wins.
  * PDB check — policy/v1 semantics on the healthy member count.
  * Evictor variants (migration/evictor/): EvictionAPIEvictor (the default —
    honors PDBs and evictability), DeleteEvictor (direct delete, still honors
    evictability but skips PDBs, the reference's "delete" mode), SoftEvictor
    (annotate only; koordlet acts on the annotation later).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.api.objects import Pod, PodDisruptionBudget
from koordinator_tpu.client.store import KIND_PDB, KIND_POD, ObjectStore

# annotations (apis/extension eviction semantics)
ANNOTATION_EVICTABLE = "descheduler.koordinator.sh/evictable"  # "true"/"false"
ANNOTATION_SOFT_EVICTION = "scheduling.koordinator.sh/soft-eviction"
SYSTEM_CRITICAL_PRIORITY = 2_000_000_000  # system-cluster-critical floor


class EvictionBlocked(Exception):
    """Eviction refused; str(exc) carries the reason."""


@dataclass
class WorkloadReplicas:
    workload: str               # "Kind/name" ("" for bare pods)
    members: List[Pod]
    healthy: int                # live members (not terminated)

    @property
    def replicas(self) -> int:
        return len(self.members)


class ControllerFinder:
    """controllerfinder/: map pod -> workload replica set via owner refs."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def workload_of(self, pod: Pod) -> WorkloadReplicas:
        if not pod.meta.owner_kind:
            live = 0 if pod.is_terminated else 1
            return WorkloadReplicas("", [pod], live)
        members = [
            p for p in self.store.list(KIND_POD)
            if p.meta.namespace == pod.meta.namespace
            and p.meta.owner_kind == pod.meta.owner_kind
            and p.meta.owner_name == pod.meta.owner_name
        ]
        healthy = sum(1 for p in members if not p.is_terminated)
        return WorkloadReplicas(
            f"{pod.meta.owner_kind}/{pod.meta.owner_name}", members, healthy)


def terminate_pod(store: ObjectStore, pod: Pod, annotation: str,
                  reason: str) -> Pod:
    """Mark a pod Failed through the store, via a COPY: the store holds live
    references, so mutating the stored object in place would make the MODIFIED
    event's old==new and hide the phase transition from subscribers (quota
    used rollback, assign caches). Single home for that invariant — eviction
    and preemption both route here."""
    updated = pod.patch_copy()
    updated.phase = "Failed"
    updated.meta.annotations[annotation] = reason
    store.update(KIND_POD, updated)
    return updated


def is_evictable(pod: Pod) -> Tuple[bool, str]:
    """(ok, reason). defaultevictor filter chain. A terminated pod is never
    evictable — that check precedes even the force annotation."""
    if pod.is_terminated:
        return False, "pod already terminated"
    ann = pod.meta.annotations.get(ANNOTATION_EVICTABLE)
    if ann == "false":
        return False, "eviction disabled by annotation"
    if ann == "true":
        return True, ""
    if pod.meta.owner_kind == "DaemonSet":
        return False, "daemonset pod"
    if not pod.meta.owner_kind:
        return False, "bare pod without a controller"
    if (pod.spec.priority or 0) >= SYSTEM_CRITICAL_PRIORITY:
        return False, "system critical priority"
    return True, ""


def check_pdbs(store: ObjectStore, pod: Pod) -> Optional[str]:
    """Violated-PDB reason, or None if eviction is allowed. policy/v1: after
    the eviction the matching pods' healthy count must stay >= minAvailable
    (and the unavailable count <= maxUnavailable)."""
    pdbs: List[PodDisruptionBudget] = [
        pdb for pdb in store.list(KIND_PDB) if pdb.matches(pod)
    ]
    if not pdbs:
        return None
    # evicting an already-unhealthy pod consumes no budget: the healthy
    # count does not drop and the unavailable count does not grow
    cost = 1 if pod.is_healthy else 0
    for pdb in pdbs:
        matching = [p for p in store.list(KIND_POD) if pdb.matches(p)]
        healthy = sum(1 for p in matching if p.is_healthy)
        if (pdb.min_available is not None
                and healthy - cost < pdb.min_available):
            return (f"pdb {pdb.meta.key}: healthy {healthy}-{cost} < "
                    f"minAvailable {pdb.min_available}")
        if pdb.max_unavailable is not None:
            unavailable = len(matching) - healthy
            if unavailable + cost > pdb.max_unavailable:
                return (f"pdb {pdb.meta.key}: unavailable {unavailable}+{cost}"
                        f" > maxUnavailable {pdb.max_unavailable}")
    return None


class EvictionAPIEvictor:
    """Default evictor: evictability + PDB guard, then terminate the pod the
    way the eviction subresource does. Subclasses override `respects_pdb`
    and `_terminate` only; the guard chain stays in one place."""

    name = "EvictionAPI"
    respects_pdb = True

    def __init__(self, store: ObjectStore):
        self.store = store

    def evict(self, pod: Pod, reason: str) -> None:
        ok, why = is_evictable(pod)
        if not ok:
            raise EvictionBlocked(why)
        if self.respects_pdb:
            violated = check_pdbs(self.store, pod)
            if violated:
                raise EvictionBlocked(violated)
        self._terminate(pod, reason)

    def _terminate(self, pod: Pod, reason: str) -> None:
        terminate_pod(self.store, pod, "koordinator.sh/evicted", reason)


class DeleteEvictor(EvictionAPIEvictor):
    """Direct-delete mode: skips PDBs (the operator asked for force)."""

    name = "Delete"
    respects_pdb = False

    def _terminate(self, pod: Pod, reason: str) -> None:
        self.store.delete(KIND_POD, pod.meta.key)


class SoftEvictor:
    """Annotate-only: marks the pod for the node agent to drain gracefully."""

    name = "SoftEviction"

    def __init__(self, store: ObjectStore):
        self.store = store

    def evict(self, pod: Pod, reason: str) -> None:
        ok, why = is_evictable(pod)
        if not ok:
            raise EvictionBlocked(why)
        pod.meta.annotations[ANNOTATION_SOFT_EVICTION] = reason
        self.store.update(KIND_POD, pod)


EVICTOR_BY_NAME = {
    EvictionAPIEvictor.name: EvictionAPIEvictor,
    DeleteEvictor.name: DeleteEvictor,
    SoftEvictor.name: SoftEvictor,
}
