"""Descheduler plugin framework: profiles + the four plugin interfaces.

Analog of reference `pkg/descheduler/framework/types.go:32-110` (Plugin,
DeschedulePlugin, BalancePlugin, EvictPlugin, FilterPlugin, Evictor, Handle)
and `pkg/descheduler/profile/`: each profile owns its plugin set and evictor;
the runner executes every profile's Deschedule plugins, then its Balance
plugins, each interval (descheduler.go deschedulerLoop).

The vendored-kubernetes adaptor layer (`framework/plugins/kubernetes/`)
collapses here: plugins are implemented natively against the ObjectStore and
the shared eviction machinery (descheduler/evictions.py) instead of adapting
sigs.k8s.io/descheduler types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from koordinator_tpu.api.objects import Pod
from koordinator_tpu.client.store import KIND_NODE, ObjectStore


@dataclass
class Status:
    """framework.Status."""

    err: Optional[str] = None


class Plugin:
    """Parent type for all descheduling plugins (types.go:76-78)."""

    name = "plugin"


class DeschedulePlugin(Plugin):
    """Per-pod violation plugins (types.go:80-83)."""

    def deschedule(self, nodes, now: float) -> Status:
        raise NotImplementedError


class BalancePlugin(Plugin):
    """Whole-cluster rebalance plugins (types.go:85-88)."""

    def balance(self, nodes, now: float) -> Status:
        raise NotImplementedError


class FilterPlugin(Plugin):
    """Evictability gates (types.go:96-102)."""

    def filter(self, pod: Pod) -> bool:
        raise NotImplementedError

    def pre_eviction_filter(self, pod: Pod) -> bool:
        raise NotImplementedError


class EvictPlugin(Plugin):
    """Eviction executors (types.go:90-94)."""

    def evict(self, pod: Pod, plugin_name: str, reason: str) -> bool:
        raise NotImplementedError


class DefaultEvictor(FilterPlugin, EvictPlugin):
    """The defaultevictor adaptor
    (framework/plugins/kubernetes/defaultevictor/evictor.go): evictability
    filter chain + PDB guard via the shared eviction machinery."""

    name = "DefaultEvictor"

    def __init__(self, store: ObjectStore) -> None:
        self.store = store

    def filter(self, pod: Pod) -> bool:
        from koordinator_tpu.descheduler.evictions import is_evictable

        ok, _ = is_evictable(pod)
        return ok

    def pre_eviction_filter(self, pod: Pod) -> bool:
        from koordinator_tpu.descheduler.evictions import check_pdbs

        return check_pdbs(self.store, pod) is None

    def evict(self, pod: Pod, plugin_name: str, reason: str) -> bool:
        # "Evict evicts a pod (no pre-check performed)" (types.go:90-94): the
        # Handle already ran Filter + PreEvictionFilter, so re-running the
        # guard chain here would double the O(|PDBs| x |pods|) scan per
        # eviction — terminate directly
        from koordinator_tpu.descheduler.evictions import terminate_pod

        terminate_pod(self.store, pod, "koordinator.sh/evicted",
                      f"{plugin_name}: {reason}")
        return True


class Handle:
    """framework.Handle subset: the per-profile evictor façade plugins use
    (Evictor() in types.go:32-47). Filter -> PreEvictionFilter -> Evict."""

    def __init__(self, store: ObjectStore, filters: List[FilterPlugin],
                 evictor: EvictPlugin) -> None:
        self.store = store
        self.filters = filters
        self.evictor = evictor
        self.evicted_count = 0  # lifetime counter (callers diff it per cycle)

    def filter(self, pod: Pod) -> bool:
        return all(f.filter(pod) for f in self.filters)

    def pre_eviction_filter(self, pod: Pod) -> bool:
        return all(f.pre_eviction_filter(pod) for f in self.filters)

    def evict(self, pod: Pod, plugin_name: str, reason: str) -> bool:
        if not self.filter(pod) or not self.pre_eviction_filter(pod):
            return False
        if self.evictor.evict(pod, plugin_name, reason):
            self.evicted_count += 1
            return True
        return False


# plugin factories: name -> (store, args) -> Plugin
PluginFactory = Callable[[ObjectStore, Optional[dict]], Plugin]
_REGISTRY: Dict[str, PluginFactory] = {}


def register_plugin(name: str, factory: PluginFactory) -> None:
    _REGISTRY[name] = factory


def registered_plugins() -> List[str]:
    return sorted(_REGISTRY)


@dataclass
class ProfileConfig:
    """One descheduler profile (profile/profile.go): which plugins run at
    which extension point, with per-plugin args."""

    name: str = "default"
    deschedule: List[str] = field(default_factory=list)
    balance: List[str] = field(default_factory=list)
    filters: List[str] = field(default_factory=lambda: ["DefaultEvictor"])
    evictor: str = "DefaultEvictor"
    plugin_args: Dict[str, dict] = field(default_factory=dict)


class Profile:
    """Instantiated profile: resolved plugin objects + its Handle."""

    def __init__(self, config: ProfileConfig, store: ObjectStore) -> None:
        self.config = config
        self.store = store

        def build(name: str) -> Plugin:
            if name not in _REGISTRY:
                raise ValueError(
                    f"descheduler plugin {name!r} not registered "
                    f"(have: {registered_plugins()})"
                )
            return _REGISTRY[name](store, config.plugin_args.get(name))

        self.filter_plugins = [build(n) for n in config.filters]
        evictor = build(config.evictor)
        if not isinstance(evictor, EvictPlugin):
            raise ValueError(f"{config.evictor} is not an EvictPlugin")
        self.handle = Handle(store, self.filter_plugins, evictor)
        self.deschedule_plugins: List[DeschedulePlugin] = []
        self.balance_plugins: List[BalancePlugin] = []
        for n in config.deschedule:
            p = build(n)
            p.handle = self.handle
            self.deschedule_plugins.append(p)
        for n in config.balance:
            p = build(n)
            p.handle = self.handle
            self.balance_plugins.append(p)

    def run(self, now: float) -> Dict[str, Status]:
        """RunDeschedulePlugins then RunBalancePlugins (descheduler.go)."""
        nodes = self.store.list(KIND_NODE)
        out: Dict[str, Status] = {}
        for p in self.deschedule_plugins:
            out[p.name] = p.deschedule(nodes, now)
        for p in self.balance_plugins:
            out[p.name] = p.balance(nodes, now)
        return out
