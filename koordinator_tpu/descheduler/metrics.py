"""Descheduler metrics registry (analog of reference pkg/descheduler/metrics/).

Same shared Registry class as the koordlet and scheduler registries, so all
three binaries expose the identical Prometheus text format through
`obs.server.ObsServer` and one scrape config covers the deployment."""

from __future__ import annotations

from koordinator_tpu.koordlet.metrics import Registry

REGISTRY = Registry()

CYCLE_SECONDS = REGISTRY.histogram(
    "koord_descheduler_cycle_seconds",
    "End-to-end descheduling round latency (profiles + migration)",
)
MIGRATION_JOBS_CREATED_TOTAL = REGISTRY.counter(
    "koord_descheduler_migration_jobs_created_total",
    "PodMigrationJob CRs created by profile plugins",
)
MIGRATION_TRANSITIONS_TOTAL = REGISTRY.counter(
    "koord_descheduler_migration_transitions_total",
    "PodMigrationJob state transitions executed by the controller",
)
PODS_EVICTED_TOTAL = REGISTRY.counter(
    "koord_descheduler_pods_evicted_total",
    "Pods evicted by descheduling, labeled by profile",
)
# koordbalance (balance/): the device-resident rebalance pass
REBALANCE_CANDIDATES = REGISTRY.counter(
    "koord_descheduler_rebalance_candidates_total",
    "Movable pods on overloaded nodes considered by rebalance passes",
)
REBALANCE_VICTIMS = REGISTRY.counter(
    "koord_descheduler_rebalance_victims_total",
    "Victims selected by rebalance passes (migration-job candidates)",
)
REBALANCE_PASS_SECONDS = REGISTRY.histogram(
    "koord_descheduler_rebalance_pass_seconds",
    "Rebalance victim-selection pass latency (device or host engine)",
)

# koordwatch (obs/timeline.py): a STANDALONE descheduler's private
# device timeline records into this registry so its own /metrics shows
# the windows; a co-located descheduler shares the scheduler's timeline
# (and that registry's series) instead
DEVICE_WINDOW_SECONDS = REGISTRY.histogram(
    "koord_device_window_seconds",
    "Device-window dispatch-to-last-sync interval, labeled by consumer "
    "and path",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
)
DEVICE_IDLE_FRACTION = REGISTRY.gauge(
    "koord_device_idle_fraction",
    "Gap time between consecutive device windows over wall time",
)
