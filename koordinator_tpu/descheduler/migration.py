"""Migration controller + arbitrator.

Analog of reference `pkg/descheduler/controllers/migration/`:
  * Arbitrator (arbitrator/arbitrator.go:46-200): sorts pending jobs (creation
    time) and filters by blast-radius rate limits — max concurrent migrations
    per node / namespace / workload owner.
  * Reconciler (controller.go:241-383): per job, ReservationFirst mode creates
    a Reservation for the victim's replacement, waits for it to be scheduled
    (Available), then evicts the victim; EvictDirectly skips the reserve leg.
    Jobs expire after their TTL.
"""

from __future__ import annotations

import math

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodMigrationJob,
    PodSpec,
    Reservation,
    ReservationOwner,
)
from koordinator_tpu.client.store import (
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    KIND_RESERVATION,
    ObjectStore,
)


@dataclass
class ArbitratorArgs:
    max_migrating_per_node: int = 2
    max_migrating_per_namespace: int = 10
    max_migrating_per_workload: int = 1


class Arbitrator:
    def __init__(self, store: ObjectStore, args: Optional[ArbitratorArgs] = None):
        self.store = store
        self.args = args or ArbitratorArgs()

    def arbitrate(self, jobs: List[PodMigrationJob]) -> List[PodMigrationJob]:
        """Sort + rate-limit filter; returns the admitted subset in order."""
        running = [
            j for j in self.store.list(KIND_POD_MIGRATION_JOB)
            if j.phase == "Running"
        ]
        per_node: Dict[str, int] = {}
        per_ns: Dict[str, int] = {}
        per_workload: Dict[str, int] = {}

        def pod_of(job: PodMigrationJob) -> Optional[Pod]:
            return self.store.get(KIND_POD, f"{job.pod_namespace}/{job.pod_name}")

        for j in running:
            pod = pod_of(j)
            if pod is None:
                continue
            per_node[pod.spec.node_name] = per_node.get(pod.spec.node_name, 0) + 1
            per_ns[pod.meta.namespace] = per_ns.get(pod.meta.namespace, 0) + 1
            wl = f"{pod.meta.owner_kind}/{pod.meta.owner_name}"
            per_workload[wl] = per_workload.get(wl, 0) + 1

        def eviction_cost(pod: Optional[Pod]) -> int:
            """scheduling.koordinator.sh/eviction-cost (descheduling.go):
            cheaper pods migrate first; int32-max opts the pod out entirely
            (FilterPodWithMaxEvictionCost); malformed values cost 0."""
            if pod is None:
                return 0
            raw = pod.meta.annotations.get(
                "scheduling.koordinator.sh/eviction-cost")
            if raw is None:
                return 0
            try:
                value = float(raw)
                if not math.isfinite(value):
                    return 0
                return int(value)
            except (TypeError, ValueError):
                return 0

        MAX_INT32 = 2**31 - 1
        # one (cost, pod) lookup per job: the sort key, the opt-out check,
        # and the admission loop all read it
        job_info = {id(j): (eviction_cost(pod_of(j)), pod_of(j))
                    for j in jobs}
        admitted: List[PodMigrationJob] = []
        for job in sorted(jobs, key=lambda j: (job_info[id(j)][0],
                                               j.meta.creation_timestamp,
                                               j.meta.key)):
            cost, pod = job_info[id(job)]
            if cost >= MAX_INT32:
                continue  # opted out of migration
            if pod is None or not pod.is_assigned or pod.is_terminated:
                continue
            node = pod.spec.node_name
            ns = pod.meta.namespace
            wl = f"{pod.meta.owner_kind}/{pod.meta.owner_name}"
            if per_node.get(node, 0) >= self.args.max_migrating_per_node:
                continue
            if per_ns.get(ns, 0) >= self.args.max_migrating_per_namespace:
                continue
            if pod.meta.owner_kind and per_workload.get(wl, 0) >= self.args.max_migrating_per_workload:
                continue
            per_node[node] = per_node.get(node, 0) + 1
            per_ns[ns] = per_ns.get(ns, 0) + 1
            per_workload[wl] = per_workload.get(wl, 0) + 1
            admitted.append(job)
        return admitted


class MigrationController:
    def __init__(self, store: ObjectStore, arbitrator: Optional[Arbitrator] = None,
                 evictor: Optional[object] = None):
        from koordinator_tpu.descheduler.evictions import (
            ControllerFinder,
            EvictionAPIEvictor,
        )

        self.store = store
        self.arbitrator = arbitrator or Arbitrator(store)
        self.evictor = evictor or EvictionAPIEvictor(store)
        self.finder = ControllerFinder(store)

    def reconcile(self, now: Optional[float] = None) -> int:
        """One pass over migration jobs; returns state transitions."""
        now = time.time() if now is None else now
        changes = 0
        pending = [
            j for j in self.store.list(KIND_POD_MIGRATION_JOB)
            if j.phase == "Pending"
        ]
        for job in self.arbitrator.arbitrate(pending):
            job.phase = "Running"
            self.store.update(KIND_POD_MIGRATION_JOB, job)
            changes += 1

        for job in self.store.list(KIND_POD_MIGRATION_JOB):
            if job.phase != "Running":
                continue
            if now - job.meta.creation_timestamp > job.ttl_seconds:
                changes += self._fail(job, "timeout")
                continue
            pod = self.store.get(KIND_POD, f"{job.pod_namespace}/{job.pod_name}")
            if pod is None or pod.is_terminated:
                job.phase = "Succeeded"
                self.store.update(KIND_POD_MIGRATION_JOB, job)
                changes += 1
                continue
            if not pod.is_assigned:
                # pod fell back to pending (binding rolled back): wait without
                # rewriting the unchanged job every pass
                continue
            if job.mode == "ReservationFirst":
                changes += self._reserve_then_evict(job, pod, now)
            else:
                changes += self._finish_with_eviction(job, pod)
        return changes

    def _finish_with_eviction(self, job: PodMigrationJob, pod: Pod) -> int:
        """Evict through the configured evictor; a blocked eviction fails the
        job with the block reason (PDB violation, non-evictable pod)."""
        from koordinator_tpu.descheduler.evictions import EvictionBlocked

        # single-replica workload guard (controllerfinder): evicting the only
        # healthy member would take the workload to zero
        workload = self.finder.workload_of(pod)
        if workload.workload and workload.healthy <= 1:
            return self._fail(job, "workload has a single healthy replica")
        try:
            self.evictor.evict(pod, f"migration/{job.meta.name}")
        except EvictionBlocked as e:
            return self._fail(job, str(e))
        job.phase = "Succeeded"
        self.store.update(KIND_POD_MIGRATION_JOB, job)
        return 1

    def _fail(self, job: PodMigrationJob, message: str) -> int:
        """Fail the job, releasing its replacement reservation if one was
        created (the reference controller aborts the reservation with the
        job; leaving it Available would strand owner-locked capacity)."""
        if job.reservation_name:
            self.store.delete(KIND_RESERVATION, f"/{job.reservation_name}")
        job.phase = "Failed"
        job.message = message
        self.store.update(KIND_POD_MIGRATION_JOB, job)
        return 1

    def _reserve_then_evict(self, job: PodMigrationJob, pod: Pod, now: float) -> int:
        from koordinator_tpu.api.objects import ANNOTATION_DECISION_ID

        if not job.reservation_name:
            # create the replacement reservation (controller.go:763-846).
            # koordwatch: the job's decision id rides onto the
            # Reservation, so the scheduler-side consumption of the
            # migration (nomination pre-pass) joins back to the
            # rebalance window that decided it.
            decision_id = job.meta.annotations.get(ANNOTATION_DECISION_ID)
            res = Reservation(
                meta=ObjectMeta(
                    name=f"migrate-{pod.meta.namespace}-{pod.meta.name}",
                    namespace="",
                    creation_timestamp=now,
                    annotations=(
                        {ANNOTATION_DECISION_ID: decision_id}
                        if decision_id else {}),
                ),
                template=PodSpec(
                    priority=pod.spec.priority,
                    requests=pod.spec.requests.copy(),
                ),
                owners=[
                    ReservationOwner(
                        controller_kind=pod.meta.owner_kind,
                        controller_name=pod.meta.owner_name,
                        namespace=pod.meta.namespace,
                    )
                    if pod.meta.owner_kind
                    else ReservationOwner(label_selector=dict(pod.meta.labels))
                ],
                ttl_seconds=job.ttl_seconds,
            )
            if self.store.get(KIND_RESERVATION, res.meta.key) is None:
                self.store.add(KIND_RESERVATION, res)
            job.reservation_name = res.meta.name
            self.store.update(KIND_POD_MIGRATION_JOB, job)
            return 1
        res = self.store.get(KIND_RESERVATION, f"/{job.reservation_name}")
        if res is None or res.phase == "Failed":
            return self._fail(job, "reservation failed or lost")
        if res.phase == "Succeeded" and res.node_name:
            # the allocate-once reservation was already consumed by an
            # owner-matched replica (another pod of the same workload
            # took the reserved spot first): the workload holds the
            # replacement capacity, so the migration completes with the
            # eviction — waiting would only wedge the job until its TTL
            if res.node_name == pod.spec.node_name:
                return self._fail(job,
                                  "reservation landed on the source node")
            return self._finish_with_eviction(job, pod)
        if not res.is_available:
            return 0  # wait for the scheduler to bind the reservation
        # replacement capacity secured away from the source -> evict
        if res.node_name == pod.spec.node_name:
            return self._fail(job, "reservation landed on the source node")
        return self._finish_with_eviction(job, pod)
