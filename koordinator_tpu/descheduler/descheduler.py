"""Descheduler profile runner.

Analog of reference `pkg/descheduler/descheduler.go` + `pkg/descheduler/profile/`:
each configured profile owns a plugin set (Deschedule/Balance/Evict/Filter,
framework/types.go:32-110) and runs every interval — Deschedule plugins first,
then Balance plugins — followed by the migration controller that executes the
PodMigrationJob CRs the plugins created (reserve-then-evict)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import koordinator_tpu.descheduler.plugins_k8s  # noqa: F401  (registers plugins)
from koordinator_tpu.client.store import ObjectStore
from koordinator_tpu.descheduler import metrics as descheduler_metrics
from koordinator_tpu.descheduler.framework import Profile, ProfileConfig
from koordinator_tpu.descheduler.lownodeload import LowNodeLoadArgs
from koordinator_tpu.descheduler.migration import MigrationController

DEFAULT_PROFILE = ProfileConfig(
    name="koord-descheduler",
    balance=["LowNodeLoad"],
)


class Descheduler:
    def __init__(
        self,
        store: ObjectStore,
        low_node_load_args: Optional[LowNodeLoadArgs] = None,
        profiles: Optional[List[ProfileConfig]] = None,
        elector=None,
        scheduler=None,
        rebalance: Optional[str] = None,
    ):
        self.store = store
        # active/standby gating (cmd/koord-descheduler mirrors the scheduler's
        # leader election): with an elector, run_once acts only on the leader
        self.elector = elector
        if profiles is None:
            profiles = [DEFAULT_PROFILE]
        if low_node_load_args is not None:
            import dataclasses

            profiles = [
                dataclasses.replace(
                    p,
                    plugin_args={
                        **p.plugin_args,
                        "LowNodeLoad": dataclasses.asdict(low_node_load_args),
                    },
                )
                if "LowNodeLoad" in p.balance
                else p
                for p in profiles
            ]
        self.profiles = [Profile(cfg, store) for cfg in profiles]
        self.migration = MigrationController(store)
        # ---- koordbalance wiring (balance/): the descheduler as the
        # SECOND consumer of the scheduler's snapshot. With a co-located
        # `scheduler`, LowNodeLoad's packed view comes from the
        # scheduler's SnapshotCache subscription chain (one encode) and
        # the device pass uploads through the scheduler's DeviceSnapshot
        # (one mirror). KOORD_TPU_REBALANCE=on|off|host picks the
        # engine; "on" (default) attaches the DeviceRebalancer with the
        # host-oracle fallback ladder underneath.
        from koordinator_tpu.balance.rebalancer import rebalance_from_env

        self.scheduler = scheduler
        self.rebalance_mode = (rebalance_from_env() if rebalance is None
                               else rebalance)
        if self.rebalance_mode not in ("on", "off", "host"):
            raise ValueError(
                f"rebalance must be 'on', 'off' or 'host'; "
                f"got {self.rebalance_mode!r}")
        self.rebalancer = None
        self._wire_rebalance()

    def _wire_rebalance(self) -> None:
        from koordinator_tpu.balance.rebalancer import DeviceRebalancer

        snapshot_cache = (getattr(self.scheduler, "snapshot_cache", None)
                          if self.scheduler is not None else None)
        for profile in self.profiles:
            for plugin in profile.balance_plugins:
                if plugin.name != "LowNodeLoad":
                    continue
                plugin.enabled = self.rebalance_mode != "off"
                inner = plugin.inner
                if snapshot_cache is not None:
                    inner.pack_cache = snapshot_cache.rebalance_pack(
                        inner.args.node_metric_expiration_seconds)
                if self.rebalance_mode != "on":
                    continue
                if self.rebalancer is None:
                    if self.scheduler is not None:
                        mesh = getattr(self.scheduler,
                                       "_configured_mesh", None)
                        getter = lambda: self.scheduler.device_snapshot  # noqa: E731
                        # a co-located rebalancer shares the scheduler's
                        # RESOLVED deadline (koordguard): a sim that
                        # pins the scheduler's deadline off must not
                        # have the rebalance pass re-read the env and
                        # demote non-deterministically
                        dl = getattr(self.scheduler,
                                     "dispatch_deadline_seconds", None)
                        deadline_ms = dl * 1000.0 if dl else 0
                        self.rebalancer = DeviceRebalancer(
                            mesh=mesh, snapshot_getter=getter,
                            dispatch_deadline_ms=deadline_ms,
                            # koordwatch: the co-located pass records
                            # into the SCHEDULER's device timeline —
                            # one device, one ring, one id sequence
                            timeline=getattr(self.scheduler,
                                             "timeline", None))
                    else:
                        from koordinator_tpu.parallel.mesh import (
                            mesh_from_env,
                        )

                        self.rebalancer = DeviceRebalancer(
                            mesh=mesh_from_env(), snapshot_getter=None)
                inner.attach_device(self.rebalancer)

    def run_once(self, now: Optional[float] = None) -> dict:
        from koordinator_tpu.client.store import KIND_POD_MIGRATION_JOB

        now = time.time() if now is None else now
        if self.elector is not None and not self.elector.tick(now):
            return {"skipped_not_leader": True, "jobs_created": 0,
                    "migration_transitions": 0, "profiles": {}, "evicted": {}}
        t_start = time.perf_counter()
        statuses: Dict[str, Dict[str, Optional[str]]] = {}
        evicted_before = {
            p.config.name: p.handle.evicted_count for p in self.profiles
        }
        jobs_before = len(self.store.list(KIND_POD_MIGRATION_JOB))
        for profile in self.profiles:
            statuses[profile.config.name] = {
                name: s.err for name, s in profile.run(now).items()
            }
        jobs_created = len(self.store.list(KIND_POD_MIGRATION_JOB)) - jobs_before
        transitions = self.migration.reconcile(now)
        evicted = {
            p.config.name: p.handle.evicted_count - evicted_before[p.config.name]
            for p in self.profiles
        }
        descheduler_metrics.CYCLE_SECONDS.observe(
            time.perf_counter() - t_start)
        if jobs_created:
            descheduler_metrics.MIGRATION_JOBS_CREATED_TOTAL.inc(jobs_created)
        if transitions:
            descheduler_metrics.MIGRATION_TRANSITIONS_TOTAL.inc(transitions)
        for profile_name, delta in evicted.items():
            if delta:
                descheduler_metrics.PODS_EVICTED_TOTAL.inc(
                    delta, profile=profile_name)
        return {
            "jobs_created": jobs_created,
            "migration_transitions": transitions,
            "profiles": statuses,
            "evicted": evicted,
        }
