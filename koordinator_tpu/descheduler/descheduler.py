"""Descheduler profile runner.

Analog of reference `pkg/descheduler/descheduler.go` + `framework/types.go:76-96`
(DeschedulePlugin/BalancePlugin interfaces + profiles): runs registered balance
plugins each interval, then drives the migration controller."""

from __future__ import annotations

import time
from typing import List, Optional

from koordinator_tpu.client.store import ObjectStore
from koordinator_tpu.descheduler.lownodeload import LowNodeLoad, LowNodeLoadArgs
from koordinator_tpu.descheduler.migration import MigrationController


class Descheduler:
    def __init__(self, store: ObjectStore,
                 low_node_load_args: Optional[LowNodeLoadArgs] = None):
        self.store = store
        self.balance_plugins = [LowNodeLoad(store, low_node_load_args)]
        self.migration = MigrationController(store)

    def run_once(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        jobs = []
        for plugin in self.balance_plugins:
            jobs.extend(plugin.balance(now))
        transitions = self.migration.reconcile(now)
        return {"jobs_created": len(jobs), "migration_transitions": transitions}
