"""Vendored-style descheduler plugins, implemented natively.

Reference routes these through the sigs.k8s.io/descheduler adaptor
(`pkg/descheduler/framework/plugins/kubernetes/plugin.go:60-`); here they run
directly against the ObjectStore through the profile Handle's
Filter -> PreEvictionFilter -> Evict chain.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, List

from koordinator_tpu.api.objects import Node, Pod
from koordinator_tpu.client.store import KIND_POD, ObjectStore
from koordinator_tpu.descheduler.framework import (
    BalancePlugin,
    DeschedulePlugin,
    Status,
    register_plugin,
)


def _live_assigned(store: ObjectStore) -> List[Pod]:
    return [
        p for p in store.list(KIND_POD)
        if p.is_assigned and not p.is_terminated
    ]


def node_matches_pod(node: Node, pod: Pod) -> bool:
    """nodeSelector + required node affinity against current node labels
    (nodeaffinity.go utils.PodMatchesNodeSelectorAndAffinityTerms)."""
    for k, v in pod.spec.node_selector.items():
        if node.meta.labels.get(k) != v:
            return False
    for k, v in pod.spec.affinity_required_node_labels.items():
        if node.meta.labels.get(k) != v:
            return False
    return True


class RemovePodsViolatingNodeAffinity(DeschedulePlugin):
    """Evict pods whose node no longer satisfies their required node
    affinity/selector (sigs.k8s.io removepodsviolatingnodeaffinity:
    requiredDuringSchedulingIgnoredDuringExecution re-checked at runtime).
    Only evicts when some OTHER node currently matches, so the pod has
    somewhere to go (the upstream feasibility pre-check)."""

    name = "RemovePodsViolatingNodeAffinity"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.handle = None  # injected by Profile

    def deschedule(self, nodes: List[Node], now: float) -> Status:
        by_name = {n.meta.name: n for n in nodes}
        for pod in _live_assigned(self.store):
            if not pod.spec.node_selector and \
                    not pod.spec.affinity_required_node_labels:
                continue
            node = by_name.get(pod.spec.node_name)
            if node is None or node_matches_pod(node, pod):
                continue
            if not any(
                node_matches_pod(n, pod)
                for n in nodes
                if n.meta.name != pod.spec.node_name and not n.unschedulable
            ):
                continue  # nowhere to go; leave it running
            self.handle.evict(pod, self.name, "node affinity violated")
        return Status()


class RemoveDuplicates(BalancePlugin):
    """Spread duplicate workload replicas: when one node runs more than one
    replica of the same controller and spare nodes exist, evict the extras so
    the scheduler can spread them (sigs.k8s.io removeduplicates)."""

    name = "RemoveDuplicates"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.handle = None

    def balance(self, nodes: List[Node], now: float) -> Status:
        schedulable = [n for n in nodes if not n.unschedulable]
        if len(schedulable) < 2:
            return Status()
        # (namespace, owner) -> node -> replicas
        groups: Dict[tuple, Dict[str, List[Pod]]] = defaultdict(
            lambda: defaultdict(list)
        )
        for pod in _live_assigned(self.store):
            if not pod.meta.owner_kind or not pod.meta.owner_name:
                continue
            key = (pod.meta.namespace, pod.meta.owner_kind, pod.meta.owner_name)
            groups[key][pod.spec.node_name].append(pod)
        for key, by_node in groups.items():
            for node_name, replicas in by_node.items():
                if len(replicas) <= 1:
                    continue
                # keep the oldest replica; evict the rest (upstream keeps one
                # per node and lets the scheduler respread) — but only when
                # some OTHER schedulable node can host the pod, else the
                # evict/reschedule-back loop churns the workload forever
                replicas.sort(key=lambda p: p.meta.creation_timestamp)
                for pod in replicas[1:]:
                    if not any(
                        n.meta.name != node_name and node_matches_pod(n, pod)
                        for n in schedulable
                    ):
                        continue
                    self.handle.evict(pod, self.name, "duplicate replica")
        return Status()


class PodLifeTime(DeschedulePlugin):
    """Evict pods older than maxPodLifeTimeSeconds, optionally restricted to
    pod phases (sigs.k8s.io podlifetime: PodLifeTimeArgs.MaxPodLifeTimeSeconds
    + States)."""

    name = "PodLifeTime"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        args = args or {}
        if "maxPodLifeTimeSeconds" not in args:
            # upstream validation treats the parameter as required; a silent
            # default would start evicting cluster-wide on an empty config
            raise ValueError("PodLifeTime requires maxPodLifeTimeSeconds")
        self.store = store
        self.handle = None
        self.max_seconds = float(args["maxPodLifeTimeSeconds"])
        self.states = set(args.get("states", []))  # empty = any phase

    def deschedule(self, nodes: List[Node], now: float) -> Status:
        for pod in _live_assigned(self.store):
            if self.states and pod.phase not in self.states:
                continue
            age = now - pod.meta.creation_timestamp
            if age > self.max_seconds:
                self.handle.evict(
                    pod, self.name,
                    f"pod lifetime {age:.0f}s exceeds {self.max_seconds:.0f}s",
                )
        return Status()


class RemoveFailedPods(DeschedulePlugin):
    """Evict Failed pods so their controllers can recreate them fresh
    (sigs.k8s.io removefailedpods: reasons filter, minPodLifetimeSeconds,
    excludeOwnerKinds)."""

    name = "RemoveFailedPods"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        args = args or {}
        self.store = store
        self.handle = None
        self.reasons = set(args.get("reasons", []))  # empty = any reason
        self.min_lifetime = float(args.get("minPodLifetimeSeconds", 0))
        self.exclude_owner_kinds = set(args.get("excludeOwnerKinds", []))
        # upstream defaultevictor EvictFailedBarePods: bare failed pods have
        # no controller to recreate them, so deleting destroys the failure
        # record — opt-in only
        self.evict_failed_bare_pods = bool(args.get("evictFailedBarePods",
                                                    False))

    def deschedule(self, nodes: List[Node], now: float) -> Status:
        for pod in self.store.list(KIND_POD):
            if pod.phase != "Failed" or not pod.is_assigned:
                continue
            if self.reasons and pod.reason not in self.reasons:
                continue
            if pod.meta.owner_kind in self.exclude_owner_kinds:
                continue
            if now - pod.meta.creation_timestamp < self.min_lifetime:
                continue
            # a Failed pod is already terminated, which the evictor chain
            # categorically refuses — but every OTHER evictability guard
            # (opt-out annotation, DaemonSet, system-critical priority, any
            # profile-configured FilterPlugins) still applies: run the full
            # chain on a view with the phase neutralized, then delete
            # (upstream's eviction of a failed pod IS deletion)
            from koordinator_tpu.descheduler.evictions import (
                ANNOTATION_EVICTABLE,
            )

            if pod.meta.annotations.get(ANNOTATION_EVICTABLE) == "false":
                continue  # explicit opt-out holds even without a Profile
            if not pod.meta.owner_kind:
                if not self.evict_failed_bare_pods:
                    continue
                # EvictFailedBarePods waives ONLY the bare-pod rule: fake an
                # owner on the view so the rest of the chain still runs
                view_meta = dataclasses.replace(
                    pod.meta, owner_kind="__evict-failed-bare__"
                )
            else:
                view_meta = pod.meta
            view = dataclasses.replace(pod, phase="Running", meta=view_meta)
            if self.handle is not None and not self.handle.filter(view):
                continue
            self.store.delete(KIND_POD, pod.meta.key)
            if self.handle is not None:
                self.handle.evicted_count += 1
        return Status()


class RemovePodsHavingTooManyRestarts(DeschedulePlugin):
    """Evict crash-looping pods past a restart threshold (sigs.k8s.io
    removepodshavingtoomanyrestarts: PodRestartThreshold)."""

    name = "RemovePodsHavingTooManyRestarts"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        args = args or {}
        self.store = store
        self.handle = None
        self.threshold = int(args.get("podRestartThreshold", 100))

    def deschedule(self, nodes: List[Node], now: float) -> Status:
        for pod in _live_assigned(self.store):
            if pod.restart_count >= self.threshold:
                self.handle.evict(
                    pod, self.name,
                    f"{pod.restart_count} restarts >= {self.threshold}",
                )
        return Status()


class RemovePodsViolatingNodeTaints(DeschedulePlugin):
    """Evict pods that no longer tolerate their node's taints (sigs.k8s.io
    removepodsviolatingnodetaints; taints carry NoSchedule semantics in this
    model)."""

    name = "RemovePodsViolatingNodeTaints"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.handle = None

    @staticmethod
    def _tolerates(pod: Pod, node: Node) -> bool:
        from koordinator_tpu.ops.taints import tolerates_taints

        return tolerates_taints(pod.spec.tolerations, node.taints)

    def deschedule(self, nodes: List[Node], now: float) -> Status:
        by_name = {n.meta.name: n for n in nodes}
        for pod in _live_assigned(self.store):
            node = by_name.get(pod.spec.node_name)
            if node is None or not node.taints:
                continue
            if self._tolerates(pod, node):
                continue
            # feasibility pre-check (same guard as the affinity/duplicates
            # plugins): evict only when some OTHER schedulable node could
            # host the pod, else the evict/reschedule-back loop churns it
            if not any(
                n.meta.name != pod.spec.node_name
                and not n.unschedulable
                and self._tolerates(pod, n)
                and node_matches_pod(n, pod)
                for n in nodes
            ):
                continue
            self.handle.evict(pod, self.name, "node taints not tolerated")
        return Status()


class RemovePodsViolatingInterPodAntiAffinity(DeschedulePlugin):
    """Evict pods whose required anti-affinity terms are violated by a
    co-located pod in the same topology domain (sigs.k8s.io
    removepodsviolatinginterpodantiaffinity). Runtime violations appear when
    pods were placed before the constraint existed or labels changed."""

    name = "RemovePodsViolatingInterPodAntiAffinity"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.handle = None

    def deschedule(self, nodes: List[Node], now: float) -> Status:
        from koordinator_tpu.ops.podaffinity import _pod_matches, _term_key

        by_name = {n.meta.name: n for n in nodes}
        live = _live_assigned(self.store)
        evicted: set = set()
        for pod in live:
            if not pod.spec.pod_anti_affinity or pod.meta.key in evicted:
                continue
            node = by_name.get(pod.spec.node_name)
            if node is None:
                continue
            violated = False
            for raw in pod.spec.pod_anti_affinity:
                term = _term_key(raw, pod)
                dom = node.meta.labels.get(raw.topology_key)
                if dom is None:
                    continue
                for other in live:
                    # pods evicted earlier in this pass no longer violate —
                    # evicting ONE of a mutually-violating pair resolves it
                    if other.meta.key == pod.meta.key or \
                            other.meta.key in evicted:
                        continue
                    other_node = by_name.get(other.spec.node_name)
                    if other_node is None or \
                            other_node.meta.labels.get(
                                raw.topology_key) != dom:
                        continue
                    if _pod_matches(term, other):
                        violated = True
                        break
                if violated:
                    break
            if violated and self.handle.evict(
                    pod, self.name, "anti-affinity violated"):
                evicted.add(pod.meta.key)
        return Status()


class RemovePodsViolatingTopologySpreadConstraint(BalancePlugin):
    """Evict pods from over-populated topology domains until every
    DoNotSchedule spread constraint's skew fits maxSkew again
    (sigs.k8s.io removepodsviolatingtopologyspreadconstraint)."""

    name = "RemovePodsViolatingTopologySpreadConstraint"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.handle = None

    def balance(self, nodes: List[Node], now: float) -> Status:
        from koordinator_tpu.ops.podaffinity import _pod_matches, _spread_key

        by_name = {n.meta.name: n for n in nodes}
        live = _live_assigned(self.store)
        # group constraints by (term identity, maxSkew): all pods carrying
        # the same constraint share one skew computation
        carriers: Dict[tuple, List[Pod]] = defaultdict(list)
        for pod in live:
            for con in pod.spec.topology_spread:
                # ScheduleAnyway is advisory scoring — the scheduler may
                # legitimately exceed its skew; enforcing it here would
                # evict/re-place in a loop (upstream includeSoftConstraints
                # defaults to false)
                if con.when_unsatisfiable == "ScheduleAnyway":
                    continue
                carriers[(_spread_key(con, pod), int(con.max_skew))].append(
                    pod)
        for (term, max_skew), constrained in carriers.items():
            topology_key = term[2]
            # a domain counts toward the minimum only if a SCHEDULABLE node
            # in it could host one of the constrained pods — the same
            # eligibility stance the scheduler's spread filter takes, so
            # the two sides can never evict/re-place in a loop (a forbidden
            # or fully-cordoned zone cannot pin the minimum at 0)
            domains: Dict[str, List[Pod]] = {}
            for n in nodes:
                val = n.meta.labels.get(topology_key)
                if val is None or n.unschedulable:
                    continue
                if any(node_matches_pod(n, p) for p in constrained):
                    domains.setdefault(val, [])
            if not domains:
                continue
            for other in live:
                node = by_name.get(other.spec.node_name)
                if node is None:
                    continue
                val = node.meta.labels.get(topology_key)
                if val in domains and _pod_matches(term, other):
                    domains[val].append(other)
            counts = {d: len(ps) for d, ps in domains.items()}
            min_count = min(counts.values())
            for dom, pods_in in sorted(domains.items()):
                excess = counts[dom] - (min_count + max_skew)
                if excess <= 0:
                    continue
                victims = sorted(
                    pods_in, key=lambda p: p.meta.creation_timestamp,
                    reverse=True)[:excess]
                for pod in victims:
                    self.handle.evict(
                        pod, self.name,
                        f"topology skew {counts[dom] - min_count} > "
                        f"maxSkew {max_skew} in {topology_key}={dom}")
        return Status()


class HighNodeUtilization(BalancePlugin):
    """Bin-packing consolidation: evict movable pods from UNDER-utilized
    nodes so the cluster can be compacted (sigs.k8s.io
    highnodeutilization — the inverse of LowNodeLoad's spreading)."""

    name = "HighNodeUtilization"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.args = args or {}
        self.handle = None

    def balance(self, nodes: List[Node], now: float) -> Status:
        from koordinator_tpu.client.store import KIND_NODE_METRIC

        threshold = float(self.args.get("cpu_threshold_percent", 20))
        under = []
        for node in nodes:
            if node.unschedulable:
                continue
            nm = self.store.get(KIND_NODE_METRIC, f"/{node.meta.name}")
            if nm is None:
                continue
            cap = node.allocatable.get("cpu", 0)
            used = nm.node_metric.node_usage.get("cpu")
            if cap and used is not None and used * 100.0 / cap < threshold:
                under.append(node)
        schedulable = [n for n in nodes if not n.unschedulable]
        if len(under) < 1 or len(under) == len(schedulable):
            return Status()  # nothing to consolidate onto
        under_names = {n.meta.name for n in under}
        # absorb budget: spare cpu on the nodes pods would consolidate onto
        # (upstream stops when target capacity runs out — evicting more
        # than fits would churn: the scheduler puts the rest back)
        requested_by_node: Dict[str, int] = defaultdict(int)
        live = _live_assigned(self.store)
        for pod in live:
            requested_by_node[pod.spec.node_name] += \
                pod.spec.requests.get("cpu", 0)
        spare = sum(
            max(n.allocatable.get("cpu", 0)
                - requested_by_node[n.meta.name], 0)
            for n in schedulable if n.meta.name not in under_names
        )
        for pod in live:
            if pod.spec.node_name not in under_names:
                continue
            need = pod.spec.requests.get("cpu", 0)
            if need > spare:
                continue
            if self.handle.evict(
                    pod, self.name, "under-utilized node consolidation"):
                spare -= need
        return Status()


def register_defaults() -> None:
    """Install the built-in plugin set into the framework registry."""
    from koordinator_tpu.descheduler.framework import DefaultEvictor
    from koordinator_tpu.descheduler.lownodeload import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )

    register_plugin("DefaultEvictor", lambda store, args: DefaultEvictor(store))
    register_plugin(
        "RemovePodsViolatingNodeAffinity",
        lambda store, args: RemovePodsViolatingNodeAffinity(store, args),
    )
    register_plugin(
        "RemoveDuplicates", lambda store, args: RemoveDuplicates(store, args)
    )
    register_plugin(
        "PodLifeTime", lambda store, args: PodLifeTime(store, args)
    )
    register_plugin(
        "RemoveFailedPods", lambda store, args: RemoveFailedPods(store, args)
    )
    register_plugin(
        "RemovePodsHavingTooManyRestarts",
        lambda store, args: RemovePodsHavingTooManyRestarts(store, args),
    )
    register_plugin(
        "RemovePodsViolatingNodeTaints",
        lambda store, args: RemovePodsViolatingNodeTaints(store, args),
    )
    register_plugin(
        "RemovePodsViolatingInterPodAntiAffinity",
        lambda store, args: RemovePodsViolatingInterPodAntiAffinity(
            store, args),
    )
    register_plugin(
        "RemovePodsViolatingTopologySpreadConstraint",
        lambda store, args: RemovePodsViolatingTopologySpreadConstraint(
            store, args),
    )
    register_plugin(
        "HighNodeUtilization",
        lambda store, args: HighNodeUtilization(store, args),
    )
    register_plugin(
        "LowNodeLoad",
        lambda store, args: _LowNodeLoadAdapter(
            store, LowNodeLoadArgs(**args) if args else None
        ),
    )


class _LowNodeLoadAdapter(BalancePlugin):
    """BalancePlugin facade over the batched LowNodeLoad classifier (it
    creates PodMigrationJob CRs; the migration controller evicts).
    ``enabled`` is the KOORD_TPU_REBALANCE=off kill switch (the
    Descheduler wires it); the other plugins keep running."""

    name = "LowNodeLoad"

    def __init__(self, store: ObjectStore, args=None) -> None:
        from koordinator_tpu.descheduler.lownodeload import LowNodeLoad

        self.inner = LowNodeLoad(store, args)
        self.handle = None
        self.enabled = True

    def balance(self, nodes, now: float) -> Status:
        if not self.enabled:
            return Status()
        self.inner.balance(now)
        return Status()


register_defaults()
