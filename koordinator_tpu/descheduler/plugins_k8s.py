"""Vendored-style descheduler plugins, implemented natively.

Reference routes these through the sigs.k8s.io/descheduler adaptor
(`pkg/descheduler/framework/plugins/kubernetes/plugin.go:60-`); here they run
directly against the ObjectStore through the profile Handle's
Filter -> PreEvictionFilter -> Evict chain.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List

from koordinator_tpu.api.objects import Node, Pod
from koordinator_tpu.client.store import KIND_POD, ObjectStore
from koordinator_tpu.descheduler.framework import (
    BalancePlugin,
    DeschedulePlugin,
    Status,
    register_plugin,
)


def _live_assigned(store: ObjectStore) -> List[Pod]:
    return [
        p for p in store.list(KIND_POD)
        if p.is_assigned and not p.is_terminated
    ]


def node_matches_pod(node: Node, pod: Pod) -> bool:
    """nodeSelector + required node affinity against current node labels
    (nodeaffinity.go utils.PodMatchesNodeSelectorAndAffinityTerms)."""
    for k, v in pod.spec.node_selector.items():
        if node.meta.labels.get(k) != v:
            return False
    for k, v in pod.spec.affinity_required_node_labels.items():
        if node.meta.labels.get(k) != v:
            return False
    return True


class RemovePodsViolatingNodeAffinity(DeschedulePlugin):
    """Evict pods whose node no longer satisfies their required node
    affinity/selector (sigs.k8s.io removepodsviolatingnodeaffinity:
    requiredDuringSchedulingIgnoredDuringExecution re-checked at runtime).
    Only evicts when some OTHER node currently matches, so the pod has
    somewhere to go (the upstream feasibility pre-check)."""

    name = "RemovePodsViolatingNodeAffinity"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.handle = None  # injected by Profile

    def deschedule(self, nodes: List[Node], now: float) -> Status:
        by_name = {n.meta.name: n for n in nodes}
        for pod in _live_assigned(self.store):
            if not pod.spec.node_selector and \
                    not pod.spec.affinity_required_node_labels:
                continue
            node = by_name.get(pod.spec.node_name)
            if node is None or node_matches_pod(node, pod):
                continue
            if not any(
                node_matches_pod(n, pod)
                for n in nodes
                if n.meta.name != pod.spec.node_name and not n.unschedulable
            ):
                continue  # nowhere to go; leave it running
            self.handle.evict(pod, self.name, "node affinity violated")
        return Status()


class RemoveDuplicates(BalancePlugin):
    """Spread duplicate workload replicas: when one node runs more than one
    replica of the same controller and spare nodes exist, evict the extras so
    the scheduler can spread them (sigs.k8s.io removeduplicates)."""

    name = "RemoveDuplicates"

    def __init__(self, store: ObjectStore, args: dict = None) -> None:
        self.store = store
        self.handle = None

    def balance(self, nodes: List[Node], now: float) -> Status:
        schedulable = [n for n in nodes if not n.unschedulable]
        if len(schedulable) < 2:
            return Status()
        # (namespace, owner) -> node -> replicas
        groups: Dict[tuple, Dict[str, List[Pod]]] = defaultdict(
            lambda: defaultdict(list)
        )
        for pod in _live_assigned(self.store):
            if not pod.meta.owner_kind or not pod.meta.owner_name:
                continue
            key = (pod.meta.namespace, pod.meta.owner_kind, pod.meta.owner_name)
            groups[key][pod.spec.node_name].append(pod)
        for key, by_node in groups.items():
            for node_name, replicas in by_node.items():
                if len(replicas) <= 1:
                    continue
                # keep the oldest replica; evict the rest (upstream keeps one
                # per node and lets the scheduler respread) — but only when
                # some OTHER schedulable node can host the pod, else the
                # evict/reschedule-back loop churns the workload forever
                replicas.sort(key=lambda p: p.meta.creation_timestamp)
                for pod in replicas[1:]:
                    if not any(
                        n.meta.name != node_name and node_matches_pod(n, pod)
                        for n in schedulable
                    ):
                        continue
                    self.handle.evict(pod, self.name, "duplicate replica")
        return Status()


def register_defaults() -> None:
    """Install the built-in plugin set into the framework registry."""
    from koordinator_tpu.descheduler.framework import DefaultEvictor
    from koordinator_tpu.descheduler.lownodeload import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )

    register_plugin("DefaultEvictor", lambda store, args: DefaultEvictor(store))
    register_plugin(
        "RemovePodsViolatingNodeAffinity",
        lambda store, args: RemovePodsViolatingNodeAffinity(store, args),
    )
    register_plugin(
        "RemoveDuplicates", lambda store, args: RemoveDuplicates(store, args)
    )
    register_plugin(
        "LowNodeLoad",
        lambda store, args: _LowNodeLoadAdapter(
            store, LowNodeLoadArgs(**args) if args else None
        ),
    )


class _LowNodeLoadAdapter(BalancePlugin):
    """BalancePlugin facade over the batched LowNodeLoad classifier (it
    creates PodMigrationJob CRs; the migration controller evicts)."""

    name = "LowNodeLoad"

    def __init__(self, store: ObjectStore, args=None) -> None:
        from koordinator_tpu.descheduler.lownodeload import LowNodeLoad

        self.inner = LowNodeLoad(store, args)
        self.handle = None

    def balance(self, nodes, now: float) -> Status:
        self.inner.balance(now)
        return Status()


register_defaults()
