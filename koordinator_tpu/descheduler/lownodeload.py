"""LowNodeLoad: utilization-based rebalancing.

Analog of reference `pkg/descheduler/framework/plugins/loadaware/low_node_load.go`
+ `utilization_util.go`: classify nodes by MEASURED utilization (NodeMetric CR)
into low (below lowThresholds on every resource) and high (above highThresholds
on any); evict movable pods from high nodes while capacity remains on low nodes.

Two engines over one packed view (balance/pack.RebalancePack — the
event-maintained arrays, shared with the scheduler's SnapshotCache when
both run in one process):

  * ``select_victims_host`` — the host numpy oracle: one stable lexsort
    + per-segment freed-prefix math, victim-set-identical to the serial
    C++ floor (bench.py --chain rebalance diffs them every run). This
    is the diagnose-style REFERENCE the device pass is gated against,
    the way ``host_stage_counts`` is for koordexplain.
  * the device tensor pass (balance/step.py via an attached
    :class:`~koordinator_tpu.balance.rebalancer.DeviceRebalancer`) —
    the same classification + selection as one jitted batched program
    on the (mesh-shardable) device mirror, decision-parity gated by
    ``pipeline_parity.run_rebalance_parity`` at mesh 1/2/4/8, with the
    PR 7 degradation ladder falling back to the host oracle on faults.

`KOORD_TPU_REBALANCE=on|off|host` picks the engine at the Descheduler
level (descheduler/descheduler.py wires the rebalancer in); a bare
``LowNodeLoad(store)`` stays pure host, so standalone descheduler
deployments and unit fixtures never touch jax.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod, PodMigrationJob, ObjectMeta
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
)
from koordinator_tpu.balance.pack import RebalancePack, has_pdb_like_guard
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    ObjectStore,
)
from koordinator_tpu.obs import Tracer

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]


@dataclass
class LowNodeLoadArgs:
    low_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 45.0, ResourceName.MEMORY: 55.0}
    )
    high_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 70.0, ResourceName.MEMORY: 80.0}
    )
    max_pods_to_evict_per_node: int = 5
    node_metric_expiration_seconds: float = 300.0


def classify_nodes(
    usage_percent: np.ndarray,   # [N, R] measured utilization percent
    has_metric: np.ndarray,      # [N]
    low_thr: np.ndarray,         # [R] (0 = unchecked)
    high_thr: np.ndarray,        # [R]
) -> Tuple[np.ndarray, np.ndarray]:
    """(is_low[N], is_high[N]) — vectorized utilization_util.go classification."""
    checked = low_thr > 0
    low = np.all(~checked | (usage_percent < low_thr), axis=-1) & has_metric
    checked_h = high_thr > 0
    high = np.any(checked_h & (usage_percent > high_thr), axis=-1) & has_metric
    return low & ~high, high


class LowNodeLoad:
    name = "LowNodeLoad"

    def __init__(self, store: ObjectStore, args: Optional[LowNodeLoadArgs] = None,
                 incremental: bool = True, pack: Optional[RebalancePack] = None,
                 device=None):
        self.store = store
        self.args = args or LowNodeLoadArgs()
        # the packed view: an explicitly shared pack (SnapshotCache
        # deployments — one encode, two consumers) wins; otherwise the
        # per-store singleton, created LAZILY on the first view so the
        # Descheduler can swap the shared pack in post-construction
        # without having orphaned a store-subscribed singleton;
        # incremental=False keeps the cold walk
        self.pack_cache: Optional[RebalancePack] = pack
        self._lazy_pack = incremental and pack is None
        # DeviceRebalancer (balance/rebalancer.py): None = host oracle
        self.device = None
        self.tracer = Tracer()
        self.last_pass_stats: Dict[str, object] = {}
        if device is not None:
            self.attach_device(device)

    def attach_device(self, device) -> None:
        """Wire a DeviceRebalancer in; its tracer becomes the plugin's
        so classify/score/readback land under the ``rebalance`` root."""
        self.device = device
        self.tracer = device.tracer

    def _thr_vec(self, thr: Dict[str, float]) -> np.ndarray:
        v = np.zeros(NUM_RESOURCES, np.float32)
        for name, t in thr.items():
            v[RESOURCE_INDEX[name]] = t
        return v

    def _cold_view(self, now: float):
        """Walk-everything packing (incremental=False path); same array
        contract as RebalancePack.view."""
        nodes: List[Node] = self.store.list(KIND_NODE)
        N = len(nodes)
        alloc = np.zeros((N, NUM_RESOURCES), np.float32)
        usage_pct = np.zeros((N, NUM_RESOURCES), np.float32)
        has_metric = np.zeros(N, bool)
        node_idx = {}
        for i, node in enumerate(nodes):
            node_idx[node.meta.name] = i
            alloc[i] = node.allocatable.to_vector()
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}")
            if nm is None or nm.update_time <= 0:
                continue
            if now - nm.update_time >= self.args.node_metric_expiration_seconds:
                continue
            usage = nm.node_metric.node_usage.to_vector()
            a = alloc[i]
            with np.errstate(divide="ignore", invalid="ignore"):
                usage_pct[i] = np.where(
                    a > 0, usage * 100.0 / np.maximum(a, 1e-9), 0.0)
            has_metric[i] = True
        pods = [p for p in self.store.list(KIND_POD)
                if p.is_assigned and not p.is_terminated]
        return {
            "alloc": alloc,
            "usage_pct": usage_pct,
            "has_metric": has_metric,
            "pod_alive": np.ones(len(pods), bool),
            "pod_node": np.asarray(
                [node_idx.get(p.spec.node_name, -1) for p in pods],
                np.int64),
            "pod_prio": np.asarray(
                [p.spec.priority or 0 for p in pods], np.int64),
            "pod_cpu": np.asarray(
                [p.spec.requests[ResourceName.CPU] for p in pods],
                np.float32),
            "pod_req": (np.stack([p.spec.requests.to_vector() for p in pods])
                        if pods else np.zeros((0, NUM_RESOURCES), np.float32)),
            "pod_movable": np.asarray(
                [p.meta.owner_kind != "DaemonSet"
                 and not has_pdb_like_guard(p) for p in pods], bool),
        }, pods

    def _view(self, now: float):
        if self.pack_cache is None and self._lazy_pack:
            self.pack_cache = RebalancePack.for_store(
                self.store, self.args.node_metric_expiration_seconds)
        if self.pack_cache is not None:
            return self.pack_cache.view(now), self.pack_cache.pod_ref
        v, pods_cold = self._cold_view(now)
        return v, pods_cold

    def select_victims(self, now: Optional[float] = None):
        """The TIMED rebalance pass: pure array math on the packed view.
        Returns (picked slot indices, slot->Pod source, view) — victim
        materialization, PodMigrationJob construction and store writes all
        happen in balance(), outside this pass, exactly as the reference's
        job creation is API-server work outside utilization_util.go's
        math (and the C++ floor's output is victim flags, not objects).
        With a DeviceRebalancer attached the pass runs on device
        (decision-identical; ladder falls back to the host oracle)."""
        now = time.time() if now is None else now
        v, pods_src = self._view(now)
        if v["alloc"].shape[0] == 0:
            self.last_pass_stats = {"engine": "host", "candidates": 0,
                                    "victims": 0}
            return np.zeros(0, np.int64), pods_src, v
        if self.device is not None:
            picked, stats = self.device.select_victims(self, v, now)
            self.last_pass_stats = stats
            return picked, pods_src, v
        t0 = time.perf_counter()
        with self.tracer.span("score", host="1"):
            picked = self.select_victims_host(v)
        from koordinator_tpu.descheduler import metrics as dm

        dm.REBALANCE_PASS_SECONDS.observe(time.perf_counter() - t0)
        cands = int(self.last_pass_stats.get("candidates", 0))
        if cands:
            dm.REBALANCE_CANDIDATES.inc(cands)
        if picked.size:
            dm.REBALANCE_VICTIMS.inc(int(picked.size))
        return picked, pods_src, v

    def select_victims_host(self, v: dict) -> np.ndarray:
        """The host numpy oracle over a packed view: classification +
        the vectorized greedy victim selection. The device pass's
        decision reference (see module doc); also sets
        ``last_pass_stats``."""
        empty = np.zeros(0, np.int64)
        self.last_pass_stats = {"engine": "host", "candidates": 0,
                                "victims": 0}
        if v["alloc"].shape[0] == 0:
            return empty
        is_low, is_high = classify_nodes(
            v["usage_pct"], v["has_metric"],
            self._thr_vec(self.args.low_thresholds),
            self._thr_vec(self.args.high_thresholds),
        )
        if not is_high.any() or not is_low.any():
            return empty

        # ---- victim selection, vectorized: one stable lexsort over
        # (node, priority asc, cpu desc) + per-segment exclusive prefix of
        # freed requests replaces the reference's per-node Go loops. The
        # greedy serial rule "take sorted candidates while the node stays
        # over any checked high threshold, capped per node" becomes:
        # candidate k is selected iff rank < cap AND every earlier
        # candidate in its segment kept the node over (prefix-AND via a
        # cumsum-of-failures == 0 test). Victim sets are identical to the
        # serial pass (bench.py --chain rebalance diffs them vs the C++
        # floor every run).
        target_pct = self._thr_vec(self.args.high_thresholds)
        usage_pct = v["usage_pct"]
        over_gate = (usage_pct - target_pct[None, :] > 0).any(axis=1)
        node_ok = is_high & over_gate
        cand_mask = (v["pod_alive"] & v["pod_movable"]
                     & (v["pod_node"] >= 0)
                     & node_ok[np.maximum(v["pod_node"], 0)])
        cand = np.nonzero(cand_mask)[0]
        self.last_pass_stats["candidates"] = int(cand.size)
        if cand.size == 0:
            return empty
        node_arr = v["pod_node"][cand]
        prio = v["pod_prio"][cand]
        cpu = v["pod_cpu"][cand]
        C = cand.size
        # (node, prio asc, cpu desc) order: when the key ranges fit one
        # int64 (the overwhelmingly common case — node ids, bounded
        # priorities, milli-cpu), ONE stable argsort of a composite key
        # replaces np.lexsort's three passes; the exact lexsort stays as
        # the general fallback
        cpu_i = cpu.astype(np.int64)
        pmin = int(prio.min()) if C else 0
        pspan = int(prio.max()) - pmin + 1 if C else 1
        cspan = int(cpu_i.max()) + 1 if C else 1
        nspan = int(node_arr.max()) + 1 if C else 1
        if (np.all(cpu_i == cpu)
                and float(nspan) * pspan * cspan < float(2 ** 62)):
            key = ((node_arr * pspan + (prio - pmin)) * cspan
                   + (cspan - 1 - cpu_i))
            order = np.argsort(key, kind="stable")
        else:
            order = np.lexsort((-cpu, prio, node_arr))
        node_s = node_arr[order]
        seg_start = np.zeros(C, bool)
        seg_start[0] = True
        seg_start[1:] = node_s[1:] != node_s[:-1]
        starts = np.nonzero(seg_start)[0]
        seg_id = np.cumsum(seg_start) - 1
        # only the CHECKED axes (high_thr > 0 — cpu+mem by default) enter
        # the freed/still-over math: slicing the request matrix to them
        # cuts the heavy [C, R] traffic ~5x at R=10
        chk = np.nonzero(target_pct > 0)[0]
        # exclusive freed-requests prefix per segment as ONE global float64
        # cumsum minus segment offsets. float64 accumulation mirrors the
        # C++ floor (double) and the reference's int64 quantity math; for
        # the integer-valued packed requests the kernel discipline already
        # requires, the re-association is exact, so victim parity holds.
        reqs_s = v["pod_req"][np.ix_(cand[order], chk)].astype(np.float64)
        gcum = np.cumsum(reqs_s, axis=0)
        excl = np.concatenate(
            [np.zeros((1, reqs_s.shape[1])), gcum[:-1]], axis=0)
        freed_excl = excl - excl[starts][seg_id]
        rank = np.arange(C) - starts[seg_id]
        # still-over in MULTIPLY form: usage - freed*100/alloc > thr
        # <=> freed*100 < (usage - thr) * alloc for alloc > 0. The rhs is
        # precomputed per NODE ([N, chk], tiny) instead of per candidate,
        # and the division disappears; the C++ floor computes the identical
        # double expression, so the comparison is bit-deterministic on both
        # sides. The device pass (balance/step.py) ships the same rhs as
        # two float32 limbs and decides the identical comparison.
        alloc_chk = np.maximum(v["alloc"][:, chk], np.float32(1e-9))
        rhs = ((usage_pct[:, chk].astype(np.float64)
                - target_pct[chk].astype(np.float64))
               * alloc_chk.astype(np.float64))
        still_over = (freed_excl * 100.0 < rhs[node_s]).any(axis=1)
        fails = np.cumsum(~still_over)
        seg_off = np.concatenate(([0], fails[starts[1:] - 1]))
        prefix_ok = (fails - seg_off[seg_id]) == 0
        selected = prefix_ok & (rank < self.args.max_pods_to_evict_per_node)
        picked = cand[order[np.nonzero(selected)[0]]]
        self.last_pass_stats["victims"] = int(picked.size)
        return picked

    def balance(self, now: Optional[float] = None) -> List[PodMigrationJob]:
        from koordinator_tpu.api.objects import ANNOTATION_DECISION_ID

        now = time.time() if now is None else now
        with self.tracer.span("rebalance"):
            picked, pods_src, _v = self.select_victims(now)
            # koordwatch decision correlation: the pass's decision id
            # (minted per device/host rebalance window) rides every job
            # it issued, and the migration controller copies it onto the
            # replacement Reservation — flight records, timeline windows
            # and store objects join on it
            decision_id = self.last_pass_stats.get("decision_id")
            jobs: List[PodMigrationJob] = []
            with self.tracer.span("migrate",
                                  victims=str(int(len(picked)))):
                for k in picked:
                    pod = pods_src[k]
                    job = PodMigrationJob(
                        meta=ObjectMeta(
                            name=f"migrate-{pod.meta.namespace}-{pod.meta.name}",
                            namespace="koordinator-system",
                            creation_timestamp=now,
                            annotations=(
                                {ANNOTATION_DECISION_ID: str(decision_id)}
                                if decision_id else {}),
                        ),
                        pod_namespace=pod.meta.namespace,
                        pod_name=pod.meta.name,
                        mode="ReservationFirst",
                    )
                    if self.store.get(KIND_POD_MIGRATION_JOB,
                                      job.meta.key) is None:
                        self.store.add(KIND_POD_MIGRATION_JOB, job)
                        jobs.append(job)
        return jobs


def _has_pdb_like_guard(pod: Pod) -> bool:
    # back-compat alias; the predicate moved to balance/pack.py with the
    # shared pack
    return has_pdb_like_guard(pod)


def pack_floor_inputs(store: ObjectStore, plugin: LowNodeLoad,
                      now: float):
    """Pack the store into the arrays `native.floor.lownodeload_floor_native`
    consumes, with the SAME classification inputs balance() sees. One home
    for this encoding — bench.py --chain rebalance and the non-dyadic
    parity regression both call it, so the floor and the plugin can never
    drift onto different encodings silently.

    Returns (pods list, dict of keyword arrays for the floor call)."""
    nodes = store.list(KIND_NODE)
    node_idx = {n.meta.name: i for i, n in enumerate(nodes)}
    alloc = np.stack([n.allocatable.to_vector() for n in nodes])
    usage_pct = np.zeros_like(alloc, np.float32)
    has_metric = np.zeros(len(nodes), np.int32)
    for i, node in enumerate(nodes):
        nm = store.get(KIND_NODE_METRIC, f"/{node.meta.name}")
        if nm is None or nm.update_time <= 0:
            continue
        if now - nm.update_time >= plugin.args.node_metric_expiration_seconds:
            continue
        a = alloc[i]
        u = nm.node_metric.node_usage.to_vector()
        usage_pct[i] = np.where(a > 0, u * 100.0 / np.maximum(a, 1e-9), 0.0)
        has_metric[i] = 1
    pods = [p for p in store.list(KIND_POD)
            if p.is_assigned and not p.is_terminated]
    pod_req = np.stack([p.spec.requests.to_vector() for p in pods]) \
        if pods else np.zeros((0, NUM_RESOURCES), np.float32)
    arrays = dict(
        alloc=alloc,
        usage_pct=usage_pct,
        has_metric=has_metric,
        low_thr=plugin._thr_vec(plugin.args.low_thresholds),
        high_thr=plugin._thr_vec(plugin.args.high_thresholds),
        pod_node=np.asarray(
            [node_idx.get(p.spec.node_name, -1) for p in pods], np.int32),
        pod_prio=np.asarray([p.spec.priority or 0 for p in pods], np.int32),
        pod_req=pod_req,
        movable=np.asarray(
            [p.meta.owner_kind != "DaemonSet" and not has_pdb_like_guard(p)
             for p in pods], np.int32),
        pod_sort_cpu=np.asarray(
            [p.spec.requests[ResourceName.CPU] for p in pods], np.float32),
        max_evict_per_node=plugin.args.max_pods_to_evict_per_node,
    )
    return pods, arrays
