"""LowNodeLoad: utilization-based rebalancing.

Analog of reference `pkg/descheduler/framework/plugins/loadaware/low_node_load.go`
+ `utilization_util.go`: classify nodes by MEASURED utilization (NodeMetric CR)
into low (below lowThresholds on every resource) and high (above highThresholds
on any); evict movable pods from high nodes while capacity remains on low nodes.

Batched formulation: classification is one [N, R] compare; victim-fit against
low nodes reuses the scheduler's one-shot score-matrix kernel
(models/scheduler_model.build_score_matrix) in "all candidate pods x low nodes"
mode — BASELINE config 5's 50k-pod global rebalance runs as a single device
pass instead of per-pod Go loops."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod, PodMigrationJob, ObjectMeta
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    ObjectStore,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]

# store -> {expiration -> RebalancePackCache}; weak so stores die normally
import weakref  # noqa: E402

_PACK_CACHES: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


@dataclass
class LowNodeLoadArgs:
    low_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 45.0, ResourceName.MEMORY: 55.0}
    )
    high_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 70.0, ResourceName.MEMORY: 80.0}
    )
    max_pods_to_evict_per_node: int = 5
    node_metric_expiration_seconds: float = 300.0


def classify_nodes(
    usage_percent: np.ndarray,   # [N, R] measured utilization percent
    has_metric: np.ndarray,      # [N]
    low_thr: np.ndarray,         # [R] (0 = unchecked)
    high_thr: np.ndarray,        # [R]
) -> Tuple[np.ndarray, np.ndarray]:
    """(is_low[N], is_high[N]) — vectorized utilization_util.go classification."""
    checked = low_thr > 0
    low = np.all(~checked | (usage_percent < low_thr), axis=-1) & has_metric
    checked_h = high_thr > 0
    high = np.any(checked_h & (usage_percent > high_thr), axis=-1) & has_metric
    return low & ~high, high


class RebalancePackCache:
    """Event-maintained packed arrays for the rebalance pass.

    The reference keeps incremental caches and walks them per run
    (utilization_util.go reads informer caches, not the API server); the
    batch analog keeps the pod/node state PACKED so `select_victims` is
    pure array math — the store walk and object packing move out of the
    per-pass cost entirely. Slots are append-only (compacted when >50%
    dead) so masked views preserve store insertion order, which the
    stable lexsort relies on for exact victim-set parity with the serial
    C++ floor."""

    _GROW = 1024

    @classmethod
    def for_store(cls, store: ObjectStore,
                  expiration_seconds: float) -> "RebalancePackCache":
        """One cache per (store, expiration): ObjectStore has no
        unsubscribe, so every construction would leak a live handler —
        repeat LowNodeLoad constructions on the same store (per-pass
        plugin re-inits) must share the subscription."""
        by_exp = _PACK_CACHES.setdefault(store, {})
        cache = by_exp.get(expiration_seconds)
        if cache is None:
            cache = cls(store, expiration_seconds)
            by_exp[expiration_seconds] = cache
        return cache

    def __init__(self, store: ObjectStore,
                 expiration_seconds: float) -> None:
        self.store = store
        self.expiration = expiration_seconds
        # node side
        self._node_names: List[str] = []
        self._node_idx: Dict[str, int] = {}
        self.alloc = np.zeros((0, NUM_RESOURCES), np.float32)
        self.usage_pct = np.zeros((0, NUM_RESOURCES), np.float32)
        self.nm_time = np.zeros(0, np.float64)
        self.has_raw = np.zeros(0, bool)
        self._nodes_stale = True
        # pod side (append-only slots)
        self._slot: Dict[str, int] = {}
        self._cap = 0
        self._len = 0
        self._dead = 0
        self.pod_alive = np.zeros(0, bool)
        self.pod_node_name: List[Optional[str]] = []
        self.pod_node = np.zeros(0, np.int64)
        self._pod_node_stale = True
        self.pod_prio = np.zeros(0, np.int64)
        self.pod_cpu = np.zeros(0, np.float32)
        self.pod_req = np.zeros((0, NUM_RESOURCES), np.float32)
        self.pod_movable = np.zeros(0, bool)
        self.pod_ref: List[Optional[Pod]] = []
        store.subscribe(KIND_NODE, self._on_node)
        store.subscribe(KIND_NODE_METRIC, self._on_metric)
        store.subscribe(KIND_POD, self._on_pod)

    # -- events --------------------------------------------------------
    def _on_node(self, ev, node, old) -> None:
        self._nodes_stale = True

    def _on_metric(self, ev, nm, old) -> None:
        # metric rows refresh lazily with the node table; a metric-only
        # update just recomputes that row
        self._nodes_stale = True

    def _on_pod(self, ev, pod: Pod, old) -> None:
        from koordinator_tpu.client.store import EventType

        key = pod.meta.key
        slot = self._slot.get(key)
        live = (ev is not EventType.DELETED and pod.is_assigned
                and not pod.is_terminated)
        if not live:
            if slot is not None and self.pod_alive[slot]:
                self.pod_alive[slot] = False
                self.pod_ref[slot] = None
                self._dead += 1
            if ev is EventType.DELETED:
                # a deleted-then-recreated pod must land in a FRESH slot:
                # the store dict re-inserts it at the end, and slot order
                # must track store insertion order for sort-parity with
                # the cold pass / C++ floor (terminated-in-place pods keep
                # their slot — the store preserves their dict position)
                self._slot.pop(key, None)
            return
        if slot is None:
            if self._len == self._cap:
                grow = max(self._GROW, self._cap)
                self.pod_alive = np.concatenate(
                    [self.pod_alive, np.zeros(grow, bool)])
                self.pod_node = np.concatenate(
                    [self.pod_node, np.full(grow, -1, np.int64)])
                self.pod_prio = np.concatenate(
                    [self.pod_prio, np.zeros(grow, np.int64)])
                self.pod_cpu = np.concatenate(
                    [self.pod_cpu, np.zeros(grow, np.float32)])
                self.pod_req = np.concatenate(
                    [self.pod_req,
                     np.zeros((grow, NUM_RESOURCES), np.float32)])
                self.pod_movable = np.concatenate(
                    [self.pod_movable, np.zeros(grow, bool)])
                self.pod_node_name.extend([None] * grow)
                self.pod_ref.extend([None] * grow)
                self._cap += grow
            slot = self._len
            self._slot[key] = slot
            self._len += 1
        elif not self.pod_alive[slot]:
            self._dead -= 1
        self.pod_alive[slot] = True
        self.pod_node_name[slot] = pod.spec.node_name
        self.pod_prio[slot] = pod.spec.priority or 0
        self.pod_cpu[slot] = pod.spec.requests[ResourceName.CPU]
        self.pod_req[slot] = pod.spec.requests.to_vector()
        self.pod_movable[slot] = (
            pod.meta.owner_kind != "DaemonSet"
            and not _has_pdb_like_guard(pod))
        self.pod_ref[slot] = pod
        self._pod_node_stale = True

    # -- refresh -------------------------------------------------------
    def _refresh_nodes(self) -> None:
        nodes = self.store.list(KIND_NODE)
        names = [n.meta.name for n in nodes]
        remap = names != self._node_names
        if remap:
            self._node_names = names
            self._node_idx = {n: i for i, n in enumerate(names)}
            self._pod_node_stale = True
        N = len(nodes)
        self.alloc = np.zeros((N, NUM_RESOURCES), np.float32)
        self.usage_pct = np.zeros((N, NUM_RESOURCES), np.float32)
        self.nm_time = np.zeros(N, np.float64)
        self.has_raw = np.zeros(N, bool)
        for i, node in enumerate(nodes):
            self.alloc[i] = node.allocatable.to_vector()
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}")
            if nm is None or nm.update_time <= 0:
                continue
            usage = nm.node_metric.node_usage.to_vector()
            a = self.alloc[i]
            with np.errstate(divide="ignore", invalid="ignore"):
                self.usage_pct[i] = np.where(
                    a > 0, usage * 100.0 / np.maximum(a, 1e-9), 0.0)
            self.nm_time[i] = nm.update_time
            self.has_raw[i] = True
        self._nodes_stale = False

    def _compact(self) -> None:
        keep = np.nonzero(self.pod_alive[: self._len])[0]
        self.pod_alive = np.concatenate(
            [np.ones(keep.size, bool), np.zeros(self._cap - keep.size, bool)])
        for arr_name in ("pod_node", "pod_prio", "pod_cpu", "pod_movable"):
            arr = getattr(self, arr_name)
            packed = arr[keep]
            arr[: keep.size] = packed
            arr[keep.size:] = 0
        self.pod_req[: keep.size] = self.pod_req[keep]
        self.pod_req[keep.size:] = 0
        names = [self.pod_node_name[k] for k in keep]
        refs = [self.pod_ref[k] for k in keep]
        pad = self._cap - keep.size
        self.pod_node_name = names + [None] * pad
        self.pod_ref = refs + [None] * pad
        self._slot = {
            refs[j].meta.key: j for j in range(keep.size)
        }
        self._len = keep.size
        self._dead = 0

    def view(self, now: float):
        """(packed arrays dict) for select_victims — refreshes lazily."""
        if self._nodes_stale:
            self._refresh_nodes()
        if self._dead * 2 > max(1, self._len):
            self._compact()
        if self._pod_node_stale:
            idx = self._node_idx
            for j in range(self._len):
                name = self.pod_node_name[j]
                self.pod_node[j] = idx.get(name, -1) if name else -1
            self._pod_node_stale = False
        has_metric = self.has_raw & (
            now - self.nm_time < self.expiration)
        return {
            "alloc": self.alloc,
            "usage_pct": self.usage_pct,
            "has_metric": has_metric,
            "pod_alive": self.pod_alive[: self._len],
            "pod_node": self.pod_node[: self._len],
            "pod_prio": self.pod_prio[: self._len],
            "pod_cpu": self.pod_cpu[: self._len],
            "pod_req": self.pod_req[: self._len],
            "pod_movable": self.pod_movable[: self._len],
        }


class LowNodeLoad:
    name = "LowNodeLoad"

    def __init__(self, store: ObjectStore, args: Optional[LowNodeLoadArgs] = None,
                 incremental: bool = True):
        self.store = store
        self.args = args or LowNodeLoadArgs()
        self.pack_cache = (
            RebalancePackCache.for_store(
                store, self.args.node_metric_expiration_seconds)
            if incremental else None)

    def _thr_vec(self, thr: Dict[str, float]) -> np.ndarray:
        v = np.zeros(NUM_RESOURCES, np.float32)
        for name, t in thr.items():
            v[RESOURCE_INDEX[name]] = t
        return v

    def _cold_view(self, now: float):
        """Walk-everything packing (incremental=False path); same array
        contract as RebalancePackCache.view."""
        nodes: List[Node] = self.store.list(KIND_NODE)
        N = len(nodes)
        alloc = np.zeros((N, NUM_RESOURCES), np.float32)
        usage_pct = np.zeros((N, NUM_RESOURCES), np.float32)
        has_metric = np.zeros(N, bool)
        node_idx = {}
        for i, node in enumerate(nodes):
            node_idx[node.meta.name] = i
            alloc[i] = node.allocatable.to_vector()
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}")
            if nm is None or nm.update_time <= 0:
                continue
            if now - nm.update_time >= self.args.node_metric_expiration_seconds:
                continue
            usage = nm.node_metric.node_usage.to_vector()
            a = alloc[i]
            with np.errstate(divide="ignore", invalid="ignore"):
                usage_pct[i] = np.where(
                    a > 0, usage * 100.0 / np.maximum(a, 1e-9), 0.0)
            has_metric[i] = True
        pods = [p for p in self.store.list(KIND_POD)
                if p.is_assigned and not p.is_terminated]
        return {
            "alloc": alloc,
            "usage_pct": usage_pct,
            "has_metric": has_metric,
            "pod_alive": np.ones(len(pods), bool),
            "pod_node": np.asarray(
                [node_idx.get(p.spec.node_name, -1) for p in pods],
                np.int64),
            "pod_prio": np.asarray(
                [p.spec.priority or 0 for p in pods], np.int64),
            "pod_cpu": np.asarray(
                [p.spec.requests[ResourceName.CPU] for p in pods],
                np.float32),
            "pod_req": (np.stack([p.spec.requests.to_vector() for p in pods])
                        if pods else np.zeros((0, NUM_RESOURCES), np.float32)),
            "pod_movable": np.asarray(
                [p.meta.owner_kind != "DaemonSet"
                 and not _has_pdb_like_guard(p) for p in pods], bool),
        }, pods

    def select_victims(self, now: Optional[float] = None):
        """The TIMED rebalance pass: pure array math on the packed view.
        Returns (picked slot indices, slot->Pod source, view) — victim
        materialization, PodMigrationJob construction and store writes all
        happen in balance(), outside this pass, exactly as the reference's
        job creation is API-server work outside utilization_util.go's
        math (and the C++ floor's output is victim flags, not objects)."""
        now = time.time() if now is None else now
        if self.pack_cache is not None:
            v = self.pack_cache.view(now)
            pods_src = self.pack_cache.pod_ref
        else:
            v, pods_cold = self._cold_view(now)
            pods_src = pods_cold
        empty = np.zeros(0, np.int64)
        if v["alloc"].shape[0] == 0:
            return empty, pods_src, v
        is_low, is_high = classify_nodes(
            v["usage_pct"], v["has_metric"],
            self._thr_vec(self.args.low_thresholds),
            self._thr_vec(self.args.high_thresholds),
        )
        if not is_high.any() or not is_low.any():
            return empty, pods_src, v

        # ---- victim selection, vectorized: one stable lexsort over
        # (node, priority asc, cpu desc) + per-segment exclusive prefix of
        # freed requests replaces the reference's per-node Go loops. The
        # greedy serial rule "take sorted candidates while the node stays
        # over any checked high threshold, capped per node" becomes:
        # candidate k is selected iff rank < cap AND every earlier
        # candidate in its segment kept the node over (prefix-AND via a
        # cumsum-of-failures == 0 test). Victim sets are identical to the
        # serial pass (bench.py --chain rebalance diffs them vs the C++
        # floor every run).
        target_pct = self._thr_vec(self.args.high_thresholds)
        usage_pct = v["usage_pct"]
        over_gate = (usage_pct - target_pct[None, :] > 0).any(axis=1)
        node_ok = is_high & over_gate
        cand_mask = (v["pod_alive"] & v["pod_movable"]
                     & (v["pod_node"] >= 0)
                     & node_ok[np.maximum(v["pod_node"], 0)])
        cand = np.nonzero(cand_mask)[0]
        if cand.size == 0:
            return empty, pods_src, v
        node_arr = v["pod_node"][cand]
        prio = v["pod_prio"][cand]
        cpu = v["pod_cpu"][cand]
        C = cand.size
        # (node, prio asc, cpu desc) order: when the key ranges fit one
        # int64 (the overwhelmingly common case — node ids, bounded
        # priorities, milli-cpu), ONE stable argsort of a composite key
        # replaces np.lexsort's three passes; the exact lexsort stays as
        # the general fallback
        cpu_i = cpu.astype(np.int64)
        pmin = int(prio.min()) if C else 0
        pspan = int(prio.max()) - pmin + 1 if C else 1
        cspan = int(cpu_i.max()) + 1 if C else 1
        nspan = int(node_arr.max()) + 1 if C else 1
        if (np.all(cpu_i == cpu)
                and float(nspan) * pspan * cspan < float(2 ** 62)):
            key = ((node_arr * pspan + (prio - pmin)) * cspan
                   + (cspan - 1 - cpu_i))
            order = np.argsort(key, kind="stable")
        else:
            order = np.lexsort((-cpu, prio, node_arr))
        node_s = node_arr[order]
        seg_start = np.zeros(C, bool)
        seg_start[0] = True
        seg_start[1:] = node_s[1:] != node_s[:-1]
        starts = np.nonzero(seg_start)[0]
        seg_id = np.cumsum(seg_start) - 1
        # only the CHECKED axes (high_thr > 0 — cpu+mem by default) enter
        # the freed/still-over math: slicing the request matrix to them
        # cuts the heavy [C, R] traffic ~5x at R=10
        chk = np.nonzero(target_pct > 0)[0]
        # exclusive freed-requests prefix per segment as ONE global float64
        # cumsum minus segment offsets. float64 accumulation mirrors the
        # C++ floor (double) and the reference's int64 quantity math; for
        # the integer-valued packed requests the kernel discipline already
        # requires, the re-association is exact, so victim parity holds.
        reqs_s = v["pod_req"][np.ix_(cand[order], chk)].astype(np.float64)
        gcum = np.cumsum(reqs_s, axis=0)
        excl = np.concatenate(
            [np.zeros((1, reqs_s.shape[1])), gcum[:-1]], axis=0)
        freed_excl = excl - excl[starts][seg_id]
        rank = np.arange(C) - starts[seg_id]
        # still-over in MULTIPLY form: usage - freed*100/alloc > thr
        # <=> freed*100 < (usage - thr) * alloc for alloc > 0. The rhs is
        # precomputed per NODE ([N, chk], tiny) instead of per candidate,
        # and the division disappears; the C++ floor computes the identical
        # double expression, so the comparison is bit-deterministic on both
        # sides.
        alloc_chk = np.maximum(v["alloc"][:, chk], np.float32(1e-9))
        rhs = ((usage_pct[:, chk].astype(np.float64)
                - target_pct[chk].astype(np.float64))
               * alloc_chk.astype(np.float64))
        still_over = (freed_excl * 100.0 < rhs[node_s]).any(axis=1)
        fails = np.cumsum(~still_over)
        seg_off = np.concatenate(([0], fails[starts[1:] - 1]))
        prefix_ok = (fails - seg_off[seg_id]) == 0
        selected = prefix_ok & (rank < self.args.max_pods_to_evict_per_node)
        picked = cand[order[np.nonzero(selected)[0]]]
        return picked, pods_src, v

    def balance(self, now: Optional[float] = None) -> List[PodMigrationJob]:
        now = time.time() if now is None else now
        picked, pods_src, _v = self.select_victims(now)
        jobs: List[PodMigrationJob] = []
        for k in picked:
            pod = pods_src[k]
            job = PodMigrationJob(
                meta=ObjectMeta(
                    name=f"migrate-{pod.meta.namespace}-{pod.meta.name}",
                    namespace="koordinator-system",
                    creation_timestamp=now,
                ),
                pod_namespace=pod.meta.namespace,
                pod_name=pod.meta.name,
                mode="ReservationFirst",
            )
            if self.store.get(KIND_POD_MIGRATION_JOB, job.meta.key) is None:
                self.store.add(KIND_POD_MIGRATION_JOB, job)
                jobs.append(job)
        return jobs


def _has_pdb_like_guard(pod: Pod) -> bool:
    return pod.meta.annotations.get("descheduler.alpha.kubernetes.io/evict") == "false"


def pack_floor_inputs(store: ObjectStore, plugin: LowNodeLoad,
                      now: float):
    """Pack the store into the arrays `native.floor.lownodeload_floor_native`
    consumes, with the SAME classification inputs balance() sees. One home
    for this encoding — bench.py --chain rebalance and the non-dyadic
    parity regression both call it, so the floor and the plugin can never
    drift onto different encodings silently.

    Returns (pods list, dict of keyword arrays for the floor call)."""
    nodes = store.list(KIND_NODE)
    node_idx = {n.meta.name: i for i, n in enumerate(nodes)}
    alloc = np.stack([n.allocatable.to_vector() for n in nodes])
    usage_pct = np.zeros_like(alloc, np.float32)
    has_metric = np.zeros(len(nodes), np.int32)
    for i, node in enumerate(nodes):
        nm = store.get(KIND_NODE_METRIC, f"/{node.meta.name}")
        if nm is None or nm.update_time <= 0:
            continue
        if now - nm.update_time >= plugin.args.node_metric_expiration_seconds:
            continue
        a = alloc[i]
        u = nm.node_metric.node_usage.to_vector()
        usage_pct[i] = np.where(a > 0, u * 100.0 / np.maximum(a, 1e-9), 0.0)
        has_metric[i] = 1
    pods = [p for p in store.list(KIND_POD)
            if p.is_assigned and not p.is_terminated]
    pod_req = np.stack([p.spec.requests.to_vector() for p in pods]) \
        if pods else np.zeros((0, NUM_RESOURCES), np.float32)
    arrays = dict(
        alloc=alloc,
        usage_pct=usage_pct,
        has_metric=has_metric,
        low_thr=plugin._thr_vec(plugin.args.low_thresholds),
        high_thr=plugin._thr_vec(plugin.args.high_thresholds),
        pod_node=np.asarray(
            [node_idx.get(p.spec.node_name, -1) for p in pods], np.int32),
        pod_prio=np.asarray([p.spec.priority or 0 for p in pods], np.int32),
        pod_req=pod_req,
        movable=np.asarray(
            [p.meta.owner_kind != "DaemonSet" and not _has_pdb_like_guard(p)
             for p in pods], np.int32),
        pod_sort_cpu=np.asarray(
            [p.spec.requests[ResourceName.CPU] for p in pods], np.float32),
        max_evict_per_node=plugin.args.max_pods_to_evict_per_node,
    )
    return pods, arrays
