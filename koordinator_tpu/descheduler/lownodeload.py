"""LowNodeLoad: utilization-based rebalancing.

Analog of reference `pkg/descheduler/framework/plugins/loadaware/low_node_load.go`
+ `utilization_util.go`: classify nodes by MEASURED utilization (NodeMetric CR)
into low (below lowThresholds on every resource) and high (above highThresholds
on any); evict movable pods from high nodes while capacity remains on low nodes.

Batched formulation: classification is one [N, R] compare; victim-fit against
low nodes reuses the scheduler's one-shot score-matrix kernel
(models/scheduler_model.build_score_matrix) in "all candidate pods x low nodes"
mode — BASELINE config 5's 50k-pod global rebalance runs as a single device
pass instead of per-pod Go loops."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod, PodMigrationJob, ObjectMeta
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    ObjectStore,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]


@dataclass
class LowNodeLoadArgs:
    low_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 45.0, ResourceName.MEMORY: 55.0}
    )
    high_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 70.0, ResourceName.MEMORY: 80.0}
    )
    max_pods_to_evict_per_node: int = 5
    node_metric_expiration_seconds: float = 300.0


def classify_nodes(
    usage_percent: np.ndarray,   # [N, R] measured utilization percent
    has_metric: np.ndarray,      # [N]
    low_thr: np.ndarray,         # [R] (0 = unchecked)
    high_thr: np.ndarray,        # [R]
) -> Tuple[np.ndarray, np.ndarray]:
    """(is_low[N], is_high[N]) — vectorized utilization_util.go classification."""
    checked = low_thr > 0
    low = np.all(~checked | (usage_percent < low_thr), axis=-1) & has_metric
    checked_h = high_thr > 0
    high = np.any(checked_h & (usage_percent > high_thr), axis=-1) & has_metric
    return low & ~high, high


class LowNodeLoad:
    name = "LowNodeLoad"

    def __init__(self, store: ObjectStore, args: Optional[LowNodeLoadArgs] = None):
        self.store = store
        self.args = args or LowNodeLoadArgs()

    def _thr_vec(self, thr: Dict[str, float]) -> np.ndarray:
        v = np.zeros(NUM_RESOURCES, np.float32)
        for name, t in thr.items():
            v[RESOURCE_INDEX[name]] = t
        return v

    def balance(self, now: Optional[float] = None) -> List[PodMigrationJob]:
        now = time.time() if now is None else now
        nodes: List[Node] = self.store.list(KIND_NODE)
        if not nodes:
            return []
        N = len(nodes)
        usage_pct = np.zeros((N, NUM_RESOURCES), np.float32)
        has_metric = np.zeros(N, bool)
        for i, node in enumerate(nodes):
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}"
            )
            if nm is None or nm.update_time <= 0:
                continue
            if now - nm.update_time >= self.args.node_metric_expiration_seconds:
                continue
            alloc = node.allocatable.to_vector()
            usage = nm.node_metric.node_usage.to_vector()
            with np.errstate(divide="ignore", invalid="ignore"):
                pct = np.where(alloc > 0, usage * 100.0 / np.maximum(alloc, 1e-9), 0.0)
            usage_pct[i] = pct
            has_metric[i] = True

        is_low, is_high = classify_nodes(
            usage_pct,
            has_metric,
            self._thr_vec(self.args.low_thresholds),
            self._thr_vec(self.args.high_thresholds),
        )
        if not is_high.any() or not is_low.any():
            return []

        # ---- victim selection, vectorized: one lexsort over (node,
        # priority asc, cpu desc) + per-segment exclusive cumsum of freed
        # requests replaces the reference's per-node Go loops. The greedy
        # serial rule "take sorted candidates while the node stays over any
        # checked high threshold, capped per node" becomes: candidate k is
        # selected iff rank < cap AND every earlier candidate in its
        # segment kept the node over (prefix-AND via a cumsum-of-failures
        # == 0 test). Identical victim sets to the serial pass
        # (bench.py --chain rebalance diffs them against the C++ floor).
        target_pct = self._thr_vec(self.args.high_thresholds)
        # per-node over-gate (max(usage - thr, 0).any()), hoisted once
        over_gate = (usage_pct - target_pct[None, :] > 0).any(axis=1)
        eligible = {
            nodes[i].meta.name: i
            for i in np.nonzero(is_high & over_gate)[0]
        }
        cand_pods: List[Pod] = []
        cand_node: List[int] = []
        for pod in self.store.list(KIND_POD):
            i = eligible.get(pod.spec.node_name)
            if i is None or not pod.is_assigned or pod.is_terminated:
                continue
            if pod.meta.owner_kind == "DaemonSet" or _has_pdb_like_guard(pod):
                continue
            cand_pods.append(pod)
            cand_node.append(i)
        jobs: List[PodMigrationJob] = []
        if not cand_pods:
            return jobs
        C = len(cand_pods)
        node_arr = np.asarray(cand_node, np.int64)
        prio = np.asarray([p.spec.priority or 0 for p in cand_pods], np.int64)
        cpu = np.asarray(
            [p.spec.requests[ResourceName.CPU] for p in cand_pods],
            np.float32)
        reqs = np.stack([p.spec.requests.to_vector() for p in cand_pods])
        order = np.lexsort((-cpu, prio, node_arr))  # node, prio asc, cpu desc
        node_s = node_arr[order]
        reqs_s = np.asarray(reqs[order], np.float32)
        seg_start = np.zeros(C, bool)
        seg_start[0] = True
        seg_start[1:] = node_s[1:] != node_s[:-1]
        starts = np.nonzero(seg_start)[0]
        seg_id = np.cumsum(seg_start) - 1
        # exclusive freed-requests prefix PER SEGMENT, as sequential f32
        # adds: a global cumsum minus segment offsets re-associates the
        # float32 sums and drifts from the serial accumulation right at the
        # still_over threshold (victim-set parity vs the C++ floor breaks)
        freed_excl = np.zeros_like(reqs_s)
        bounds = np.append(starts, C)
        for j in range(len(starts)):
            s0, s1 = bounds[j], bounds[j + 1]
            if s1 - s0 > 1:
                freed_excl[s0 + 1:s1] = np.cumsum(
                    reqs_s[s0:s1 - 1], axis=0, dtype=np.float32)
        # rank within segment
        rank = np.arange(C) - starts[seg_id]
        alloc_s = np.stack([nodes[i].allocatable.to_vector()
                            for i in node_s]).astype(np.float32)
        checked = target_pct > 0
        still_over = (
            (usage_pct[node_s] - freed_excl * 100.0 / np.maximum(alloc_s, 1e-9)
             > target_pct) & checked
        ).any(axis=1)
        # prefix rule: selected while EVERY candidate so far (inclusive)
        # still saw the node over — cumsum of failures within the segment
        fails = np.cumsum(~still_over)
        prefix_ok = (fails - np.asarray(
            [0, *np.asarray(fails)[starts[1:] - 1]])[seg_id]) == 0
        selected = prefix_ok & (rank < self.args.max_pods_to_evict_per_node)
        for k in np.nonzero(selected)[0]:
            pod = cand_pods[order[k]]
            job = PodMigrationJob(
                meta=ObjectMeta(
                    name=f"migrate-{pod.meta.namespace}-{pod.meta.name}",
                    namespace="koordinator-system",
                    creation_timestamp=now,
                ),
                pod_namespace=pod.meta.namespace,
                pod_name=pod.meta.name,
                mode="ReservationFirst",
            )
            if self.store.get(KIND_POD_MIGRATION_JOB, job.meta.key) is None:
                self.store.add(KIND_POD_MIGRATION_JOB, job)
                jobs.append(job)
        return jobs


def _has_pdb_like_guard(pod: Pod) -> bool:
    return pod.meta.annotations.get("descheduler.alpha.kubernetes.io/evict") == "false"


def pack_floor_inputs(store: ObjectStore, plugin: LowNodeLoad,
                      now: float):
    """Pack the store into the arrays `native.floor.lownodeload_floor_native`
    consumes, with the SAME classification inputs balance() sees. One home
    for this encoding — bench.py --chain rebalance and the non-dyadic
    parity regression both call it, so the floor and the plugin can never
    drift onto different encodings silently.

    Returns (pods list, dict of keyword arrays for the floor call)."""
    nodes = store.list(KIND_NODE)
    node_idx = {n.meta.name: i for i, n in enumerate(nodes)}
    alloc = np.stack([n.allocatable.to_vector() for n in nodes])
    usage_pct = np.zeros_like(alloc, np.float32)
    has_metric = np.zeros(len(nodes), np.int32)
    for i, node in enumerate(nodes):
        nm = store.get(KIND_NODE_METRIC, f"/{node.meta.name}")
        if nm is None or nm.update_time <= 0:
            continue
        if now - nm.update_time >= plugin.args.node_metric_expiration_seconds:
            continue
        a = alloc[i]
        u = nm.node_metric.node_usage.to_vector()
        usage_pct[i] = np.where(a > 0, u * 100.0 / np.maximum(a, 1e-9), 0.0)
        has_metric[i] = 1
    pods = [p for p in store.list(KIND_POD)
            if p.is_assigned and not p.is_terminated]
    pod_req = np.stack([p.spec.requests.to_vector() for p in pods]) \
        if pods else np.zeros((0, NUM_RESOURCES), np.float32)
    arrays = dict(
        alloc=alloc,
        usage_pct=usage_pct,
        has_metric=has_metric,
        low_thr=plugin._thr_vec(plugin.args.low_thresholds),
        high_thr=plugin._thr_vec(plugin.args.high_thresholds),
        pod_node=np.asarray(
            [node_idx.get(p.spec.node_name, -1) for p in pods], np.int32),
        pod_prio=np.asarray([p.spec.priority or 0 for p in pods], np.int32),
        pod_req=pod_req,
        movable=np.asarray(
            [p.meta.owner_kind != "DaemonSet" and not _has_pdb_like_guard(p)
             for p in pods], np.int32),
        pod_sort_cpu=np.asarray(
            [p.spec.requests[ResourceName.CPU] for p in pods], np.float32),
        max_evict_per_node=plugin.args.max_pods_to_evict_per_node,
    )
    return pods, arrays
