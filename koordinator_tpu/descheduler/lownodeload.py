"""LowNodeLoad: utilization-based rebalancing.

Analog of reference `pkg/descheduler/framework/plugins/loadaware/low_node_load.go`
+ `utilization_util.go`: classify nodes by MEASURED utilization (NodeMetric CR)
into low (below lowThresholds on every resource) and high (above highThresholds
on any); evict movable pods from high nodes while capacity remains on low nodes.

Batched formulation: classification is one [N, R] compare; victim-fit against
low nodes reuses the scheduler's one-shot score-matrix kernel
(models/scheduler_model.build_score_matrix) in "all candidate pods x low nodes"
mode — BASELINE config 5's 50k-pod global rebalance runs as a single device
pass instead of per-pod Go loops."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod, PodMigrationJob, ObjectMeta
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    ObjectStore,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]


@dataclass
class LowNodeLoadArgs:
    low_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 45.0, ResourceName.MEMORY: 55.0}
    )
    high_thresholds: Dict[str, float] = field(
        default_factory=lambda: {ResourceName.CPU: 70.0, ResourceName.MEMORY: 80.0}
    )
    max_pods_to_evict_per_node: int = 5
    node_metric_expiration_seconds: float = 300.0


def classify_nodes(
    usage_percent: np.ndarray,   # [N, R] measured utilization percent
    has_metric: np.ndarray,      # [N]
    low_thr: np.ndarray,         # [R] (0 = unchecked)
    high_thr: np.ndarray,        # [R]
) -> Tuple[np.ndarray, np.ndarray]:
    """(is_low[N], is_high[N]) — vectorized utilization_util.go classification."""
    checked = low_thr > 0
    low = np.all(~checked | (usage_percent < low_thr), axis=-1) & has_metric
    checked_h = high_thr > 0
    high = np.any(checked_h & (usage_percent > high_thr), axis=-1) & has_metric
    return low & ~high, high


class LowNodeLoad:
    name = "LowNodeLoad"

    def __init__(self, store: ObjectStore, args: Optional[LowNodeLoadArgs] = None):
        self.store = store
        self.args = args or LowNodeLoadArgs()

    def _thr_vec(self, thr: Dict[str, float]) -> np.ndarray:
        v = np.zeros(NUM_RESOURCES, np.float32)
        for name, t in thr.items():
            v[RESOURCE_INDEX[name]] = t
        return v

    def balance(self, now: Optional[float] = None) -> List[PodMigrationJob]:
        now = time.time() if now is None else now
        nodes: List[Node] = self.store.list(KIND_NODE)
        if not nodes:
            return []
        N = len(nodes)
        usage_pct = np.zeros((N, NUM_RESOURCES), np.float32)
        has_metric = np.zeros(N, bool)
        for i, node in enumerate(nodes):
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}"
            )
            if nm is None or nm.update_time <= 0:
                continue
            if now - nm.update_time >= self.args.node_metric_expiration_seconds:
                continue
            alloc = node.allocatable.to_vector()
            usage = nm.node_metric.node_usage.to_vector()
            with np.errstate(divide="ignore", invalid="ignore"):
                pct = np.where(alloc > 0, usage * 100.0 / np.maximum(alloc, 1e-9), 0.0)
            usage_pct[i] = pct
            has_metric[i] = True

        is_low, is_high = classify_nodes(
            usage_pct,
            has_metric,
            self._thr_vec(self.args.low_thresholds),
            self._thr_vec(self.args.high_thresholds),
        )
        if not is_high.any() or not is_low.any():
            return []

        low_names = {nodes[i].meta.name for i in np.nonzero(is_low)[0]}
        jobs: List[PodMigrationJob] = []
        pods_by_node: Dict[str, List[Pod]] = {}
        for pod in self.store.list(KIND_POD):
            if pod.is_assigned and not pod.is_terminated:
                pods_by_node.setdefault(pod.spec.node_name, []).append(pod)

        for i in np.nonzero(is_high)[0]:
            node = nodes[i]
            target_pct = self._thr_vec(self.args.high_thresholds)
            over = np.maximum(usage_pct[i] - target_pct, 0.0)
            if not (over > 0).any():
                continue
            movable = [
                p for p in pods_by_node.get(node.meta.name, [])
                if p.meta.owner_kind != "DaemonSet" and not _has_pdb_like_guard(p)
            ]
            # evict highest-usage BE/low-priority pods first (sorter analog)
            movable.sort(key=lambda p: (p.spec.priority or 0, -(
                p.spec.requests[ResourceName.CPU])))
            alloc = node.allocatable.to_vector()
            freed = np.zeros(NUM_RESOURCES, np.float32)
            count = 0
            for pod in movable:
                if count >= self.args.max_pods_to_evict_per_node:
                    break
                still_over = (
                    usage_pct[i]
                    - (freed * 100.0 / np.maximum(alloc, 1e-9))
                    > target_pct
                )
                if not (still_over & (target_pct > 0)).any():
                    break
                job = PodMigrationJob(
                    meta=ObjectMeta(
                        name=f"migrate-{pod.meta.namespace}-{pod.meta.name}",
                        namespace="koordinator-system",
                        creation_timestamp=now,
                    ),
                    pod_namespace=pod.meta.namespace,
                    pod_name=pod.meta.name,
                    mode="ReservationFirst",
                )
                if self.store.get(KIND_POD_MIGRATION_JOB, job.meta.key) is None:
                    self.store.add(KIND_POD_MIGRATION_JOB, job)
                    jobs.append(job)
                freed += pod.spec.requests.to_vector()
                count += 1
        return jobs


def _has_pdb_like_guard(pod: Pod) -> bool:
    return pod.meta.annotations.get("descheduler.alpha.kubernetes.io/evict") == "false"
