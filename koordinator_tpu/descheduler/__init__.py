"""koord-descheduler (analog of reference `pkg/descheduler/`, SURVEY.md 2.5):
profile-driven Deschedule/Balance plugin runner, the LowNodeLoad utilization
balancer (vectorized node classification + the scheduler's score-matrix kernel
for target selection), and the arbitration-gated MigrationController."""

from koordinator_tpu.descheduler.lownodeload import LowNodeLoad  # noqa: F401
from koordinator_tpu.descheduler.migration import (  # noqa: F401
    Arbitrator,
    MigrationController,
)
from koordinator_tpu.descheduler.descheduler import Descheduler  # noqa: F401
