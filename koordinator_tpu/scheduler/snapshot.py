"""Cluster snapshot builder: store objects -> FullChainInputs.

The analog of the scheduler's cache/snapshot layer plus every plugin's PreFilter
precompute (SURVEY.md section 3.1): one pass over nodes/pods/CRs produces the
packed device arrays for the fused full-chain step. With a SnapshotCache
attached the pass is INCREMENTAL — O(changed objects), not O(cluster):
packed pod rows, flags, masks and selector sets gather from the previous
build's pack memo with batched fancy indexing, node-side LoadAware/NUMA
rows refresh only where store events or plugin epochs dirtied them, and
the cold code path is otherwise identical so cached and cold builds
cannot drift (tests/test_snapshot_cache.py diffs every array).
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.api.objects import (
    ANNOTATION_RESOURCE_SPEC,
    ElasticQuota,
    Node,
    NodeMetric,
    NodeResourceTopology,
    Pod,
    PodGroup,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceList,
    ResourceName,
)
from koordinator_tpu.models.full_chain import FullChainInputs
from koordinator_tpu.models.scheduler_model import make_inputs
from koordinator_tpu.ops.loadaware import LoadAwareArgs, build_loadaware_node_state
from koordinator_tpu.ops.numa import MAX_NUMA, POLICY_BY_NAME, POLICY_NONE
from koordinator_tpu.ops.packing import (
    NodeBatch,
    PodBatch,
    fill_ids_from_names,
    pack_nodes,
    pack_pods,
)
from koordinator_tpu.ops.taints import (
    admission_mask,
    degraded_node_count,
    group_node_admission,
    selector_pairs_of,
)
from koordinator_tpu.scheduler.metrics import (
    ADMISSION_DEGRADED_NODES,
    ENCODING_OVERFLOW_PODS,
    VOL_GROUP_DEGRADED_NODES,
)
from koordinator_tpu.ops.quota import (
    MAX_QUOTA_DEPTH,
    QuotaTreeArrays,
    build_quota_tree,
    compute_runtime_quotas,
    merge_group_request,
)
from koordinator_tpu.scheduler.cpu_topology import CPUAllocationState, FULL_PCPUS

logger = logging.getLogger(__name__)

# volume-group budget: more distinct attached-set intersections than this
# degrade to the conservative full count (group 0) — the same stance as the
# admission-signature overflow (ops/taints.py)
MAX_VOL_GROUPS = 16

CPU_IDX = RESOURCE_INDEX[ResourceName.CPU]
PODS_IDX = RESOURCE_INDEX[ResourceName.PODS]


def reduce_to_active_axes(fc: FullChainInputs):
    """Slice every resource axis down to the axes that can actually constrain or
    score this batch: axes with a nonzero pod request, score weight, or filter
    threshold (zero axes never constrain — k8s semantics), plus the pods axis.
    Cuts per-iteration memory traffic of the serial loop by ~3x at the 10k x 5k
    config; the parity emulator consumes the same sliced arrays, so semantics are
    unchanged by construction. Returns (sliced_inputs, active_axis_ids).

    The NUMA zone axis is sliced the same way: trailing all-zero zones (the
    MAX_NUMA padding past the cluster's real socket count) can never fit a
    pod with any positive request nor contribute to the cross-zone total, so
    dropping them is exact for every consumer (XLA/Pallas/wave kernels, the
    numpy oracle and the C++ floor all read K from the array shape). A
    2-socket fleet pays for 2 zones instead of 8 — the per-pod NUMA fit and
    waterfall are the serial loop's widest row blocks."""
    base = fc.base
    active = np.zeros(NUM_RESOURCES, bool)
    active[PODS_IDX] = True
    # cpu/memory always stay: the balanced-allocation score reads their
    # EXISTING node usage even when no pending pod requests the axis —
    # slicing one away would silently disable the term in reduced runs
    active[CPU_IDX] = True
    active[RESOURCE_INDEX[ResourceName.MEMORY]] = True
    for arr in (
        np.asarray(base.fit_requests),
        np.asarray(base.estimated),
        np.asarray(fc.requests),
        np.asarray(base.weights)[None, :],
        np.asarray(base.la_filter_thresholds),
        np.asarray(base.la_prod_thresholds),
    ):
        active |= (arr != 0).any(axis=tuple(range(arr.ndim - 1)))
    idx = np.nonzero(active)[0]

    def cut(arr):
        # host-side slice: arrays are still numpy at pack time and device ops
        # here would trigger per-shape XLA compiles before the step even runs
        return np.take(np.asarray(arr), idx, axis=-1)

    r_fields_base = {
        "fit_requests", "estimated", "allocatable", "requested",
        "la_filter_usage", "la_filter_thresholds", "la_prod_thresholds",
        "la_prod_pod_usage", "la_term_nonprod", "la_term_prod", "weights",
    }
    new_base = ScheduleInputsReplace(base, {k: cut(getattr(base, k)) for k in r_fields_base})
    r_fields_fc = {
        "requests", "numa_free", "numa_capacity", "quota_used", "quota_runtime"
    }
    kwargs = {
        k: (cut(v) if k in r_fields_fc else v)
        for k, v in fc._asdict().items()
        if k != "base"
    }
    # zone-axis slice: keep zones up to the highest with any capacity or
    # free anywhere in the fleet (>=1 so shapes stay rank-stable)
    nf = np.asarray(kwargs["numa_free"])
    nc = np.asarray(kwargs["numa_capacity"])
    zone_any = (nf != 0).any(axis=(0, 2)) | (nc != 0).any(axis=(0, 2))
    k_eff = max(1, int(np.nonzero(zone_any)[0].max()) + 1 if zone_any.any() else 1)
    if k_eff < nf.shape[1]:
        kwargs["numa_free"] = nf[:, :k_eff]
        kwargs["numa_capacity"] = nc[:, :k_eff]
    return FullChainInputs(base=new_base, **kwargs), [int(i) for i in idx]


def ScheduleInputsReplace(base, updates):
    d = base._asdict()
    d.update(updates)
    return type(base)(**d)

# re-exported for existing importers; canonical home is topologymanager.py
from koordinator_tpu.scheduler.topologymanager import (  # noqa: E402
    LABEL_NUMA_TOPOLOGY_POLICY,
    resolve_numa_policy,
)


@dataclass
class ClusterState:
    """Everything the snapshot needs from the store + plugin caches."""

    nodes: List[Node]
    pending_pods: List[Pod]
    node_metrics: Dict[str, NodeMetric]
    pods_by_key: Dict[str, Pod]
    assigned: Dict[str, List[Tuple[Pod, float]]] = field(default_factory=dict)
    assigned_requests: Dict[str, np.ndarray] = field(default_factory=dict)
    topologies: Dict[str, NodeResourceTopology] = field(default_factory=dict)
    cpu_states: Dict[str, CPUAllocationState] = field(default_factory=dict)
    numa_allocated: Dict[str, np.ndarray] = field(default_factory=dict)  # [K, R]
    quotas: List[ElasticQuota] = field(default_factory=list)
    pod_groups: List[PodGroup] = field(default_factory=list)
    gang_assumed: Dict[str, int] = field(default_factory=dict)
    # VolumeZone/volume-limit/VolumeBinding inputs: PVCs by "namespace/name"
    # key, PVs by volume name, StorageClasses by name (all optional — empty
    # means no volume constraints)
    pvcs: Dict[str, object] = field(default_factory=dict)
    pvs: Dict[str, object] = field(default_factory=dict)
    storage_classes: Dict[str, object] = field(default_factory=dict)
    cluster_total: Optional[np.ndarray] = None
    now: float = 0.0


def volume_zone_pairs(pod: Pod, pvcs: Dict[str, object],
                      pvs: Dict[str, object]):
    """VolumeZone filter folded into the admission-signature machinery: a
    pod mounting a claim whose bound PV carries zone/region topology labels
    may only land on nodes carrying the same labels — exactly a
    nodeSelector pair, so it rides the existing (taints x selector) group
    bitmask with no new kernel state. Unbound claims contribute nothing
    (upstream VolumeZone skips them; volume binding is out of scope)."""
    pairs = []
    for claim in pod.spec.pvc_names:
        pvc = pvcs.get(f"{pod.meta.namespace}/{claim}")
        if pvc is None or not getattr(pvc, "volume_name", ""):
            continue
        pv = pvs.get(pvc.volume_name)
        if pv is None:
            continue
        pairs.extend(pv.zone_pairs())
    return frozenset(pairs)


def _pod_cpuset_flags(pod: Pod, default_policy: str = FULL_PCPUS) -> Tuple[bool, float, bool]:
    """(needs_bind, cores_needed, full_pcpus) — AllowUseCPUSet + resource-spec
    annotation (nodenumaresource/plugin.go:219-268)."""
    qos = pod.qos_class
    if qos not in (QoSClass.LSE, QoSClass.LSR):
        return False, 0.0, False
    cpu_milli = pod.spec.requests[ResourceName.CPU]
    if cpu_milli <= 0 or cpu_milli % 1000 != 0:
        return False, 0.0, False
    policy = default_policy
    raw = pod.meta.annotations.get(ANNOTATION_RESOURCE_SPEC)
    if raw:
        try:
            spec = json.loads(raw)
            policy = (
                spec.get("requiredCPUBindPolicy")
                or spec.get("preferredCPUBindPolicy")
                or default_policy
            )
        except (ValueError, TypeError):
            pass
    return True, float(cpu_milli // 1000), policy == FULL_PCPUS


def _pod_flag_tuple(pod: Pod) -> tuple:
    """The per-pod flag row (needs_bind, cores, full_pcpus, needs_numa,
    vol_needed, has_aff, has_ports, has_img, has_npref) — ONE
    implementation shared by the build loop and the in-window pre-pack
    (prepack_pending_rows), so the overlapped pack can never drift from
    the cold fill."""
    spec = pod.spec
    nb, cn, fp = _pod_cpuset_flags(pod)
    return (nb, cn, fp, bool(spec.requests), float(len(set(spec.pvc_names))),
            bool(spec.pod_affinity or spec.pod_anti_affinity
                 or spec.topology_spread or spec.pod_affinity_preferred),
            bool(spec.host_ports), bool(spec.images),
            bool(spec.affinity_preferred))


def _pod_sel_pairs(pod: Pod) -> frozenset:
    """The pod's nodeSelector/required-affinity pair set — the "sel"
    memo column's cold expression, shared with the pre-pack."""
    return frozenset(pod.spec.node_selector.items()) | frozenset(
        pod.spec.affinity_required_node_labels.items())


def prepack_pending_rows(cache, pods: List[Pod], args: LoadAwareArgs) -> int:
    """Pack/device overlap (PR 15): refresh the pack memo's rows for
    every given pod whose (key, resourceVersion) is stale or absent —
    called from INSIDE a device window (cycle.py _prepack_in_window), so
    the per-object Python the next build would have paid in the
    inter-window gap runs while the device executes instead.

    Only memo state is touched: the packed wire rows + estimator output
    (ops/packing.prepack_memo_rows), the flag columns, the selector-pair
    sets and the per-pod flag dict. Admission masks are NOT precomputed
    — their validity is keyed on the admission grouping the NEXT build
    resolves — so pre-packed rows carry ``mask_valid=False`` and the
    build recomputes exactly those masks. Rows dirtied AFTER this runs
    (bind patches, watch events later in the window) bump their
    resourceVersion and miss the memo at the real pack: reconciliation
    is the memo keying itself, which is why the produced ScheduleInputs
    are byte-identical to the non-overlapped pack (parity-gated).

    Returns the number of rows pre-packed."""
    from koordinator_tpu.ops.packing import prepack_memo_rows

    memo = cache.pack_memo
    if memo is None or "f_needs_bind" not in memo or "sel" not in memo:
        return 0  # no completed build yet: nothing to warm against
    if "mask_valid" not in memo:
        return 0
    placed = prepack_memo_rows(cache, pods, args.resource_weights,
                               args.estimated_scaling_factors)
    if not placed:
        return 0
    flag_cols = ("f_needs_bind", "f_cores", "f_fullp", "f_needs_numa",
                 "f_vol", "f_aff", "f_ports", "f_img", "f_npref")
    n_new = max((j for j, _p in placed), default=-1) + 1
    grown = memo[flag_cols[0]].shape[0]
    if n_new > grown:
        pad = n_new - grown
        for col in flag_cols:
            memo[col] = np.concatenate(
                [memo[col], np.zeros(pad, memo[col].dtype)])
        memo["mask"] = np.concatenate(
            [memo["mask"], np.ones(pad, memo["mask"].dtype)])
        memo["mask_valid"] = np.concatenate(
            [memo["mask_valid"], np.zeros(pad, bool)])
        sel_pad = np.empty(pad, object)
        memo["sel"] = np.concatenate([memo["sel"], sel_pad])
    for j, pod in placed:
        flags = _pod_flag_tuple(pod)
        for col, value in zip(flag_cols, flags):
            memo[col][j] = value
        memo["mask_valid"][j] = False
        memo["sel"][j] = _pod_sel_pairs(pod)
        cache.put_pod_flag(pod, flags)
    return len(placed)


def build_full_chain_inputs(
    state: ClusterState, args: LoadAwareArgs, cache=None
) -> Tuple[FullChainInputs, PodBatch, NodeBatch, QuotaTreeArrays, Dict[str, int], int, int]:
    """Returns (inputs, pod_batch, node_batch, quota_tree, gang_index,
    num_gangs, num_groups).

    With `cache` (scheduler/snapshot_cache.SnapshotCache) the expensive
    blocks consult event-maintained memos instead of walking the cluster;
    the code path is otherwise IDENTICAL, so cached and cold builds cannot
    drift (tests/test_snapshot_cache.py diffs every produced array)."""
    if cache is not None:
        cache.begin_build()
    # ---- gangs indexed first so pods pack in one pass; quota ids are filled
    # into the packed batch after the tree is built (they need the tree)
    gang_index = {pg.meta.key: i for i, pg in enumerate(state.pod_groups)}
    pods = pack_pods(
        state.pending_pods,
        args.resource_weights,
        args.estimated_scaling_factors,
        gang_ids=gang_index,
        gang_sort={
            pg.meta.key: (pg.meta.creation_timestamp, pg.meta.key)
            for pg in state.pod_groups
        },
        cache=cache,
    )
    # keyed off the packed batch (keys computed once inside pack_pods)
    pods_by_key_pending = dict(zip(pods.keys, pods.objs))

    # ---- quota tree: pending requests accumulate from the PACKED rows (one
    # to_vector per pod already happened inside pack_pods). Grouped by the
    # quota-name column with one segment-sum; np.add.at processes rows in
    # ascending packed order, the same float32 accumulation sequence the
    # per-pod loop produced.
    pod_req_by_quota: Dict[str, np.ndarray] = {}
    n_valid = pods.num_valid
    qn_col = pods.quota_names[:n_valid]
    q_rows = np.nonzero(qn_col != "")[0]
    if q_rows.size:
        q_uniq, q_inv = np.unique(qn_col[q_rows].astype(str),
                                  return_inverse=True)
        q_sums = np.zeros((len(q_uniq), NUM_RESOURCES), np.float32)
        np.add.at(q_sums, q_inv, pods.requests[q_rows])
        pod_req_by_quota = {str(q): q_sums[j] for j, q in enumerate(q_uniq)}
    # assigned quota usage: event-maintained sums when cached, else ONE
    # wire-matrix fill + scale + segment-sum instead of a per-pod
    # to_vector allocation (the 10k-pod store walk's hot cost)
    used_by_quota: Dict[str, np.ndarray] = {}
    if cache is not None:
        used_by_quota = cache.used_by_quota()
    else:
        quota_pods: List[Tuple[str, Pod]] = []
        for pod in state.pods_by_key.values():
            q = pod.quota_name
            if q and pod.is_assigned and not pod.is_terminated:
                quota_pods.append((q, pod))
        if quota_pods:
            mat = ResourceList.pack_wire_matrix(
                pod.spec.requests for _q, pod in quota_pods)
            names = sorted({q for q, _p in quota_pods})
            row_of = {q: j for j, q in enumerate(names)}
            sums = np.zeros((len(names), NUM_RESOURCES), np.float32)
            np.add.at(sums, [row_of[q] for q, _p in quota_pods], mat)
            used_by_quota = {q: sums[j] for q, j in row_of.items()}
    # group request counts EVERY member pod — running AND pending; a
    # pending-only request would understate runtime for groups with running
    # usage and deny admission their min already guarantees
    pod_req_by_quota = merge_group_request(pod_req_by_quota, used_by_quota)
    tree = build_quota_tree(state.quotas, pod_req_by_quota, used_by_quota)
    if state.cluster_total is None:
        if cache is not None:
            # memoized on the node epoch: any Node add/update/delete
            # invalidates, so the warm path skips the O(N) matrix fill
            total = cache.cluster_total(state.nodes)
        else:
            # one matrix fill + scale + sum (not 5k per-node to_vector calls)
            total = ResourceList.pack_wire_matrix(
                node.allocatable for node in state.nodes).sum(axis=0)
    else:
        total = state.cluster_total
    runtime = (
        compute_runtime_quotas(tree, total)
        if tree.names
        else np.zeros((1, NUM_RESOURCES), np.float32)
    )
    quota_ids = {name: i for i, name in enumerate(tree.names)}

    # ---- gangs
    ng = max(1, len(state.pod_groups))
    gang_min = np.zeros(ng, np.float32)
    gang_assumed = np.zeros(ng, np.float32)
    gang_total = np.zeros(ng, np.float32)
    for pg in state.pod_groups:
        i = gang_index[pg.meta.key]
        gang_min[i] = pg.min_member
        gang_assumed[i] = state.gang_assumed.get(pg.meta.key, 0)
        gang_total[i] = gang_assumed[i]
    # pending members per gang: unique-count over the packed gang column
    # (integer counts — accumulation order free)
    gk_col = pods.gang_keys[:n_valid]
    gk_rows = np.nonzero(gk_col != "")[0]
    if gk_rows.size:
        gk_uniq, gk_counts = np.unique(gk_col[gk_rows].astype(str),
                                       return_counts=True)
        for g, c in zip(gk_uniq, gk_counts):
            gi = gang_index.get(str(g))
            if gi is not None:
                gang_total[gi] += c
    gang_valid = gang_total >= gang_min
    gang_group = np.arange(ng, dtype=np.int32)  # group == gang (annotation later)

    # ---- per-pod flags (single pass over the packed order)
    P = pods.padded_size
    needs_bind = np.zeros(P, bool)
    cores_needed = np.zeros(P, np.float32)
    full_pcpus = np.zeros(P, bool)
    needs_numa = np.zeros(P, bool)
    pod_taint_mask = np.ones(P, np.float32)  # padding admits group 0
    # admission factorization (ops/taints.py): node (taint set, matched
    # selector pairs) signatures -> group ids, pod tolerations +
    # nodeSelector -> group bitmasks. This is how TaintToleration AND
    # NodeAffinity (nodeSelector) batch into one bit test.
    # VolumeZone: PV topology labels become per-pod required pairs riding
    # the admission bitmask (no new kernel state). VolumeBinding (unbound
    # WaitForFirstConsumer claims) rides the same bitmask as OR-of-AND
    # alternatives — scheduler/volumebinding.py — so the kernel's one bit
    # test covers schedule-time volume feasibility too, in every backend.
    zone_pairs_by_key = {}
    vb_any_of_by_key: Dict[str, tuple] = {}
    vb_reason_by_key: Dict[str, str] = {}
    # volume-aware mode: any PVC/PV/StorageClass object in the store turns
    # classification on (a cluster that has ever used storage keeps its
    # StorageClasses even when all claims are deleted, so a pod referencing
    # a vanished claim is still PreFilter-rejected). A store with NONE of
    # the three is the informal harness mode where pvc_names are opaque
    # CSI-count tokens (synth clusters, kernel-level benches).
    if state.pvcs or state.pvs or state.storage_classes:
        from koordinator_tpu.scheduler.volumebinding import (
            any_of_pair_universe,
            classify_pod_volumes,
            index_pvs_by_class,
        )

        pvs_by_class = None  # built once, on the first cache miss
        for key, pod in pods_by_key_pending.items():
            if not pod.spec.pvc_names:
                continue
            zp = volume_zone_pairs(pod, state.pvcs, state.pvs)
            if zp:
                zone_pairs_by_key[key] = zp
            vb = (cache.pod_vb(pod) if cache is not None else None)
            if vb is None:
                if pvs_by_class is None:
                    pvs_by_class = index_pvs_by_class(state.pvs)
                vb = classify_pod_volumes(
                    pod, state.pvcs, state.pvs, state.storage_classes,
                    pvs_by_class=pvs_by_class)
                if cache is not None:
                    cache.put_pod_vb(pod, vb)
            if vb.reason is not None:
                vb_reason_by_key[key] = vb.reason
            elif vb.any_of_sets:
                vb_any_of_by_key[key] = vb.any_of_sets
    # distinct nodeSelector/affinity pair universe: per-pod pair sets are
    # cached in the pack memo (frozensets hash-cache themselves), so the
    # warm path unions a handful of DISTINCT sets instead of walking every
    # pod's label dicts
    if cache is not None and pods.reused_src is not None:
        sel_col = np.empty(n_valid, object)
        sel_done = np.zeros(n_valid, bool)
        prevm_sel = cache.pack_memo_prev
        if prevm_sel is not None and "sel" in prevm_sel:
            sel_hit = np.nonzero(pods.reused_src >= 0)[0]
            if sel_hit.size:
                sel_col[sel_hit] = prevm_sel["sel"][pods.reused_src[sel_hit]]
                sel_done[sel_hit] = True
        for i in np.nonzero(~sel_done)[0]:
            sel_col[i] = _pod_sel_pairs(pods_by_key_pending[pods.keys[i]])
        cache.pack_memo["sel"] = sel_col
        pair_union = (set().union(*set(sel_col.tolist()))
                      if n_valid else set())
        for zp in zone_pairs_by_key.values():
            pair_union |= zp
        sel_pairs = frozenset(pair_union)
    else:
        sel_pairs = selector_pairs_of(pods_by_key_pending.values(),
                                      zone_pairs_by_key)
    if vb_any_of_by_key:
        sel_pairs = frozenset(
            sel_pairs
            | {p for sets in vb_any_of_by_key.values()
               for p in any_of_pair_universe(sets)})
    if cache is not None:
        node_taint_ids, admission_groups, adm_seq = cache.node_admission(
            state.nodes, sel_pairs)
    else:
        node_taint_ids, admission_groups = group_node_admission(
            state.nodes, sel_pairs)
        adm_seq = 0
    ADMISSION_DEGRADED_NODES.set(
        float(degraded_node_count(node_taint_ids, admission_groups)))
    vol_needed = np.zeros(P, np.float32)
    # per-row feature presence (affinity/spread specs, hostPorts, images,
    # preferred node affinity): the candidate-row sets the batch encoders
    # below restrict their extraction loops to
    has_aff = np.zeros(P, bool)
    has_ports = np.zeros(P, bool)
    has_img = np.zeros(P, bool)
    has_npref = np.zeros(P, bool)
    # dirty-row flags/masks: rows carried over from the previous build
    # gather their cached columns with batched fancy indexing (the same
    # reused_src mapping pack_pods used); only changed rows pay the
    # per-object Python below. Masks are position-independent (pure pod ->
    # group bitmask), so gathering across reordered rows is exact.
    src = pods.reused_src
    prevm = cache.pack_memo_prev if cache is not None else None
    flag_done = np.zeros(n_valid, bool)
    mask_done = np.zeros(n_valid, bool)
    if prevm is not None and src is not None and "f_needs_bind" in prevm:
        f_hit = np.nonzero(src >= 0)[0]
        if f_hit.size:
            hsrc = src[f_hit]
            needs_bind[f_hit] = prevm["f_needs_bind"][hsrc]
            cores_needed[f_hit] = prevm["f_cores"][hsrc]
            full_pcpus[f_hit] = prevm["f_fullp"][hsrc]
            needs_numa[f_hit] = prevm["f_needs_numa"][hsrc]
            vol_needed[f_hit] = prevm["f_vol"][hsrc]
            has_aff[f_hit] = prevm["f_aff"][hsrc]
            has_ports[f_hit] = prevm["f_ports"][hsrc]
            has_img[f_hit] = prevm["f_img"][hsrc]
            has_npref[f_hit] = prevm["f_npref"][hsrc]
            flag_done[f_hit] = True
            # cached masks are valid only under the SAME admission grouping
            # and PVC/PV/StorageClass epoch, and only for volume-less pods
            # (pvc carriers fold VolumeZone/VolumeBinding state into theirs)
            if prevm.get("mask_epoch") == (adm_seq, cache.pvcpv_epoch):
                # pre-packed rows (pack overlap) carry mask_valid=False:
                # their flag/pack columns are exact but the admission
                # mask is keyed on THIS build's grouping, so it
                # recomputes below
                mvalid = prevm.get("mask_valid")
                m_ok = prevm["f_vol"][hsrc] == 0.0
                if mvalid is not None:
                    m_ok = m_ok & mvalid[hsrc].astype(bool)
                m_hit = f_hit[m_ok]
                if m_hit.size:
                    pod_taint_mask[m_hit] = prevm["mask"][src[m_hit]]
                    mask_done[m_hit] = True
    for i in np.nonzero(~(flag_done & mask_done))[0]:
        key = pods.keys[i]
        pod = pods_by_key_pending[key]
        if not flag_done[i]:
            flags = cache.pod_flag(pod) if cache is not None else None
            if flags is not None:
                (needs_bind[i], cores_needed[i], full_pcpus[i],
                 needs_numa[i], vol_needed[i], has_aff[i], has_ports[i],
                 has_img[i], has_npref[i]) = flags
            else:
                flags = _pod_flag_tuple(pod)
                (needs_bind[i], cores_needed[i], full_pcpus[i],
                 needs_numa[i], vol_needed[i], has_aff[i], has_ports[i],
                 has_img[i], has_npref[i]) = flags
                if cache is not None:
                    cache.put_pod_flag(pod, flags)
        if mask_done[i]:
            continue
        if key in vb_reason_by_key:
            # VolumeBinding PreFilter rejection (missing claim/class,
            # unbound immediate claim, claim satisfiable nowhere): no
            # group admits the pod, and the cycle surfaces the reason on
            # the pod's condition (upstream PreFilter unschedulable status)
            pod_taint_mask[i] = 0.0
            pods.unschedulable_reasons[i] = vb_reason_by_key[key]
        else:
            mask = (cache.pod_mask(pod, adm_seq)
                    if cache is not None else None)
            if mask is not None:
                pod_taint_mask[i] = mask
            else:
                pod_taint_mask[i] = admission_mask(
                    pod, admission_groups,
                    zone_pairs_by_key.get(key, frozenset()),
                    any_of_sets=vb_any_of_by_key.get(key, ()))
                if cache is not None:
                    cache.put_pod_mask(pod, adm_seq,
                                       float(pod_taint_mask[i]))
    # quota ids resolve only after the tree exists — one vectorized
    # unique-name map over the packed quota column
    fill_ids_from_names(pods.quota_id, pods.quota_names[:n_valid], quota_ids)
    if cache is not None and cache.pack_memo is not None:
        memo = cache.pack_memo
        memo["f_needs_bind"] = needs_bind[:n_valid].copy()
        memo["f_cores"] = cores_needed[:n_valid].copy()
        memo["f_fullp"] = full_pcpus[:n_valid].copy()
        memo["f_needs_numa"] = needs_numa[:n_valid].copy()
        memo["f_vol"] = vol_needed[:n_valid].copy()
        memo["f_aff"] = has_aff[:n_valid].copy()
        memo["f_ports"] = has_ports[:n_valid].copy()
        memo["f_img"] = has_img[:n_valid].copy()
        memo["f_npref"] = has_npref[:n_valid].copy()
        memo["mask"] = pod_taint_mask[:n_valid].copy()
        # build-written masks are all valid; the in-window pre-pack
        # appends rows with mask_valid=False (see prepack_pending_rows)
        memo["mask_valid"] = np.ones(n_valid, bool)
        memo["mask_epoch"] = (adm_seq, cache.pvcpv_epoch)

    # ---- nodes
    if cache is not None:
        from koordinator_tpu.ops.packing import NodeBatch, bucket_size

        N = bucket_size(len(state.nodes))
        cache.node_layout(state.nodes, N)
        alloc_m = cache.alloc_matrix(state.nodes)
        requested_m = np.zeros((N, NUM_RESOURCES), np.float32)
        for name, vec in state.assigned_requests.items():
            idx_n = cache.node_index.get(name)
            if idx_n is not None:
                requested_m[idx_n] = vec
        valid_m = np.zeros(N, bool)
        valid_m[: len(state.nodes)] = True
        nodes = NodeBatch(
            names=[nd.meta.name for nd in state.nodes],
            allocatable=alloc_m, requested=requested_m, valid=valid_m)
        nodes.extras = cache.loadaware_extras(state, args, N)
    else:
        nodes = pack_nodes(state.nodes,
                           assigned_requests=state.assigned_requests)
        N = nodes.padded_size
        nodes.extras = build_loadaware_node_state(
            state.nodes,
            state.node_metrics,
            state.pods_by_key,
            state.assigned,
            args,
            state.now,
            pad_to=N,
        )
    node_taint_group = np.zeros(N, np.int32)  # padding: empty set
    node_taint_group[: len(node_taint_ids)] = node_taint_ids
    if cache is not None:
        na = cache.numa_arrays(state, nodes.requested, N)
        numa_free = na["numa_free"]
        numa_capacity = na["numa_capacity"]
        numa_policy = na["numa_policy"]
        has_topology = na["has_topology"]
        bind_free = na["bind_free"]
        cpus_per_core = na["cpus_per_core"]
    else:
        numa_free = np.zeros((N, MAX_NUMA, NUM_RESOURCES), np.float32)
        numa_capacity = np.zeros((N, MAX_NUMA, NUM_RESOURCES), np.float32)
        numa_policy = np.full(N, POLICY_NONE, np.int32)
        has_topology = np.zeros(N, bool)
        bind_free = np.zeros(N, np.float32)
        cpus_per_core = np.ones(N, np.float32)
        # zone capacities via ONE wire-matrix fill + scale + scatter (not a
        # per-zone to_vector allocation: ~2 zones x every topology node)
        zone_at: List[Tuple[int, int]] = []
        zone_lists: List = []
        topo_nodes: List[int] = []
        for i, node in enumerate(state.nodes):
            topo_cr = state.topologies.get(node.meta.name)
            if topo_cr is not None and topo_cr.cpus:
                topo_nodes.append(i)
                has_topology[i] = True
                numa_policy[i] = POLICY_BY_NAME.get(
                    resolve_numa_policy(node.meta.labels,
                                        topo_cr.kubelet_cpu_manager_policy),
                    POLICY_NONE)
                for zone in topo_cr.zones:
                    if 0 <= zone.numa_id < MAX_NUMA:
                        zone_at.append((i, zone.numa_id))
                        zone_lists.append(zone.allocatable)
        if zone_at:
            zmat = ResourceList.pack_wire_matrix(zone_lists)
            idx = np.asarray(zone_at)
            numa_capacity[idx[:, 0], idx[:, 1]] = zmat
        for i in topo_nodes:
            node = state.nodes[i]
            name = node.meta.name
            alloc = state.numa_allocated.get(name)
            numa_free[i] = numa_capacity[i] - (alloc if alloc is not None else 0.0)
            cpu_state = state.cpu_states.get(name)
            if cpu_state is not None:
                bind_free[i] = cpu_state.num_available()
                cpus_per_core[i] = cpu_state.topology.cpus_per_core
            else:
                bind_free[i] = numa_free[i, :, CPU_IDX].sum() / 1000.0
                cpus_per_core[i] = 2.0
        # no topology: NUMA admission passes only via POLICY_NONE; spread the
        # node allocatable into one virtual zone so zero-topology clusters
        # still quota-fit (vectorized over the non-topology rows)
        no_topo = np.nonzero(~has_topology[: len(state.nodes)])[0]
        if no_topo.size:
            numa_capacity[no_topo, 0] = nodes.allocatable[no_topo]
            numa_free[no_topo, 0] = (nodes.allocatable[no_topo]
                                     - nodes.requested[no_topo])

    # inter-pod (anti-)affinity factorization (ops/podaffinity.py): the
    # batch's distinct terms -> per-node domain/count state + per-pod term
    # rows, in pods.keys order, padded to the bucketed shapes
    from koordinator_tpu.ops.podaffinity import build_affinity_state

    ordered_pending = pods.objs
    existing = [
        p for p in state.pods_by_key.values()
        if p.is_assigned and not p.is_terminated
    ]
    (_aff_terms, term_ids, dom_v, count_v, cover_v, aff_exists, aff_req_v,
     anti_req_v, match_v, spread_v, aff_overflow) = build_affinity_state(
        ordered_pending, state.nodes, existing,
        rows=np.nonzero(has_aff[:n_valid])[0])
    T = dom_v.shape[1]
    aff_dom = np.full((N, T), -1.0, np.float32)
    aff_dom[: dom_v.shape[0]] = dom_v
    aff_count = np.zeros((N, T), np.float32)
    aff_count[: count_v.shape[0]] = count_v
    anti_cover = np.zeros((N, T), np.float32)
    anti_cover[: cover_v.shape[0]] = cover_v
    pod_aff_req = np.zeros((P, T), bool)
    pod_aff_req[: aff_req_v.shape[0]] = aff_req_v
    pod_anti_req = np.zeros((P, T), bool)
    pod_anti_req[: anti_req_v.shape[0]] = anti_req_v
    pod_aff_match = np.zeros((P, T), bool)
    pod_aff_match[: match_v.shape[0]] = match_v
    pod_spread_skew = np.zeros((P, T), np.float32)
    pod_spread_skew[: spread_v.shape[0]] = spread_v
    for i in aff_overflow:  # conservative: term encoding overflow
        pods.valid[i] = False
        pods.unschedulable_reasons[i] = (
            "(anti-)affinity term budget exceeded for this round")
        ENCODING_OVERFLOW_PODS.inc(kind="affinity_terms")

    # preferred node affinity (soft scoring), profile-bucketed
    from koordinator_tpu.ops.podaffinity import (
        build_preferred_pod_profiles,
        build_preferred_scores,
    )

    pref_rows_v, pref_id_v = build_preferred_scores(
        ordered_pending, state.nodes, rows=np.nonzero(has_npref[:n_valid])[0])
    # TRUE zero columns when no pod carries a preference: the kernels gate
    # profile work on the column count, so empty batches pay nothing
    n_pref = pref_rows_v.shape[0] if (pref_id_v >= 0).any() else 0
    pref_scores = np.zeros((N, n_pref), np.float32)
    pref_scores[: pref_rows_v.shape[1], :] = pref_rows_v[:n_pref].T
    pod_pref_id = np.full(P, -1, np.int32)
    pod_pref_id[: pref_id_v.shape[0]] = pref_id_v

    # preferred POD affinity (weighted, over the shared term space)
    ppref_w, ppref_id_v, ppref_mask_v = build_preferred_pod_profiles(
        ordered_pending, term_ids, T, rows=np.nonzero(has_aff[:n_valid])[0])
    pod_ppref_id = np.full(P, -1, np.int32)
    pod_ppref_id[: ppref_id_v.shape[0]] = ppref_id_v
    pod_ppref_mask = np.zeros((P, T), bool)
    pod_ppref_mask[: ppref_mask_v.shape[0]] = ppref_mask_v[:, :T]

    # NodePorts factorization + CSI volume-limit counts + ImageLocality
    # profiles (ops/ports.py)
    from koordinator_tpu.ops.ports import build_image_scores, build_port_state

    _slots, used_v, wants_v, port_overflow = build_port_state(
        ordered_pending, state.nodes, existing,
        rows=np.nonzero(has_ports[:n_valid])[0])
    PT = used_v.shape[1]
    port_used = np.zeros((N, PT), np.float32)
    port_used[: used_v.shape[0]] = used_v
    pod_port_wants = np.zeros((P, PT), bool)
    pod_port_wants[: wants_v.shape[0]] = wants_v
    for i in port_overflow:  # conservative: slot encoding overflow
        pods.valid[i] = False
        pods.unschedulable_reasons[i] = (
            "hostPort slot budget exceeded for this round")
        ENCODING_OVERFLOW_PODS.inc(kind="port_slots")
    vol_free = np.full(N, np.inf, np.float32)
    if cache is not None:
        attached: Dict[str, set] = cache.attached_sets()
    else:
        attached = {}
        for pod in existing:
            if pod.spec.pvc_names:
                attached.setdefault(pod.spec.node_name, set()).update(
                    f"{pod.meta.namespace}/{c}" for c in pod.spec.pvc_names)
    for i, node in enumerate(state.nodes):
        if node.attachable_volume_limit > 0:
            vol_free[i] = node.attachable_volume_limit - len(
                attached.get(node.meta.name, ()))
    # volume-group factorization (upstream NodeVolumeLimits' already-
    # attached exemption): nodes whose attached-claim sets intersect the
    # PENDING batch's claims identically share a group, and vol_needed
    # expands to [P, VG] rows counting only NEW attachments per group.
    # Group 0 is the empty intersection (the common case: VG == 1 and the
    # column equals the plain per-pod count). Budget overflow degrades a
    # node to group 0 — the conservative full count, the pre-exemption
    # behavior. Known divergence: TWO PENDING pods sharing a claim in the
    # same batch each count it (the groups are frozen at pack time, while
    # upstream's assume cache sees the first binding); conservative, and
    # self-corrects next cycle when the binding reaches the attached sets.
    node_vol_group = np.zeros(N, np.int32)
    group_sets: List[frozenset] = [frozenset()]
    pending_claims: Dict[str, frozenset] = {}
    for key, pod in pods_by_key_pending.items():
        if pod.spec.pvc_names:
            pending_claims[key] = frozenset(
                f"{pod.meta.namespace}/{c}" for c in pod.spec.pvc_names)
    vol_degraded = 0
    if pending_claims and attached:
        claim_universe = frozenset().union(*pending_claims.values())
        gid_of = {frozenset(): 0}
        for i, node in enumerate(state.nodes):
            s = frozenset(attached.get(node.meta.name, ())) & claim_universe
            gid = gid_of.get(s)
            if gid is None:
                if len(group_sets) >= MAX_VOL_GROUPS:
                    # overflow: the node loses its exemption (full count) —
                    # surfaced like the admission-signature degradation
                    gid = 0
                    vol_degraded += 1
                    logger.debug(
                        "node %s exceeds the volume-group budget (%d)",
                        node.meta.name, MAX_VOL_GROUPS)
                else:
                    gid = gid_of[s] = len(group_sets)
                    group_sets.append(s)
            node_vol_group[i] = gid
    if vol_degraded:
        # one aggregate line per build, not one per node per cycle
        logger.warning(
            "%d nodes exceed the volume-group budget (%d): pods pay the "
            "full attachment count there", vol_degraded, MAX_VOL_GROUPS)
    VOL_GROUP_DEGRADED_NODES.set(float(vol_degraded))
    VG = len(group_sets)
    vol_needed_g = np.zeros((P, VG), np.float32)
    vol_needed_g[:, 0] = vol_needed
    if VG > 1:
        for i, key in enumerate(pods.keys):
            claims = pending_claims.get(key)
            for g in range(1, VG):
                vol_needed_g[i, g] = (len(claims - group_sets[g])
                                      if claims else 0.0)
    img_rows_v, img_id_v = build_image_scores(
        ordered_pending, state.nodes, rows=np.nonzero(has_img[:n_valid])[0])
    n_img = img_rows_v.shape[0] if (img_id_v >= 0).any() else 0
    img_scores = np.zeros((N, n_img), np.float32)
    img_scores[: img_rows_v.shape[1], :] = img_rows_v[:n_img].T
    pod_img_id = np.full(P, -1, np.int32)
    pod_img_id[: img_id_v.shape[0]] = img_id_v

    base = make_inputs(pods, nodes, args)
    G = max(1, len(tree.names))
    fc = FullChainInputs(
        base=base,
        requests=np.asarray(pods.requests),
        gang_id=np.asarray(pods.gang_id),
        quota_id=np.asarray(pods.quota_id),
        needs_numa=np.asarray(needs_numa),
        needs_bind=np.asarray(needs_bind),
        cores_needed=np.asarray(cores_needed),
        full_pcpus=np.asarray(full_pcpus),
        pod_taint_mask=np.asarray(pod_taint_mask),
        pod_aff_req=np.asarray(pod_aff_req),
        pod_anti_req=np.asarray(pod_anti_req),
        pod_aff_match=np.asarray(pod_aff_match),
        pod_spread_skew=np.asarray(pod_spread_skew),
        pod_pref_id=np.asarray(pod_pref_id),
        pref_scores=np.asarray(pref_scores),
        pod_ppref_id=np.asarray(pod_ppref_id),
        pod_ppref_mask=np.asarray(pod_ppref_mask),
        ppref_w=np.asarray(ppref_w),
        pod_port_wants=np.asarray(pod_port_wants),
        vol_needed=np.asarray(vol_needed_g),
        pod_img_id=np.asarray(pod_img_id),
        port_used=np.asarray(port_used),
        vol_free=np.asarray(vol_free),
        node_vol_group=np.asarray(node_vol_group),
        img_scores=np.asarray(img_scores),
        node_taint_group=np.asarray(node_taint_group),
        aff_dom=np.asarray(aff_dom),
        aff_count=np.asarray(aff_count),
        anti_cover=np.asarray(anti_cover),
        aff_exists=np.asarray(aff_exists),
        numa_free=np.asarray(numa_free),
        numa_capacity=np.asarray(numa_capacity),
        numa_policy=np.asarray(numa_policy),
        has_topology=np.asarray(has_topology),
        bind_free=np.asarray(bind_free),
        cpus_per_core=np.asarray(cpus_per_core),
        quota_ancestors=np.asarray(
            tree.ancestors
            if tree.names
            else np.full((1, MAX_QUOTA_DEPTH), -1, np.int32)
        ),
        quota_used=np.asarray(
            tree.used if tree.names else np.zeros((1, NUM_RESOURCES), np.float32)
        ),
        quota_runtime=np.asarray(runtime if tree.names else np.zeros((1, NUM_RESOURCES), np.float32)),
        gang_min_member=np.asarray(gang_min),
        gang_assumed=np.asarray(gang_assumed),
        gang_valid=np.asarray(gang_valid),
        gang_group_id=np.asarray(gang_group),
    )
    if cache is not None:
        # clear dirty sets NOW: binding mutations after this point must
        # re-dirty for the NEXT cycle, not be swallowed by a later clear
        cache.end_build()
    return fc, pods, nodes, tree, gang_index, ng, ng
