"""Serial parity emulator: the reference's per-pod plugin chain, scalar in numpy.

This is the trustworthy oracle of SURVEY.md section 7 ("parity harness ... is the
only trustworthy test"): a direct, unvectorized transcription of the reference's
Filter/Score/Reserve semantics (load_aware.go + kube NodeResourcesFit), operating on
the SAME packed inputs as the batched kernel. The batched step must produce
IDENTICAL bindings on any trace. It is also the measured performance floor standing
in for the reference's serial Go chain (BASELINE.md: baseline must be measured).

Everything here is float32 numpy with the same go_round/floor arithmetic as
ops/common.py so the two paths cannot diverge on rounding.
"""

from __future__ import annotations

from typing import List

import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.models.scheduler_model import ScheduleInputs
from koordinator_tpu.ops.fit import with_pod_count  # noqa: F401  (packing parity)
from koordinator_tpu.ops.loadaware import LoadAwareArgs

MAX_NODE_SCORE = 100.0


def _go_round(x: np.float32) -> np.float32:
    return np.float32(np.floor(x + np.float32(0.5)))


def _least_requested(requested: np.float32, capacity: np.float32) -> np.float32:
    if capacity <= 0 or requested > capacity:
        return np.float32(0.0)
    return np.float32(np.floor((capacity - requested) * np.float32(MAX_NODE_SCORE) / capacity))


def serial_schedule(inputs: ScheduleInputs, args: LoadAwareArgs) -> np.ndarray:
    """Schedule the batch pod-by-pod, node-by-node; returns chosen[P] int32."""
    fit_requests = np.asarray(inputs.fit_requests, np.float32)
    estimated = np.asarray(inputs.estimated, np.float32)
    is_prod = np.asarray(inputs.is_prod)
    is_daemonset = np.asarray(inputs.is_daemonset)
    pod_valid = np.asarray(inputs.pod_valid)
    allocatable = np.asarray(inputs.allocatable, np.float32)
    requested = np.array(inputs.requested, np.float32)
    node_ok = np.asarray(inputs.node_ok)
    filter_usage = np.asarray(inputs.la_filter_usage, np.float32)
    has_filter_usage = np.asarray(inputs.la_has_filter_usage)
    filter_thr = np.asarray(inputs.la_filter_thresholds, np.float32)
    prod_thr = np.asarray(inputs.la_prod_thresholds, np.float32)
    prod_usage = np.asarray(inputs.la_prod_pod_usage, np.float32)
    term_np = np.array(inputs.la_term_nonprod, np.float32)
    term_pr = np.array(inputs.la_term_prod, np.float32)
    score_valid = np.asarray(inputs.la_score_valid)
    filter_skip = np.asarray(inputs.la_filter_skip)
    weights = np.asarray(inputs.weights, np.float32)

    P, R = fit_requests.shape
    N = allocatable.shape[0]
    weight_idx = [int(r) for r in np.nonzero(weights)[0]]
    wsum = np.float32(weights.sum())
    prod_mode = args.score_according_prod_usage
    chosen = np.full(P, -1, np.int32)

    def filter_loadaware(p: int, n: int) -> bool:
        # load_aware.go:123-171
        if is_daemonset[p]:
            return True
        if filter_skip[n]:
            # expired or missing NodeMetric: allowed before any profile check
            # (load_aware.go:135-150)
            return True
        prod_configured = bool((prod_thr[n] > 0).any())
        if is_prod[p] and prod_configured:
            # filterProdUsage (load_aware.go:226-255)
            for r in range(R):
                thr = prod_thr[n, r]
                if thr == 0:
                    continue
                total = allocatable[n, r]
                if total == 0:
                    continue
                ratio = _go_round(np.float32(prod_usage[n, r] * 100.0 / total))
                if ratio >= thr:
                    return False
            return True
        if not has_filter_usage[n]:
            return True
        for r in range(R):
            thr = filter_thr[n, r]
            if thr == 0:
                continue
            total = allocatable[n, r]
            if total == 0:
                continue
            ratio = _go_round(np.float32(filter_usage[n, r] * 100.0 / total))
            if ratio >= thr:
                return False
        return True

    def filter_fit(p: int, n: int) -> bool:
        for r in range(R):
            need = fit_requests[p, r]
            if need <= 0:
                continue
            if requested[n, r] + need > allocatable[n, r]:
                return False
        return True

    def score_loadaware(p: int, n: int) -> np.float32:
        # load_aware.go:269-335
        if not score_valid[n]:
            return np.float32(0.0)
        acc = np.float32(0.0)
        use_prod = prod_mode and is_prod[p]
        for r in weight_idx:
            term = term_pr[n, r] if use_prod else term_np[n, r]
            used = np.float32(estimated[p, r] + term)
            acc += np.float32(weights[r]) * _least_requested(used, allocatable[n, r])
        return np.float32(np.floor(acc / max(wsum, np.float32(1.0))))

    for p in range(P):
        if not pod_valid[p]:
            continue
        best_n, best_score = -1, np.float32(-1.0)
        for n in range(N):
            if not node_ok[n]:
                continue
            if not filter_fit(p, n):
                continue
            if not filter_loadaware(p, n):
                continue
            s = score_loadaware(p, n)
            if s > best_score:  # strict: lowest index wins ties
                best_n, best_score = n, s
        if best_n < 0:
            continue
        chosen[p] = best_n
        # Reserve: Fit state + podAssignCache (load_aware.go:263-267)
        requested[best_n] += fit_requests[p]
        term_np[best_n] += estimated[p]
        if prod_mode and is_prod[p]:
            term_pr[best_n] += estimated[p]

    return chosen


def serial_schedule_full(fc, args: LoadAwareArgs,
                         active_axes=None) -> np.ndarray:
    """Scalar full-chain oracle: Fit + LoadAware + NUMA/cpuset + quota admission
    in queue order, then the gang Permit barrier. Mirrors
    models/full_chain.build_full_chain_step exactly (same float32 arithmetic).
    active_axes: the original axis ids when fc was sliced by
    reduce_to_active_axes (resolves the balanced-allocation cpu/mem columns)."""
    chosen = serial_schedule_full_core(fc, args, active_axes=active_axes)
    # ---- gang permit barrier
    gang_id = np.asarray(fc.gang_id)
    gang_min = np.asarray(fc.gang_min_member)
    gang_assumed = np.asarray(fc.gang_assumed)
    gang_group = np.asarray(fc.gang_group_id)
    ng = gang_min.shape[0]
    per_gang = np.zeros(ng)
    for p in range(len(chosen)):
        if gang_id[p] >= 0 and chosen[p] >= 0:
            per_gang[gang_id[p]] += 1
    gang_ok = per_gang + gang_assumed >= gang_min
    group_fail = np.zeros(int(gang_group.max()) + 1 if ng else 1)
    for g in range(ng):
        if not gang_ok[g]:
            group_fail[gang_group[g]] += 1
    for p in range(len(chosen)):
        g = gang_id[p]
        if g >= 0 and (not gang_ok[g] or group_fail[gang_group[g]] > 0):
            chosen[p] = -1
    return chosen


def serial_schedule_full_core(fc, args: LoadAwareArgs,
                              active_axes=None) -> np.ndarray:
    from koordinator_tpu.models.full_chain import resolve_balance_idx

    bal_ci, bal_mi = resolve_balance_idx(active_axes)
    inputs = fc.base
    fit_requests = np.asarray(inputs.fit_requests, np.float32)
    requests = np.asarray(fc.requests, np.float32)
    estimated = np.asarray(inputs.estimated, np.float32)
    is_prod = np.asarray(inputs.is_prod)
    is_daemonset = np.asarray(inputs.is_daemonset)
    pod_valid = np.asarray(inputs.pod_valid)
    allocatable = np.asarray(inputs.allocatable, np.float32)
    requested = np.array(inputs.requested, np.float32)
    node_ok = np.asarray(inputs.node_ok)
    filter_usage = np.asarray(inputs.la_filter_usage, np.float32)
    has_filter_usage = np.asarray(inputs.la_has_filter_usage)
    filter_thr = np.asarray(inputs.la_filter_thresholds, np.float32)
    prod_thr = np.asarray(inputs.la_prod_thresholds, np.float32)
    prod_usage = np.asarray(inputs.la_prod_pod_usage, np.float32)
    term_np = np.array(inputs.la_term_nonprod, np.float32)
    term_pr = np.array(inputs.la_term_prod, np.float32)
    score_valid = np.asarray(inputs.la_score_valid)
    filter_skip = np.asarray(inputs.la_filter_skip)
    weights = np.asarray(inputs.weights, np.float32)
    gang_id = np.asarray(fc.gang_id)
    quota_id = np.asarray(fc.quota_id)
    needs_numa = np.asarray(fc.needs_numa)
    needs_bind = np.asarray(fc.needs_bind)
    cores_needed = np.asarray(fc.cores_needed, np.float32)
    full_pcpus = np.asarray(fc.full_pcpus)
    numa_free = np.array(fc.numa_free, np.float32)
    numa_policy = np.asarray(fc.numa_policy)
    has_topology = np.asarray(fc.has_topology)
    bind_free = np.array(fc.bind_free, np.float32)
    cpus_per_core = np.asarray(fc.cpus_per_core, np.float32)
    ancestors = np.asarray(fc.quota_ancestors)
    quota_used = np.array(fc.quota_used, np.float32)
    quota_runtime = np.asarray(fc.quota_runtime, np.float32)
    gang_valid = np.asarray(fc.gang_valid)
    pod_taint_mask = np.asarray(fc.pod_taint_mask)
    node_taint_group = np.asarray(fc.node_taint_group)
    aff_dom = np.asarray(fc.aff_dom, np.float32)
    aff_count = np.array(fc.aff_count, np.float32)
    anti_cover = np.array(fc.anti_cover, np.float32)
    aff_exists = np.array(fc.aff_exists, bool)
    pod_aff_req = np.asarray(fc.pod_aff_req)
    pod_anti_req = np.asarray(fc.pod_anti_req)
    pod_aff_match = np.asarray(fc.pod_aff_match)
    pod_spread_skew = np.asarray(fc.pod_spread_skew, np.float32)
    pod_pref_id = np.asarray(fc.pod_pref_id)
    pref_scores = np.asarray(fc.pref_scores, np.float32)
    pod_ppref_id = np.asarray(fc.pod_ppref_id)
    ppref_w = np.asarray(fc.ppref_w, np.float32)
    pod_port_wants = np.asarray(fc.pod_port_wants)
    port_used = np.array(fc.port_used, np.float32)
    vol_needed = np.asarray(fc.vol_needed, np.float32)  # [P, VG]
    vol_free = np.array(fc.vol_free, np.float32)
    node_vol_group = np.asarray(fc.node_vol_group, np.int64)
    pod_img_id = np.asarray(fc.pod_img_id)
    img_scores = np.asarray(fc.img_scores, np.float32)
    T = aff_dom.shape[1]
    PT = port_used.shape[1]

    P, R = fit_requests.shape
    N, K, _ = numa_free.shape
    weight_idx = [int(r) for r in np.nonzero(weights)[0]]
    wsum = np.float32(weights.sum())
    prod_mode = args.score_according_prod_usage
    chosen = np.full(P, -1, np.int32)
    POLICY_SINGLE = 1

    def la_filter_ok(p, n):
        if is_daemonset[p]:
            return True
        if filter_skip[n]:
            return True
        prod_configured = bool((prod_thr[n] > 0).any())
        usage, thr = (
            (prod_usage, prod_thr)
            if (is_prod[p] and prod_configured)
            else (filter_usage, filter_thr)
        )
        if usage is filter_usage and not has_filter_usage[n]:
            return True
        for r in range(R):
            if thr[n, r] == 0 or allocatable[n, r] == 0:
                continue
            ratio = _go_round(np.float32(usage[n, r] * 100.0 / allocatable[n, r]))
            if ratio >= thr[n, r]:
                return False
        return True

    for p in range(P):
        if not pod_valid[p]:
            continue
        # PreFilter: gang validity + quota admission
        if gang_id[p] >= 0 and not gang_valid[gang_id[p]]:
            continue
        admit = True
        if quota_id[p] >= 0:
            for g in ancestors[quota_id[p]]:
                if g < 0:
                    continue
                for r in range(R):
                    if requests[p, r] > 0 and (
                        quota_used[g, r] + requests[p, r] > quota_runtime[g, r]
                    ):
                        admit = False
                        break
                if not admit:
                    break
        if not admit:
            continue
        best_n, best_score = -1, np.float32(-1.0)
        best_zone = -1
        # preferred POD affinity: weighted count row + max-min norm, hoisted
        # per pod (counts are frozen during one pod's node scan)
        ppref_norm = None
        if T and pod_ppref_id[p] >= 0:
            w_row = ppref_w[pod_ppref_id[p], :T]
            raw = (aff_count[:, :T] * w_row[None, :]).sum(axis=1,
                                                          dtype=np.float32)
            # max-min over node_ok only (upstream NormalizeScore spans the
            # candidate set; padded rows must not anchor the scale)
            ok_raw = raw[node_ok]
            mx = ok_raw.max() if ok_raw.size else np.float32(0.0)
            mn = ok_raw.min() if ok_raw.size else np.float32(0.0)
            if mx > mn:
                ppref_norm = np.floor(
                    (raw - mn) * np.float32(100.0) / np.float32(mx - mn))
            else:
                ppref_norm = np.zeros_like(raw)
        # spread minimums hoisted per (pod, term): invariant across the node
        # scan, restricted to domains of nodes the pod is ELIGIBLE for
        # (admission bit test), matching the batched evaluators
        spread_min = {}
        if T:
            elig = (
                (int(pod_taint_mask[p]) >> node_taint_group) & 1) > 0  # [N]
            for t in range(T):
                if pod_spread_skew[p, t] > 0:
                    valid = (aff_dom[:, t] >= 0) & elig
                    spread_min[t] = (aff_count[valid, t].min()
                                     if valid.any() else np.inf)
        for n in range(N):
            if not node_ok[n]:
                continue
            # Fit
            if any(
                fit_requests[p, r] > 0
                and requested[n, r] + fit_requests[p, r] > allocatable[n, r]
                for r in range(R)
            ):
                continue
            if not la_filter_ok(p, n):
                continue
            # TaintToleration: group bit test (ops/taints.py)
            if not (int(pod_taint_mask[p]) >> int(node_taint_group[n])) & 1:
                continue
            # InterPodAffinity (ops/podaffinity.py)
            affinity_ok = True
            for t in range(T):
                if pod_anti_req[p, t] and aff_count[n, t] > 0:
                    affinity_ok = False
                    break
                # symmetric anti-affinity: a carrier of anti term t in this
                # node's domain blocks any pod matching t
                if pod_aff_match[p, t] and anti_cover[n, t] > 0:
                    affinity_ok = False
                    break
                if pod_aff_req[p, t]:
                    bootstrap = pod_aff_match[p, t] and not aff_exists[t]
                    if not ((aff_dom[n, t] >= 0 and aff_count[n, t] > 0)
                            or bootstrap):
                        affinity_ok = False
                        break
                skew = pod_spread_skew[p, t]
                if skew > 0:
                    if aff_dom[n, t] < 0:
                        affinity_ok = False
                        break
                    self_match = 1.0 if pod_aff_match[p, t] else 0.0
                    if aff_count[n, t] + self_match - spread_min[t] > skew:
                        affinity_ok = False
                        break
            if not affinity_ok:
                continue
            # NodePorts: no wanted hostPort slot already bound on the node
            if PT and any(
                pod_port_wants[p, s] and port_used[n, s] > 0
                for s in range(PT)
            ):
                continue
            # CSI volume limit (+inf when the node reports none); the node's
            # volume group selects NEW attachments only (already-attached
            # exemption)
            vn = vol_needed[p, node_vol_group[n]]
            if vn > 0 and vol_free[n] < vn:
                continue
            # cpuset filter
            if needs_bind[p]:
                if not has_topology[n]:
                    continue
                if full_pcpus[p] and cores_needed[p] % max(cpus_per_core[n], 1.0) != 0:
                    continue
                if cores_needed[p] > bind_free[n]:
                    continue
            # NUMA admit
            zone = -1
            if needs_numa[p] and numa_policy[n] != 0:
                if numa_policy[n] == POLICY_SINGLE:
                    zone = -1
                    for k in range(K):
                        if all(
                            requests[p, r] <= 0
                            or requests[p, r] <= numa_free[n, k, r]
                            for r in range(R)
                        ):
                            zone = k
                            break
                    if zone < 0:
                        continue
                else:
                    total = numa_free[n].sum(axis=0)
                    if any(
                        requests[p, r] > 0 and requests[p, r] > total[r]
                        for r in range(R)
                    ):
                        continue
            # scores
            use_prod = prod_mode and is_prod[p]
            acc = np.float32(0.0)
            for r in weight_idx:
                term = term_pr[n, r] if use_prod else term_np[n, r]
                acc += np.float32(weights[r]) * _least_requested(
                    np.float32(estimated[p, r] + term), allocatable[n, r]
                )
            la_score = np.float32(np.floor(acc / max(wsum, np.float32(1.0))))
            if not score_valid[n]:
                la_score = np.float32(0.0)
            acc2 = np.float32(0.0)
            for r in weight_idx:
                acc2 += np.float32(weights[r]) * _least_requested(
                    np.float32(requested[n, r] + requests[p, r]), allocatable[n, r]
                )
            numa_score = np.float32(np.floor(acc2 / max(wsum, np.float32(1.0))))
            # NodeResourcesBalancedAllocation: std of the 2 balanced axes'
            # requested fractions == |fc - fm| / 2 (no sqrt)
            if bal_ci >= 0:
                def _frac(axis):
                    cap = allocatable[n, axis]
                    if cap <= 0:
                        return np.float32(0.0)
                    # reciprocal-multiply, NOT division: every impl
                    # (XLA/Pallas/C++) uses used * f32(1/cap) so the
                    # f32 results are bit-identical across the four
                    inv = np.float32(1.0) / cap
                    f = np.float32(
                        (requested[n, axis] + fit_requests[p, axis]) * inv)
                    return min(f, np.float32(1.0))
                std = np.float32(
                    np.abs(_frac(bal_ci) - _frac(bal_mi)) * np.float32(0.5))
                numa_score = numa_score + np.float32(
                    np.floor((np.float32(1.0) - std) * np.float32(100.0)))
            s = la_score + numa_score
            if pod_pref_id[p] >= 0:
                s = s + pref_scores[n, pod_pref_id[p]]
            if ppref_norm is not None:
                s = s + ppref_norm[n]
            if pod_img_id[p] >= 0:
                s = s + img_scores[n, pod_img_id[p]]
            if s > best_score:
                best_n, best_score, best_zone = n, s, zone
        if best_n < 0:
            continue
        chosen[p] = best_n
        requested[best_n] += fit_requests[p]
        term_np[best_n] += estimated[p]
        if prod_mode and is_prod[p]:
            term_pr[best_n] += estimated[p]
        if needs_numa[p]:
            if best_zone >= 0:
                numa_free[best_n, best_zone] -= requests[p]
            else:
                remaining = requests[p].copy()
                for k in range(K):
                    take = np.minimum(numa_free[best_n, k], remaining)
                    numa_free[best_n, k] -= take
                    remaining -= take
        if needs_bind[p]:
            bind_free[best_n] -= cores_needed[p]
        for s in range(PT):
            if pod_port_wants[p, s]:
                port_used[best_n, s] = 1.0
        vn_best = vol_needed[p, node_vol_group[best_n]]
        if vn_best > 0:
            vol_free[best_n] -= vn_best
        if quota_id[p] >= 0:
            for g in ancestors[quota_id[p]]:
                if g >= 0:
                    quota_used[g] += requests[p]
        for t in range(T):
            if pod_aff_match[p, t]:
                aff_exists[t] = True
                if aff_dom[best_n, t] >= 0:
                    dom = aff_dom[:, t] == aff_dom[best_n, t]
                    aff_count[dom, t] += 1.0
            if pod_anti_req[p, t] and aff_dom[best_n, t] >= 0:
                dom = aff_dom[:, t] == aff_dom[best_n, t]
                anti_cover[dom, t] += 1.0
    return chosen


def diff_bindings(chosen_a: np.ndarray, chosen_b: np.ndarray, keys: List[str]) -> List[str]:
    """Human-readable diff of two binding vectors (parity failures)."""
    out = []
    for i, key in enumerate(keys):
        if chosen_a[i] != chosen_b[i]:
            out.append(f"{key}: {int(chosen_a[i])} != {int(chosen_b[i])}")
    return out
