"""Serial parity emulator: the reference's per-pod plugin chain, scalar in numpy.

This is the trustworthy oracle of SURVEY.md section 7 ("parity harness ... is the
only trustworthy test"): a direct, unvectorized transcription of the reference's
Filter/Score/Reserve semantics (load_aware.go + kube NodeResourcesFit), operating on
the SAME packed inputs as the batched kernel. The batched step must produce
IDENTICAL bindings on any trace. It is also the measured performance floor standing
in for the reference's serial Go chain (BASELINE.md: baseline must be measured).

Everything here is float32 numpy with the same go_round/floor arithmetic as
ops/common.py so the two paths cannot diverge on rounding.
"""

from __future__ import annotations

from typing import List

import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.models.scheduler_model import ScheduleInputs
from koordinator_tpu.ops.fit import with_pod_count  # noqa: F401  (packing parity)
from koordinator_tpu.ops.loadaware import LoadAwareArgs

MAX_NODE_SCORE = 100.0


def _go_round(x: np.float32) -> np.float32:
    return np.float32(np.floor(x + np.float32(0.5)))


def _least_requested(requested: np.float32, capacity: np.float32) -> np.float32:
    if capacity <= 0 or requested > capacity:
        return np.float32(0.0)
    return np.float32(np.floor((capacity - requested) * np.float32(MAX_NODE_SCORE) / capacity))


def serial_schedule(inputs: ScheduleInputs, args: LoadAwareArgs) -> np.ndarray:
    """Schedule the batch pod-by-pod, node-by-node; returns chosen[P] int32."""
    fit_requests = np.asarray(inputs.fit_requests, np.float32)
    estimated = np.asarray(inputs.estimated, np.float32)
    is_prod = np.asarray(inputs.is_prod)
    is_daemonset = np.asarray(inputs.is_daemonset)
    pod_valid = np.asarray(inputs.pod_valid)
    allocatable = np.asarray(inputs.allocatable, np.float32)
    requested = np.array(inputs.requested, np.float32)
    node_ok = np.asarray(inputs.node_ok)
    filter_usage = np.asarray(inputs.la_filter_usage, np.float32)
    has_filter_usage = np.asarray(inputs.la_has_filter_usage)
    filter_thr = np.asarray(inputs.la_filter_thresholds, np.float32)
    prod_thr = np.asarray(inputs.la_prod_thresholds, np.float32)
    prod_usage = np.asarray(inputs.la_prod_pod_usage, np.float32)
    term_np = np.array(inputs.la_term_nonprod, np.float32)
    term_pr = np.array(inputs.la_term_prod, np.float32)
    score_valid = np.asarray(inputs.la_score_valid)
    filter_skip = np.asarray(inputs.la_filter_skip)
    weights = np.asarray(inputs.weights, np.float32)

    P, R = fit_requests.shape
    N = allocatable.shape[0]
    weight_idx = [int(r) for r in np.nonzero(weights)[0]]
    wsum = np.float32(weights.sum())
    prod_mode = args.score_according_prod_usage
    chosen = np.full(P, -1, np.int32)

    def filter_loadaware(p: int, n: int) -> bool:
        # load_aware.go:123-171
        if is_daemonset[p]:
            return True
        if filter_skip[n]:
            # expired or missing NodeMetric: allowed before any profile check
            # (load_aware.go:135-150)
            return True
        prod_configured = bool((prod_thr[n] > 0).any())
        if is_prod[p] and prod_configured:
            # filterProdUsage (load_aware.go:226-255)
            for r in range(R):
                thr = prod_thr[n, r]
                if thr == 0:
                    continue
                total = allocatable[n, r]
                if total == 0:
                    continue
                ratio = _go_round(np.float32(prod_usage[n, r] * 100.0 / total))
                if ratio >= thr:
                    return False
            return True
        if not has_filter_usage[n]:
            return True
        for r in range(R):
            thr = filter_thr[n, r]
            if thr == 0:
                continue
            total = allocatable[n, r]
            if total == 0:
                continue
            ratio = _go_round(np.float32(filter_usage[n, r] * 100.0 / total))
            if ratio >= thr:
                return False
        return True

    def filter_fit(p: int, n: int) -> bool:
        for r in range(R):
            need = fit_requests[p, r]
            if need <= 0:
                continue
            if requested[n, r] + need > allocatable[n, r]:
                return False
        return True

    def score_loadaware(p: int, n: int) -> np.float32:
        # load_aware.go:269-335
        if not score_valid[n]:
            return np.float32(0.0)
        acc = np.float32(0.0)
        use_prod = prod_mode and is_prod[p]
        for r in weight_idx:
            term = term_pr[n, r] if use_prod else term_np[n, r]
            used = np.float32(estimated[p, r] + term)
            acc += np.float32(weights[r]) * _least_requested(used, allocatable[n, r])
        return np.float32(np.floor(acc / max(wsum, np.float32(1.0))))

    for p in range(P):
        if not pod_valid[p]:
            continue
        best_n, best_score = -1, np.float32(-1.0)
        for n in range(N):
            if not node_ok[n]:
                continue
            if not filter_fit(p, n):
                continue
            if not filter_loadaware(p, n):
                continue
            s = score_loadaware(p, n)
            if s > best_score:  # strict: lowest index wins ties
                best_n, best_score = n, s
        if best_n < 0:
            continue
        chosen[p] = best_n
        # Reserve: Fit state + podAssignCache (load_aware.go:263-267)
        requested[best_n] += fit_requests[p]
        term_np[best_n] += estimated[p]
        if prod_mode and is_prod[p]:
            term_pr[best_n] += estimated[p]

    return chosen


def diff_bindings(chosen_a: np.ndarray, chosen_b: np.ndarray, keys: List[str]) -> List[str]:
    """Human-readable diff of two binding vectors (parity failures)."""
    out = []
    for i, key in enumerate(keys):
        if chosen_a[i] != chosen_b[i]:
            out.append(f"{key}: {int(chosen_a[i])} != {int(chosen_b[i])}")
    return out
