"""NUMA topology manager: merge per-provider NUMA hints and admit pods.

Analog of reference `pkg/scheduler/frameworkext/topologymanager/` (manager.go:58,
policy.go:26-224, policy_none.go, policy_best_effort.go, policy_restricted.go,
policy_single_numa_node.go). Hint providers (NodeNUMAResource, DeviceShare)
produce per-resource lists of candidate NUMA affinities; the manager takes the
cross-product across providers/resources, ANDs the masks, and picks the
narrowest preferred merged hint. The policy decides admission:

  none             -> always admit, no affinity
  best-effort      -> always admit, use best merged hint
  restricted       -> admit only if the best merged hint is preferred
  single-numa-node -> consider only single-node (or don't-care) preferred
                      hints; admit only if the result is preferred

In the batched design the device kernel (ops/numa.py) performs the coarse
feasibility cut over all nodes at once; this host module runs the exact
bitmask merge only for the winning (pod, node) pair at Reserve time, mirroring
how the reference runs Admit once per Filter'd node but keeping the hot loop
on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Sequence, Tuple

from koordinator_tpu.utils.bitmask import BitMask

# node label selecting the NUMA topology policy (apis/extension); defined here
# (not in snapshot.py) so both the snapshot packer and host plugins import it
# without a cycle
LABEL_NUMA_TOPOLOGY_POLICY = "node.koordinator.sh/numa-topology-policy"

POLICY_NONE = "none"
POLICY_BEST_EFFORT = "best-effort"
POLICY_RESTRICTED = "restricted"
POLICY_SINGLE_NUMA_NODE = "single-numa-node"

_CANON = {
    "": POLICY_NONE,
    "none": POLICY_NONE,
    "None": POLICY_NONE,
    "best-effort": POLICY_BEST_EFFORT,
    "BestEffort": POLICY_BEST_EFFORT,
    "restricted": POLICY_RESTRICTED,
    "Restricted": POLICY_RESTRICTED,
    "single-numa-node": POLICY_SINGLE_NUMA_NODE,
    "SingleNUMANode": POLICY_SINGLE_NUMA_NODE,
}


def canonical_policy(name: str) -> str:
    return _CANON.get(name, POLICY_NONE)


def resolve_numa_policy(node_labels, kubelet_policy: str) -> str:
    """Label-vs-kubelet-policy precedence, shared by the snapshot packer and
    the host plugin (snapshot.py packs the same rule into the device tensors;
    the two must agree): an explicit label — even an empty one — wins over the
    reported kubelet cpu-manager policy."""
    return canonical_policy(
        node_labels.get(LABEL_NUMA_TOPOLOGY_POLICY, kubelet_policy))


@dataclass
class NUMATopologyHint:
    """One candidate affinity (policy.go:34-42). affinity=None means
    "don't care" (any NUMA node)."""

    affinity: Optional[BitMask] = None
    preferred: bool = True
    score: int = 0

    def is_equal(self, other: "NUMATopologyHint") -> bool:
        if self.preferred != other.preferred:
            return False
        if self.affinity is None or other.affinity is None:
            return self.affinity is other.affinity
        return self.affinity == other.affinity


# providers hand back {resource_name: [hints] | None}; None value = no
# preference for that resource, empty list = no possible placement.
ProviderHints = Optional[Dict[str, Optional[List[NUMATopologyHint]]]]


class NUMATopologyHintProvider(Protocol):
    """manager.go:33-40 NUMATopologyHintProvider."""

    def get_pod_topology_hints(self, pod, node_name: str) -> ProviderHints:
        ...

    def allocate(self, pod, node_name: str, affinity: NUMATopologyHint) -> Optional[str]:
        """Commit an allocation under the merged affinity; error string vetoes."""
        ...


def _filter_providers_hints(
    providers_hints: Sequence[ProviderHints],
) -> List[List[NUMATopologyHint]]:
    """policy.go:94-125: flatten to one hint-list per (provider, resource);
    absent hints become a single preferred don't-care, an explicit empty list
    becomes a single non-preferred don't-care."""
    out: List[List[NUMATopologyHint]] = []
    for hints in providers_hints:
        if not hints:
            out.append([NUMATopologyHint(None, True)])
            continue
        for resource in hints:
            per = hints[resource]
            if per is None:
                out.append([NUMATopologyHint(None, True)])
            elif len(per) == 0:
                out.append([NUMATopologyHint(None, False)])
            else:
                out.append(list(per))
    return out


def _merge_permutation(
    default_affinity: BitMask, permutation: Sequence[NUMATopologyHint]
) -> NUMATopologyHint:
    """policy.go:68-92: AND all masks; preferred iff every hint preferred."""
    preferred = True
    merged = default_affinity
    for hint in permutation:
        mask = hint.affinity if hint.affinity is not None else default_affinity
        merged = merged.and_(mask)
        if not hint.preferred:
            preferred = False
    return NUMATopologyHint(merged, preferred, 0)


def _iter_permutations(hint_lists: List[List[NUMATopologyHint]]):
    """policy.go:207-224 cross-product iteration."""
    if not hint_lists:
        yield []
        return
    stack: List[Tuple[int, List[NUMATopologyHint]]] = [(0, [])]
    while stack:
        i, accum = stack.pop()
        if i == len(hint_lists):
            yield accum
            continue
        for h in reversed(hint_lists[i]):
            stack.append((i + 1, accum + [h]))


def _merge_filtered_hints(
    numa_nodes: Sequence[int], filtered: List[List[NUMATopologyHint]]
) -> NUMATopologyHint:
    """policy.go:127-185: best = narrowest preferred merged hint; score is a
    tie-break at equal width."""
    default_affinity = BitMask(numa_nodes)
    best = NUMATopologyHint(default_affinity, False, 0)
    for permutation in _iter_permutations(filtered):
        merged = _merge_permutation(default_affinity, permutation)
        assert merged.affinity is not None
        if merged.affinity.count() == 0:
            continue
        for h in permutation:
            if h.affinity is not None and merged.affinity == h.affinity:
                if h.score > merged.score:
                    merged.score = h.score
        if merged.preferred and not best.preferred:
            best = merged
            continue
        if not merged.preferred and best.preferred:
            continue
        assert best.affinity is not None
        if not merged.affinity.is_narrower_than(best.affinity):
            if (
                merged.affinity.count() == best.affinity.count()
                and merged.score > best.score
            ):
                best = merged
            continue
        best = merged
    return best


def merge_hints(
    policy: str,
    numa_nodes: Sequence[int],
    providers_hints: Sequence[ProviderHints],
) -> Tuple[NUMATopologyHint, bool]:
    """(best_hint, admit) under the given policy — the four Merge()
    implementations in policy_*.go."""
    policy = canonical_policy(policy)
    if policy == POLICY_NONE:
        return NUMATopologyHint(None, True), True

    filtered = _filter_providers_hints(providers_hints)
    if policy == POLICY_SINGLE_NUMA_NODE:
        # policy_single_numa_node.go:46-62: keep only preferred don't-care or
        # single-node hints before merging.
        filtered = [
            [
                h
                for h in per
                if h.preferred and (h.affinity is None or h.affinity.count() == 1)
            ]
            for per in filtered
        ]
    best = _merge_filtered_hints(numa_nodes, filtered)

    if policy == POLICY_SINGLE_NUMA_NODE:
        default_affinity = BitMask(numa_nodes)
        if best.affinity == default_affinity:
            best = NUMATopologyHint(None, best.preferred, best.score)
        return best, best.preferred
    if policy == POLICY_RESTRICTED:
        return best, best.preferred
    # best-effort
    return best, True


class TopologyManager:
    """manager.go:44-111: gather hints from all providers, merge under the
    node policy, and fan Allocate back out with the winning affinity."""

    def __init__(self, providers: Optional[List[NUMATopologyHintProvider]] = None):
        self.providers: List[NUMATopologyHintProvider] = providers or []

    def register_provider(self, provider: NUMATopologyHintProvider) -> None:
        self.providers.append(provider)

    def admit(
        self, pod, node_name: str, numa_nodes: Sequence[int], policy: str
    ) -> Optional[str]:
        """Returns an error string when the pod cannot be admitted
        (manager.go:58-80); on success fans the winning affinity back out via
        provider Allocate()s (the providers own any durable record of it —
        the reference's Store lives in per-cycle state and dies with it)."""
        providers_hints = [
            p.get_pod_topology_hints(pod, node_name) for p in self.providers
        ]
        best, admit = merge_hints(policy, numa_nodes, providers_hints)
        if not admit:
            return "node(s) NUMA Topology affinity error"
        for p in self.providers:
            err = p.allocate(pod, node_name, best)
            if err:
                return err
        return None


def generate_fit_hints(
    request,  # np-like [R] request vector
    zone_free,  # np-like [K, R] per-zone free
    numa_ids: Sequence[int],
    score_fn=None,
) -> List[NUMATopologyHint]:
    """Hints for a request against per-zone free resources
    (resource_manager.go:418-532): every zone subset whose pooled free covers
    the request is a candidate; preferred iff the subset is minimal-width."""
    import itertools

    k = len(numa_ids)
    fitting: List[Tuple[BitMask, int]] = []
    min_width = k + 1
    for width in range(1, k + 1):
        for combo in itertools.combinations(range(k), width):
            pooled = zone_free[list(combo)].sum(axis=0)
            if all(r <= 0 or r <= f for r, f in zip(request, pooled)):
                mask = BitMask(numa_ids[i] for i in combo)
                fitting.append((mask, width))
                min_width = min(min_width, width)
    hints = []
    for mask, width in fitting:
        score = int(score_fn(mask)) if score_fn else 0
        hints.append(NUMATopologyHint(mask, width == min_width, score))
    return hints
