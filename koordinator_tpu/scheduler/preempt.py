"""ElasticQuota PostFilter preemption.

Reference: `pkg/scheduler/plugins/elasticquota/preempt.go:1-294` (+
`candidate.go`). Semantics kept:

  * canPreempt (preempt.go:276-294): a victim must belong to the SAME quota
    group as the preemptor, have strictly lower priority, and not carry
    `quota.scheduling.koordinator.sh/preemptible: "false"`
    (extension.IsPodNonPreemptible, apis/extension/elastic_quota.go:82-84).
  * usedLimit check (preempt.go:189-200): preemption frees quota `used` until
    used + podRequest <= runtimeQuota holds on EVERY ancestor of the group
    (the same recursive rule the admission kernel enforces, ops/quota.py).
  * minimal victim set with reprieve (preempt.go:154-215): tentatively remove
    all candidates, then re-add ("reprieve") from the most important down while
    the preemptor still fits. PDB-violating candidates are reprieved FIRST so
    the selected victims prefer pods whose budgets have headroom; as in
    upstream preemption, a PDB is advisory here — a violating victim is still
    evicted when no non-violating set suffices.
  * nominated-pod accounting (PostFilterState, plugin.go:57-72): within one
    PostFilter pass, earlier preemptors' requests count as used for later
    ones, so two starved pods in one group each claim their own victims
    instead of the second seeing phantom headroom.

Architecture note (TPU-first): victim selection is host control-plane work
(G ~ 10^2 groups, member lists are small); the *retry* after eviction is the
batched kernel itself — the cycle driver reruns the fused full-chain step once
after a successful preemption round, so a starved min-guaranteed group reclaims
within the same cycle instead of waiting for the next one.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.api.objects import QUOTA_DOMAIN_PREFIX, Pod
from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.client.store import KIND_POD, ObjectStore

LABEL_PREEMPTIBLE = QUOTA_DOMAIN_PREFIX + "/preemptible"


def is_pod_non_preemptible(pod: Pod) -> bool:
    """extension.IsPodNonPreemptible (elastic_quota.go:82-84)."""
    return pod.meta.labels.get(LABEL_PREEMPTIBLE, "") == "false"


class GangVictimGuard:
    """Gang all-or-nothing vs preemption.

    Evicting a bound gang member below its PodGroup's min_member
    silently breaks the barrier the admission kernel enforced at bind
    time — the koordsim churn soak caught DefaultPreemption doing
    exactly this to priority-less gang pods (the upstream vendored
    DefaultPreemption has the same hole; the coscheduling plugin only
    protects gangs BEFORE they bind). One guard instance spans a whole
    post_filter call, so victim sets chosen for different preemptors
    share one spare-member ledger:

      * ``protected(pod)`` — the pod's gang has no spare bound members:
        never a candidate;
      * ``admissible(victims)`` — would this victim set overdraw any
        gang's spare count? (two same-gang victims can each look fine
        alone);
      * ``commit(victims)`` — debit the ledger once a round is taken.

    Gangs whose bound count already sits below min_member (external
    lifecycle churn) have no spare either — preemption never makes a
    broken gang worse."""

    def __init__(self, store: ObjectStore, live=None) -> None:
        """``live``: an already-built list of assigned, non-terminated
        pods — callers that just walked the store (post_filter) pass it
        to avoid a second full O(|pods|) scan on the hot path."""
        from koordinator_tpu.client.store import KIND_POD_GROUP

        mins = {g.meta.key: g.min_member
                for g in store.list(KIND_POD_GROUP)}
        if live is None:
            live = (p for p in store.list(KIND_POD)
                    if p.is_assigned and not p.is_terminated)
        bound: dict = {}
        for p in live:
            g = p.gang_key
            if g and g in mins:
                bound[g] = bound.get(g, 0) + 1
        self._spare = {g: bound[g] - mins[g] for g in bound}

    def protected(self, pod: Pod) -> bool:
        g = pod.gang_key
        return g in self._spare and self._spare[g] <= 0

    def admissible(self, victims) -> bool:
        taken: dict = {}
        for v in victims:
            g = v.gang_key
            if g in self._spare:
                taken[g] = taken.get(g, 0) + 1
        return all(self._spare[g] >= n for g, n in taken.items())

    def commit(self, victims) -> None:
        for v in victims:
            g = v.gang_key
            if g in self._spare:
                self._spare[g] -= 1


@dataclass
class PreemptionRound:
    """Outcome of one preemptor's PostFilter attempt."""

    preemptor_key: str
    quota_name: str
    victim_keys: List[str] = field(default_factory=list)


class QuotaPreemptor:
    """PostFilter path: evict lower-priority same-group pods to free quota."""

    def __init__(self, store: ObjectStore, quota_plugin) -> None:
        self.store = store
        self.plugin = quota_plugin

    # -- candidate selection -------------------------------------------
    def _quota_index(self) -> dict:
        """quota name -> assigned live member pods, built in ONE store walk.
        post_filter hands this to every _select_victims call instead of
        re-walking the whole store per rejected pod (at 10k+ pods x dozens
        of rejections that walk dominated the cycle)."""
        index: dict = {}
        for p in self.store.list(KIND_POD):
            q = p.quota_name
            if q and p.is_assigned and not p.is_terminated:
                index.setdefault(q, []).append(p)
        return index

    def _candidates(self, preemptor: Pod, quota_index: dict,
                    gang_guard: Optional["GangVictimGuard"] = None,
                    ) -> List[Pod]:
        """canPreempt filter: live assigned members of the preemptor's quota
        group with strictly lower priority, not marked non-preemptible, and
        not protected by their gang's min_member (GangVictimGuard)."""
        pri = preemptor.spec.priority or 0
        return [
            p
            for p in quota_index.get(preemptor.quota_name, ())
            if not p.is_terminated
            and (p.spec.priority or 0) < pri
            and not is_pod_non_preemptible(p)
            and not (gang_guard is not None and gang_guard.protected(p))
        ]

    @staticmethod
    def _importance_key(pod: Pod):
        """util.MoreImportantPod order: higher priority first, then longer
        running (older) first. Reprieve walks this order, so the final victims
        are the least important members."""
        return (-(pod.spec.priority or 0), pod.meta.creation_timestamp)

    @staticmethod
    def _fits(req: np.ndarray, chain: np.ndarray, used: np.ndarray,
              runtime: np.ndarray, freed: np.ndarray) -> bool:
        """checkQuotaRecursive with `freed` subtracted along the chain."""
        for g in chain:
            if g < 0:
                continue
            avail_used = np.maximum(used[g] - freed, 0.0)
            if ((req > 0) & (avail_used + req > runtime[g])).any():
                return False
        return True

    def _select_victims(
        self,
        preemptor: Pod,
        req: np.ndarray,
        chain: np.ndarray,
        used: np.ndarray,     # [G, R] incl. inflight nominations
        runtime: np.ndarray,  # [G, R]
        quota_index: Optional[dict] = None,
        gang_guard: Optional["GangVictimGuard"] = None,
    ) -> Optional[List[Pod]]:
        """Minimal victim set freeing enough quota, or None if preemption
        cannot help (no candidates / still over limit with all of them gone —
        preempt.go:149-163)."""
        candidates = self._candidates(
            preemptor,
            quota_index if quota_index is not None else self._quota_index(),
            gang_guard=gang_guard)
        if not candidates:
            return None
        freed_all = np.zeros(req.shape, np.float32)
        for c in candidates:
            freed_all += c.spec.requests.to_vector()
        if not self._fits(req, chain, used, runtime, freed_all):
            return None  # even evicting every candidate can't make room

        # classify by PDB headroom with a shared budget across the sorted list
        # (filterPodsWithPDBViolation keeps a pdbsAllowed counter, not a
        # per-pod check — two victims sharing one budget must not both pass)
        ordered = sorted(candidates, key=self._importance_key)
        violating, non_violating = self._split_by_pdb(ordered)

        victims: List[Pod] = []
        freed = freed_all.copy()
        for c in violating + non_violating:
            # reprieve: add c back unless the preemptor then stops fitting
            without = freed - c.spec.requests.to_vector()
            if self._fits(req, chain, used, runtime, without):
                freed = without
            else:
                victims.append(c)
        if victims and gang_guard is not None and (
                not gang_guard.admissible(victims)):
            # the minimal set needs more same-gang victims than the gang
            # has spare bound members: preemption cannot help without
            # breaking all-or-nothing — leave the gang whole
            return None
        return victims or None

    def _split_by_pdb(self, ordered: List[Pod]):
        """Stable split into (violating, non_violating) with shared
        DisruptionsAllowed budgets (preempt.go:219-268) — the module-level
        helpers, budgets computed fresh per call."""
        pdbs, allowed = pdb_disruption_budgets(self.store)
        return split_by_pdb(pdbs, allowed, ordered)

    # -- the PostFilter entry ------------------------------------------
    def post_filter(self, rejected: List[Pod]) -> List[PreemptionRound]:
        """One PostFilter pass over every quota-rejected pod, in queue order.

        The tree snapshot is built once and only rebuilt after a round that
        actually evicted (store `used` changed); earlier preemptors' requests
        ride an inflight ledger so later ones see them as used
        (PostFilterState nominated-pod accounting). The cycle driver reruns
        the batched kernel afterwards — victims terminate synchronously, so
        the retry binds the preemptors."""
        rounds: List[PreemptionRound] = []
        snap = self.plugin.tree_snapshot(self.store)
        if snap is None:
            return rounds
        tree, runtime = snap
        inflight: List[Tuple[str, np.ndarray]] = []  # (quota, request)

        def used_with_inflight() -> np.ndarray:
            extra = tree.used.copy()
            for qname, vec in inflight:
                gid = tree.index.get(qname)
                if gid is None:
                    continue
                for g in tree.ancestors[gid]:
                    if g >= 0:
                        extra[g] += vec
            return extra

        quota_index = self._quota_index()
        gang_guard = GangVictimGuard(self.store)
        for pod in rejected:
            gid = tree.index.get(pod.quota_name)
            if gid is None:
                continue
            chain = tree.ancestors[gid]
            req = pod.spec.requests.to_vector()
            used = used_with_inflight()
            if self._fits(req, chain, used, runtime, np.zeros_like(req)):
                # quota headroom exists. If an earlier round freed it, the pod
                # will bind on retry — account it for later preemptors. With
                # no evictions yet, the rejection wasn't quota-driven (node
                # fit etc.): adding it to the ledger would make later
                # preemptors evict victims for a pod that still can't bind.
                if rounds:
                    inflight.append((pod.quota_name, req))
                continue
            victims = self._select_victims(pod, req, chain, used, runtime,
                                           quota_index=quota_index,
                                           gang_guard=gang_guard)
            if not victims:
                continue
            rounds.append(evict_round(self.store, pod, victims))
            gang_guard.commit(victims)
            inflight.append((pod.quota_name, req))
            # evictions changed store-backed used (and group request):
            # rebuild the snapshot AND the candidate index
            snap = self.plugin.tree_snapshot(self.store)
            if snap is None:
                break
            tree, runtime = snap
            quota_index = self._quota_index()
        return rounds


def pdb_disruption_budgets(store: ObjectStore):
    """(pdbs, allowed): each PDB's DisruptionsAllowed computed once —
    preempt.go:219-268 / upstream filterPodsWithPDBViolation keep a shared
    counter per PDB, so callers hand split_by_pdb a COPY of `allowed`."""
    from koordinator_tpu.client.store import KIND_PDB

    pdbs = list(store.list(KIND_PDB))
    if not pdbs:
        return [], []
    pods = list(store.list(KIND_POD))
    allowed: List[int] = []
    for pdb in pdbs:
        matching = [p for p in pods if pdb.matches(p)]
        healthy = sum(1 for p in matching if p.is_healthy)
        if pdb.min_available is not None:
            allowed.append(healthy - pdb.min_available)
        elif pdb.max_unavailable is not None:
            unavailable = len(matching) - healthy
            allowed.append(pdb.max_unavailable - unavailable)
        else:
            allowed.append(0)
    return pdbs, allowed


def split_by_pdb(pdbs, allowed: List[int], ordered: List[Pod]):
    """Stable split of `ordered` into (violating, non_violating), consuming
    the shared `allowed` budgets in order (the caller passes a copy)."""
    if not pdbs:
        return [], list(ordered)
    violating, non_violating = [], []
    for pod in ordered:
        violated = False
        for i, pdb in enumerate(pdbs):
            # an unhealthy victim consumes no budget and can never
            # violate: evicting it leaves the healthy count unchanged
            if not pdb.matches(pod) or not pod.is_healthy:
                continue
            allowed[i] -= 1
            if allowed[i] < 0:
                violated = True
        (violating if violated else non_violating).append(pod)
    return violating, non_violating


def evict_round(store: ObjectStore, preemptor: Pod,
                victims: List[Pod]) -> PreemptionRound:
    """Terminate the victims and record the round (shared by the quota and
    default preemptors)."""
    from koordinator_tpu.descheduler.evictions import terminate_pod

    round_ = PreemptionRound(preemptor_key=preemptor.meta.key,
                             quota_name=preemptor.quota_name)
    for v in victims:
        terminate_pod(store, v, "koordinator.sh/preempted-by",
                      preemptor.meta.key)
        round_.victim_keys.append(v.meta.key)
    return round_


class DefaultPreemption:
    """Priority (pod-level) preemption — the analog of the vendored
    kube-scheduler DefaultPreemption PostFilter the reference binary ships.

    For each pod that failed Filter on every node, dry-run removing
    lower-priority victims per node host-side — static admission (taints +
    selector/affinity labels), resources (allocatable vs assigned
    requests), and the pod's required (anti-)affinity terms against the
    post-eviction state — reprieving candidates from the most important
    down, PDB-violating ones first, and pick the node upstream's
    pickOneNodeForPreemption would: fewest PDB violations, then lowest max
    victim priority, then smallest priority sum, then fewest victims.
    Earlier preemptors' requests ride a per-node inflight ledger so later
    ones don't count freed space twice. Victims terminate synchronously
    and the cycle driver reruns the batched kernel, which is the REAL
    feasibility gate (NUMA/cpuset/LoadAware/spread re-check there; the
    cycle's attempted-latch stops a pod that still cannot bind from
    draining victims every cycle).

    kernel_admission: the (node_name -> group id, pod key -> group bitmask)
    view of the LAST kernel pass's admission grouping (ops/taints.py). The
    raw label/taint dry-run can be more permissive than the kernel when the
    signature budget overflowed (a node degraded to its label-unknown
    bucket admits no selector pods there) — without this check the dry-run
    would accept a node the kernel can never bind and evict victims in
    vain every retry window."""

    def __init__(self, store: ObjectStore, kernel_admission=None,
                 attempt_seed: int = 0) -> None:
        self.store = store
        self._node_groups, self._pod_masks = kernel_admission or ({}, {})
        # rotates the candidate-sampling window across retry attempts
        # (upstream's random offset analog, deterministic here)
        self.attempt_seed = attempt_seed

    def _static_admission(self, pod: Pod, node) -> bool:
        from koordinator_tpu.ops.taints import (
            required_node_pairs,
            tolerates_taints,
        )

        if node.unschedulable:
            return False
        if not tolerates_taints(pod.spec.tolerations, node.taints):
            return False
        labels = node.meta.labels
        if not all(labels.get(k) == v for k, v in required_node_pairs(pod)):
            return False
        # consult the kernel's admission grouping: the dry-run must never
        # accept a node the batched encoding cannot bind
        gid = self._node_groups.get(node.meta.name)
        mask = self._pod_masks.get(pod.meta.key)
        if gid is not None and mask is not None and not ((mask >> gid) & 1):
            return False
        return True

    @staticmethod
    def _affinity_feasible(pod: Pod, node, survivors: List[Pod],
                           nodes_by_name: Dict[str, object]) -> bool:
        """Required (anti-)affinity dry-run against the post-eviction pod
        set: every anti term has no surviving match in the node's domain,
        every affinity term keeps a match (or bootstraps). Without this, a
        pod blocked by kernel-only constraints would evict victims in vain
        every cycle."""
        from koordinator_tpu.ops.podaffinity import _pod_matches, _term_key

        def domain_match(term, key) -> bool:
            dom = node.meta.labels.get(key)
            if dom is None:
                return False
            for other in survivors:
                onode = nodes_by_name.get(other.spec.node_name)
                if onode is None or onode.meta.labels.get(key) != dom:
                    continue
                if _pod_matches(term, other):
                    return True
            return False

        for raw in pod.spec.pod_anti_affinity:
            if node.meta.labels.get(raw.topology_key) is None:
                continue
            if domain_match(_term_key(raw, pod), raw.topology_key):
                return False
        # SYMMETRIC anti-affinity: a surviving pod CARRYING an anti term the
        # preemptor matches blocks its whole domain (the kernel enforces
        # this via anti_cover — the dry-run must not accept what the kernel
        # will reject, or victims die in vain every retry window)
        for other in survivors:
            for raw in other.spec.pod_anti_affinity:
                dom = node.meta.labels.get(raw.topology_key)
                if dom is None:
                    continue
                onode = nodes_by_name.get(other.spec.node_name)
                if onode is None or onode.meta.labels.get(
                        raw.topology_key) != dom:
                    continue
                if _pod_matches(_term_key(raw, other), pod):
                    return False
        for raw in pod.spec.pod_affinity:
            term = _term_key(raw, pod)
            if any(_pod_matches(term, o) for o in survivors):
                if not domain_match(term, raw.topology_key):
                    return False
            # no match anywhere: feasible only via self-match bootstrap
            elif not _pod_matches(term, pod):
                return False
        return True

    def post_filter(self, failed: List[Pod]) -> List[PreemptionRound]:
        from koordinator_tpu.client.store import KIND_NODE

        nodes = list(self.store.list(KIND_NODE))
        nodes_by_name = {n.meta.name: n for n in nodes}
        live = [p for p in self.store.list(KIND_POD)
                if p.is_assigned and not p.is_terminated]
        by_node: Dict[str, List[Pod]] = {}
        req_of: Dict[str, np.ndarray] = {}
        for p in live:
            by_node.setdefault(p.spec.node_name, []).append(p)
            req_of[p.meta.key] = p.spec.requests.to_vector()
        pdbs, budgets = pdb_disruption_budgets(self.store)
        gang_guard = GangVictimGuard(self.store, live=live)
        evicted: set = set()
        inflight: Dict[str, np.ndarray] = {}  # node -> earlier preemptors' req

        # ---- packed node pre-filter (the dominant cost at scale was the
        # per-(pod, node) Python resource sums: |failed| x |nodes| x
        # |assigned| generator passes). Per node, precompute free capacity
        # and the prefix request sums of its preemptible pods sorted by
        # priority; per failed pod ONE vectorized pass yields the nodes
        # where free + gain(prio) covers the request AND the kernel's
        # admission bit admits the pod. The pre-filter itself is exact (an
        # over-approximation of the inner predicate, so no feasible node is
        # lost); the CANDIDATE CAP below is upstream's sampling semantics,
        # not a pure optimization.
        N = len(nodes)
        R = NUM_RESOURCES
        alloc_arr = np.zeros((N, R))
        unsched_arr = np.zeros(N, bool)
        gid_arr = np.full(N, -1, np.int64)
        for j, node in enumerate(nodes):
            alloc_arr[j] = node.allocatable.to_vector()
            unsched_arr[j] = node.unschedulable
            gid_arr[j] = self._node_groups.get(node.meta.name, -1)
        assigned_sum = np.zeros((N, R))
        node_prios: List[np.ndarray] = [None] * N
        node_prefix: List[np.ndarray] = [None] * N
        node_idx = {n.meta.name: j for j, n in enumerate(nodes)}

        def pack_node(j: int) -> None:
            name = nodes[j].meta.name
            assigned = [p for p in by_node.get(name, [])
                        if p.meta.key not in evicted]
            assigned_sum[j] = (
                np.sum([req_of[p.meta.key] for p in assigned], axis=0)
                if assigned else 0.0)
            cands = sorted(
                (p for p in assigned if not is_pod_non_preemptible(p)
                 and not gang_guard.protected(p)),
                key=lambda p: p.spec.priority or 0)
            node_prios[j] = np.asarray(
                [p.spec.priority or 0 for p in cands], np.int64)
            pref = np.zeros((len(cands) + 1, R))
            for k, p in enumerate(cands):
                pref[k + 1] = pref[k] + req_of[p.meta.key]
            node_prefix[j] = pref

        for j in range(N):
            pack_node(j)

        # ragged-but-flat gather tables (no N x kmax padding — a single
        # hot node with many preemptible pods must not inflate a dense
        # tensor): per-node candidate priorities and prefix sums
        # concatenated with offsets. Rebuilt wholesale after an eviction
        # repacks a node (one concatenate over ~|live| rows, rare).
        gather: Dict[str, np.ndarray] = {}

        def build_gather() -> None:
            offsets = np.zeros(N + 1, np.int64)
            for j in range(N):
                offsets[j + 1] = offsets[j] + node_prios[j].shape[0]
            gather["offsets"] = offsets
            gather["flat_prios"] = (
                np.concatenate(node_prios) if N and offsets[-1]
                else np.zeros(0, np.int64))
            gather["flat_prefix"] = (
                np.concatenate(node_prefix) if N
                else np.zeros((0, R)))
            # prefix rows: node j owns rows [offsets[j] + j,
            # offsets[j+1] + j + 1) — each node contributes k_j + 1 rows
            gather["prefix_base"] = offsets[:-1] + np.arange(N)

        build_gather()

        def feasible_nodes(pod: Pod, req: np.ndarray, prio: int):
            offsets = gather["offsets"]
            below = np.concatenate(
                [[0], np.cumsum(gather["flat_prios"] < prio)])
            counts = below[offsets[1:]] - below[offsets[:-1]]   # [N]
            gain = gather["flat_prefix"][gather["prefix_base"] + counts]
            free = alloc_arr - assigned_sum
            for name, vec in inflight.items():
                free[node_idx[name]] = free[node_idx[name]] - vec
            ok = ~unsched_arr & ((free + gain - req) >= 0).all(axis=1)
            mask = self._pod_masks.get(pod.meta.key)
            if mask is not None:
                known = gid_arr >= 0
                ok &= ~known | (
                    (mask >> np.maximum(gid_arr, 0)) & 1).astype(bool)
            return np.nonzero(ok)[0]

        # pods that can influence an (anti-)affinity dry-run: carriers of
        # anti terms plus (per preemptor, below) pods matching its own
        # terms. _affinity_feasible only ever consults these, so the
        # survivor set passed in shrinks from |live| to |relevant| —
        # everything else cannot change any verdict.
        anti_carriers = [p for p in live if p.spec.pod_anti_affinity]

        def relevant_for(pod: Pod) -> List[Pod]:
            if not (anti_carriers or pod.spec.pod_anti_affinity
                    or pod.spec.pod_affinity):
                return []
            from koordinator_tpu.ops.podaffinity import (
                _pod_matches,
                _term_key,
            )

            terms = [_term_key(t, pod)
                     for t in pod.spec.pod_anti_affinity]
            terms += [_term_key(t, pod) for t in pod.spec.pod_affinity]
            seen = {p.meta.key for p in anti_carriers}
            out = list(anti_carriers)
            if terms:
                for p in live:
                    if p.meta.key in seen:
                        continue
                    if any(_pod_matches(t, p) for t in terms):
                        out.append(p)
                        seen.add(p.meta.key)
            return out

        rounds: List[PreemptionRound] = []
        for pod in failed:
            req = pod.spec.requests.to_vector()
            prio = pod.spec.priority or 0
            best = None  # (score tuple, node, victims)
            feasible = feasible_nodes(pod, req, prio)
            # upstream DefaultPreemption samples candidate nodes instead of
            # dry-running the whole fleet (minCandidateNodesPercentage=10%,
            # floor 100). The window ROTATES per pod and per retry attempt
            # (the deterministic analog of upstream's random offset), so a
            # pod whose first window is blocked by affinity/victim checks
            # reaches different nodes on later cycles instead of replaying
            # the same failures forever.
            # upstream bases the percentage on the WHOLE fleet
            # (minCandidateNodesPercentage of numNodes, floor 100), not on
            # the prefiltered subset — an aggressive prefilter must not
            # shrink the dry-run window below upstream's
            max_candidates = max(100, len(nodes) // 10)
            evaluated = 0
            # every VISITED node counts toward a hard scan bound —
            # admission/recheck failures included. Without it, a fleet
            # where most nodes fail _static_admission still walks every
            # prefiltered node per failed pod, each paying per-node Python
            # sums (the round-5 advisor's unbounded-scan finding); 2x the
            # candidate budget bounds total per-pod work while the
            # rotating window still reaches fresh nodes on later attempts
            visited = 0
            scan_cap = 2 * max_candidates
            relevant = relevant_for(pod)
            if len(feasible):
                # stable hash: Python's builtin str hash is salted per
                # process, which would make replayed cycles preempt
                # different victims than production. The seed advances the
                # window by MAX_CANDIDATES per attempt: a +1 stride would
                # leave a pod behind an admission-failing window waiting
                # ~scan_cap cycles to reach fresh nodes, while any stride
                # LARGER than the minimum consumed window (the evaluated
                # cap can fire after max_candidates nodes) would tile the
                # ring with permanent gaps — stride == min window width
                # guarantees full coverage across attempts for every
                # feasible-set size
                import zlib

                start = (zlib.crc32(pod.meta.key.encode())
                         + self.attempt_seed * max_candidates) % len(feasible)
                feasible = np.roll(feasible, -start)
            for j in feasible:
                if evaluated >= max_candidates or visited >= scan_cap:
                    break
                visited += 1
                node = nodes[j]
                if not self._static_admission(pod, node):
                    continue
                assigned = [p for p in by_node.get(node.meta.name, [])
                            if p.meta.key not in evicted]
                free = (node.allocatable.to_vector()
                        - sum((req_of[p.meta.key] for p in assigned),
                              np.zeros_like(req))
                        - inflight.get(node.meta.name, 0.0))
                candidates = [
                    p for p in assigned
                    if (p.spec.priority or 0) < prio
                    and not is_pod_non_preemptible(p)
                    and not gang_guard.protected(p)
                ]
                gain = sum((req_of[p.meta.key] for p in candidates),
                           np.zeros_like(req))
                if ((free + gain - req) < 0).any():
                    continue
                evaluated += 1
                # reprieve from the most important down, violating first
                ordered = sorted(candidates,
                                 key=QuotaPreemptor._importance_key)
                violating, non_violating = split_by_pdb(
                    pdbs, list(budgets), ordered)
                victims = list(candidates)
                headroom = free + gain - req
                for p in violating + non_violating:
                    vec = req_of[p.meta.key]
                    if ((headroom - vec) >= 0).all():
                        headroom = headroom - vec
                        victims.remove(p)
                if not victims:
                    continue
                if not gang_guard.admissible(victims):
                    # two same-gang victims can each look fine alone but
                    # jointly overdraw the gang's spare members — skip
                    # the node rather than break all-or-nothing
                    continue
                victim_keys = {v.meta.key for v in victims}
                survivors = [
                    p for p in relevant
                    if p.meta.key not in evicted
                    and p.meta.key not in victim_keys
                    and p.meta.key != pod.meta.key
                ]
                if not self._affinity_feasible(pod, node, survivors,
                                               nodes_by_name):
                    continue
                violating_keys = {v.meta.key for v in violating}
                score = (
                    sum(1 for v in victims if v.meta.key in violating_keys),
                    max((v.spec.priority or 0) for v in victims),
                    sum((v.spec.priority or 0) for v in victims),
                    len(victims),
                    node.meta.name,
                )
                if best is None or score < best[0]:
                    best = (score, node, victims)
            if best is None:
                continue
            _, node, victims = best
            rounds.append(evict_round(self.store, pod, victims))
            evicted.update(v.meta.key for v in victims)
            gang_guard.commit(victims)
            inflight[node.meta.name] = (
                inflight.get(node.meta.name, np.zeros_like(req)) + req)
            # the victim node's assigned set shrank: repack its per-node
            # entries, then rebuild the flat gather tables (O(N + sum k)
            # concatenate — evictions are rare)
            pack_node(node_idx[node.meta.name])
            build_gather()
            # evicted victims consumed disruption budget: recompute so a
            # later preemptor's split/ranking sees the debited PDBs
            pdbs, budgets = pdb_disruption_budgets(self.store)
        return rounds
