"""Incremental snapshot cache: store deltas -> packed arrays, not rebuilds.

SURVEY section 7's design stance — "the caches become device-resident arrays
updated by deltas" — made concrete. `build_full_chain_inputs` (snapshot.py)
rebuilds every packed array from the object store each cycle (~0.3-0.7s at
10k pods x 5k nodes); with a `SnapshotCache` attached it reuses everything
whose inputs did not change since the previous cycle:

  * per-pod packed rows (requests/limits/estimates/flags/masks, queue-key
    tuples, selector-pair sets) keyed by (pod key, resourceVersion) in the
    VECTORIZED pack memo (`pack_memo`): the previous build's column
    matrices are gathered into the next build with batched fancy indexing
    (ops/packing.pack_pods), so only changed rows pay per-object Python —
    reference analog: the scheduling queue caches pod info objects rather
    than re-parsing specs (pkg/scheduler/ vendored internal queue);
  * per-node assigned-request sums, per-quota used sums and per-node
    attached-volume sets maintained from store pod events — reference
    analogs: pod_assign_cache.go, group_quota_manager.go:184-256;
  * per-node LoadAware rows recomputed only for nodes whose Node/NodeMetric
    objects, assign-cache entries, node-local pods, or metric-expiry state
    changed — reference analog: loadaware keeps NodeMetric-derived state per
    node and re-reads only on informer events;
  * per-node NUMA/cpuset rows recomputed only on topology CR or plugin
    allocation-state changes (plugin `node_epoch` counters);
  * the node admission grouping (taints x selector pairs) memoized on
    (node-set epoch, the batch's selector-pair set).

Exactness contract: every cached value is either reused bit-identically
(per-pod rows, per-node recomputes run the same code on the same inputs) or
maintained as float64 accumulation of the exact float32 per-pod rows the
cold path sums — identical for the packed-integer quantities the kernel's
own f32-exactness discipline already requires. tests/test_snapshot_cache.py
diffs every array of cached vs cold builds across churn sequences.

The arrays handed out by a cached build are OWNED by the cache and mutated
in place by later builds; consumers must not hold them across cycles (the
cycle driver consumes them within the cycle; `DeviceSnapshot` uploads the
changed fields before the next build).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from koordinator_tpu.api.objects import Node, Pod
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    PACK_SCALE,
    ResourceList,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_TOPOLOGY,
    KIND_POD,
    KIND_PV,
    KIND_PVC,
    KIND_STORAGECLASS,
    EventType,
    ObjectStore,
)
from koordinator_tpu.ops.fit import PODS_AXIS


def _packed_row(rl) -> np.ndarray:
    """The exact f32 row the cold path's pack_wire_matrix/to_vector emits."""
    wire = np.zeros(NUM_RESOURCES, np.float64)
    rl.fill_wire_row(wire)
    return (wire / PACK_SCALE).astype(np.float32)


class SnapshotCache:
    """Event-driven memo for `build_full_chain_inputs` (see module doc)."""

    def __init__(self, store: ObjectStore, loadaware_plugin=None,
                 numa_plugin=None) -> None:
        self.store = store
        self.loadaware = loadaware_plugin
        self.numa = numa_plugin

        # ---- per-pod caches (keyed key -> (rv, payload)) ----
        self.pod_flags: Dict[str, Tuple[int, tuple]] = {}
        self.pod_masks: Dict[str, Tuple[tuple, float]] = {}
        # VolumeBinding classification (scheduler/volumebinding.py): the
        # PV-scan feeding the admission mask, keyed like the mask itself
        self.pod_vbs: Dict[str, Tuple[tuple, object]] = {}

        # ---- incremental aggregates over ASSIGNED pods ----
        # pod key -> (node, packed f32 row with pods-axis=1) for fit sums
        self._fit_contrib: Dict[str, Tuple[str, np.ndarray]] = {}
        self._node_fit: Dict[str, np.ndarray] = {}       # node -> f64 [R]
        # pod key -> (quota name, packed f32 row) for quota used sums
        self._quota_contrib: Dict[str, Tuple[str, np.ndarray]] = {}
        self._quota_used: Dict[str, np.ndarray] = {}     # quota -> f64 [R]
        # pod key -> (node, frozenset of claim keys); node -> claim -> refs
        self._vol_contrib: Dict[str, Tuple[str, frozenset]] = {}
        self._attached: Dict[str, Dict[str, int]] = {}

        # ---- vectorized pack memo (ops/packing.pack_pods): the previous
        # build's packed pod rows + the flag/mask columns snapshot.py adds,
        # gathered into the next build with batched fancy indexing. The
        # `_prev` handle keeps the outgoing memo readable during the build
        # that replaces it (pack rotates first; the flags block still needs
        # the old columns under the same reused_src mapping).
        self.pack_memo: Optional[dict] = None
        self.pack_memo_prev: Optional[dict] = None
        self._cluster_total: Optional[Tuple[int, np.ndarray]] = None

        # ---- epochs / dirty sets ----
        self.nodes_epoch = 0          # any Node add/update/delete
        self.pvcpv_epoch = 0          # any PVC/PV event
        self._la_dirty: Set[str] = set()   # node names needing LA recompute
        self._node_dirty: Set[str] = set()  # node rows (alloc/taint) to refresh
        self._numa_dirty: Set[str] = set()  # node/topology NUMA rows to refresh
        self._la_keys: Dict[str, tuple] = {}
        self._numa_keys: Dict[str, tuple] = {}
        # per-node NodeMetric update times aligned to the layout (0.0 =
        # missing), plus the last build's expiry bits: metric EXPIRY is the
        # one LA input that changes with pure time passage, so the warm
        # path detects boundary crossings with one vectorized compare
        # instead of a per-node key scan
        self._nm_ut: Optional[np.ndarray] = None
        self._la_expired: Optional[np.ndarray] = None

        # ---- cached node-side arrays (owned; padded to the node bucket) ----
        self._node_names: List[str] = []
        self._pad: int = 0
        self._alloc: Optional[np.ndarray] = None         # [Np, R] f32
        self._la: Dict[str, np.ndarray] = {}
        self._numa: Dict[str, np.ndarray] = {}
        self._adm_cache: Dict[tuple, tuple] = {}
        self._adm_seq = 0

        # per-build change log: node-side field names the build touched.
        # Not load-bearing for the device mirror (DeviceSnapshot compares
        # host values — transformers may rewrite fields post-build); it IS
        # the recompute-hygiene signal tests assert on (a steady-state
        # build must touch nothing).
        self.dirty_fields: Set[str] = set()

        self.stats = {"builds": 0, "pod_row_hits": 0, "pod_row_misses": 0,
                      "la_recomputed": 0, "numa_recomputed": 0,
                      "full_rebuilds": 0}

        # koordbalance (balance/pack.py): rebalance packs fed from THIS
        # cache's subscription chain — the descheduler's second encode of
        # the same cluster is gone (one event stream, two consumers).
        # Keyed by metric-expiration like the standalone per-store packs.
        self._rebalance_packs: Dict[float, object] = {}
        # koordcolo (colo/pack.py): the colo pack fed the same way — the
        # manager's reconciler is the THIRD consumer of this one event
        # stream (one per cache; the config source keys the strategy rows)
        self._colo_pack = None

        store.subscribe(KIND_POD, self._on_pod)
        store.subscribe(KIND_NODE, self._on_node)
        store.subscribe(KIND_NODE_METRIC, self._on_metric)
        store.subscribe(KIND_NODE_TOPOLOGY, self._on_topology)
        store.subscribe(KIND_PVC, self._on_pvcpv)
        store.subscribe(KIND_PV, self._on_pvcpv)
        # StorageClass changes feed the VolumeBinding classification that
        # shapes the admission mask (scheduler/volumebinding.py), so they
        # share the PVC/PV epoch the mask cache is keyed on
        store.subscribe(KIND_STORAGECLASS, self._on_pvcpv)

    # ------------------------------------------------------------------
    # event handlers
    # ------------------------------------------------------------------
    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        for pack in self._rebalance_packs.values():
            pack.on_pod(ev, pod, old)
        if self._colo_pack is not None:
            self._colo_pack.on_pod(ev, pod, old)
        key = pod.meta.key
        self.pod_flags.pop(key, None)
        self.pod_masks.pop(key, None)
        self.pod_vbs.pop(key, None)
        counted = (ev is not EventType.DELETED and pod.is_assigned
                   and not pod.is_terminated)
        self._retract(key)
        if counted:
            node = pod.spec.node_name
            row = _packed_row(pod.spec.requests)
            fit_row = row.copy()
            fit_row[PODS_AXIS] = 1.0
            self._fit_contrib[key] = (node, fit_row)
            self._node_fit.setdefault(
                node, np.zeros(NUM_RESOURCES, np.float64))
            self._node_fit[node] += fit_row
            q = pod.quota_name
            if q:
                self._quota_contrib[key] = (q, row)
                self._quota_used.setdefault(
                    q, np.zeros(NUM_RESOURCES, np.float64))
                self._quota_used[q] += row
            claims = frozenset(
                f"{pod.meta.namespace}/{c}" for c in pod.spec.pvc_names)
            if claims:
                self._vol_contrib[key] = (node, claims)
                refs = self._attached.setdefault(node, {})
                for c in claims:
                    refs[c] = refs.get(c, 0) + 1
        # any pod event on a node invalidates that node's LoadAware rows
        # (assign-cache entries, metric-map membership, prod-class changes)
        for p in (pod, old):
            if p is not None and p.spec.node_name:
                self._la_dirty.add(p.spec.node_name)

    def _retract(self, key: str) -> None:
        hit = self._fit_contrib.pop(key, None)
        if hit is not None:
            node, row = hit
            self._node_fit[node] -= row
        hit = self._quota_contrib.pop(key, None)
        if hit is not None:
            q, row = hit
            self._quota_used[q] -= row
        hit = self._vol_contrib.pop(key, None)
        if hit is not None:
            node, claims = hit
            refs = self._attached.get(node, {})
            for c in claims:
                left = refs.get(c, 0) - 1
                if left <= 0:
                    refs.pop(c, None)
                else:
                    refs[c] = left

    def _on_node(self, ev: EventType, node, old) -> None:
        for pack in self._rebalance_packs.values():
            pack.on_node(ev, node, old)
        if self._colo_pack is not None:
            self._colo_pack.on_node(ev, node, old)
        self.nodes_epoch += 1
        self._node_dirty.add(node.meta.name)
        self._la_dirty.add(node.meta.name)
        self._numa_dirty.add(node.meta.name)

    def _on_metric(self, ev: EventType, nm, old) -> None:
        for pack in self._rebalance_packs.values():
            pack.on_metric(ev, nm, old)
        if self._colo_pack is not None:
            self._colo_pack.on_metric(ev, nm, old)
        self._la_dirty.add(nm.meta.name)
        # keep the layout-aligned update-time vector current so the expiry
        # compare in loadaware_extras never consults a stale timestamp
        if self._nm_ut is not None:
            idx = self.node_index.get(nm.meta.name)
            if idx is not None:
                self._nm_ut[idx] = (
                    0.0 if ev is EventType.DELETED else nm.update_time)

    def _on_topology(self, ev: EventType, cr, old) -> None:
        # numa keys include the plugin epoch; the direct subscription covers
        # cache use without a NUMA plugin attached
        self._numa_keys.pop(cr.meta.name, None)
        self._numa_dirty.add(cr.meta.name)

    def _on_pvcpv(self, ev: EventType, obj, old) -> None:
        self.pvcpv_epoch += 1

    # ------------------------------------------------------------------
    # koordbalance: the shared rebalance pack
    # ------------------------------------------------------------------
    def rebalance_pack(self, expiration_seconds: float):
        """The rebalance pack maintained from THIS cache's store
        subscriptions (no second subscription chain, no duplicate
        encode): the descheduler's LowNodeLoad consumes it as its view
        source when scheduler and descheduler share a process. Existing
        pods replay list-then-watch style at first attach."""
        pack = self._rebalance_packs.get(expiration_seconds)
        if pack is None:
            from koordinator_tpu.balance.pack import RebalancePack

            pack = RebalancePack(self.store, expiration_seconds,
                                 subscribe=False)
            for pod in self.store.list(KIND_POD):
                pack.on_pod(EventType.ADDED, pod, None)
            self._rebalance_packs[expiration_seconds] = pack
        return pack

    # ------------------------------------------------------------------
    # koordcolo: the shared colo pack (third consumer)
    # ------------------------------------------------------------------
    def colo_pack(self, config_source):
        """The colo pack maintained from THIS cache's store
        subscriptions (no second subscription chain, no duplicate
        encode): the koord-manager's DeviceColoReconciler consumes it as
        its view source when manager and scheduler share a process.
        Existing pods replay list-then-watch style at first attach;
        ``config_source`` is the host oracle's hot-reload source so both
        engines derive strategy rows from the same parsed config."""
        if self._colo_pack is None:
            from koordinator_tpu.colo.pack import ColoPack

            pack = ColoPack(self.store, config_source, subscribe=False)
            for pod in self.store.list(KIND_POD):
                pack.on_pod(EventType.ADDED, pod, None)
            self._colo_pack = pack
        return self._colo_pack

    # ------------------------------------------------------------------
    # aggregates (cycle-facing)
    # ------------------------------------------------------------------
    def cluster_total(self, nodes: Sequence[Node]) -> np.ndarray:
        """Sum of node allocatable wire rows, memoized on the node epoch
        (any Node add/update/delete recomputes)."""
        hit = self._cluster_total
        if hit is not None and hit[0] == self.nodes_epoch:
            return hit[1]
        total = ResourceList.pack_wire_matrix(
            node.allocatable for node in nodes).sum(axis=0)
        self._cluster_total = (self.nodes_epoch, total)
        return total

    def assigned_requests(self) -> Dict[str, np.ndarray]:
        """Per-node assigned fit sums — replaces Scheduler._assigned_requests'
        full store walk. Fresh f32 copies (transformers mutate them)."""
        return {
            node: s.astype(np.float32)
            for node, s in self._node_fit.items() if s.any()
        }

    def used_by_quota(self) -> Dict[str, np.ndarray]:
        return {
            q: s.astype(np.float32)
            for q, s in self._quota_used.items() if s.any()
        }

    def attached_sets(self) -> Dict[str, Set[str]]:
        return {n: set(refs) for n, refs in self._attached.items() if refs}

    # ------------------------------------------------------------------
    # pod-side caches
    # ------------------------------------------------------------------
    def pod_flag(self, pod: Pod) -> Optional[tuple]:
        hit = self.pod_flags.get(pod.meta.key)
        if hit is not None and hit[0] == pod.meta.resource_version:
            return hit[1]
        return None

    def put_pod_flag(self, pod: Pod, payload: tuple) -> None:
        self.pod_flags[pod.meta.key] = (pod.meta.resource_version, payload)

    def pod_mask(self, pod: Pod, adm_seq: int) -> Optional[float]:
        hit = self.pod_masks.get(pod.meta.key)
        want = (pod.meta.resource_version, adm_seq, self.pvcpv_epoch)
        if hit is not None and hit[0] == want:
            return hit[1]
        return None

    def put_pod_mask(self, pod: Pod, adm_seq: int, mask: float) -> None:
        self.pod_masks[pod.meta.key] = (
            (pod.meta.resource_version, adm_seq, self.pvcpv_epoch), mask)

    def pod_vb(self, pod: Pod):
        """Memoized VolumeBinding classification — valid while neither the
        pod spec nor any PVC/PV/StorageClass changed."""
        hit = self.pod_vbs.get(pod.meta.key)
        want = (pod.meta.resource_version, self.pvcpv_epoch)
        if hit is not None and hit[0] == want:
            return hit[1]
        return None

    def put_pod_vb(self, pod: Pod, vb) -> None:
        self.pod_vbs[pod.meta.key] = (
            (pod.meta.resource_version, self.pvcpv_epoch), vb)

    # ------------------------------------------------------------------
    # node admission grouping memo
    # ------------------------------------------------------------------
    def node_admission(self, nodes: Sequence[Node], sel_pairs: frozenset):
        """(group ids, groups, adm_seq) — memoized on (node-set epoch,
        selector-pair set). adm_seq keys the per-pod mask cache."""
        from koordinator_tpu.ops.taints import group_node_admission

        key = (self.nodes_epoch, sel_pairs)
        hit = self._adm_cache.get(key)
        if hit is None:
            if len(self._adm_cache) > 16:
                self._adm_cache.clear()
            self._adm_seq += 1
            ids, groups = group_node_admission(nodes, sel_pairs)
            hit = (ids, groups, self._adm_seq)
            self._adm_cache[key] = hit
        return hit

    # ------------------------------------------------------------------
    # node-side arrays
    # ------------------------------------------------------------------
    def _mark(self, field: str) -> None:
        self.dirty_fields.add(field)

    def node_layout(self, nodes: Sequence[Node], pad_to: int) -> bool:
        """Realign to the cycle's node list; returns True when the whole
        node axis must be rebuilt (membership/order/padding changed)."""
        names = [n.meta.name for n in nodes]
        if names == self._node_names and pad_to == self._pad:
            return False
        self._node_names = names
        self.node_index = {n: i for i, n in enumerate(names)}
        self._pad = pad_to
        self._la_keys.clear()
        self._numa_keys.clear()
        self._alloc = None
        self._la.clear()
        self._numa.clear()
        self._nm_ut = None
        self._la_expired = None
        self.stats["full_rebuilds"] += 1
        return True

    def _dirty_indices(self, names: Set[str]) -> List[int]:
        """Layout row indices of a dirty-name set (names outside the
        current layout — deleted/unschedulable nodes — are dropped)."""
        if not names:
            return []
        idx = self.node_index
        return sorted(i for i in (idx.get(n) for n in names)
                      if i is not None)

    def alloc_matrix(self, nodes: Sequence[Node]) -> np.ndarray:
        """[pad, R] estimate_node_allocatable rows, refreshed per node rv."""
        from koordinator_tpu.ops.estimator import estimate_node_allocatable

        if self._alloc is None:
            self._alloc = np.zeros((self._pad, NUM_RESOURCES), np.float32)
            dirty = range(len(nodes))
            self._mark("allocatable")
        else:
            dirty = self._dirty_indices(self._node_dirty)
            if dirty:
                self._mark("allocatable")
        for i in dirty:
            self._alloc[i] = estimate_node_allocatable(nodes[i])
        return self._alloc

    def _metric_expiry_flips(self, state, args, n_real: int) -> List[int]:
        """Rows whose metric-expiry bit flipped since the previous build.
        Expiry is the one LoadAware input that changes with pure time
        passage (no store event), so the warm path detects boundary
        crossings with one vectorized compare over the layout-aligned
        update-time vector instead of a per-node Python key scan."""
        ut = self._nm_ut
        expired = ut <= 0.0
        T = args.node_metric_expiration_seconds
        if T > 0:
            expired = expired | (state.now - ut >= T)
        prev = self._la_expired
        self._la_expired = expired
        if prev is None:
            return []
        return np.nonzero(expired[:n_real] != prev[:n_real])[0].tolist()

    def loadaware_extras(self, state, args, pad_to: int) -> Dict[str, np.ndarray]:
        """Cached per-node LoadAware rows; recomputes only dirty nodes.
        Dirtiness is event-driven: store events land in `_la_dirty`, plugin
        assign-cache mutations drain from the plugin's `epoch_dirty` set,
        and metric expiry flips come from the vectorized compare above — a
        steady-state build touches no per-node Python at all."""
        from koordinator_tpu.ops.loadaware import build_loadaware_node_state

        nodes = state.nodes
        plugin_epoch = (self.loadaware.node_epoch
                        if self.loadaware is not None else {})

        def key_of(node) -> tuple:
            name = node.meta.name
            nm = state.node_metrics.get(name)
            nm_rv = nm.meta.resource_version if nm is not None else -1
            expired = (
                nm is None or nm.update_time <= 0
                or (args.node_metric_expiration_seconds > 0
                    and state.now - nm.update_time
                    >= args.node_metric_expiration_seconds))
            return (node.meta.resource_version, nm_rv,
                    plugin_epoch.get(name, 0), expired)

        if not self._la:
            full = build_loadaware_node_state(
                nodes, state.node_metrics, state.pods_by_key, state.assigned,
                args, state.now, pad_to=pad_to)
            self._la = full
            self._la_keys = {n.meta.name: key_of(n) for n in nodes}
            self.stats["la_recomputed"] += len(nodes)
            self._nm_ut = np.zeros(pad_to, np.float64)
            for i, n in enumerate(nodes):
                nm = state.node_metrics.get(n.meta.name)
                if nm is not None:
                    self._nm_ut[i] = nm.update_time
            self._la_expired = None
            self._metric_expiry_flips(state, args, len(nodes))
            ed = getattr(self.loadaware, "epoch_dirty", None)
            if ed:
                ed.clear()  # the full build covered every node
            for f in full:
                self._mark(f)
            return self._la

        ed = (getattr(self.loadaware, "epoch_dirty", None)
              if self.loadaware is not None else set())
        if self.loadaware is not None and ed is None:
            # plugin without change-reporting (custom subclass): fall back
            # to the conservative per-node key scan
            dirty_idx = [
                i for i, n in enumerate(nodes)
                if n.meta.name in self._la_dirty
                or self._la_keys.get(n.meta.name) != key_of(n)
            ]
        else:
            if ed:
                self._la_dirty |= ed
                ed.clear()
            flips = self._metric_expiry_flips(state, args, len(nodes))
            dirty_idx = sorted(
                set(self._dirty_indices(self._la_dirty)) | set(flips))
        if dirty_idx:
            sub = [nodes[i] for i in dirty_idx]
            rows = build_loadaware_node_state(
                sub, state.node_metrics, state.pods_by_key, state.assigned,
                args, state.now, pad_to=len(sub))
            idx = np.asarray(dirty_idx)
            for f, arr in rows.items():
                self._la[f][idx] = arr[: len(sub)]
                self._mark(f)
            for n in sub:
                self._la_keys[n.meta.name] = key_of(n)
            self.stats["la_recomputed"] += len(sub)
        return self._la

    def numa_arrays(self, state, nodes_requested: np.ndarray,
                    pad_to: int) -> Dict[str, np.ndarray]:
        """Cached NUMA/cpuset node state. Topology nodes refresh on
        (node rv, plugin epoch); non-topology nodes' virtual zone-0 free is
        alloc - requested, recomputed vectorized every build (requested
        changes with every binding)."""
        from koordinator_tpu.ops.numa import (
            MAX_NUMA,
            POLICY_BY_NAME,
            POLICY_NONE,
        )
        from koordinator_tpu.scheduler.snapshot import resolve_numa_policy

        nodes = state.nodes
        n_real = len(nodes)
        plugin_epoch = (self.numa.node_epoch
                        if self.numa is not None else {})
        first = not self._numa
        if first:
            self._numa = {
                "numa_free": np.zeros((pad_to, MAX_NUMA, NUM_RESOURCES),
                                      np.float32),
                "numa_capacity": np.zeros((pad_to, MAX_NUMA, NUM_RESOURCES),
                                          np.float32),
                "numa_policy": np.full(pad_to, POLICY_NONE, np.int32),
                "has_topology": np.zeros(pad_to, bool),
                "bind_free": np.zeros(pad_to, np.float32),
                "cpus_per_core": np.ones(pad_to, np.float32),
            }
        a = self._numa

        def key_of(node) -> tuple:
            name = node.meta.name
            topo = state.topologies.get(name)
            topo_rv = topo.meta.resource_version if topo is not None else -1
            return (node.meta.resource_version, topo_rv,
                    plugin_epoch.get(name, 0))

        ed = (getattr(self.numa, "epoch_dirty", None)
              if self.numa is not None else set())
        if first:
            dirty = list(range(len(nodes)))
            if ed:
                ed.clear()  # the full pass covers every node
        elif self.numa is not None and ed is None:
            # plugin without change-reporting: conservative key scan
            dirty = [
                i for i, n in enumerate(nodes)
                if self._numa_keys.get(n.meta.name) != key_of(n)
            ]
        else:
            if ed:
                self._numa_dirty |= ed
                ed.clear()
            dirty = self._dirty_indices(self._numa_dirty)
        zone_rows: List[Tuple[int, int]] = []
        zone_lists: List = []
        topo_dirty: List[int] = []
        for i in dirty:
            node = nodes[i]
            name = node.meta.name
            topo_cr = state.topologies.get(name)
            if topo_cr is not None and topo_cr.cpus:
                a["has_topology"][i] = True
                a["numa_policy"][i] = POLICY_BY_NAME.get(
                    resolve_numa_policy(node.meta.labels,
                                        topo_cr.kubelet_cpu_manager_policy),
                    POLICY_NONE)
                a["numa_capacity"][i] = 0.0
                for zone in topo_cr.zones:
                    if 0 <= zone.numa_id < MAX_NUMA:
                        zone_rows.append((i, zone.numa_id))
                        zone_lists.append(zone.allocatable)
                topo_dirty.append(i)
            else:
                a["has_topology"][i] = False
                a["numa_policy"][i] = POLICY_NONE
                a["numa_capacity"][i] = 0.0
                a["numa_free"][i] = 0.0
                a["bind_free"][i] = 0.0
                a["cpus_per_core"][i] = 1.0
            self._numa_keys[name] = key_of(node)
        if zone_rows:
            zmat = ResourceList.pack_wire_matrix(zone_lists)
            zi = np.asarray(zone_rows)
            a["numa_capacity"][zi[:, 0], zi[:, 1]] = zmat
        from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName

        cpu_idx = RESOURCE_INDEX[ResourceName.CPU]
        for i in topo_dirty:
            node = nodes[i]
            name = node.meta.name
            alloc = state.numa_allocated.get(name)
            a["numa_free"][i] = a["numa_capacity"][i] - (
                alloc if alloc is not None else 0.0)
            cpu_state = state.cpu_states.get(name)
            if cpu_state is not None:
                a["bind_free"][i] = cpu_state.num_available()
                a["cpus_per_core"][i] = cpu_state.topology.cpus_per_core
            else:
                a["bind_free"][i] = (
                    a["numa_free"][i, :, cpu_idx].sum() / 1000.0)
                a["cpus_per_core"][i] = 2.0
        self.stats["numa_recomputed"] += len(dirty)

        # non-topology virtual zone 0: alloc - requested, refreshed every
        # build but marked dirty only where the value actually moved
        no_topo = np.nonzero(~a["has_topology"][:n_real])[0]
        changed0 = np.zeros(0, np.int64)
        if no_topo.size:
            new_cap = self._alloc[no_topo]
            new_free = new_cap - nodes_requested[no_topo]
            moved = ((a["numa_capacity"][no_topo, 0] != new_cap).any(axis=1)
                     | (a["numa_free"][no_topo, 0] != new_free).any(axis=1))
            changed0 = no_topo[moved]
            if changed0.size:
                a["numa_capacity"][changed0, 0] = self._alloc[changed0]
                a["numa_free"][changed0, 0] = (
                    self._alloc[changed0] - nodes_requested[changed0])
        if dirty or changed0.size:
            self._mark("numa_free")
            self._mark("numa_capacity")
            if dirty:
                for f in ("numa_policy", "has_topology", "bind_free",
                          "cpus_per_core"):
                    self._mark(f)
        return a

    def begin_build(self) -> None:
        self.dirty_fields = set()
        self.stats["builds"] += 1

    def end_build(self) -> None:
        self._la_dirty.clear()
        self._node_dirty.clear()
        self._numa_dirty.clear()
        # the outgoing memo's last consumer is the build that just ended
        # (flags/mask/sel gathers) — release it now instead of carrying a
        # second full copy of the packed columns across the idle period
        self.pack_memo_prev = None


# ---------------------------------------------------------------------------
# device-resident mirror
# ---------------------------------------------------------------------------

# fraction of node rows above which a scatter update loses to a full put
_SCATTER_FRACTION = 0.125


class DeviceAllocationError(RuntimeError):
    """An upload/scatter failed with an allocation-shaped error
    (RESOURCE_EXHAUSTED / out-of-memory): a DEVICE fault, not a bug in
    the snapshot. The dispatch windows treat it exactly like any raised
    device fault — retry once, then demote down the ladder — instead of
    letting an OOM-shaped transfer failure escape as a cycle exception.
    The mirror entry for the failed field is rolled back before
    raising, so a ladder retry re-uploads it from scratch and the
    donation/double-buffer guard re-arms cleanly."""


def _is_resource_exhausted(exc: BaseException) -> bool:
    text = f"{type(exc).__name__}: {exc}".lower()
    return ("resource_exhausted" in text or "resource exhausted" in text
            or "out of memory" in text or "allocation fail" in text)


def _pad_bucket(n: int) -> int:
    """Scatter-row pad bucket: 8, 64, 512, 4096, ... (x8 steps, not x2).
    Each distinct pad is a distinct jitted scatter program; with x2
    buckets the churn-driven dirty-row count hopped buckets nearly every
    warm cycle and re-paid a ~40ms XLA compile — the dominant cost of the
    steady-state cycle. Coarse buckets over-pad by at most 8x with
    DUPLICATE rows (the scatter is an idempotent .at[].set, and the padded
    transfer is still tiny), and the program set stays <= 4 in practice."""
    p = 8
    while p < n:
        p *= 8
    return p


def _mesh_node_fields() -> Set[str]:
    """Field names (FullChainInputs + ScheduleInputs + the fused side
    arrays) whose leading axis is the node axis — the set the mesh-backed
    DeviceSnapshot shards over all devices; everything else is replicated.
    Derived from the SAME sets the dry-run sharders use
    (parallel/mesh.py, parallel/full_chain_mesh.py) so the production
    upload can never disagree with the proven parity layout."""
    from koordinator_tpu.models.scheduler_model import ScheduleInputs
    from koordinator_tpu.parallel.full_chain_mesh import _FC_NODE_FIELDS

    from koordinator_tpu.balance.rebalancer import RB_NODE_FIELDS
    from koordinator_tpu.colo.reconciler import COLO_NODE_FIELDS

    pod_fields = {"fit_requests", "estimated", "is_prod", "is_daemonset",
                  "pod_valid", "weights"}
    base_node = set(ScheduleInputs._fields) - pod_fields
    return (base_node | set(_FC_NODE_FIELDS)
            | {"la_est_nonprod", "la_adj_nonprod",
               # PR 14 fused side arrays: the prod term split and the
               # hot-claim coverage rows ride the node axis too
               "la_est_prod", "la_adj_prod", "claim_covered0"}
            | set(RB_NODE_FIELDS) | set(COLO_NODE_FIELDS))


class DeviceSnapshot:
    """Per-field device mirror of the (sliced) FullChainInputs.

    upload(fc) returns a FullChainInputs of device arrays where every field
    whose host value is unchanged since the previous cycle reuses the
    previous device buffer (zero transfer), small row-deltas of node-axis
    arrays are applied as DONATED scatter updates (transfer = changed rows
    only), and everything else is re-put.

    With ``mesh`` (KOORD_TPU_MESH, parallel/mesh.py) every buffer lives
    under a NamedSharding: node-axis fields shard over all mesh devices
    (zero-padded to the mesh factor by ``put_on_mesh``), pod/quota/gang
    fields replicate, and the incremental scatter routes dirty rows to
    their owning shard — the jitted update pins the node sharding on its
    output and XLA lowers the replicated-index scatter shard-locally, so
    a row delta never reshards (or re-ships) the whole array. The
    donation/double-buffer guard (begin/end_dispatch) is sharding-agnostic
    and applies unchanged."""

    def __init__(self, mesh=None) -> None:
        self.mesh = mesh
        self._node_fields: Optional[Set[str]] = (
            _mesh_node_fields() if mesh is not None else None)
        self._shardings: Dict[bool, object] = {}
        self._fields: Dict[str, Tuple[np.ndarray, object]] = {}
        self._scatter_cache: Dict[tuple, object] = {}
        # dispatches whose consumers may still be in flight on device. A
        # DONATED scatter source reachable by an un-synced dispatch is the
        # double-buffering hazard: donation aliases the input buffer into
        # the output, so the in-flight program could read memory the
        # scatter just overwrote. While any dispatch is outstanding the
        # scatter runs WITHOUT donation (the old buffer stays live as the
        # second buffer until the dispatch syncs) — the cycle driver
        # brackets every async kernel window with begin/end_dispatch. The
        # rebalance mirror shares this snapshot across the cycle thread
        # and the descheduler pass, so the ledger takes a lock; this is
        # the OUTERMOST leg of the canonical order (obs/lockorder.py).
        self._lock = threading.Lock()
        self._in_flight = 0  # koordlint: guarded-by(_lock)
        # sim/test upload-failure hook: callable(field name) invoked
        # before each field's transfer — raising RESOURCE_EXHAUSTED-
        # shaped errors from it exercises the OOM-upload fault model
        self.fault_injector = None
        self.stats = {"reused": 0, "scattered": 0, "scattered_safe": 0,
                      "put": 0, "bytes_put": 0, "bytes_scattered": 0}

    def begin_dispatch(self) -> None:
        """A kernel consuming this snapshot's buffers was dispatched and
        not yet synced: donation of those buffers is unsafe until
        ``end_dispatch``."""
        with self._lock:
            self._in_flight += 1

    def end_dispatch(self) -> None:
        with self._lock:
            self._in_flight = max(0, self._in_flight - 1)

    def _sharding(self, node_axis: bool):
        """The field's NamedSharding under the mesh: node-axis fields flat
        over every device, the rest replicated. Cached per kind."""
        hit = self._shardings.get(node_axis)
        if hit is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from koordinator_tpu.parallel.mesh import _node_axis_spec

            spec = (_node_axis_spec(self.mesh, flat=True) if node_axis
                    else P())
            hit = NamedSharding(self.mesh, spec)
            self._shardings[node_axis] = hit
        return hit

    def _scatter(self, dev, idx: np.ndarray, rows: np.ndarray,
                 sharding=None):
        import jax

        if idx.size == 0:
            # guard the empty dirty-row set: idx[-1] below indexes a
            # zero-length array (IndexError), and a zero-row scatter is a
            # pointless device launch — the unchanged buffer IS the result
            return dev
        pad = _pad_bucket(idx.size)
        idx_p = np.full(pad, idx[-1], np.int32)
        idx_p[: idx.size] = idx
        rows_p = np.broadcast_to(
            rows[-1], (pad,) + rows.shape[1:]).copy()
        rows_p[: idx.size] = rows
        with self._lock:
            donate = self._in_flight == 0
        # the sharding itself (hashable) keys the cache: node-sharded and
        # replicated fields of equal shape/dtype must NOT share a jitted
        # fn, or the pinned out_shardings of whichever compiled first
        # would silently reshard the other
        key = (dev.shape, str(dev.dtype), pad, donate, sharding)
        fn = self._scatter_cache.get(key)
        if fn is None:
            # under a mesh the output sharding is pinned to the input's
            # node sharding: the dirty rows (replicated operands) land on
            # their owning shard via XLA's shard-local scatter lowering —
            # no reshard, no cross-shard traffic beyond the tiny operands
            fn = jax.jit(lambda a, i, r: a.at[i].set(r),
                         donate_argnums=(0,) if donate else (),
                         out_shardings=sharding)
            self._scatter_cache[key] = fn
        if not donate:
            self.stats["scattered_safe"] += 1
        if sharding is not None:
            from koordinator_tpu.parallel.mesh import put_on_mesh

            rep = self._sharding(False)
            idx_p = put_on_mesh(idx_p, rep)
            rows_p = put_on_mesh(rows_p, rep)
        return fn(dev, idx_p, rows_p)

    def _one(self, name: str, new) -> object:
        """One field through the reuse/scatter/put machinery, with
        allocation-shaped transfer failures CLASSIFIED as device faults
        (DeviceAllocationError): a failed field never lands in the host
        mirror, so a ladder retry re-uploads it through the normal
        put/scatter path with the double-buffer guard intact."""
        try:
            if self.fault_injector is not None:
                self.fault_injector(name)
            return self._one_transfer(name, new)
        except Exception as exc:
            # roll the field's mirror entry back on ANY transfer failure:
            # a donated scatter may have consumed the old device buffer
            # before the error surfaced, and a retry gathering against
            # the stale entry would read a deleted array — the fresh
            # full put is always safe
            self._fields.pop(name, None)
            if isinstance(exc, DeviceAllocationError):
                raise
            if _is_resource_exhausted(exc):
                raise DeviceAllocationError(
                    f"device allocation failed uploading {name!r} "
                    f"({type(exc).__name__}: {exc})") from exc
            raise

    def _one_transfer(self, name: str, new) -> object:
        import jax

        new = np.asarray(new)
        sharding = None
        if self.mesh is not None:
            from koordinator_tpu.parallel.mesh import (
                pad_for_sharding,
                put_on_mesh,
            )

            sharding = self._sharding(name in self._node_fields)
            # the host mirror is kept in PADDED coordinates so the change
            # compare and the dirty-row indices line up with the device
            # layout; pad rows are constant zero and never show up dirty
            new = pad_for_sharding(new, sharding)
        hit = self._fields.get(name)
        if (hit is not None and hit[0].shape == new.shape
                and hit[0].dtype == new.dtype):
            prev_np, dev = hit
            # the host equality compare (~1ms total) is the source of
            # truth on purpose: score-phase transformers may rewrite
            # any fc field after the build, so SnapshotCache's
            # dirty_fields cannot vouch for the final arrays
            if np.array_equal(prev_np, new):
                self.stats["reused"] += 1
                return dev
            if new.ndim >= 1 and new.shape[0] == prev_np.shape[0] > 8:
                axes = tuple(range(1, new.ndim))
                rows = np.nonzero(
                    (prev_np != new).any(axis=axes) if axes
                    else prev_np != new)[0]
                if 0 < rows.size <= new.shape[0] * _SCATTER_FRACTION:
                    dev2 = self._scatter(
                        dev, rows.astype(np.int32), new[rows],
                        sharding=sharding)
                    self._fields[name] = (new.copy(), dev2)
                    self.stats["scattered"] += 1
                    self.stats["bytes_scattered"] += int(
                        new[rows].nbytes)
                    return dev2
        if sharding is not None:
            from koordinator_tpu.parallel.mesh import put_on_mesh

            dev = put_on_mesh(new, sharding)
        else:
            dev = jax.device_put(new)
        self._fields[name] = (new.copy(), dev)
        self.stats["put"] += 1
        self.stats["bytes_put"] += int(new.nbytes)
        return dev

    def upload(self, fc):
        base = fc.base
        new_base = type(base)(**{
            k: self._one(k, v) for k, v in base._asdict().items()})
        rest = {k: self._one(k, v)
                for k, v in fc._asdict().items() if k != "base"}
        return type(fc)(base=new_base, **rest)

    def upload_fields(self, fields: Dict[str, np.ndarray]) -> Dict[str, object]:
        """Upload side arrays outside FullChainInputs (the fused wave
        step's LoadAware term split) through the same reuse/scatter/put
        machinery, keyed by the given names."""
        return {k: self._one(k, v) for k, v in fields.items()}
