"""Versioned componentconfig: the v1beta2 external schema round-trip.

Analog of reference `pkg/scheduler/apis/config/v1beta2/` (register.go
addKnownTypes, defaults.go, zz_generated.conversion.go): plugin args are
carried on the wire as camelCase objects with an apiVersion/kind header in
the kube-scheduler config group, embedded in a KubeSchedulerConfiguration's
``profiles[].pluginConfig[].args``. Decoding applies POINTER defaulting —
an absent (or null) field takes the v1beta2 default, while an explicitly
present value is kept even when falsy (the same nil-pointer vs zero-value
distinction the Go defaulter makes) — then converts to the internal form
(scheduler/config.py dataclasses). Encoding emits the fully-defaulted
external form, so decode(encode(cfg)) == cfg (the conversion round-trip
the reference's scheme fuzz-tests).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.config import (
    ConfigValidationError,
    CoschedulingArgs,
    DeviceShareArgs,
    ElasticQuotaArgs,
    NodeNUMAResourceArgs,
    ReservationArgs,
    SchedulerConfiguration,
)

# the koordinator plugin args register into the upstream kube-scheduler
# config group (reference v1beta2/register.go:26 uses
# schedschemev1beta2.GroupName)
API_VERSION = "kubescheduler.config.k8s.io/v1beta2"
CONFIG_KIND = "KubeSchedulerConfiguration"


def _camel(snake: str) -> str:
    head, *rest = snake.split("_")
    out = head + "".join(w.capitalize() for w in rest)
    # acronym spellings the reference uses in JSON tags
    return out.replace("Cpu", "CPU").replace("Numa", "NUMA")


# kind -> (internal dataclass, SchedulerConfiguration attr, plugin name).
# Derived from config.py's section registry so a plugin added there cannot
# silently miss the wire format; every koordinator kind is <plugin>Args
# (reference v1beta2/register.go addKnownTypes).
from koordinator_tpu.scheduler.config import _SECTION_TYPES  # noqa: E402

KINDS: Dict[str, Tuple[type, str, str]] = {
    f"{plugin}Args": (cls, attr, plugin)
    for plugin, (attr, cls) in _SECTION_TYPES.items()
}

# LoadAware's aggregated percentile knobs nest under "aggregated" in the
# external form (reference v1beta2/types.go LoadAwareSchedulingAggregatedArgs)
_AGG_FIELDS = {
    "agg_usage_thresholds": "usageThresholds",
    "agg_usage_aggregation_type": "usageAggregationType",
    "agg_usage_duration_seconds": "usageAggregatedDurationSeconds",
    "agg_score_aggregation_type": "scoreAggregationType",
    "agg_score_duration_seconds": "scoreAggregatedDurationSeconds",
}
_AGG_REV = {ext: snake for snake, ext in _AGG_FIELDS.items()}


def _external_field_map(cls: type) -> Dict[str, str]:
    """snake field -> external camelCase name (aggregated fields excluded:
    they nest)."""
    return {
        f.name: _camel(f.name)
        for f in dataclasses.fields(cls)
        if not (cls is LoadAwareArgs and f.name in _AGG_FIELDS)
    }


def _default_of(f: dataclasses.Field) -> Any:
    if f.default is not dataclasses.MISSING:
        return f.default
    return f.default_factory()  # type: ignore[misc]


def _type_error(kind: str, ext_name: str, value: Any,
                default: Any) -> Optional[str]:
    """Wire-type check against the field's default: bad YAML must become a
    ConfigValidationError here, not a raw TypeError out of validate()."""
    if default is None:
        return None
    if isinstance(default, bool):
        ok = isinstance(value, bool)
    elif isinstance(default, (int, float)):
        ok = isinstance(value, (int, float)) and not isinstance(value, bool)
    elif isinstance(default, str):
        ok = isinstance(value, str)
    elif isinstance(default, dict):
        ok = isinstance(value, dict)
    elif isinstance(default, list):
        ok = isinstance(value, list)
    else:
        return None
    if ok:
        return None
    return (f"{kind}.{ext_name}: expected "
            f"{type(default).__name__}, got {type(value).__name__}")


def decode_args(obj: Dict[str, Any]) -> Tuple[str, Any]:
    """One versioned args object -> (plugin name, internal args), with
    pointer defaulting and strict unknown-field rejection."""
    errs: List[str] = []
    api = obj.get("apiVersion")
    kind = obj.get("kind")
    if api != API_VERSION:
        raise ConfigValidationError([f"unknown apiVersion {api!r}"])
    if kind not in KINDS:
        raise ConfigValidationError([f"unknown kind {kind!r}"])
    cls, _attr, plugin = KINDS[kind]
    fmap = _external_field_map(cls)
    rev = {ext: snake for snake, ext in fmap.items()}
    defaults = {f.name: _default_of(f) for f in dataclasses.fields(cls)}
    kwargs: Dict[str, Any] = {}

    def take(snake: str, ext_name: str, value: Any) -> None:
        terr = _type_error(kind, ext_name, value, defaults.get(snake))
        if terr is not None:
            errs.append(terr)
        else:
            kwargs[snake] = value

    for key, value in obj.items():
        if key in ("apiVersion", "kind"):
            continue
        if cls is LoadAwareArgs and key == "aggregated":
            if value is None:
                continue
            if not isinstance(value, dict):
                errs.append(f"{kind}.aggregated: expected object, got "
                            f"{type(value).__name__}")
                continue
            for akey, avalue in value.items():
                if akey not in _AGG_REV:
                    errs.append(f"{kind}.aggregated: unknown field {akey!r}")
                    continue
                if avalue is not None:  # null == unset == default
                    take(_AGG_REV[akey], f"aggregated.{akey}", avalue)
            continue
        if key not in rev:
            errs.append(f"{kind}: unknown field {key!r}")
            continue
        if value is not None:  # pointer semantics: null -> default
            take(rev[key], key, value)
    if errs:
        raise ConfigValidationError(errs)
    return plugin, cls(**kwargs)


def encode_args(args: Any) -> Dict[str, Any]:
    """Internal args -> the fully-defaulted external form (every field
    explicit, so a round-trip is lossless)."""
    for kind, (cls, _attr, _plugin) in KINDS.items():
        if isinstance(args, cls):
            break
    else:
        raise TypeError(f"not a registered args type: {type(args)!r}")
    out: Dict[str, Any] = {"apiVersion": API_VERSION, "kind": kind}
    for snake, ext in _external_field_map(cls).items():
        out[ext] = getattr(args, snake)
    if cls is LoadAwareArgs:
        out["aggregated"] = {
            ext: getattr(args, snake) for snake, ext in _AGG_FIELDS.items()
        }
    return out


def decode_component_config(
    raw: Dict[str, Any], scheduler_name: str = "koord-scheduler"
) -> SchedulerConfiguration:
    """KubeSchedulerConfiguration (v1beta2 external form) -> internal
    SchedulerConfiguration. Only the matching profile's pluginConfig is
    consumed; absent sections keep their defaults; duplicate args for one
    plugin are an error (the scheme rejects them)."""
    if raw.get("apiVersion") != API_VERSION:
        raise ConfigValidationError(
            [f"unknown apiVersion {raw.get('apiVersion')!r}"])
    if raw.get("kind") != CONFIG_KIND:
        raise ConfigValidationError([f"unknown kind {raw.get('kind')!r}"])
    cfg = SchedulerConfiguration()
    seen: set = set()
    errs: List[str] = []
    # every nested wire layer is isinstance-guarded before container/dict
    # access: malformed YAML (profiles: 17, a string profile, pluginConfig:
    # "oops", args: "foo") must surface as ConfigValidationError, never
    # TypeError/AttributeError — and a string container must be rejected
    # whole, not iterated per character
    profiles = raw.get("profiles") or []
    if not isinstance(profiles, list):
        raise ConfigValidationError(
            [f"profiles: expected list, got {type(profiles).__name__}"])
    for pi, profile in enumerate(profiles):
        if not isinstance(profile, dict):
            errs.append(f"profiles[{pi}]: expected object, got "
                        f"{type(profile).__name__}")
            continue
        if profile.get("schedulerName", scheduler_name) != scheduler_name:
            continue
        plugin_config = profile.get("pluginConfig") or []
        if not isinstance(plugin_config, list):
            errs.append(
                f"profiles[{pi}].pluginConfig: expected list, got "
                f"{type(plugin_config).__name__}")
            continue
        for ei, entry in enumerate(plugin_config):
            if not isinstance(entry, dict):
                errs.append(
                    f"profiles[{pi}].pluginConfig[{ei}]: expected object, "
                    f"got {type(entry).__name__}")
                continue
            name = entry.get("name", "")
            args_obj = entry.get("args")
            if not args_obj:
                continue  # args-less entry == use defaults (legal upstream)
            if not isinstance(args_obj, dict):
                errs.append(
                    f"profiles[{pi}].pluginConfig[{ei}]"
                    f"{f' ({name})' if name else ''}: args must be an "
                    f"object, got {type(args_obj).__name__}")
                continue
            if args_obj.get("kind") not in KINDS:
                # not a koordinator kind: upstream kube-scheduler plugin
                # args (NodeResourcesFitArgs, ...) ride the same profile —
                # they belong to the vendored defaults, pass them through
                continue
            try:
                plugin, args = decode_args(args_obj)
            except ConfigValidationError as e:
                errs.extend(e.errors)
                continue
            if name and name != plugin:
                errs.append(
                    f"pluginConfig name {name!r} does not match args kind "
                    f"for {plugin!r}")
                continue
            if plugin in seen:
                errs.append(f"duplicate pluginConfig for {plugin!r}")
                continue
            seen.add(plugin)
            _cls, attr, _plugin = KINDS[args_obj["kind"]]
            setattr(cfg, attr, args)
    if errs:
        raise ConfigValidationError(errs)
    try:
        cfg.validate()
    except ConfigValidationError:
        raise
    except (TypeError, ValueError) as e:
        # a wire value of the right container type but wrong element type
        # (resourceWeights: {"cpu": "high"}) trips validate()'s comparisons;
        # callers contract on ConfigValidationError
        raise ConfigValidationError([f"invalid config value: {e}"])
    return cfg


def encode_component_config(
    cfg: SchedulerConfiguration, scheduler_name: str = "koord-scheduler"
) -> Dict[str, Any]:
    """Internal -> fully-defaulted external KubeSchedulerConfiguration."""
    plugin_config = []
    for kind, (cls, attr, plugin) in KINDS.items():
        plugin_config.append(
            {"name": plugin, "args": encode_args(getattr(cfg, attr))})
    return {
        "apiVersion": API_VERSION,
        "kind": CONFIG_KIND,
        "profiles": [
            {"schedulerName": scheduler_name, "pluginConfig": plugin_config}
        ],
    }
