"""The scheduling cycle driver: the rebuild's scheduleOne loop.

Where the reference runs one pod at a time through Go plugin dispatch
(frameworkext/framework_extender_factory.go:156-185), this driver drains the
whole pending queue per cycle:

  1. collect pending pods + unscheduled Reservation CRs (reservations ride the
     same queue as pseudo-pods, eventhandlers/reservation_handler.go semantics)
  2. reservation nomination pre-pass: pods matching an Available reservation are
     host-assigned to its node (the nominator prefers reservations; reserved
     resources are owner-restricted, so they bypass the open-capacity kernel)
  3. snapshot -> fused full-chain kernel -> tentative bindings (exact serial
     semantics, see models/full_chain.py)
  4. per binding in queue order: plugin Reserve hooks (cpuset take, device pick)
     -> PreBind annotation accumulation -> single store patch (defaultprebind)
  5. Reserve failure vetoes the binding (unreserve earlier plugins); the pod
     stays pending for the next cycle — mirroring the reference's assume/bind
     error path.

Compiled steps are cached by static shape signature (bucketed P/N/G/NG), so a
steady-state cluster never recompiles.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from koordinator_tpu.api.objects import (
    ANNOTATION_RESERVATION_ALLOCATED,
    ObjectMeta,
    Pod,
    PodSpec,
    Reservation,
)
from koordinator_tpu.api.resources import NUM_RESOURCES, PACK_SCALE
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_PV,
    KIND_PVC,
    KIND_RESERVATION,
    KIND_STORAGECLASS,
    ObjectStore,
)
from koordinator_tpu.models.full_chain import build_best_full_chain_step
from koordinator_tpu.models.fused_waves import (
    MAX_WAVES,
    WAVE_STATE_FIELDS,
    WAVE_STATE_NODE_SLOTS,
    ClaimSides,
    ProdSides,
    ResSides,
    WaveSideInputs,
    initial_wave_carry,
)
from koordinator_tpu.obs import Tracer
from koordinator_tpu.ops.volumes import (
    analyze_pending_claims,
    attached_claim_sets,
    build_claim_pack,
    host_effective_vol_needed,
)
from koordinator_tpu.scheduler.deadline import (
    DeadlineWatchdog,
    DispatchDeadlineExceeded,
    deadline_seconds_from,
)
from koordinator_tpu.scheduler.degrade import (
    LEVEL_FULL,
    LEVEL_HOST_FALLBACK,
    LEVEL_NO_EXPLAIN,
    LEVEL_NO_MESH,
    LEVEL_PARTIAL_MESH,
    LEVEL_SERIAL_WAVES,
    DegradationLadder,
    FusedDispatchDemoted,
    attributable_device_ids,
    host_fallback_schedule,
)
from koordinator_tpu.ops.fit import with_pod_count
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.frameworkext import (
    BindResult,
    CycleContext,
    CycleResult,
    FrameworkExtender,
    ScoreTransformer,
)
from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.plugins import DEFAULT_PLUGINS
from koordinator_tpu.scheduler.sidecar import SidecarClient
from koordinator_tpu.scheduler.snapshot import (
    ClusterState,
    build_full_chain_inputs,
    reduce_to_active_axes,
)

RESERVATION_POD_PREFIX = "__reservation__/"

# ---------------------------------------------------------------------------
# koordwatch demotion-reason registry (PR 14): every `_note_demotion` call
# site must use a registered reason — the chokepoint enforces it at runtime
# and tests/test_static_analysis.py pins the call-site literals against this
# set — and RETIRED reasons (the four data-driven fused-wave demotions burned
# down by the PR-14 carried state) can never silently reappear: re-adding one
# requires touching BOTH sets, which the registry pin test fails loudly.
# ---------------------------------------------------------------------------
DEMOTION_REASONS = frozenset({
    # wave-depth demotions (_effective_waves)
    "ladder-serial-waves",      # degradation ladder at/below serial rung
    "sidecar",                  # the gRPC sidecar protocol is single-round
    "non-expressible-transformer",  # a ScoreTransformer without device_pass
    "claim-entangled",          # unbound-WFFC claim interference or claim
                                # factorization budget overflow (ops/volumes)
    # koordexplain demotions (_effective_explain)
    "explain-sidecar",
    "explain-ladder",
    # per-cycle mesh reconfiguration accounting (run_cycle)
    "mesh-off",
    "partial-mesh",
})
RETIRED_DEMOTION_REASONS = frozenset({
    "pending-reservations",     # carried: reservation rows + in-kernel
                                # nomination (models/fused_waves.py)
    "claim-pods",               # carried: hot-claim columns (ops/volumes.py)
    "prod-usage-score",         # carried: est_sum_prod + la_adj_prod split
    "score-transformer",        # expressible transformers run as tensor
                                # passes; the rest demote as
                                # non-expressible-transformer
})
assert not (DEMOTION_REASONS & RETIRED_DEMOTION_REASONS)

# failure reasons whose condition message is recomputed from the packed
# batch (scheduler/diagnose.py); the deferral path keeps the batch alive
# only when one of these is present — the two sites must stay in sync
DIAGNOSED_REASONS = ("no feasible node", "admission rejected")


def waves_from_env():
    """KOORD_TPU_WAVES=K pins the fused multi-wave depth (K rounds per
    device dispatch, models/fused_waves.py); "auto" (the default) picks K
    from the pending-queue depth, K=1 being the exact serial path."""
    import os

    raw = os.environ.get("KOORD_TPU_WAVES", "auto").strip().lower()
    if raw in ("", "auto"):
        return "auto"
    try:
        return max(1, min(int(raw), MAX_WAVES))
    except ValueError:
        logger.warning("KOORD_TPU_WAVES=%r not an int; using auto", raw)
        return "auto"


def explain_from_env():
    """KOORD_TPU_EXPLAIN=off|counts|full gates koordexplain decision
    attribution (models/full_chain.explain_stage_counts): "counts" emits
    the per-pod per-stage rejected-node counts in the scheduling dispatch
    (diagnose becomes a pure formatter over them), "full" adds the winning
    node's per-plugin score terms + runner-up for bound pods. Returns
    None (off), "counts" or "full"."""
    import os

    raw = os.environ.get("KOORD_TPU_EXPLAIN", "off").strip().lower()
    if raw in ("", "off", "0", "false", "no"):
        return None
    if raw in ("counts", "on", "1", "true"):
        return "counts"
    if raw == "full":
        return "full"
    logger.warning("KOORD_TPU_EXPLAIN=%r unknown; explain stays off", raw)
    return None


def replay_overlap_from_env() -> bool:
    """KOORD_TPU_REPLAY_OVERLAP=0 restores the single-program fused
    dispatch whose host replay runs strictly serially after the one
    readback — the byte-exact parity twin. Default on: the fused
    dispatch runs as a CHAIN of per-wave device programs
    (models/fused_waves.build_chained_wave_step) and the host replays
    logical cycle w while the device executes wave w+1."""
    import os

    return os.environ.get("KOORD_TPU_REPLAY_OVERLAP", "1") != "0"


def pack_overlap_from_env() -> bool:
    """KOORD_TPU_PACK_OVERLAP=0 keeps the incremental pack strictly in
    the inter-window gap (the pre-PR-15 behavior, and the byte-parity
    twin). Default on: cycle N's device window pre-packs the next
    cycle's candidate pod rows into the pack memo (snapshot.py
    prepack_pending_rows) while the device runs — rows dirtied later in
    the window reconcile through the (key, resourceVersion) memo keys,
    so the produced ScheduleInputs are byte-identical either way
    (run_pack_overlap_parity gates it)."""
    import os

    return os.environ.get("KOORD_TPU_PACK_OVERLAP", "1") != "0"


def cycle_deadline_from_env():
    """KOORD_TPU_CYCLE_DEADLINE_MS=N arms the flight recorder's
    deadline-overrun trigger: a cycle slower than N ms dumps the ring.
    Unset/0 disables (the default)."""
    import os

    raw = os.environ.get("KOORD_TPU_CYCLE_DEADLINE_MS", "").strip()
    if not raw:
        return None
    try:
        ms = float(raw)
    except ValueError:
        logger.warning("KOORD_TPU_CYCLE_DEADLINE_MS=%r not a number; "
                       "deadline trigger off", raw)
        return None
    return ms / 1000.0 if ms > 0 else None


def watch_from_env() -> bool:
    """KOORD_TPU_WATCH=0 turns koordwatch off (see the canonical helper
    in obs/timeline.py — shared with the standalone rebalance/colo
    timelines so the kill switch covers every consumer)."""
    from koordinator_tpu.obs.timeline import watch_from_env as _watch

    return _watch()


def _auto_waves(queue_depth: int) -> int:
    """Depth-based auto-K: the fused dispatch amortizes the fixed
    dispatch+readback overhead over K dependent rounds, but each extra
    wave costs real device work, so shallow queues (where one round
    drains everything bindable and the fixed overhead is small relative
    to host work anyway) stay serial. Powers of two only, so the
    compile cache sees at most 4 distinct K values."""
    if queue_depth >= 4096:
        return 8
    if queue_depth >= 1024:
        return 4
    if queue_depth >= 256:
        return 2
    return 1


def _np_spread_fill(row: np.ndarray, req: np.ndarray, zone: int) -> None:
    """In-place numpy replica of ops/numa.numa_spread_fill on one node's
    [K, R] free block: all from ``zone`` when single-numa, else the
    lowest-zones-first waterfall. Same float32 operations in the same
    order as the kernel, so the mirror cannot drift by a ULP."""
    if zone >= 0:
        row[zone] -= req
        return
    remaining = req.astype(np.float32, copy=True)
    for k in range(row.shape[0]):
        take = np.minimum(row[k], remaining)
        row[k] = row[k] - take
        remaining = remaining - take


class _HostWriteFailure(Exception):
    """Control flow: the deferred host work (unschedulability diagnosis +
    condition store writes) failed INSIDE a device-dispatch window. That
    is a store/host-side fault, not a device fault — the degradation
    ladder must not absorb it (shedding device capability cannot fix a
    store, and a retry would silently drop the popped deferred entries).
    The dispatch wrappers unwrap and re-raise the original error, which
    then propagates as an unhandled cycle exception (flight recorder
    ``cycle_exception`` trigger), exactly as it did pre-ladder."""


class _DeferredFlushTxn:
    """Read-your-writes view for one deferred-condition flush: patches
    accumulate here and land as ONE ``store.update_many`` transaction
    (the vectorized store write the wave-replay batching promises),
    while later entries in the same flush still see earlier entries'
    patches — the sequential supersede/idempotence semantics of per-pod
    writes are preserved exactly; only the per-pod lock round-trips and
    duplicate MODIFIED events for re-verdicted pods are gone."""

    def __init__(self, store: ObjectStore) -> None:
        self._store = store
        self.pending: Dict[str, Pod] = {}

    def get(self, key: str) -> Optional[Pod]:
        obj = self.pending.get(key)
        return obj if obj is not None else self._store.get(KIND_POD, key)

    def put(self, obj: Pod) -> None:
        self.pending[obj.meta.key] = obj

    def flush(self) -> None:
        if self.pending:
            self._store.update_many(KIND_POD, list(self.pending.values()))
            self.pending.clear()


class _WaveStateMirror:
    """Host numpy replica of the fused kernel's carried node/quota state
    (models/fused_waves.py), advanced wave by wave with the read-back
    bindings. Feeds per-wave unschedulability diagnosis
    (scheduler/diagnose.py) the SAME wave-start state serial cycle w's
    packed batch would contain — a pod that stays unbound across waves
    must report cycle-w's per-stage counts, not cycle-1's."""

    def __init__(self, fc, claims=None, res_alloc=None) -> None:
        self._fc = fc
        # PR-14 carried-state twins: the hot-claim pack (ops/volumes.py
        # ClaimPack, host arrays) and the reservation rows' packed
        # allocatable vectors — None when the dispatch carries neither
        self._claims = claims
        self._res_alloc = (np.asarray(res_alloc, np.float32)
                           if res_alloc is not None else None)
        self.requested = np.array(fc.base.requested, np.float32, copy=True)
        self.quota_used = np.array(fc.quota_used, np.float32, copy=True)
        self.numa_free = np.array(fc.numa_free, np.float32, copy=True)
        self.bind_free = np.array(fc.bind_free, np.float32, copy=True)
        self.port_used = np.array(fc.port_used, np.float32, copy=True)
        self.vol_free = np.array(fc.vol_free, np.float32, copy=True)
        self.aff_count = np.array(fc.aff_count, np.float32, copy=True)
        self.anti_cover = np.array(fc.anti_cover, np.float32, copy=True)
        self.aff_exists = np.array(fc.aff_exists, bool, copy=True)
        # static per-pod gathers
        self._fit_requests = np.asarray(fc.base.fit_requests, np.float32)
        self._requests = np.asarray(fc.requests, np.float32)
        self._needs_numa = np.asarray(fc.needs_numa, bool)
        self._needs_bind = np.asarray(fc.needs_bind, bool)
        self._cores = np.asarray(fc.cores_needed, np.float32)
        self._wants = np.asarray(fc.pod_port_wants, bool)
        self._vol_needed = np.asarray(fc.vol_needed, np.float32)
        self._vol_group = np.asarray(fc.node_vol_group)
        self._quota_id = np.asarray(fc.quota_id)
        self._ancestors = np.asarray(fc.quota_ancestors)
        self._aff_dom = np.asarray(fc.aff_dom, np.float32)
        self._aff_match = np.asarray(fc.pod_aff_match, bool)
        self._anti_req = np.asarray(fc.pod_anti_req, bool)
        if self._claims is not None:
            n = self.requested.shape[0]
            self._claim_new = np.zeros((n, self._claims.n_claims),
                                       np.float32)
            self._vol_new = np.zeros(n, np.float32)
            self._vol_free0 = np.array(fc.vol_free, np.float32, copy=True)

    def commit(self, i: int, node: int, zone: int) -> None:
        """Apply one kernel-committed binding, mirroring
        commit_pod_state's kept-only replay form."""
        self.requested[node] += self._fit_requests[i]
        self._commit_footprint(i, node, zone)
        if self._claims is None:
            # exemption-free batches: the running count IS the attached-
            # set rebuild (every claim unique — ops/volumes.py)
            self.vol_free[node] -= self._vol_needed[i][self._vol_group[node]]
        else:
            # hot claims: track set growth; end_wave() rebuilds vol_free
            cp = self._claims
            self._claim_new[node] = np.maximum(
                self._claim_new[node],
                cp.pod_claim[i] * (1.0 - cp.covered0[node]))
            self._vol_new[node] += cp.pod_nonhot[i]

    def commit_reservation(self, slot: int, node: int) -> None:
        """A reservation pseudo-pod row bound: the CR holds capacity but
        is not a pod — next-wave state carries the restore transformer's
        allocatable add only (no pod-count slot, no estimate, no NUMA or
        affinity footprint)."""
        self.requested[node] += self._res_alloc[slot]

    def commit_nominated(self, i: int, node: int, zone: int) -> None:
        """A pod nominated onto an Available reservation: its usage
        lives inside the reservation's already-counted footprint, so the
        node's requested row is untouched; NUMA/cpuset/affinity effects
        apply like any bind."""
        self._commit_footprint(i, node, zone)

    def apply_succeed(self, consumer_row: int, slot: int,
                      node: int) -> None:
        """The reconcile's consumed-allocate-once transition, one wave
        after the consumption: the reservation stops being counted and
        its consumer falls back to direct accounting."""
        self.requested[node] = (
            (self.requested[node] - self._res_alloc[slot])
            + self._fit_requests[consumer_row])

    def end_wave(self) -> None:
        """Wave-boundary claim rebuild: vol_free recomputed set-wise
        from the dispatch-start value (integer-exact, like the host's
        limit - len(attached) recompute)."""
        if self._claims is not None:
            self.vol_free = (self._vol_free0 - self._vol_new
                             - self._claim_new.sum(axis=1))

    def _commit_footprint(self, i: int, node: int, zone: int) -> None:
        req = self._requests[i]
        if self._needs_numa[i]:
            _np_spread_fill(self.numa_free[node], req, zone)
        if self._needs_bind[i]:
            self.bind_free[node] -= self._cores[i]
        if self._wants.shape[1]:
            self.port_used[node] = np.maximum(
                self.port_used[node],
                self._wants[i].astype(np.float32))
        qid = int(self._quota_id[i])
        if qid >= 0:
            for g in self._ancestors[qid]:
                if g >= 0:
                    self.quota_used[g] += req
        for t in range(self._aff_dom.shape[1]):
            dom = self._aff_dom[node, t]
            if self._aff_match[i, t]:
                self.aff_exists[t] = True
                if dom >= 0:
                    self.aff_count[self._aff_dom[:, t] == dom, t] += 1.0
            if self._anti_req[i, t] and dom >= 0:
                self.anti_cover[self._aff_dom[:, t] == dom, t] += 1.0

    def patched_fc(self):
        """A FullChainInputs view with the mirror's CURRENT state frozen
        in (copies: the deferred-diagnosis queue may hold it while later
        waves advance the mirror)."""
        fc = self._fc
        patched = fc._replace(
            base=fc.base._replace(requested=self.requested.copy()),
            quota_used=self.quota_used.copy(),
            numa_free=self.numa_free.copy(),
            bind_free=self.bind_free.copy(),
            port_used=self.port_used.copy(),
            vol_free=self.vol_free.copy(),
            aff_count=self.aff_count.copy(),
            anti_cover=self.anti_cover.copy(),
            aff_exists=self.aff_exists.copy(),
        )
        if self._claims is not None:
            # the per-(pod, node) effective volume view at current claim
            # state — what the regrouped [P, VG'] gather would produce
            patched = patched._replace(
                vol_needed=host_effective_vol_needed(
                    fc.vol_needed, fc.node_vol_group,
                    self._claims.pod_claim, self._claim_new),
                node_vol_group=np.arange(
                    self.requested.shape[0], dtype=np.int32))
        return patched


def _apply_mirror_op(mirror: _WaveStateMirror, op: Tuple) -> None:
    """Replay one typed wave-state mirror op (the lazy backlog entries
    the fused replay accumulates): pod/nominated/reservation commits,
    the delayed Succeeded transition, and the wave-boundary claim
    rebuild — in the exact order the device carry applied them."""
    kind = op[0]
    if kind == "pod":
        mirror.commit(op[1], op[2], op[3])
    elif kind == "nom":
        mirror.commit_nominated(op[1], op[2], op[3])
    elif kind == "res":
        mirror.commit_reservation(op[1], op[2])
    elif kind == "succ":
        mirror.apply_succeed(op[1], op[2], op[3])
    elif kind == "wave_end":
        mirror.end_wave()
    else:  # pragma: no cover - programming error
        raise ValueError(f"unknown mirror op {kind!r}")


class Scheduler:
    """koord-scheduler analog: batched cycles against the object store."""

    def __init__(
        self,
        store: ObjectStore,
        args: Optional[LoadAwareArgs] = None,
        scheduler_name: str = "koord-scheduler",
        config: Optional["SchedulerConfiguration"] = None,
        elector=None,
        sidecar_address: Optional[str] = None,
        waves=None,
        explain=None,
        mesh=None,
        ladder=None,
        replay_overlap=None,
        dispatch_deadline_ms=None,
        watch=None,
        pack_overlap=None,
        warmup=None,
    ):
        from koordinator_tpu.scheduler.config import SchedulerConfiguration
        from koordinator_tpu.scheduler.plugins.reservation import (
            ReservationController,
        )

        import dataclasses as _dc

        base = config or SchedulerConfiguration()
        # explicit args win over config (older call sites pass args directly);
        # keep a private copy so the caller's config object is never mutated,
        # and validate what will actually be used
        self.config = (_dc.replace(base, load_aware=args)
                       if args is not None else base)
        self.config.validate()
        self.store = store
        self.args = self.config.load_aware
        self.scheduler_name = scheduler_name
        self.extender = FrameworkExtender(store)
        numa_args = self.config.node_numa_resource
        plugin_kwargs = {
            "NodeNUMAResource": dict(
                max_ref_count=numa_args.max_ref_count,
                default_cpu_bind_policy=numa_args.default_cpu_bind_policy,
                numa_allocate_strategy=numa_args.numa_allocate_strategy,
            ),
            "Coscheduling": dict(
                default_timeout_seconds=self.config.coscheduling.default_timeout_seconds,
            ),
            "DeviceShare": dict(
                scoring_strategy=self.config.device_share.scoring_strategy,
            ),
        }
        for cls in DEFAULT_PLUGINS:
            plugin = cls(**plugin_kwargs.get(cls.name, {}))
            self.extender.register_plugin(plugin)
        # DeviceShare contributes NUMA hints to the shared topology admit
        # (GetPodTopologyHints, deviceshare/topology_hint.go:33)
        numa_plugin = self.extender.plugin("NodeNUMAResource")
        device_plugin = self.extender.plugin("DeviceShare")
        if numa_plugin is not None and device_plugin is not None:
            numa_plugin.topology_manager.register_provider(device_plugin)
        res_plugin = self.extender.plugin("Reservation")
        self.reservation_controller = (
            ReservationController(
                res_plugin, store,
                self.config.reservation.gc_duration_seconds)
            if res_plugin else None
        )
        if res_plugin is not None:
            from koordinator_tpu.scheduler.plugins.reservation import (
                ReservationRestoreTransformer,
            )

            self.extender.register_transformer(
                ReservationRestoreTransformer(store)
            )
        quota_plugin = self.extender.plugin("ElasticQuota")
        self.quota_revoke_controller = (
            quota_plugin.revoke_controller(store, self.config.elastic_quota)
            if quota_plugin else None
        )
        from koordinator_tpu.scheduler.preempt import QuotaPreemptor

        self.preemptor = (
            QuotaPreemptor(store, quota_plugin) if quota_plugin else None
        )
        # active/standby gating (cmd/koord-scheduler/app/server.go:227-256):
        # with an elector, a cycle runs only while this replica holds the lease
        self.elector = elector
        # koordtrace: every cycle emits a root span with the per-stage
        # split (snapshot/encode/kernel/bind); dump via /traces or
        # `python -m koordinator_tpu.obs`
        self.tracer = Tracer()
        import threading as _threading

        # the compiled-step memo is shared with the background warm-up
        # ladder: its thread replays rungs through the same _get_*step
        # chokepoints while the cycle thread dispatches. The lock covers
        # only the dict probes — never a step BUILD, which can hold XLA
        # for seconds (a racing miss costs one duplicate compile, last
        # write wins; torn dict state would cost correctness).
        self._step_lock = _threading.Lock()
        # koordlint: guarded-by(_step_lock)
        self._step_cache: Dict[Tuple, object] = {}
        # per-thread: the background warm-up ladder replays rungs
        # through _get_*step from its own thread, and its misses must
        # not leak into the cycle thread's compiled-dispatch
        # attribution (the flag is always read on the thread that just
        # called _get_*step)
        self._step_tls = _threading.local()
        # host-tail instrumentation (PR 15): cumulative wall seconds of
        # pack/encode work and of compile work (step builds + the kernel
        # windows of freshly-built steps, where the lazy XLA build
        # lands). The crash-restart report splits its recovery wall
        # clock with these (restart_wall_compile/pack_seconds).
        # Lock-guarded accumulation: the background warm-up ladder adds
        # from its own thread, and a lost += would under-report compile.
        self._wall_lock = _threading.Lock()
        self.pack_wall_seconds = 0.0     # koordlint: guarded-by(_wall_lock)
        self.compile_wall_seconds = 0.0  # koordlint: guarded-by(_wall_lock)
        # pack/device overlap (KOORD_TPU_PACK_OVERLAP): pre-pack the
        # next cycle's candidate pod rows inside this cycle's device
        # window. An explicit argument pins it (the parity twins and the
        # bench A/B pair need that).
        self.pack_overlap = (pack_overlap_from_env()
                             if pack_overlap is None else bool(pack_overlap))
        # persistent compile cache + warm-up (scheduler/warmup.py):
        # KOORD_TPU_COMPILE_CACHE_DIR arms jax's on-disk executable
        # cache and the rung index; the warm-up ladder (started at the
        # END of construction, once the mesh/transformers are final)
        # replays recorded rungs so a restarted scheduler's first cycle
        # is an in-memory step-cache hit.
        from koordinator_tpu.scheduler.warmup import (
            compile_cache_dir_from_env,
            configure_compile_cache,
            warmup_mode_from_env,
        )

        self.compile_cache_dir = configure_compile_cache()
        self._warmup_mode = (warmup_mode_from_env() if warmup is None
                             else warmup)
        if self._warmup_mode == "auto":
            # keyed on the ENV knob, not the process-global dir: a test
            # (or co-resident tool) that armed the cache for itself must
            # not opt every later Scheduler into a background ladder
            self._warmup_mode = ("background"
                                 if compile_cache_dir_from_env()
                                 else "off")
        self.warmup = None
        # steady-state compile guard (koordlint rule 20, runtime half):
        # armed when warm-up completes, dropped on every ladder
        # transition (those legitimately re-key the step cache). A miss
        # while armed counts + calls the injectable hook — the sim
        # harness's runtime assert. Single-writer bool handoff (warm-up
        # thread arms it once, the cycle thread reads/clears): a GIL-
        # atomic flip with no compound read-modify-write, so it is
        # deliberately lock-free.
        self._steady_state = False   # koordlint: guarded-by(none)
        self.compile_miss_hook = None
        # parity/test hook: called with the post-reduce host
        # FullChainInputs at the end of every encode (the
        # ScheduleInputs-level byte-parity gate for pack overlap)
        self.encode_observer = None
        # fused multi-wave depth: K rounds per device dispatch
        # (models/fused_waves.py). "auto" picks from queue depth per
        # cycle; an int pins it. K=1 always takes the exact serial path.
        self.waves_spec = waves_from_env() if waves is None else waves
        # overlapped wave replay (KOORD_TPU_REPLAY_OVERLAP): the fused
        # dispatch becomes a chain of per-wave programs and the host
        # drains the replay queue while the device runs the next wave.
        # An explicit argument pins it (the parity twins need that).
        self.replay_overlap = (replay_overlap_from_env()
                               if replay_overlap is None
                               else bool(replay_overlap))
        # koordexplain (KOORD_TPU_EXPLAIN): None=off, "counts", "full".
        # An explicit "off" argument pins it off regardless of env (the
        # bench A/B pairs and parity twins need that determinism). Unknown
        # strings fail loudly — a typo like "Full" would otherwise build
        # the counts kernel and silently drop the score terms.
        if explain not in (None, "off", "counts", "full"):
            raise ValueError(
                f"explain must be None, 'off', 'counts' or 'full'; "
                f"got {explain!r}")
        self.explain_spec = (explain_from_env() if explain is None
                             else (None if explain == "off" else explain))
        # cycle flight recorder (obs/flight.py): decision records for the
        # last N cycles, dumped on deadline overrun / unhandled cycle
        # exception / parity mismatch / HTTP demand
        import threading

        from koordinator_tpu.obs.flight import FlightRecorder
        from koordinator_tpu.obs.timeline import DeviceTimeline

        self.flight = FlightRecorder(
            dump_counter=scheduler_metrics.FLIGHT_DUMPS)
        # koordwatch (PR 13): demotion accounting + the cross-consumer
        # device timeline. KOORD_TPU_WATCH=0 (or watch=False) is the
        # kill switch / bench A/B off-world — decision ids keep minting
        # (cheap, and correlation must never go None-shaped) but the
        # ring stops recording and the chokepoint stops accounting.
        self.watch_enabled = (watch_from_env() if watch is None
                              else bool(watch))
        self.timeline = DeviceTimeline(
            window_histogram=scheduler_metrics.DEVICE_WINDOW_SECONDS,
            idle_gauge=scheduler_metrics.DEVICE_IDLE_FRACTION,
            enabled=self.watch_enabled)
        # per-cycle koordwatch state, reset at run_cycle start: the
        # structured demotion reasons (deduped, first-hit order), the
        # decision ids of this cycle's device windows, and the id of the
        # window currently open (stamped onto /explain attribution)
        self._cycle_demotions: List[str] = []
        self._cycle_decision_ids: List[str] = []
        self._current_decision_id: Optional[str] = None
        # per-cycle claim analysis (ops/volumes.py): set by
        # _effective_waves when the fused path carries claims, consumed
        # by the dispatch's side-input encode
        self._claim_analysis = None
        self.cycle_deadline_seconds = cycle_deadline_from_env()
        # /explain surface state: written by the cycle thread, read by the
        # ObsServer thread — lock-guarded (koordlint concurrency gate)
        self._explain_lock = threading.Lock()
        self.explain_index: Dict[str, dict] = {}
        self._cycle_attrib: Dict[str, dict] = {}
        self._cycle_terms: Dict[str, dict] = {}
        self._cycle_counter = 0
        self._last_cycle_end: Optional[Tuple[float, int]] = None
        # SURVEY 7 step 6: the host event loop may offload the kernel pass
        # to a gRPC sidecar (the Go<->JAX integration shape); transport
        # failures degrade to the in-process step, never wedging the cycle
        self._sidecar_client = (
            SidecarClient(sidecar_address) if sidecar_address else None)
        self.sidecar_fallbacks = 0
        # mesh-backed dispatch (KOORD_TPU_MESH=<ndev>|auto): node-state
        # tensors shard over the device mesh (parallel/mesh.py), the
        # filter/score rows compute shard-locally and the argmax reduces
        # over ICI — the cluster sizes one chip cannot hold. An explicit
        # argument pins it (int/"auto"/"off"/a jax Mesh); None reads the
        # env. The gRPC sidecar protocol is single-device, so a sidecar
        # demotes the mesh off.
        from koordinator_tpu.parallel.mesh import mesh_from_env

        if mesh is None:
            self.mesh = mesh_from_env()
        elif isinstance(mesh, (int, str)):
            self.mesh = mesh_from_env(env_value=mesh)
        else:
            self.mesh = mesh
        if self.mesh is not None and self._sidecar_client is not None:
            logger.warning("KOORD_TPU_MESH ignored: the sidecar RPC "
                           "protocol is single-device")
            self.mesh = None
        scheduler_metrics.MESH_DEVICES.set(
            float(self.mesh.devices.size) if self.mesh is not None else 0.0)
        # graceful-degradation ladder (scheduler/degrade.py): dispatch
        # failures demote mesh -> partial mesh (koordguard, when the
        # fault names its dead devices) -> single-device -> serial waves
        # -> no explain -> pure-host fallback instead of killing the
        # scheduler; clean cycles probe back up. The configured mesh is
        # remembered so a re-promotion can restore it; device ids shed
        # by attributable faults accumulate in _lost_device_ids until a
        # promotion to full probes the whole mesh back.
        self._configured_mesh = self.mesh
        self._lost_device_ids: set = set()
        self._submesh_cache: Dict[frozenset, object] = {}
        self.ladder = ladder if ladder is not None else DegradationLadder()
        self.ladder.observer = self._on_ladder_transition
        scheduler_metrics.DEGRADED_LEVEL.set(float(self.ladder.level))
        # koordguard dispatch deadline (scheduler/deadline.py,
        # KOORD_TPU_DISPATCH_DEADLINE_MS): every designated device sync
        # runs under the watchdog; an overrun counts, flight-dumps
        # (reason dispatch_deadline) and feeds the ladder exactly like a
        # raised fault, so a slow-not-dead device demotes instead of
        # wedging the cycle. None/0 (the default) keeps syncs inline.
        self.dispatch_deadline_seconds = deadline_seconds_from(
            dispatch_deadline_ms)
        self.dispatch_watchdog = DeadlineWatchdog(
            self.dispatch_deadline_seconds,
            on_overrun=self._on_deadline_overrun)
        # sim/test failure-injection hook: a callable(stage) invoked at
        # the top of every device-dispatch window ("serial"/"fused");
        # raising from it exercises the ladder exactly like a real
        # XLA/mesh fault (koordinator_tpu/sim FaultPlan arms it)
        self.fault_injector = None
        # sim/test latency hook: a callable() invoked inside every
        # monitored readback sync — sleeping in it is a slow-not-dead
        # device, the dispatch-deadline fault model
        self.sync_delay_injector = None
        # sim/test upload-failure hook, propagated onto every
        # DeviceSnapshot this scheduler builds (see the property below)
        self._upload_fault_injector = None
        # pipelined-cycle mode (CyclePipeline): the kernel dispatch is
        # non-blocking and diagnose/condition writes for unbound pods are
        # deferred into the NEXT cycle's kernel window so host work
        # overlaps device execution. Off by default — plain run_cycle
        # callers keep the strictly serial path.
        self.pipeline_mode = False
        # (items, last-batch tuple, now, precomputed messages) per deferral
        self._deferred_diagnose: List[Tuple[list, object, float,
                                            Optional[Dict[str, str]]]] = []
        self._flushed_this_cycle = False
        # fused-dispatch condition-write batching: while a multi-wave
        # replay is in progress every logical cycle's PodScheduled writes
        # queue on the SAME deferred machinery the pipeline uses, and the
        # dispatch drains them in ONE flush instead of K store-write
        # batches serializing against the next dispatch
        self._defer_condition_writes = False
        # last DeviceSnapshot stats snapshot, for counter deltas
        self._upload_stats_last: Dict[str, int] = {}
        # admission grouping of the last encode: raw arrays, with the
        # dict view materialized lazily on the preemption path
        self._last_admission_raw = None
        self._last_admission = None
        # incremental snapshot packing (SURVEY 7: caches become
        # device-resident arrays updated by deltas) — event-driven memos
        # replacing the per-cycle cluster walks; gate off for the
        # rebuild-everything behavior
        from koordinator_tpu.utils.features import SCHEDULER_GATES

        self.snapshot_cache = None
        self.device_snapshot = None
        if SCHEDULER_GATES.enabled("IncrementalSnapshot"):
            from koordinator_tpu.scheduler.snapshot_cache import (
                SnapshotCache,
            )

            self.snapshot_cache = SnapshotCache(
                store,
                loadaware_plugin=self.extender.plugin("LoadAwareScheduling"),
                numa_plugin=self.extender.plugin("NodeNUMAResource"),
            )
        if self.snapshot_cache is not None or self.mesh is not None:
            # the mesh path REQUIRES the device mirror even without the
            # incremental-snapshot gate: it owns the sharded upload
            # (put_on_mesh) and the shard-aware scatter — gate off it
            # still dedups on host equality, it just sees full rebuilds
            # each cycle. Same condition _apply_degraded_level re-applies
            # on every ladder transition.
            self.device_snapshot = self._new_device_snapshot(self.mesh)
        # warm-up ladder LAST: it replays rungs through _get_*step, which
        # reads the final mesh placement and transformer registrations
        if self._warmup_mode != "off" and self.compile_cache_dir:
            from koordinator_tpu.scheduler.warmup import WarmupRunner

            self.warmup = WarmupRunner(
                self, background=self._warmup_mode == "background")
            self.warmup.start()

    # ------------------------------------------------------------------
    def note_warmup_complete(self, stats: Dict) -> None:
        """The warm-up ladder finished: arm the steady-state compile
        guard (koordlint rule 20's runtime half) — from here on, a
        step-cache miss in the hot path is flagged until a ladder
        transition legitimately re-keys the cache. The guard arms only
        when the ladder actually COVERED something (a first boot
        against an empty index promised nothing, and flagging its
        legitimate cold compiles would make the metric unusable)."""
        scheduler_metrics.WARMUP_COMPLETE.set(1.0)
        self._steady_state = (
            stats.get("warmed", 0) + stats.get("built", 0)) > 0
        logger.info(
            "warm-up ladder complete: %d/%d rungs warmed in %.2fs "
            "(%d skipped, %d failed, %d invalidated)",
            stats["warmed"], stats["rungs"], stats["seconds"],
            stats["skipped"], stats["failed"], stats["invalidated"])

    def _add_compile_wall(self, seconds: float) -> None:
        with self._wall_lock:
            self.compile_wall_seconds += seconds

    def _add_pack_wall(self, seconds: float) -> None:
        with self._wall_lock:
            self.pack_wall_seconds += seconds

    @property
    def _last_step_compiled(self) -> bool:
        """Whether THIS thread's most recent _get_*step call built a
        fresh step (thread-local: the background warm-up ladder's
        misses must never leak into the cycle thread's attribution)."""
        return getattr(self._step_tls, "compiled", False)

    @_last_step_compiled.setter
    def _last_step_compiled(self, value: bool) -> None:
        self._step_tls.compiled = bool(value)

    def _note_compile_miss(self, key: Tuple) -> None:
        """Shared step-cache miss accounting for the three _get_*step
        chokepoints — including the steady-state flagging the warm-up
        contract promises (a warm-cache restart binds its first pod with
        ZERO of these)."""
        self._last_step_compiled = True
        scheduler_metrics.COMPILE_CACHE_MISSES.inc()
        if self._steady_state:
            scheduler_metrics.STEADY_STATE_COMPILES.inc()
            hook = self.compile_miss_hook
            if hook is not None:
                hook(key)

    def _record_step_compile(self, kind: str, meta: Dict, args: Tuple) -> None:
        """Record a freshly-compiled rung into the persistent warm-up
        index (no-op without KOORD_TPU_COMPILE_CACHE_DIR). Best-effort
        by contract — the index is for the NEXT process."""
        if self.compile_cache_dir is None:
            return
        from koordinator_tpu.scheduler.warmup import record_step_compile

        record_step_compile(kind, meta, args)

    def _step_meta(self, signature: Tuple, ng: int, ngroups: int, active,
                   explain, **extra) -> Dict:
        meta = {
            "signature": [int(x) for x in signature],
            "ng": int(ng), "ngroups": int(ngroups),
            "active": [int(a) for a in active],
            "explain": explain,
            "mesh_tag": [int(i) for i in self._mesh_tag()],
            # config the step STRUCTURE bakes in: a replaying scheduler
            # whose prod split or transformer registrations differ would
            # build a different carry pytree than the recorded avals —
            # warm-up must skip such rungs, not trip over them
            "prod": bool(self.args.score_according_prod_usage),
            "score_tag": [[name, int(epoch)]
                          for name, epoch in self._score_pass_tag()],
        }
        meta.update(extra)
        return meta

    # ------------------------------------------------------------------
    def _new_device_snapshot(self, mesh):
        """Build a DeviceSnapshot with the sim's upload-failure hook
        propagated — every rebuild site (ladder transitions, deadline
        abandons) must keep the hook armed or fault tests go blind."""
        from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

        snap = DeviceSnapshot(mesh=mesh)
        snap.fault_injector = self._upload_fault_injector
        return snap

    @property
    def upload_fault_injector(self):
        return self._upload_fault_injector

    @upload_fault_injector.setter
    def upload_fault_injector(self, fn) -> None:
        self._upload_fault_injector = fn
        if self.device_snapshot is not None:
            self.device_snapshot.fault_injector = fn

    # ------------------------------------------------------------------
    # koordwatch: demotion accounting + device-timeline windows
    # ------------------------------------------------------------------
    def _note_demotion(self, reason: str, value):
        """THE demotion chokepoint (koordwatch): every branch that runs
        a cycle below its configured wave/explain/mesh level routes its
        fallback value through here — ``return self._note_demotion(
        "reason", 1)`` — so no demotion is ever silent again. Counted
        once per cycle per reason (the wave_demotions counter therefore
        reads as demoted CYCLES, and the sim's per-scenario demotion
        profile sums exactly). koordlint rule 19 (silent-demotion-branch)
        errors on demotion-resolving branches that bypass this. The
        reason must be registered (DEMOTION_REASONS) — retired reasons
        (the PR-14 burn-down) can never silently come back."""
        if reason not in DEMOTION_REASONS:
            raise ValueError(
                f"unregistered demotion reason {reason!r}"
                + (" (RETIRED — the fused path carries this state now)"
                   if reason in RETIRED_DEMOTION_REASONS else
                   "; add it to DEMOTION_REASONS"))
        if self.watch_enabled and reason not in self._cycle_demotions:
            self._cycle_demotions.append(reason)
            scheduler_metrics.WAVE_DEMOTIONS.inc(reason=reason)
        return value

    def _window_path(self, base: str) -> str:
        """The timeline path label for a dispatch window: the mesh
        placement wins over the program shape (a ladder demotion mid-
        pass re-stamps via mark_dispatch)."""
        return "mesh" if self.mesh is not None else base

    def _open_window(self, base: str):
        """Open a device-timeline window for one dispatch pass; the
        minted decision id joins spans, flight records and /explain."""
        win = self.timeline.open("scheduler", self._window_path(base))
        self._current_decision_id = win.decision_id
        self._cycle_decision_ids.append(win.decision_id)
        return win

    def _close_window(self, win, attempts: int, had_deadline: bool,
                      level0: int, end_mono=None) -> None:
        """Record a completed dispatch window. Outcome precedence:
        deadline (a monitored sync was abandoned this pass) > demoted
        (the ladder moved down) > retried (same level, second attempt)
        > clean."""
        outcome = ("deadline" if had_deadline
                   else "demoted" if self.ladder.level > level0
                   else "retried" if attempts else "clean")
        self.timeline.close(win, outcome, end_mono=end_mono)

    # ------------------------------------------------------------------
    def _pending_queue(self, now: float) -> Tuple[List[Pod], Dict[str, Reservation]]:
        pods = [
            p
            for p in self.store.list(KIND_POD)
            if not p.is_assigned
            and not p.is_terminated
            and p.spec.scheduler_name == self.scheduler_name
        ]
        reservations: Dict[str, Reservation] = {}
        for res in self.store.list(KIND_RESERVATION):
            if res.phase == "Pending" and not res.node_name and not res.is_expired(now):
                pseudo = Pod(
                    meta=ObjectMeta(
                        name=res.meta.name,
                        namespace="__reservation__",
                        creation_timestamp=res.meta.creation_timestamp,
                    ),
                    spec=PodSpec(
                        priority=(
                            res.template.priority
                            if res.template.priority is not None
                            else 9500
                        ),
                        requests=res.template.requests,
                        limits=res.template.limits,
                    ),
                )
                pods.append(pseudo)
                reservations[pseudo.meta.key] = res
        # pending-queue visibility (koordwatch, pre-work for the ROADMAP
        # admission/queueing item): the depth this cycle drains and every
        # entry's enqueue-to-dispatch age. creation_timestamp is the
        # enqueue instant for both real pods and reservation pseudo-pods.
        if self.watch_enabled:
            scheduler_metrics.PENDING_QUEUE_DEPTH.set(float(len(pods)))
            for p in pods:
                created = p.meta.creation_timestamp or now
                scheduler_metrics.QUEUE_WAIT_SECONDS.observe(
                    max(0.0, now - created))
        return pods, reservations

    def _process_resizes(self, now: float, result: CycleResult) -> None:
        """In-place pod resize (KEP-1287 shape; reference gates it behind
        the ResizePod feature and runs Reserve + ResizePod instead of a
        scheduling pass): an assigned pod carrying spec.resize_requests is
        granted when its node still fits the DELTA against every other
        assigned pod's requests; otherwise it stays pending and retries
        next cycle. cpuset-bound (LSE/LSR integer-cpu) pods are refused —
        their core allocation would need a release/re-take, which in-place
        resize cannot do safely."""
        import dataclasses

        from koordinator_tpu.scheduler.snapshot import _pod_cpuset_flags

        candidates = [
            p for p in self.store.list(KIND_POD)
            if p.is_assigned and not p.is_terminated
            and p.spec.resize_requests is not None
            and p.spec.scheduler_name == self.scheduler_name
        ]
        if not candidates:
            return
        assigned = self._assigned_requests()
        # Available reservations HOLD capacity the batch pass counts via
        # ReservationRestoreTransformer — the resize fit base must count it
        # too, or a granted resize overcommits against a reservation whose
        # owner binds later
        for res in self.store.list(KIND_RESERVATION):
            if res.is_available and not res.is_expired(now) and res.node_name:
                vec = res.allocatable.to_vector()
                assigned[res.node_name] = (
                    assigned.get(res.node_name, np.zeros_like(vec)) + vec)
        nodes = {n.meta.name: n for n in self.store.list(KIND_NODE)}
        numa_plugin = self.extender.plugin("NodeNUMAResource")
        from koordinator_tpu.scheduler.topologymanager import (
            POLICY_SINGLE_NUMA_NODE,
        )
        for pod in candidates:
            node = nodes.get(pod.spec.node_name)
            if node is None:
                result.resize_pending.append(pod.meta.key)
                self.extender.error_handlers.dispatch(
                    pod, "resize target node not found")
                continue
            # cpuset guard on BOTH shapes: the old allocation AND what the
            # pod would become (a resize to integer-cpu LSR must not dodge
            # the cpuset release/re-take it cannot do in place)
            needs_bind_old, _c, _f = _pod_cpuset_flags(pod)
            resized_view = dataclasses.replace(
                pod, spec=dataclasses.replace(
                    pod.spec, requests=pod.spec.resize_requests))
            needs_bind_new, _c, _f = _pod_cpuset_flags(resized_view)
            if needs_bind_old or needs_bind_new:
                result.resize_pending.append(pod.meta.key)
                self.extender.error_handlers.dispatch(
                    pod, "in-place resize unsupported for cpuset-bound pods")
                continue
            # SingleNUMANode-policy nodes account per-zone state the
            # whole-node delta check below cannot see: a granted resize
            # could overcommit a zone the batch pass believes free.
            # Refuse, the same stance as cpuset-bound pods.
            if numa_plugin is not None:
                topo = numa_plugin.topologies.get(pod.spec.node_name)
                if (topo is not None and topo.zones
                        and numa_plugin.node_policy(pod.spec.node_name)
                        == POLICY_SINGLE_NUMA_NODE):
                    result.resize_pending.append(pod.meta.key)
                    self.extender.error_handlers.dispatch(
                        pod, "in-place resize unsupported on "
                             "SingleNUMANode-policy nodes")
                    continue
            new_vec = pod.spec.resize_requests.to_vector()
            old_vec = pod.spec.requests.to_vector()
            others = (assigned.get(pod.spec.node_name,
                                   np.zeros_like(new_vec)) - old_vec)
            # the SAME trimmed allocatable the batch kernel fits against
            # (node-reservation annotation trims — ops/estimator.py); raw
            # status.allocatable would grant resizes into reserved cores
            from koordinator_tpu.ops.estimator import (
                estimate_node_allocatable,
            )

            alloc = estimate_node_allocatable(node)
            need = new_vec > 0
            if np.any(need & (others + new_vec > alloc)):
                result.resize_pending.append(pod.meta.key)
                self.extender.error_handlers.dispatch(
                    pod, "resize does not fit the node")
                continue
            pod.spec.requests = pod.spec.resize_requests
            pod.spec.resize_requests = None
            self.store.update(KIND_POD, pod)
            # the node's fit base shifts for later candidates on it
            assigned[pod.spec.node_name] = others + new_vec
            result.resized.append(pod.meta.key)

    def _assigned_requests(self) -> Dict[str, np.ndarray]:
        """Base fit state per node: every assigned pod's requests. Reservation
        accounting (reserved capacity + double-count restore) is layered on by
        ReservationRestoreTransformer via the declared before-Filter extension
        point — a custom transformer can rewrite the same view.

        Rebuilt per cycle (robust against in-place object mutation), but as
        ONE wire-matrix fill + scale + segment-sum instead of per-pod vector
        allocations. With the incremental snapshot cache the sums are
        event-maintained instead (same values; test_snapshot_cache.py)."""
        if self.snapshot_cache is not None:
            return self.snapshot_cache.assigned_requests()
        assigned = [
            p for p in self.store.list(KIND_POD)
            if p.is_assigned and not p.is_terminated
        ]
        if not assigned:
            return {}
        node_ids: Dict[str, int] = {}
        rows = np.zeros(len(assigned), np.int64)
        wire = np.zeros((len(assigned), NUM_RESOURCES), np.float64)
        for i, pod in enumerate(assigned):
            pod.spec.requests.fill_wire_row(wire[i])
            rows[i] = node_ids.setdefault(pod.spec.node_name, len(node_ids))
        packed = with_pod_count((wire / PACK_SCALE).astype(np.float32))
        sums = np.zeros((len(node_ids), NUM_RESOURCES), np.float32)
        np.add.at(sums, rows, packed)
        return {node: sums[i] for node, i in node_ids.items()}

    def _cluster_state(self, pending: List[Pod], now: float) -> ClusterState:
        la = self.extender.plugin("LoadAwareScheduling")
        numa = self.extender.plugin("NodeNUMAResource")
        quota = self.extender.plugin("ElasticQuota")
        gang = self.extender.plugin("Coscheduling")
        return ClusterState(
            nodes=[n for n in self.store.list(KIND_NODE) if not n.unschedulable],
            pending_pods=pending,
            node_metrics={
                m.meta.name: m for m in self.store.list(KIND_NODE_METRIC)
            },
            pods_by_key={p.meta.key: p for p in self.store.list(KIND_POD)},
            assigned=la.assigned_view() if la else {},
            assigned_requests=self._assigned_requests(),
            topologies=dict(numa.topologies) if numa else {},
            cpu_states=dict(numa.cpu_states) if numa else {},
            numa_allocated=dict(numa.numa_allocated) if numa else {},
            quotas=quota.quota_list() if quota else [],
            pod_groups=list(gang.pod_groups.values()) if gang else [],
            gang_assumed=dict(gang.assumed) if gang else {},
            pvcs={c.meta.key: c for c in self.store.list(KIND_PVC)},
            pvs={v.meta.name: v for v in self.store.list(KIND_PV)},
            storage_classes={
                s.meta.name: s
                for s in self.store.list(KIND_STORAGECLASS)
            },
            now=now,
        )

    def _mesh_tag(self) -> Tuple:
        """Step-cache key component for the mesh placement. Device IDS,
        not just the count: the partial-mesh rung can produce two
        same-size submeshes over different survivors across one
        scheduler lifetime, and a step compiled against the old Mesh
        must never serve the new one."""
        if self.mesh is None:
            return ()
        return tuple(d.id for d in self.mesh.devices.flat)

    def _get_step(self, signature: Tuple, ng: int, ngroups: int, active,
                  explain=None) -> object:
        key = (signature, ng, ngroups, tuple(active), explain,
               self._mesh_tag())
        with self._step_lock:
            step = self._step_cache.get(key)
        if step is not None:
            self._last_step_compiled = False
            scheduler_metrics.COMPILE_CACHE_HITS.inc()
            return step
        # shape-signature miss: this span times host-side step
        # construction only — jit is lazy, so the multi-second XLA build
        # itself lands in the NEXT kernel launch, which is why the kernel
        # span carries compiled="1" on that cycle. Together with the
        # hit/miss counters that makes the recompile pathology visible
        # (a steady-state cluster should be all hits)
        self._note_compile_miss(key)
        with self.tracer.span("compile", signature=str(key)) as csp:
            if self.mesh is not None:
                from koordinator_tpu.parallel import (
                    build_sharded_full_chain_step,
                )

                step = build_sharded_full_chain_step(
                    self.args, ng, ngroups, self.mesh, active_axes=active,
                    explain=explain)
            else:
                step = build_best_full_chain_step(
                    self.args, ng, ngroups, active_axes=active,
                    explain=explain)
        self._add_compile_wall(csp.duration_seconds)
        with self._step_lock:
            self._step_cache[key] = step
        return step

    def _device_score_passes(self) -> Tuple:
        """Registered ScoreTransformers' device tensor passes, in
        registration order (the host before_score order). The fused path
        only runs when EVERY ScoreTransformer is device-expressible
        (_effective_waves demotes otherwise)."""
        return tuple(
            t.device_pass for t in self.extender.transformers
            if isinstance(t, ScoreTransformer)
            and getattr(t, "device_pass", None) is not None)

    def _score_pass_tag(self) -> Tuple:
        """Step-cache key component for the baked-in transformer passes:
        a pass is compiled INTO the wave program, so a registration or a
        declared parameter change (``device_epoch``) must miss the
        cache."""
        return tuple(
            (t.name, getattr(t, "device_epoch", 0))
            for t in self.extender.transformers
            if isinstance(t, ScoreTransformer)
            and getattr(t, "device_pass", None) is not None)

    def _get_fused_step(self, signature: Tuple, ng: int, ngroups: int,
                        active, waves: int, explain=None,
                        sides_tag: Tuple = (0, 0)) -> object:
        from koordinator_tpu.models.fused_waves import build_fused_wave_step

        nc, nres = sides_tag
        key = ("fused", waves, signature, ng, ngroups, tuple(active),
               explain, self._mesh_tag(), sides_tag,
               self._score_pass_tag())
        with self._step_lock:
            step = self._step_cache.get(key)
        if step is not None:
            self._last_step_compiled = False
            scheduler_metrics.COMPILE_CACHE_HITS.inc()
            return step
        self._note_compile_miss(key)
        prod = self.args.score_according_prod_usage
        passes = self._device_score_passes()
        with self.tracer.span("compile", signature=str(key)) as csp:
            if self.mesh is not None:
                from koordinator_tpu.parallel import (
                    build_sharded_fused_wave_step,
                )

                step = build_sharded_fused_wave_step(
                    self.args, ng, ngroups, waves=waves, mesh=self.mesh,
                    active_axes=active, explain=explain, prod=prod,
                    claims=nc > 0, res=nres > 0, score_passes=passes)
            else:
                step = build_fused_wave_step(
                    self.args, ng, ngroups, waves=waves, active_axes=active,
                    explain=explain, prod=prod, claims=nc > 0,
                    res=nres > 0, score_passes=passes)
        self._add_compile_wall(csp.duration_seconds)
        with self._step_lock:
            self._step_cache[key] = step
        return step

    def _get_chain_step(self, signature: Tuple, ng: int, ngroups: int,
                        active, explain=None,
                        sides_tag: Tuple = (0, 0)) -> object:
        """The chained per-wave step (overlapped replay). NOTE: no wave
        depth in the cache key — one compiled program serves every K,
        which also collapses the fused path's per-K compile fan-out."""
        from koordinator_tpu.models.fused_waves import (
            build_chained_wave_step,
        )

        nc, nres = sides_tag
        key = ("chain", signature, ng, ngroups, tuple(active), explain,
               self._mesh_tag(), sides_tag, self._score_pass_tag())
        with self._step_lock:
            step = self._step_cache.get(key)
        if step is not None:
            self._last_step_compiled = False
            scheduler_metrics.COMPILE_CACHE_HITS.inc()
            return step
        self._note_compile_miss(key)
        prod = self.args.score_according_prod_usage
        passes = self._device_score_passes()
        with self.tracer.span("compile", signature=str(key)) as csp:
            if self.mesh is not None:
                from koordinator_tpu.parallel import (
                    build_sharded_chained_wave_step,
                )

                step = build_sharded_chained_wave_step(
                    self.args, ng, ngroups, mesh=self.mesh,
                    active_axes=active, explain=explain, prod=prod,
                    claims=nc > 0, res=nres > 0, score_passes=passes)
            else:
                step = build_chained_wave_step(
                    self.args, ng, ngroups, active_axes=active,
                    explain=explain, prod=prod, claims=nc > 0,
                    res=nres > 0, score_passes=passes)
        self._add_compile_wall(csp.duration_seconds)
        with self._step_lock:
            self._step_cache[key] = step
        return step

    # ------------------------------------------------------------------
    # degradation ladder (scheduler/degrade.py)
    # ------------------------------------------------------------------
    def _ladder_features(self) -> Dict[str, bool]:
        """Which ladder rungs actually change behavior for this
        scheduler's configuration — demotion and re-promotion both skip
        rungs whose feature was never on."""
        waves_capable = (self.waves_spec == "auto"
                         or (isinstance(self.waves_spec, int)
                             and self.waves_spec > 1))
        return {
            "mesh": self._configured_mesh is not None,
            "waves": waves_capable and self._sidecar_client is None,
            "explain": (self.explain_spec is not None
                        and self._sidecar_client is None),
        }

    def _on_ladder_transition(self, record: dict) -> None:
        """Every ladder transition is observable: gauge, loud log, the
        effective settings re-applied, and a flight-recorder dump (the
        preceding cycles' decision records ARE the incident context)."""
        scheduler_metrics.DEGRADED_LEVEL.set(float(record["to_level"]))
        # a ladder transition legitimately re-keys the step cache (mesh
        # tag, explain level): drop the steady-state compile guard — it
        # re-arms only with the next warm-up ladder (i.e. a restart)
        self._steady_state = False
        log = (logger.warning if record["to_level"] > record["from_level"]
               else logger.info)
        log("dispatch degradation ladder: %s -> %s (%s)",
            record["from"], record["to"], record["reason"])
        if record["to_level"] == LEVEL_FULL:
            # re-promotion probes the FULL configured mesh back: the
            # lost-device set resets, and a still-dead device re-records
            # itself when the probe's dispatch fails attributably
            self._lost_device_ids = set()
        self._apply_degraded_level()
        self.flight.dump("degradation")

    def _partial_mesh(self):
        """The surviving submesh for the partial-mesh rung: the
        configured mesh minus every device id shed so far. Cached per
        lost-set so `_apply_degraded_level`'s identity compare sees a
        stable Mesh while the set is unchanged."""
        from koordinator_tpu.parallel.mesh import surviving_submesh

        key = frozenset(self._lost_device_ids)
        hit = self._submesh_cache.get(key)
        if hit is None:
            # never None-valued: _on_dispatch_failure records losses
            # only while survivors remain, so the submesh is non-empty
            hit = surviving_submesh(self._configured_mesh, key)
            self._submesh_cache[key] = hit
        return hit

    def _apply_degraded_level(self) -> None:
        """Reconcile the mesh with the ladder level (the waves/explain
        rungs are consulted per cycle by _effective_waves/_effective_
        explain; the mesh owns device buffers, so it reconfigures here).
        The partial-mesh rung runs the surviving submesh — snapshot and
        step cache rebuild against it, re-padding through the normal
        pad_for_sharding/put path. Idempotent and cheap when nothing
        changed."""
        if self.ladder.level >= LEVEL_NO_MESH:
            want_mesh = None
        elif (self.ladder.level == LEVEL_PARTIAL_MESH
                and self._configured_mesh is not None):
            want_mesh = self._partial_mesh()
        else:
            want_mesh = self._configured_mesh
        if want_mesh is self.mesh:
            return
        self.mesh = want_mesh
        scheduler_metrics.MESH_DEVICES.set(
            float(want_mesh.devices.size) if want_mesh is not None else 0.0)
        # rebuild the device mirror for the new placement: the next
        # upload repopulates it (one cycle of full puts, then steady-
        # state reuse). Stats baseline resets with it so the per-cycle
        # counter deltas never go negative.
        if self.snapshot_cache is not None or want_mesh is not None:
            self.device_snapshot = self._new_device_snapshot(want_mesh)
        else:
            self.device_snapshot = None
        self._upload_stats_last = {}

    def _on_deadline_overrun(self, path: str) -> None:
        """The dispatch watchdog abandoned a monitored sync: count it
        and dump the flight ring (reason dispatch_deadline) — the
        DispatchDeadlineExceeded it raises right after lands in the
        dispatch window's failure handler, which abandons the device
        state and feeds the ladder like any raised fault."""
        scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS.inc(path=path)
        self.flight.dump("dispatch_deadline")

    def _abandon_device_state(self) -> None:
        """A deadline overrun left a device program running against the
        mirror's buffers. Never block on it (that IS the wedge being
        escaped) and never donate those buffers out from under it: the
        mirror is replaced wholesale — the next upload repopulates the
        fresh one through the normal put/scatter path, and the slow
        program keeps the old buffers alive until its background sync
        drains."""
        if self.device_snapshot is None:
            return
        self.device_snapshot = self._new_device_snapshot(self.mesh)
        self._upload_stats_last = {}

    def _on_dispatch_failure(self, stage: str, exc: Exception) -> None:
        """A device-dispatch attempt failed before any binding was
        applied. Count it, consult the ladder; returns normally when a
        retry or demotion was arranged (the caller re-runs its dispatch
        window), re-raises when the ladder is exhausted."""
        scheduler_metrics.DISPATCH_RETRIES.inc(stage=stage)
        if isinstance(exc, DispatchDeadlineExceeded):
            # slow-not-dead device: the in-flight window was abandoned,
            # so the retry/demoted re-run must upload into a fresh
            # mirror whose donation guard the slow program cannot bite
            self._abandon_device_state()
        features = self._ladder_features()
        # koordguard partial-mesh: a failure that NAMES dead mesh
        # devices engages the partial-mesh rung — record the loss first
        # (the transition observer rebuilds the submesh), then let the
        # ladder pick the rung. A repeat loss while already at
        # partial-mesh shrinks the submesh in place.
        ids = attributable_device_ids(exc)
        if ids and self._configured_mesh is not None:
            all_ids = {d.id for d in self._configured_mesh.devices.flat}
            named = ids & all_ids
            fresh = named - self._lost_device_ids
            survivors = all_ids - self._lost_device_ids - named
            if named and survivors:
                # the rung is engaged whenever the failure NAMES devices
                # with survivors left — including the second attempt of
                # the same fault, whose ids the retry already recorded
                self._lost_device_ids |= fresh
                features["partial_mesh"] = True
                if (self.ladder.level == LEVEL_PARTIAL_MESH
                        and self.mesh is not None
                        and named & {d.id
                                     for d in self.mesh.devices.flat}):
                    # the loss names a device still in the ACTIVE
                    # submesh: shrink in place. Keyed off the current
                    # mesh, not the fresh set — the retry attempt
                    # already recorded the id, but the submesh only
                    # rebuilds on the ladder transition, so both
                    # attempts must see the shrink flag.
                    features["partial_mesh_shrink"] = True
                if fresh:
                    logger.warning(
                        "%s dispatch failure attributed to device(s) %s; "
                        "%d of %d mesh devices survive", stage,
                        sorted(fresh), len(survivors), len(all_ids))
        action = self.ladder.on_failure(
            features, error=f"{type(exc).__name__}: {exc}")
        if action == "exhausted":
            raise exc
        if action == "retry":
            logger.warning(
                "%s dispatch failed (%s: %s); retrying once at ladder "
                "level %s", stage, type(exc).__name__, exc,
                self.ladder.level_name)
        # "demoted" (including a partial-mesh shrink in place): the
        # transition observer already re-applied settings

    def _effective_explain(self):
        """This cycle's koordexplain level. The sidecar path demotes to
        off: the RPC protocol ships only the chosen vector, so attribution
        falls back to the legacy host recompute. The degradation ladder's
        no-explain rung (and below) pins it off too. Every demotion
        routes through the koordwatch chokepoint (rule 19 pins that)."""
        if self.explain_spec is None:
            return self.explain_spec  # nothing configured: not a demotion
        if self._sidecar_client is not None:
            return self._note_demotion("explain-sidecar", None)
        if self.ladder.level >= LEVEL_NO_EXPLAIN:
            return self._note_demotion("explain-ladder", None)
        return self.explain_spec

    def _analyze_claims(self, pending: List[Pod]):
        """The batch's claim structure (ops/volumes.analyze_pending_claims)
        for the fused path: None when no pending pod carries claims. The
        analysis is stashed for the dispatch's side-input encode so the
        hot-claim factorization is computed exactly once per cycle."""
        carriers = [p for p in pending if p.spec.pvc_names]
        if not carriers:
            return None
        # volume-aware mode (real PVC/PV/StorageClass objects, the
        # SHARED gate in ops/volumes.py): a bind can rewrite another
        # pending pod's CLASSIFICATION through the store — count the
        # pods whose claims are unbound/missing, the only channel such
        # a rewrite can travel
        from koordinator_tpu.ops.volumes import store_volume_aware

        volume_aware = store_volume_aware(self.store)
        unbound = 0
        if volume_aware:
            for pod in carriers:
                for claim in pod.spec.pvc_names:
                    pvc = self.store.get(
                        KIND_PVC, f"{pod.meta.namespace}/{claim}")
                    if pvc is None or not pvc.is_bound:
                        unbound += 1
                        break
                if unbound >= 2:
                    break
        attached = (self.snapshot_cache.attached_sets()
                    if self.snapshot_cache is not None
                    else attached_claim_sets(self.store))
        return analyze_pending_claims(
            pending, attached, volume_aware=volume_aware,
            unbound_claim_pods=unbound)

    def _effective_waves(self, pending: List[Pod],
                         pending_reservations: Dict[str, Reservation],
                         override=None) -> int:
        """Resolve this cycle's fused-wave depth. Demotions to K=1 keep
        the fused path exactly equivalent to K serial cycles (see
        models/fused_waves.py module doc for the remaining cases).

        PR 14 burned the four data-driven demotions down: pending
        Reservation CRs ride the batch as carried rows with an in-kernel
        nomination pre-pass, claim-carrying pods ride the hot-claim
        factorization (ops/volumes.py), prod-usage scoring rides the
        est/adj prod split, and device-expressible ScoreTransformers run
        as in-kernel tensor passes — only genuinely non-expressible
        residues (a transformer without ``device_pass``, claim
        entanglement) still force the serial path, plus the ladder and
        the single-round sidecar protocol."""
        self._claim_analysis = None
        spec = self.waves_spec if override is None else override
        k = _auto_waves(len(pending)) if spec == "auto" else int(spec)
        k = max(1, min(k, MAX_WAVES))
        if k == 1:
            return k  # resolved to serial by spec/depth: not a demotion
        if self.ladder.level >= LEVEL_SERIAL_WAVES:
            # degradation ladder: fused dispatch demoted off
            return self._note_demotion("ladder-serial-waves", 1)
        if self._sidecar_client is not None:
            # the sidecar RPC protocol is single-round
            return self._note_demotion("sidecar", 1)
        if any(isinstance(t, ScoreTransformer)
               and getattr(t, "device_pass", None) is None
               for t in self.extender.transformers):
            # a host-only ScoreTransformer may rewrite any packed field
            # AFTER the build; the fused waves rebuild the score terms
            # from carried state every wave, which would silently discard
            # the rewrite. Transformers implementing the device protocol
            # (frameworkext.DeviceScoreTransformer) run in-kernel instead.
            return self._note_demotion("non-expressible-transformer", 1)
        analysis = self._analyze_claims(pending)
        if analysis is not None and analysis.entangled is not None:
            # the narrow claim residue: classification drift through the
            # PV/PVC objects or a factorization-budget overflow — the
            # carried columns cannot express it (ops/volumes.py)
            return self._note_demotion("claim-entangled", 1)
        self._claim_analysis = analysis
        return k

    # ------------------------------------------------------------------
    def run_cycle(self, now: Optional[float] = None,
                  waves=None) -> CycleResult:
        now = time.time() if now is None else now
        if self.elector is not None and not self.elector.tick(now):
            return CycleResult(skipped_not_leader=True)
        # degradation ladder: make sure the effective settings match the
        # current rung (a promotion at the end of the previous cycle
        # reconfigures here). The retry budget is armed per dispatch
        # window, not per cycle — a cycle can open several (initial pass,
        # preemption retry, the serial re-run after a fused demotion) and
        # each is promised its own retry-once before demoting.
        self._cycle_demotions = []
        self._cycle_decision_ids = []
        self._current_decision_id = None
        self._apply_degraded_level()
        # koordwatch mesh accounting: a cycle dispatching below the
        # CONFIGURED mesh placement (ladder mesh-off reconfiguration, or
        # the koordguard partial-mesh submesh) is a demoted cycle —
        # counted per cycle, like the wave/explain chokepoints, so the
        # demotion profile's fractions compare across reasons
        if (self._configured_mesh is not None
                and self.mesh is not self._configured_mesh):
            self._note_demotion(
                "mesh-off" if self.mesh is None else "partial-mesh", None)
        result = CycleResult()
        carried_deferred = bool(self._deferred_diagnose)
        self._flushed_this_cycle = False
        self._cycle_attrib = {}
        self._cycle_terms = {}
        self._cycle_counter += 1
        flight_base = self._flight_metric_base()
        root = None
        # root span: the ONE place the cycle duration is stamped. Every
        # early-return path inside the traced body (empty queue, pre-pass
        # binds everything, full pass) exits through the span's finally,
        # so no return path can ship a zero duration — the old three-site
        # assignment pattern broke exactly that way.
        try:
            with self.tracer.span("cycle") as root:
                self._run_cycle_traced(now, result, waves_override=waves)
                # a cycle with no local kernel window (empty queue, sidecar
                # path) never reached the overlap flush: drain carried-over
                # deferred writes here so they cannot linger unboundedly —
                # without device work to overlap, flushing now IS the serial
                # timing
                if (self.pipeline_mode and carried_deferred
                        and not self._flushed_this_cycle
                        and self._deferred_diagnose):
                    self.flush_deferred()
        except Exception as exc:
            # flight-recorder trigger: an unhandled cycle exception leaves
            # the wreck behind — the partial result, the span tree (the
            # span's finally already committed the root with an error
            # attribute) and the preceding cycles in the ring — then
            # re-raises unchanged
            result.duration_seconds = (root.duration_seconds
                                       if root is not None else 0.0)
            result.demotions = list(self._cycle_demotions)
            result.decision_ids = list(self._cycle_decision_ids)
            self.flight.record_cycle(self._flight_record(
                result, now, root, flight_base,
                error=f"{type(exc).__name__}: {exc}"))
            self.flight.dump("cycle_exception")
            raise
        result.duration_seconds = root.duration_seconds
        result.demotions = list(self._cycle_demotions)
        result.decision_ids = list(self._cycle_decision_ids)
        scheduler_metrics.CYCLE_SECONDS.observe(result.duration_seconds)
        if result.duration_seconds > 0:
            # device-busy fraction of this cycle: the "is the device the
            # bottleneck yet" gauge (bench's pipeline_occupancy, now on
            # /metrics). Clamped — the busy window is wall-clock around
            # dispatch..last-readback and timer skew must not read >1.
            scheduler_metrics.PIPELINE_OCCUPANCY.set(min(
                1.0, result.device_busy_seconds / result.duration_seconds))
        if result.bound:
            scheduler_metrics.PODS_BOUND_TOTAL.inc(len(result.bound))
        self.extender.monitor.record(result)
        self._finish_cycle_obs(result, now, root, flight_base)
        # a completed cycle feeds the ladder's clean-cycle counter (a
        # cycle that needed retries/demotions does not count as clean);
        # enough clean cycles probe one rung back up
        self.ladder.note_cycle()
        return result

    # ------------------------------------------------------------------
    def _flight_metric_base(self) -> Dict[str, float]:
        """Cycle-start counter values, so the flight record carries per-
        cycle METRIC DELTAS instead of meaningless cumulative totals."""
        return {
            "pods_bound": scheduler_metrics.PODS_BOUND_TOTAL.get() or 0.0,
            "compile_cache_misses":
                scheduler_metrics.COMPILE_CACHE_MISSES.get() or 0.0,
            "readback_bytes": scheduler_metrics.READBACK_BYTES.get() or 0.0,
            "explain_readback_bytes":
                scheduler_metrics.EXPLAIN_READBACK_BYTES.get() or 0.0,
        }

    def _flight_record(self, result: CycleResult, now: float, root,
                       base: Dict[str, float], error=None) -> dict:
        """One flight-recorder cycle record (obs/flight.py schema)."""
        from koordinator_tpu.obs.flight import FLIGHT_SCHEMA_VERSION

        end = self._flight_metric_base()
        bound = []
        for b in result.bound:
            entry: Dict[str, object] = {"pod": b.pod_key, "node": b.node_name}
            terms = self._cycle_terms.get(b.pod_key)
            if terms is not None:
                entry["terms"] = terms
            bound.append(entry)

        def unbound(keys: List[str]) -> List[dict]:
            out = []
            for key in keys:
                entry: Dict[str, object] = {"pod": key}
                attrib = self._cycle_attrib.get(key)
                if attrib:
                    for field in ("reason", "stages", "message"):
                        if field in attrib:
                            entry[field] = attrib[field]
                out.append(entry)
            return out

        record = {
            "v": FLIGHT_SCHEMA_VERSION,
            "kind": "cycle",
            "seq": self._cycle_counter,
            "ts": float(now),
            "duration_ms": result.duration_seconds * 1000.0,
            "waves": int(result.waves),
            "bound": bound,
            "failed": unbound(result.failed),
            "rejected": unbound(result.rejected),
            "preempted": list(result.preempted_victims),
            # koordwatch: the cycle's structured demotion reasons and
            # the decision ids of its device windows (joinable against
            # the timeline bundle and the kernel spans' decision_id)
            "demotions": list(result.demotions),
            "decision_ids": list(result.decision_ids),
            "metrics": {k: end[k] - base.get(k, 0.0) for k in end},
            "spans": ([s.to_record() for s in root.walk()]
                      if root is not None else []),
        }
        if error is not None:
            record["error"] = str(error)
        return record

    def _finish_cycle_obs(self, result: CycleResult, now: float, root,
                          flight_base: Dict[str, float]) -> None:
        """Post-cycle koordexplain bookkeeping: bound-pod attribution, the
        /explain index, the flight ring, liveness state and the deadline
        trigger."""
        if self.explain_spec is not None:
            for b in result.bound:
                rec: Dict[str, object] = {"verdict": "bound",
                                          "node": b.node_name}
                if self._current_decision_id is not None:
                    # koordwatch decision correlation: /explain output
                    # joins the timeline window that bound the pod
                    rec["decision_id"] = self._current_decision_id
                terms = self._cycle_terms.get(b.pod_key)
                if terms is not None:
                    rec["terms"] = terms
                    # margin vs the runner-up node; meaningful only when a
                    # feasible runner-up existed (runner_up >= 0)
                    rec["margin"] = terms["best_score"] - terms["runner_up"]
                self._cycle_attrib[b.pod_key] = rec
            with self._explain_lock:
                for key, rec in self._cycle_attrib.items():
                    rec = dict(rec)
                    rec["cycle"] = self._cycle_counter
                    rec["ts"] = float(now)
                    # pop-then-insert keeps dict order = recency, so the
                    # cap below evicts the genuinely oldest records
                    self.explain_index.pop(key, None)
                    self.explain_index[key] = rec
                overflow = len(self.explain_index) - 4096
                if overflow > 0:
                    # dict preserves insertion order: drop the oldest
                    for key in list(self.explain_index)[:overflow]:
                        del self.explain_index[key]
        self.flight.record_cycle(
            self._flight_record(result, now, root, flight_base))
        with self._explain_lock:
            self._last_cycle_end = (time.time(), int(result.waves))
        if (self.cycle_deadline_seconds is not None
                and result.duration_seconds > self.cycle_deadline_seconds):
            self.flight.dump("deadline_overrun")

    def health_snapshot(self) -> Dict[str, object]:
        """The ObsServer /healthz payload: last-completed-cycle age + wave
        count — a stale-cycle liveness signal instead of a bare 200 —
        plus the degradation-ladder state: a scheduler surviving at a
        demoted rung must not look identical to a healthy one on its
        liveness probe."""
        with self._explain_lock:
            last = self._last_cycle_end
            cycles = self._cycle_counter
        degraded = self.ladder.snapshot()
        if last is None:
            return {"status": "ok", "cycles": 0, "degraded": degraded}
        end_wall, waves = last
        return {
            "status": "ok",
            "cycles": cycles,
            "last_cycle_age_seconds": max(0.0, time.time() - end_wall),
            "last_cycle_waves": waves,
            "degraded": degraded,
        }

    def explain_record(self, pod_key: str) -> Optional[dict]:
        """The /explain?pod= payload: the pod's most recent decision
        attribution, or None."""
        with self._explain_lock:
            rec = self.explain_index.get(pod_key)
            return dict(rec) if rec is not None else None

    def _run_cycle_traced(self, now: float, result: CycleResult,
                          waves_override=None) -> None:
        # [ResizePod gate] in-place resize of assigned pods, before the
        # batch pass sees their requests (frameworkext factory
        # RunReservePluginsReserve + RunResizePod analog)
        from koordinator_tpu.utils.features import SCHEDULER_GATES

        if SCHEDULER_GATES.enabled("ResizePod"):
            self._process_resizes(now, result)
        res_plugin = self.extender.plugin("Reservation")
        if self.reservation_controller is not None:
            self.reservation_controller.reconcile(now)
        if self.quota_revoke_controller is not None:
            self.quota_revoke_controller.reconcile(now)
        pending, pending_reservations = self._pending_queue(now)
        # permit-timeout rejection: pods of terminally-failed gangs never
        # re-enter admission (gang.go WaitingPods timeout semantics)
        gang_plugin = self.extender.plugin("Coscheduling")
        if gang_plugin is not None:
            gang_plugin.update_pod_group_status(self.store, now)
            dead_gangs = set(gang_plugin.timed_out_gangs())
            if dead_gangs:
                kept = []
                timed_out: List[Tuple[Pod, str]] = []
                for pod in pending:
                    if pod.gang_key in dead_gangs:
                        result.rejected.append(pod.meta.key)
                        self.extender.error_handlers.dispatch(
                            pod, "gang schedule timeout")
                        timed_out.append((pod, "gang schedule timeout"))
                    else:
                        kept.append(pod)
                pending = kept
                # these pods never reach the batch pass, so the terminal
                # reason must land on their status here (the end-of-cycle
                # writer only sees batch-pass failures)
                self._write_unschedulable_conditions([], timed_out, now)
        if not pending:
            return

        # ---- per-pod view transforms (BeforePreFilter) run before ANY
        # scheduling decision — the nomination pre-pass must see the same
        # views the kernel pass packs; originals are kept for the
        # preemption retry, which re-transforms from scratch
        ctx = CycleContext(now=now)
        originals = {p.meta.key: p for p in pending}
        pending = self.extender.transform_before_prefilter(pending, ctx)

        # ---- reservation nomination pre-pass. Gang/quota pods are excluded:
        # their admission barriers live in the batched kernel, and binding them
        # here would bypass min-member and quota checks. So are pods whose
        # placement the kernel Filter chain must vet — hostPorts, CSI
        # volume claims, inter-pod (anti-)affinity, topology spread: the
        # nominator checks only the reservation's resource fit, and with
        # descheduler-issued migration reservations (owner-matched to a
        # whole workload) a port-carrying replica nominated onto the
        # reserved node could double-bind a hostPort the kernel would
        # have rejected (the koordbalance drain-storm scenario caught
        # exactly that). Such pods schedule through the kernel, which
        # still counts reserved capacity via the restore transformer.
        with self.tracer.span("reservation_prepass") as presp:
            remaining: List[Pod] = []
            nominated = 0
            for pod in pending:
                if (
                    pod.meta.key in pending_reservations
                    or res_plugin is None
                    or pod.gang_name
                    or pod.quota_name
                    or pod.spec.host_ports
                    or pod.spec.pvc_names
                    or pod.spec.pod_affinity
                    or pod.spec.pod_anti_affinity
                    or pod.spec.topology_spread
                ):
                    remaining.append(pod)
                    continue
                res = res_plugin.nominate(pod, now)
                if res is None:
                    remaining.append(pod)
                    continue
                err = self._reserve_and_bind(pod, res.node_name, ctx, result,
                                             via_reservation=res)
                if err:
                    remaining.append(pod)
                else:
                    nominated += 1
            presp.attributes["nominated"] = str(nominated)
        pending = remaining
        if not pending:
            return

        # ---- fused multi-wave path: K dependent rounds in one device
        # dispatch, replayed host-side as logical cycles (byte-identical
        # to K sequential single-round cycles — pipeline_parity gates it)
        k_waves = self._effective_waves(pending, pending_reservations,
                                        waves_override)
        if k_waves > 1:
            try:
                # _fused_wave_cycles refreshes pod-group status at the end
                # of every logical cycle — no trailing refresh here, or a
                # fused K-cycle would walk the groups K+1 times where K
                # serial cycles walk them K times
                self._fused_wave_cycles(pending, now, ctx, result,
                                        pending_reservations, originals,
                                        k_waves)
                return
            except FusedDispatchDemoted:
                # the fused dispatch window failed before ANY binding was
                # applied and the ladder demoted below fused waves: fall
                # through and run this same pass through the serial path
                # at the demoted settings
                pass

        # ---- batched kernel pass
        rejected_pods, failed_pods = self._batch_pass(
            pending, now, ctx, result, pending_reservations
        )

        any_victims = self._post_filter_preempt(
            rejected_pods, failed_pods, result)
        if any_victims:
            # retry transforms from the ORIGINAL queued pods, not the
            # already-transformed views — a non-idempotent rewrite would
            # otherwise apply twice (BeforePreFilter runs per attempt on
            # the queued pod in the reference too)
            retry = self.extender.transform_before_prefilter(
                [
                    originals.get(p.meta.key, p)
                    for p in rejected_pods + [p for p, _ in failed_pods]
                ],
                ctx,
            )
            rejected_pods, failed_pods = self._batch_pass(
                retry, now, ctx, result, pending_reservations
            )
        for b in result.bound:
            self._preempt_attempted.pop(b.pod_key, None)

        for pod in rejected_pods:
            result.rejected.append(pod.meta.key)
            self.extender.error_handlers.dispatch(pod, "admission rejected")
        for pod, reason in failed_pods:
            result.failed.append(pod.meta.key)
            self.extender.error_handlers.dispatch(pod, reason)
        # pod-status propagation (upstream PodScheduled=False/Unschedulable
        # with the per-stage message): the reason becomes store-visible on
        # the pod object, not just the failure trail
        self._write_unschedulable_conditions(
            rejected_pods, failed_pods, now)
        # the packed batch is only needed within this cycle; don't pin
        # tens of MB of host arrays across idle periods
        self._last_batch = None

        if gang_plugin is not None:
            gang_plugin.update_pod_group_status(self.store, now)

    # ------------------------------------------------------------------
    def _post_filter_preempt(self, rejected_pods: List[Pod],
                             failed_pods: List[Tuple[Pod, str]],
                             result: CycleResult) -> bool:
        """PostFilter preemption for ONE logical scheduling cycle: the
        shared block behind both the serial flow and every fused-wave
        logical cycle, so their preemption cadence can never drift.
        Advances the cycle sequence (the rotation/latch clock) and returns
        whether any victims were evicted (the caller reruns the kernel
        then, exactly as the reference's nominate-then-reschedule).

        ElasticQuota preemption (preempt.go): quota-rejected non-gang pods
        try to reclaim from lower-priority same-group members.
        DefaultPreemption (the vendored kube fallback): pods with no
        feasible node try priority preemption; victims terminate
        synchronously and the kernel rerun is the real gate. The
        attempted-latch stops a pod the kernel STILL rejects (e.g.
        spread/NUMA constraints the host dry-run cannot see) from
        draining a fresh victim set EVERY cycle: a latched pod may retry
        only every PREEMPT_RETRY_CYCLES (cluster state may have unblocked
        it by then — bounded drain instead of either extreme). Keys of
        pods that bound or left the queue are dropped each cycle."""
        any_victims = False
        if self.preemptor is not None and rejected_pods:
            quota_rejected = [
                p for p in rejected_pods if p.quota_name and not p.gang_name
            ]
            for round_ in self.preemptor.post_filter(quota_rejected):
                any_victims = True
                result.preempted_victims.extend(round_.victim_keys)
        PREEMPT_RETRY_CYCLES = 5
        attempted: Dict[str, int] = getattr(self, "_preempt_attempted", {})
        self._preempt_attempted = attempted
        self._cycle_seq = getattr(self, "_cycle_seq", 0) + 1
        still_failed_keys = {p.meta.key for p, _ in failed_pods}
        for key in [k for k in attempted if k not in still_failed_keys]:
            del attempted[key]
        no_fit = [
            p for p, reason in failed_pods
            if reason == "no feasible node" and not p.gang_name
            and self._cycle_seq - attempted.get(p.meta.key, -10**9)
            >= PREEMPT_RETRY_CYCLES
        ]
        if no_fit:
            from koordinator_tpu.scheduler.preempt import DefaultPreemption

            preempter = DefaultPreemption(
                self.store,
                kernel_admission=self._resolve_admission(),
                attempt_seed=self._cycle_seq,
            )
            for round_ in preempter.post_filter(no_fit):
                any_victims = True
                attempted[round_.preemptor_key] = self._cycle_seq
                result.preempted_victims.extend(round_.victim_keys)
        return any_victims

    # ------------------------------------------------------------------
    def _write_unschedulable_conditions(
        self,
        rejected_pods: List[Pod],
        failed_pods: List[Tuple[Pod, str]],
        now: float,
    ) -> None:
        """PodScheduled=False/Unschedulable on every pod ending the cycle
        unbound. Specific reasons (encoding overflow, volume PreFilter,
        Reserve vetoes) pass through verbatim; generic kernel rejections
        get the per-stage breakdown from scheduler/diagnose.py. Idempotent:
        an unchanged condition writes nothing (no store churn, no snapshot
        cache invalidation for permanently-pending pods)."""
        last = getattr(self, "_last_batch", None)
        items = list(failed_pods) + [
            (p, "admission rejected") for p in rejected_pods]
        if not items:
            return
        messages = self._capture_attribution(items, last)
        if self.pipeline_mode or self._defer_condition_writes:
            # pipelined cycle: the writes run inside the NEXT cycle's
            # kernel window (flush_deferred), overlapping device work.
            # `now` and the packed batch are captured here, so the
            # diagnosis content is byte-identical to the serial path.
            # Only generic kernel rejections consult the packed batch —
            # drop it when no item needs it, so a deferred entry does not
            # pin the fc arrays the `_last_batch = None` release below
            # exists to free. (Streaming use bounds the pinning to one
            # cycle anyway: the next kernel window or a kernel-less cycle
            # drains the queue; idle drivers must call flush().)
            if not any(r in DIAGNOSED_REASONS for _p, r in items):
                last = None
            elif last is not None and last[3] is not None:
                # kernel-emitted counts captured: the deferred formatter
                # needs only (index, n_nodes, counts) — never pin the
                # packed fc arrays across the deferral
                last = (None, last[1], last[2], last[3])
            self._deferred_diagnose.append((items, last, now, messages))
            scheduler_metrics.DIAGNOSE_DEFERRED_TOTAL.inc(len(items))
            scheduler_metrics.DIAGNOSE_DEFERRED_DEPTH.set(
                float(len(self._deferred_diagnose)))
            return
        with self.tracer.span("diagnose", pods=str(len(items))):
            self._diagnose_and_write(items, last, now, messages=messages)

    def _stash_terms(self, keys, chosen_mask, terms_np) -> None:
        """KOORD_TPU_EXPLAIN=full: per-pod decision-time score attribution
        rows for pods the kernel chose a node for. Only pods that finish
        the cycle BOUND are surfaced (a Reserve veto leaves the row
        unread)."""
        from koordinator_tpu.models.full_chain import EXPLAIN_TERMS

        for i, key in enumerate(keys):
            if bool(chosen_mask[i]):
                row = terms_np[i]
                self._cycle_terms[key] = {
                    name: float(row[j])
                    for j, name in enumerate(EXPLAIN_TERMS)
                }

    def _capture_attribution(self, items, last) -> Optional[Dict[str, str]]:
        """koordexplain capture, at verdict time (NOT at deferred-flush
        time, so pipeline mode cannot skew metrics or the flight record):
        per-stage rejection counters + the /explain and flight-recorder
        attribution entries for pods ending this logical cycle unbound.
        Returns the formatted message per pod key so the condition writer
        never formats the same counts twice. No-op (None) when kernel
        counts were not emitted (explain off, sidecar)."""
        if self.explain_spec is None:
            return None
        counts = last[3] if last is not None else None
        if counts is not None:
            from koordinator_tpu.models.full_chain import EXPLAIN_STAGE_KEYS
            from koordinator_tpu.scheduler.diagnose import (
                format_stage_counts,
            )
        messages: Dict[str, str] = {}
        for pod, reason in items:
            entry: Dict[str, object] = {"verdict": "unschedulable",
                                        "reason": reason}
            if self._current_decision_id is not None:
                # koordwatch: join the verdict to its device window
                entry["decision_id"] = self._current_decision_id
            if counts is not None and reason in DIAGNOSED_REASONS:
                j = last[1].get(pod.meta.key)
                if j is not None:
                    row = counts[j]
                    stages = {}
                    for stage_key, c in zip(EXPLAIN_STAGE_KEYS, row):
                        if int(c):
                            stages[stage_key] = int(c)
                            scheduler_metrics.FILTER_REJECTIONS.inc(
                                int(c), stage=stage_key)
                    entry["stages"] = stages
                    msg = format_stage_counts(row, last[2])
                    entry["message"] = msg
                    messages[pod.meta.key] = msg
            self._cycle_attrib[pod.meta.key] = entry
        return messages or None

    def _flush_deferred_in_window(self) -> None:
        """flush_deferred from inside a ladder-wrapped dispatch window:
        tag host/store-side failures so the ladder's except does not
        mistake them for device failures (see _HostWriteFailure)."""
        try:
            self.flush_deferred()
        except Exception as exc:
            raise _HostWriteFailure() from exc

    def _prepack_in_window(self) -> None:
        """Pack/device overlap (KOORD_TPU_PACK_OVERLAP): pre-pack the
        NEXT cycle's candidate pod rows into the pack memo while the
        device executes this cycle's kernel — the store-delta snapshot
        is taken HERE, at dispatch time, after the deferred flush bumped
        the condition-written pods. Rows dirtied later in the window
        (bind patches from the in-flight replay, watch events) simply
        miss the (key, resourceVersion) memo keys at the real pack and
        re-pack there, so the produced ScheduleInputs are byte-identical
        to the non-overlapped pack by construction (and gated by
        run_pack_overlap_parity + the mid-window-mutation test).

        Purely a memo warm: a failure here costs nothing but the
        overlap — the next cycle packs in the gap exactly as before —
        so it is caught, logged and never fed to the ladder.

        A registered BeforePreFilter view transform disables the
        pre-pack: the real pack consumes TRANSFORMED pod views that
        keep the store resourceVersion, so a pre-packed raw row would
        be a (key, rv) hit serving untransformed bytes — the same
        cannot-see-the-rewrite stance as the fused path's host-only
        transformer demotion."""
        if not self.pack_overlap or self.snapshot_cache is None:
            return
        from koordinator_tpu.scheduler.frameworkext import (
            PreFilterTransformer,
        )

        if any(isinstance(t, PreFilterTransformer)
               and type(t).before_prefilter
               is not PreFilterTransformer.before_prefilter
               for t in self.extender.transformers):
            return
        try:
            from koordinator_tpu.scheduler.snapshot import (
                prepack_pending_rows,
            )

            with self.tracer.span("prepack") as sp:
                pods = [
                    p for p in self.store.list(KIND_POD)
                    if not p.is_assigned and not p.is_terminated
                    and p.spec.scheduler_name == self.scheduler_name
                ]
                n = prepack_pending_rows(self.snapshot_cache, pods,
                                         self.args)
                sp.attributes["rows"] = str(n)
            if n:
                scheduler_metrics.PREPACK_ROWS.inc(n)
        except Exception:
            # a pre-pack wreck may have left HALF-updated memo rows
            # (resourceVersion bumped before every column refreshed) —
            # rows the next build would serve as hits with stale bytes.
            # Poison the memo wholesale: the next pack runs the cold
            # path (bit-identical by the snapshot-cache contract) and
            # rebuilds it; one expensive build buys back correctness.
            self.snapshot_cache.pack_memo = None
            self.snapshot_cache.pack_memo_prev = None
            logger.exception("in-window pre-pack failed; pack memo "
                             "dropped — the next cycle repacks cold in "
                             "the gap")

    def flush_deferred(self) -> None:
        """Drain deferred diagnose/condition work (pipeline mode). Runs in
        the next cycle's kernel window — host work the device never waits
        on — and from CyclePipeline.flush() at end of stream. FIFO order
        preserves the serial path's write sequence when a pod accumulates
        verdicts across cycles."""
        self._flushed_this_cycle = True
        # overlapped-replay mode batches the whole flush into one store
        # transaction; overlap=0 keeps the per-pod writes of the parity
        # twin byte-for-byte (event granularity included)
        txn = (_DeferredFlushTxn(self.store)
               if self.replay_overlap and self._deferred_diagnose else None)
        while self._deferred_diagnose:
            items, last, now, messages = self._deferred_diagnose.pop(0)
            with self.tracer.span("diagnose", pods=str(len(items)),
                                  deferred="1"):
                self._diagnose_and_write(items, last, now, deferred=True,
                                         messages=messages, txn=txn)
        if txn is not None and txn.pending:
            with self.tracer.span("store_flush",
                                  writes=str(len(txn.pending))):
                txn.flush()
        scheduler_metrics.DIAGNOSE_DEFERRED_DEPTH.set(
            float(len(self._deferred_diagnose)))

    def _diagnose_and_write(self, items, last, now: float,
                            deferred: bool = False, messages=None,
                            txn: Optional[_DeferredFlushTxn] = None) -> None:
        shared = None  # node-level diagnosis state, built once per cycle
        for pod, reason in items:
            msg = reason
            if messages is not None and pod.meta.key in messages:
                # koordexplain: _capture_attribution already formatted the
                # kernel-emitted counts at verdict time — reuse, don't
                # recompute
                msg = messages[pod.meta.key]
            elif last is not None and reason in DIAGNOSED_REASONS:
                fc, index, n_nodes, counts = last
                j = index.get(pod.meta.key)
                if j is not None:
                    try:
                        if counts is not None:
                            # koordexplain: pure formatter over the
                            # KERNEL-emitted stage counts — no host
                            # recompute (tier-1 pins this string-for-
                            # string against the legacy path below)
                            from koordinator_tpu.scheduler.diagnose import (
                                format_stage_counts,
                            )

                            msg = format_stage_counts(counts[j], n_nodes)
                        elif fc is not None:
                            # legacy host-numpy recompute: the parity
                            # oracle, and the path explain-off keeps
                            from koordinator_tpu.scheduler.diagnose import (
                                diagnose_unbound,
                                shared_state,
                            )

                            if shared is None:
                                shared = shared_state(fc, n_nodes)
                            msg = diagnose_unbound(fc, j, n_nodes,
                                                   shared=shared)
                    except Exception:  # diagnosis must never wedge a cycle
                        logger.exception(
                            "unschedulability diagnosis failed for %s",
                            pod.meta.key)
            stored = (txn.get(pod.meta.key) if txn is not None
                      else self.store.get(KIND_POD, pod.meta.key))
            if stored is None:  # reservation pseudo-pods, raced deletions
                continue
            if deferred:
                # the flush runs after later store activity; two ways the
                # verdict can be superseded, both of which the serial path
                # resolved by writing BEFORE that activity:
                #  * the pod was bound (next cycle's nomination pre-pass):
                #    serial's transient False was overwritten by the
                #    bind's PodScheduled=True — skipping converges;
                #  * the pod was deleted and RECREATED under the same key
                #    (stable StatefulSet-style names, fresh uid): serial
                #    stamped the old incarnation; the new pod must wait
                #    for its own verdict.
                if stored.is_assigned:
                    continue
                if (stored.meta.uid and pod.meta.uid
                        and stored.meta.uid != pod.meta.uid):
                    continue
                # uid-less objects (bare test fixtures): creation time is
                # the remaining identity signal — a recreated incarnation
                # carries a fresh timestamp, the same incarnation never
                # changes its own
                if (stored.meta.creation_timestamp
                        != pod.meta.creation_timestamp):
                    continue
            cur = stored.get_condition("PodScheduled")
            if cur is not None and (cur.status, cur.message) == ("False", msg):
                continue
            patched = stored.patch_copy()
            patched.set_condition(
                "PodScheduled", "False", "Unschedulable", msg, now)
            if txn is not None:
                txn.put(patched)
            else:
                self.store.update(KIND_POD, patched)

    # ------------------------------------------------------------------
    def _resolve_admission(self):
        """The (node -> group, pod key -> mask) dicts host-side dry-runs
        consult. Built lazily from the raw arrays the last encode stashed:
        materializing 10k-entry dicts on every cycle charged the hot path
        for a mapping only the (rare) preemption path reads."""
        raw = getattr(self, "_last_admission_raw", None)
        if raw is None:
            return None
        if self._last_admission is None:
            node_group_arr, node_names, pod_mask_arr, pod_keys = raw
            self._last_admission = (
                {n: int(node_group_arr[i]) for i, n in enumerate(node_names)},
                {key: int(pod_mask_arr[i])
                 for i, key in enumerate(pod_keys)},
            )
        return self._last_admission

    def _encode_batch(self, pending: List[Pod], now: float,
                      ctx: CycleContext, transform_score: bool = True):
        """Snapshot + encode: store objects -> packed FullChainInputs.
        Returns (fc, pods, nodes, ng, ngroups, active) or None when no
        schedulable node exists. Shared by the serial and fused paths.

        ``transform_score=False`` (the fused dispatchers): registered
        ScoreTransformers are NOT applied host-side — the wave kernel
        applies their device passes to every wave's rebuilt inputs
        instead (applying both would transform twice)."""
        # pods arrive already view-transformed (run_cycle runs BeforePreFilter
        # ahead of the nomination pre-pass); here the state-level transformer
        # chain runs: ClusterState rewrites, then packed-input rewrites
        t_pack = time.perf_counter()
        with self.tracer.span("snapshot") as ssp:
            state = self._cluster_state(pending, now)
            self.extender.transform_after_prefilter(state, ctx)
            self.extender.transform_before_filter(state, ctx)
            ssp.attributes["nodes"] = str(len(state.nodes))
            ssp.attributes["pods"] = str(len(pending))
        if not state.nodes:
            self._add_pack_wall(time.perf_counter() - t_pack)
            return None
        with self.tracer.span("encode"):
            cs = (self.snapshot_cache.stats
                  if self.snapshot_cache is not None else None)
            hits0 = cs["pod_row_hits"] if cs is not None else 0
            miss0 = cs["pod_row_misses"] if cs is not None else 0
            with self.tracer.span("pack_incremental") as pis:
                fc, pods, nodes, tree, gang_index, ng, ngroups = (
                    build_full_chain_inputs(
                        state, self.args, cache=self.snapshot_cache
                    ))
            if cs is not None:
                reused = cs["pod_row_hits"] - hits0
                repacked = cs["pod_row_misses"] - miss0
                pis.attributes["rows_reused"] = str(reused)
                pis.attributes["rows_repacked"] = str(repacked)
                scheduler_metrics.PACK_ROWS_REUSED.inc(reused)
                scheduler_metrics.PACK_ROWS_REPACKED.inc(repacked)
            # stash the admission grouping this kernel pass used so
            # host-side dry-runs (DefaultPreemption) consult the SAME
            # encoding — the raw label check can be more permissive when
            # the signature budget overflowed, and the dry-run must never
            # accept a node the kernel cannot bind (it would evict victims
            # in vain). Raw arrays only: _resolve_admission materializes
            # the dicts on the (rare) preemption path instead of charging
            # every cycle for them.
            self._last_admission_raw = (
                np.asarray(fc.node_taint_group),
                [n.meta.name for n in state.nodes],
                np.asarray(fc.pod_taint_mask),
                list(pods.keys),
            )
            self._last_admission = None
            if transform_score:
                fc = self.extender.transform_before_score(fc, ctx)
            fc, active = reduce_to_active_axes(fc)
            # keep the packed batch for end-of-cycle unschedulability
            # diagnosis (scheduler/diagnose.py reads the same arrays the
            # kernel consumed); a retry pass overwrites this with the
            # final batch. 4th slot: kernel-emitted explain counts, filled
            # after the dispatch when KOORD_TPU_EXPLAIN is on.
            self._last_batch = (
                fc, {key: j for j, key in enumerate(pods.keys)},
                len(state.nodes), None)
        self._add_pack_wall(time.perf_counter() - t_pack)
        if self.encode_observer is not None:
            # parity/test hook: the post-reduce host arrays — the
            # ScheduleInputs level the pack-overlap byte-parity gates on
            self.encode_observer(fc)
        return fc, pods, nodes, ng, ngroups, active

    def _record_upload_deltas(self) -> None:
        """DeviceSnapshot stats -> per-cycle counter deltas."""
        ds = self.device_snapshot.stats
        prev_ds = self._upload_stats_last
        for key, counter in (
            ("reused", scheduler_metrics.UPLOAD_FIELDS_REUSED),
            ("scattered", scheduler_metrics.UPLOAD_FIELDS_SCATTERED),
            ("put", scheduler_metrics.UPLOAD_FIELDS_PUT),
            ("bytes_scattered", scheduler_metrics.UPLOAD_BYTES_SCATTERED),
            ("bytes_put", scheduler_metrics.UPLOAD_BYTES_PUT),
        ):
            counter.inc(ds[key] - prev_ds.get(key, 0))
        self._upload_stats_last = dict(ds)

    def _readback_sync(self, n_shape: Tuple[int, int], *arrays,
                       path: str = "serial"):
        """The designated host sync point: materialize kernel outputs,
        MONITORED by the dispatch-deadline watchdog (koordguard). With a
        deadline armed the blocking body runs on a watchdog worker; an
        overrun abandons the window (DispatchDeadlineExceeded into the
        dispatch's failure handler) instead of wedging the cycle behind
        a slow-not-dead device. ``path`` labels the overrun counter.
        Note: under a mesh the per-shard marker spans then land as
        detached roots in the tracer ring (the worker thread has no
        cycle root); the default no-deadline path is inline and
        byte-identical to the pre-koordguard behavior."""
        return self.dispatch_watchdog.run(
            lambda: self._readback_sync_now(n_shape, *arrays), path)

    def _readback_sync_now(self, n_shape: Tuple[int, int], *arrays):
        """The blocking readback body. Mesh mode routes through the
        per-shard merge (compacted packed order + shard observability);
        single-device is a plain blocking asarray. ``n_shape`` is (real
        nodes, padded node axis) for the shard-imbalance gauge."""
        if self.sync_delay_injector is not None:
            # sim latency injection: a slow-not-dead device is a sync
            # that takes too long, exactly where the watchdog watches
            self.sync_delay_injector()
        if self.mesh is not None:
            return self._mesh_merge_readback(n_shape, *arrays)
        # the single intended host-blocking sync of the dispatch window
        # koordlint: disable=blocking-readback-in-pipeline
        return [np.asarray(a) for a in arrays]

    def _mesh_merge_readback(self, n_shape: Tuple[int, int], *arrays):
        """Mesh-branch readback: merge the (replicated) compacted output
        buffers from the per-shard device copies (parallel/mesh.py
        merge_readback — the packed order is identical to what the serial
        driver replays), then surface how the dispatch split across the
        mesh: per-shard readback bytes + real-row imbalance gauges and a
        `shard[i]` marker span per device under the kernel span."""
        from koordinator_tpu.parallel import merge_readback, mesh_row_layout

        out, per_shard = merge_readback(*arrays)
        n_real, n_padded = n_shape
        rows = mesh_row_layout(self.mesh, n_real, n_padded)
        mean_rows = float(np.mean(rows)) if rows else 0.0
        scheduler_metrics.MESH_SHARD_IMBALANCE.set(
            float(max(rows)) / mean_rows if mean_rows > 0 else 0.0)
        for i, dev in enumerate(self.mesh.devices.flat):
            nbytes = per_shard.get(dev.id, 0)
            scheduler_metrics.MESH_SHARD_READBACK_BYTES.set(
                float(nbytes), shard=str(i))
            with self.tracer.span("shard", index=str(i),
                                  rows=str(rows[i]),
                                  readback_bytes=str(nbytes)):
                pass
        return out

    def _batch_pass(
        self,
        pending: List[Pod],
        now: float,
        ctx: CycleContext,
        result: CycleResult,
        pending_reservations: Dict[str, Reservation],
    ) -> Tuple[List[Pod], List[Tuple[Pod, str]]]:
        """One snapshot -> kernel -> bind pass. Appends bindings to `result`
        and returns (rejected_pods, failed) still unbound — `failed` carries
        (pod, reason) so Reserve/PreBind veto reasons survive to dispatch —
        the caller decides whether to retry them (preemption) or record them."""
        rejected_pods: List[Pod] = []
        failed_pods: List[Tuple[Pod, str]] = []
        enc = self._encode_batch(pending, now, ctx)
        if enc is None:
            return rejected_pods, [(p, "no schedulable node") for p in pending]
        fc, pods, nodes, ng, ngroups, active = enc
        if self._sidecar_client is not None:
            chosen = self._dispatch_sidecar(fc, pods, nodes, ng, ngroups,
                                            active, result)
        else:
            chosen = self._dispatch_serial(fc, pods, nodes, ng, ngroups,
                                           active, result)

        # apply bindings in queue order
        with self.tracer.span("bind") as bsp:
            bound_before = len(result.bound)
            by_key = {p.meta.key: p for p in pending}
            for i, key in enumerate(pods.keys):
                node_idx = int(chosen[i])
                pod = by_key[key]
                if node_idx < 0:
                    # encoding-budget overflows carry their own first-class
                    # reason (surfaced via the error-handler event trail
                    # and the overflow metric) and never enter preemption —
                    # no victim set can fix an encoding cut
                    reason = pods.unschedulable_reasons.get(i)
                    if reason is not None:
                        failed_pods.append((pod, reason))
                    elif pod.gang_name or pod.quota_name:
                        rejected_pods.append(pod)
                    else:
                        failed_pods.append((pod, "no feasible node"))
                    continue
                node_name = nodes.names[node_idx]
                reservation = pending_reservations.get(key)
                err = self._reserve_and_bind(
                    pod, node_name, ctx, result, reservation_cr=reservation
                )
                if err:
                    failed_pods.append((pod, err))
            bsp.attributes["bound"] = str(len(result.bound) - bound_before)
        return rejected_pods, failed_pods

    # ------------------------------------------------------------------
    def _dispatch_sidecar(self, fc, pods, nodes, ng, ngroups, active,
                          result: CycleResult) -> np.ndarray:
        """Sidecar-served batch pass: the RPC layer owns its own
        degradation (transport failure falls back to the in-process
        step), so the ladder does not wrap this path."""
        # the sidecar protocol ships only the chosen vector: explain
        # resolves to off through the koordwatch chokepoint (the reason
        # is accounted, the value is None exactly as before)
        explain = self._effective_explain()
        step = self._get_step(
            (pods.padded_size, nodes.padded_size, fc.quota_runtime.shape[0]),
            ng, ngroups, active, explain=explain,
        )
        with self.tracer.span(
                "kernel",
                compiled="1" if self._last_step_compiled else "0") as ksp:
            from koordinator_tpu.scheduler.sidecar import (
                schedule_batch_or_fallback,
            )

            chosen, _, _, used_fallback = schedule_batch_or_fallback(
                self._sidecar_client, fc, ng, ngroups, self.args,
                active_axes=active, local_step=step,
            )
            if used_fallback:
                self.sidecar_fallbacks += 1
                scheduler_metrics.SIDECAR_FALLBACKS.inc()
            # remote RPC: the call blocked already; asarray is a no-op
            # copy of host data, not a device sync
            # koordlint: disable=blocking-readback-in-pipeline
            chosen = np.asarray(chosen)
        result.kernel_seconds += ksp.duration_seconds
        scheduler_metrics.KERNEL_SECONDS.observe(ksp.duration_seconds)
        return chosen

    def _dispatch_serial(self, fc_host, pods, nodes, ng, ngroups, active,
                         result: CycleResult) -> np.ndarray:
        """The single-round device-dispatch window, wrapped in the
        degradation ladder: a failure anywhere between step construction
        and readback (strictly before any binding) retries once, then
        demotes — mesh off, explain off, finally the pure-host pass —
        instead of killing the scheduler. ``fc_host`` keeps the pre-
        upload host arrays so every retry re-uploads from scratch
        against the (possibly rebuilt) device snapshot."""
        self.ladder.begin_pass()
        # koordwatch device window for this pass: the decision id joins
        # the kernel span, the flight record and /explain; the window
        # records the SUCCESSFUL attempt's dispatch->last-sync interval
        win = self._open_window("serial")
        attempts = 0
        had_deadline = False
        level0 = self.ladder.level
        while True:
            if self.ladder.level >= LEVEL_HOST_FALLBACK:
                # no device dispatch: the window never completes
                return self._dispatch_host_fallback(fc_host, pods, nodes,
                                                    result)
            explain = self._effective_explain()
            ex_out = None
            try:
                step = self._get_step(
                    (pods.padded_size, nodes.padded_size,
                     fc_host.quota_runtime.shape[0]),
                    ng, ngroups, active, explain=explain,
                )
                with self.tracer.span(
                        "kernel",
                        compiled="1" if self._last_step_compiled
                        else "0",
                        decision_id=win.decision_id) as ksp:
                    fc = fc_host
                    if self.device_snapshot is not None:
                        # device-resident steady state: unchanged fields
                        # reuse the previous cycle's device buffers, small
                        # node-row deltas go up as donated scatters
                        # (snapshot_cache.DeviceSnapshot)
                        fc = self.device_snapshot.upload(fc)
                        self._record_upload_deltas()
                        self.device_snapshot.begin_dispatch()
                    if self._last_step_compiled:
                        # persistent warm-up index: a fresh compile's
                        # rung (builder meta + call avals) so the NEXT
                        # process can pre-build this exact step
                        self._record_step_compile(
                            "serial",
                            self._step_meta(
                                (pods.padded_size, nodes.padded_size,
                                 fc_host.quota_runtime.shape[0]),
                                ng, ngroups, active, explain),
                            (fc, np.int32(len(nodes.names)))
                            if explain is not None else (fc,))
                    t_dispatch = time.perf_counter()
                    win.mark_dispatch(self._window_path("serial"))
                    n_shape = (len(nodes.names),
                               int(np.shape(fc.base.allocatable)[0]))
                    try:
                        if self.fault_injector is not None:
                            self.fault_injector("serial")
                        if explain is not None:
                            # same dispatch, extra attribution outputs;
                            # n_real masks padded node rows out of the
                            # stage counts
                            chosen, _, _, ex_out = step(
                                fc, np.int32(len(nodes.names)))
                        else:
                            chosen, _, _ = step(fc)  # async — no sync
                        if self.pipeline_mode:
                            # overlap window: the previous cycle's
                            # deferred host work (unschedulability
                            # diagnosis + condition writes) runs while
                            # the device executes this cycle's kernel,
                            # then the next cycle's candidate rows
                            # pre-pack into the memo (pack overlap)
                            self._flush_deferred_in_window()
                            self._prepack_in_window()
                            with self.tracer.span("overlap_wait"):
                                # the pipeline's single designated sync
                                # point: bind needs the chosen vector,
                                # nothing before does
                                chosen, = self._readback_sync(
                                    n_shape, chosen)
                        else:
                            # serial path: block immediately (the pre-
                            # pipeline behavior, and the
                            # KOORD_TPU_PIPELINE=0 fallback)
                            chosen, = self._readback_sync(n_shape, chosen)
                    finally:
                        if self.device_snapshot is not None:
                            self.device_snapshot.end_dispatch()
                    result.device_busy_seconds += (
                        time.perf_counter() - t_dispatch)
                    # local dispatch only: a sidecar-served batch arrived
                    # over RPC — counting it as device readback would
                    # poison the readback-regression signal
                    scheduler_metrics.WAVES_PER_DISPATCH.observe(1.0)
                    scheduler_metrics.READBACK_BYTES.inc(int(chosen.nbytes))
                    if ex_out is not None:
                        # the program completed at the chosen sync above;
                        # these are materialized outputs, not fresh syncs
                        # koordlint: disable=blocking-readback-in-pipeline
                        explain_counts = np.asarray(ex_out.stage_counts)
                        ex_bytes = explain_counts.nbytes
                        if ex_out.terms is not None:
                            # koordlint: disable=blocking-readback-in-pipeline
                            terms_np = np.asarray(ex_out.terms)
                            ex_bytes += terms_np.nbytes
                            # chosen is already host-side (synced above)
                            self._stash_terms(pods.keys, chosen >= 0,
                                              terms_np)
                        scheduler_metrics.EXPLAIN_READBACK_BYTES.inc(
                            int(ex_bytes))
                        fc_lb, idx_lb, n_lb, _ = self._last_batch
                        self._last_batch = (fc_lb, idx_lb, n_lb,
                                            explain_counts)
                result.kernel_seconds += ksp.duration_seconds
                scheduler_metrics.KERNEL_SECONDS.observe(
                    ksp.duration_seconds)
                if self._last_step_compiled:
                    # the lazy XLA build landed in this window: its wall
                    # is compile time for the restart attribution split
                    self._add_compile_wall(ksp.duration_seconds)
                self._close_window(win, attempts, had_deadline, level0)
                return chosen
            except _HostWriteFailure as hw:
                # deferred store writes died, not the device: the ladder
                # must not absorb this — re-raise the original error as
                # an unhandled cycle exception
                raise hw.__cause__
            except Exception as exc:
                attempts += 1
                if isinstance(exc, DispatchDeadlineExceeded):
                    had_deadline = True
                # retry or demote (settings re-applied by the transition
                # observer); re-raises when the ladder is exhausted
                self._on_dispatch_failure("serial", exc)

    def _dispatch_host_fallback(self, fc_host, pods, nodes,
                                result: CycleResult) -> np.ndarray:
        """The ladder's bottom rung: no device dispatch at all — a
        pure-host numpy scheduling pass over the diagnose oracle
        (scheduler/degrade.host_fallback_schedule). A failure here has
        no deeper rung to absorb it and propagates as an unhandled cycle
        exception (flight recorder ``cycle_exception`` trigger).
        ``_last_batch`` keeps the host arrays, so unschedulability
        diagnosis runs through the legacy host recompute unchanged."""
        with self.tracer.span("kernel", host_fallback="1") as ksp:
            chosen = host_fallback_schedule(fc_host, pods,
                                            len(nodes.names))
        result.kernel_seconds += ksp.duration_seconds
        scheduler_metrics.KERNEL_SECONDS.observe(ksp.duration_seconds)
        return chosen

    # ------------------------------------------------------------------
    def _fused_wave_cycles(
        self,
        pending: List[Pod],
        now: float,
        ctx: CycleContext,
        result: CycleResult,
        pending_reservations: Dict[str, Reservation],
        originals: Dict[str, Pod],
        k_waves: int,
    ) -> None:
        """K scheduling rounds in ONE device dispatch, replayed host-side
        as logical cycles (models/fused_waves.py module doc has the kernel
        contract). Each logical cycle w binds wave w's pods, runs the SAME
        preemption block serial cycle w would (_post_filter_preempt —
        including its per-cycle rotation clock), and writes conditions
        diagnosed against wave-w-start state (a host numpy mirror advanced
        with the read-back bindings). A Reserve veto or a preemption
        retry truncates: the device state beyond that wave assumed a world
        that didn't happen, so the remaining rounds fall to the next
        cycle. result.waves reports the logical cycles completed.

        Condition writes are BATCHED per dispatch: each logical cycle's
        PodScheduled/condition verdicts are captured at verdict time
        (content byte-identical — same packed state, same ``now``) but
        queue on the pipeline's deferred machinery; the dispatch drains
        them in one flush at the end (pipeline mode keeps deferring into
        the next kernel window as before). The supersede guards in
        ``_diagnose_and_write`` make the late writes converge to exactly
        the serial end state: a pod bound by a later wave skips its stale
        False verdict the same way the next cycle's bind would have
        overwritten it."""
        self._defer_condition_writes = True
        try:
            dispatch = (self._fused_wave_dispatch_overlap
                        if self.replay_overlap
                        else self._fused_wave_dispatch)
            dispatch(pending, now, ctx, result,
                     pending_reservations, originals, k_waves)
        finally:
            self._defer_condition_writes = False
            if not self.pipeline_mode and self._deferred_diagnose:
                # ONE store-write flush for the whole dispatch (pipeline
                # mode leaves the queue for the next kernel window)
                self.flush_deferred()

    def _fused_no_node_cycles(self, pending: List[Pod], now: float,
                              result: CycleResult, k_waves: int) -> None:
        """The serial early-return (no schedulable node), repeated K
        times: every logical cycle re-dispatches the same verdicts
        (idempotent condition writes, per-cycle failure-trail events —
        exactly what K no-node serial cycles produce). Shared by the
        fused and overlapped-replay dispatch paths."""
        failed = [(p, "no schedulable node") for p in pending]
        gang_plugin = self.extender.plugin("Coscheduling")
        for _w in range(k_waves):
            self._post_filter_preempt([], failed, result)
            for pod, reason in failed:
                result.failed.append(pod.meta.key)
                self.extender.error_handlers.dispatch(pod, reason)
            self._write_unschedulable_conditions([], failed, now)
            result.waves += 1
            if gang_plugin is not None:
                gang_plugin.update_pod_group_status(self.store, now)

    def _encode_wave_sides(self, fc_host, pods, nodes, pending: List[Pod],
                           pending_reservations: Dict[str, Reservation],
                           active, now: float):
        """Build one dispatch's WaveSideInputs (host arrays) + the replay
        context: the LoadAware term splits, the hot-claim factorization
        (ops/volumes.py) and the packed reservation rows (owner-match
        columns, allocatable remainders, nomination eligibility) the
        in-kernel pre-passes consume. Returns (fields dict for upload,
        assembler, replay context dict)."""
        ex = nodes.extras
        axis_idx = np.asarray(active)

        def take(name):
            return np.ascontiguousarray(np.take(ex[name], axis_idx,
                                                axis=-1))

        fields = {"la_est_nonprod": take("la_est_nonprod"),
                  "la_adj_nonprod": take("la_adj_nonprod")}
        prod = self.args.score_according_prod_usage
        if prod:
            fields["la_est_prod"] = take("la_est_prod")
            fields["la_adj_prod"] = take("la_adj_prod")
        n_pad = int(np.shape(fc_host.base.allocatable)[0])
        p_pad = pods.padded_size
        claim_pack = None
        analysis = self._claim_analysis
        if analysis is not None and analysis.hot:
            # the attached view rides the analysis (stashed at
            # _effective_waves time — never materialized twice per cycle)
            attached = (analysis.attached if analysis.attached is not None
                        else attached_claim_sets(self.store))
            claim_pack = build_claim_pack(
                analysis, pods.keys, nodes.names, attached, p_pad, n_pad)
        if claim_pack is not None:
            fields["claim_pod"] = claim_pack.pod_claim
            fields["claim_nonhot"] = claim_pack.pod_nonhot
            fields["claim_covered0"] = claim_pack.covered0
        res_slots: List[Reservation] = []
        res_ctx: Dict[str, object] = {"claim_pack": claim_pack,
                                      "res_slots": res_slots,
                                      "res_slot_of": {},
                                      "res_alloc": None, "res_once": None}
        res_plugin = self.extender.plugin("Reservation")
        slot_keys = [k for k in pods.keys if k in pending_reservations]
        if slot_keys:
            nres = len(slot_keys)
            row_index = {key: i for i, key in enumerate(pods.keys)}
            row_of = np.full(nres, -1, np.int32)
            pod_slot = np.full(p_pad, -1, np.int32)
            alloc = np.zeros((nres, len(axis_idx)), np.float32)
            once = np.zeros(nres, np.float32)
            expired = np.zeros(nres, bool)
            for j, key in enumerate(slot_keys):
                res = pending_reservations[key]
                res_slots.append(res)
                row = row_index[key]
                row_of[j] = row
                pod_slot[row] = j
                alloc[j] = res.template.requests.to_vector()[axis_idx]
                once[j] = 1.0 if res.allocate_once else 0.0
                expired[j] = res.is_expired(now)
            # the host nominator's preference: earliest created wins
            order = sorted(
                range(nres),
                key=lambda j: (res_slots[j].meta.creation_timestamp,
                               res_slots[j].meta.name))
            rank = np.zeros(nres, np.int32)
            for pos, j in enumerate(order):
                rank[j] = pos
            owner_match = np.zeros((p_pad, nres), bool)
            nominate_ok = np.zeros(p_pad, bool)
            if res_plugin is not None:
                by_key = {p.meta.key: p for p in pending}
                for i, key in enumerate(pods.keys):
                    if key in pending_reservations:
                        continue
                    pod = by_key.get(key)
                    if pod is None:
                        continue
                    spec = pod.spec
                    # the host pre-pass eligibility class (run_cycle's
                    # nomination loop): gang/quota admission lives in
                    # the kernel, and hostPort/PVC/affinity/spread
                    # placement must pass the Filter chain
                    if (pod.gang_name or pod.quota_name
                            or spec.host_ports or spec.pvc_names
                            or spec.pod_affinity or spec.pod_anti_affinity
                            or spec.topology_spread):
                        continue
                    nominate_ok[i] = True
                    for j, rkey in enumerate(slot_keys):
                        owner_match[i, j] = (
                            not expired[j]
                            and pending_reservations[rkey].matches(pod))
            fields["res_owner_match"] = owner_match
            fields["res_rank"] = rank
            fields["res_alloc"] = alloc
            fields["res_once"] = once
            fields["res_row_of"] = row_of
            fields["res_pod_slot"] = pod_slot
            fields["res_nominate_ok"] = nominate_ok
            res_ctx["res_alloc"] = alloc
            res_ctx["res_once"] = once
            res_ctx["res_slot_of"] = {k: j for j, k in
                                      enumerate(slot_keys)}

        def assemble(up: Dict[str, object]) -> WaveSideInputs:
            return WaveSideInputs(
                la_est=up["la_est_nonprod"],
                la_adj=up["la_adj_nonprod"],
                prod=(ProdSides(est=up["la_est_prod"],
                                adj=up["la_adj_prod"]) if prod else None),
                claims=(ClaimSides(pod_claim=up["claim_pod"],
                                   pod_nonhot=up["claim_nonhot"],
                                   covered0=up["claim_covered0"])
                        if claim_pack is not None else None),
                res=(ResSides(owner_match=up["res_owner_match"],
                              rank=up["res_rank"],
                              alloc=up["res_alloc"],
                              once=up["res_once"],
                              row_of=up["res_row_of"],
                              pod_slot=up["res_pod_slot"],
                              nominate_ok=up["res_nominate_ok"])
                     if slot_keys else None),
            )

        res_ctx["tag"] = (
            claim_pack.n_claims if claim_pack is not None else 0,
            len(slot_keys))
        return fields, assemble, res_ctx

    def _new_wave_mirror(self, fc_host, res_ctx) -> "_WaveStateMirror":
        return _WaveStateMirror(fc_host, claims=res_ctx["claim_pack"],
                                res_alloc=res_ctx["res_alloc"])

    def _replay_nominated_binds(self, seg_rows, pod_of, nodes, res_ctx,
                                ctx, result: CycleResult,
                                failed_pods: List[Tuple[Pod, str]],
                                txn=None):
        """Replay ONE wave's in-kernel nominations host-side, FIRST — the
        serial pre-pass position: via-reservation Reserve hooks +
        consume(). ``pod_of`` resolves a packed row to its Pod (the two
        replay paths index differently). Returns (veto, bound_rows,
        failed_rows, mirror_ops, succ_next_ops) — ONE implementation for
        both the fused and the overlapped-chain replay, so their
        nomination semantics can never drift. A Reserve veto truncates
        the dispatch (serial would retry the pod through the SAME
        cycle's kernel batch, which the device already excluded — the
        next dispatch's host pre-pass re-runs it: one lost cycle, the
        documented envelope)."""
        res_slots = res_ctx["res_slots"]
        res_once = res_ctx["res_once"]
        veto = False
        bound_rows: set = set()
        failed_rows: set = set()
        mirror_ops: List[Tuple] = []
        succ_next: List[Tuple] = []
        for row, node_idx, zone, slot in seg_rows:
            if slot < 0:
                continue
            pod = pod_of(row)
            res = res_slots[slot]
            err = self._reserve_and_bind(
                pod, nodes.names[node_idx], ctx, result,
                via_reservation=res, txn=txn)
            if err:
                failed_pods.append((pod, err))
                failed_rows.add(row)
                veto = True
            else:
                bound_rows.add(row)
                mirror_ops.append(("nom", row, node_idx, zone))
                if res_once is not None and res_once[slot] > 0:
                    # the reconcile's Succeeded transition lands one
                    # wave later — both on device and in the mirror
                    succ_next.append(("succ", row, slot, node_idx))
        return veto, bound_rows, failed_rows, mirror_ops, succ_next

    def _fused_wave_dispatch(
        self,
        pending: List[Pod],
        now: float,
        ctx: CycleContext,
        result: CycleResult,
        pending_reservations: Dict[str, Reservation],
        originals: Dict[str, Pod],
        k_waves: int,
    ) -> None:
        result.waves = 0
        # transform_score=False: registered ScoreTransformers run as
        # in-kernel passes on every wave's rebuilt inputs — the host
        # before_score must NOT also apply at encode (a non-rebuilt
        # field like the score weights would transform twice; the
        # transformer parity gate pins this)
        enc = self._encode_batch(pending, now, ctx, transform_score=False)
        if enc is None:
            self._fused_no_node_cycles(pending, now, result, k_waves)
            return
        fc, pods, nodes, ng, ngroups, active = enc
        fc_host = fc  # the pre-upload host arrays feed the wave mirror
        side_fields, assemble_sides, res_ctx = self._encode_wave_sides(
            fc_host, pods, nodes, pending, pending_reservations, active,
            now)
        # ---- the fused dispatch window, wrapped in the degradation
        # ladder: a failure between step construction and readback
        # (strictly before any binding is replayed) retries once, then
        # demotes — a demotion below fused waves raises
        # FusedDispatchDemoted and the cycle driver re-runs this pass
        # through the serial path. `fc_host`/`side_fields` hold the
        # host arrays, so a retry after a mesh demotion re-uploads from
        # scratch against the rebuilt device snapshot.
        self.ladder.begin_pass()
        win = self._open_window("fused")
        attempts = 0
        had_deadline = False
        level0 = self.ladder.level
        while True:
            explain = self._effective_explain()
            ex_out = None
            try:
                step = self._get_fused_step(
                    (pods.padded_size, nodes.padded_size,
                     fc_host.quota_runtime.shape[0]),
                    ng, ngroups, active, k_waves, explain=explain,
                    sides_tag=res_ctx["tag"],
                )
                with self.tracer.span(
                        "kernel",
                        compiled="1" if self._last_step_compiled else "0",
                        waves=str(k_waves),
                        decision_id=win.decision_id) as ksp:
                    fc = fc_host
                    up_fields = side_fields
                    if self.device_snapshot is not None:
                        fc = self.device_snapshot.upload(fc)
                        up_fields = self.device_snapshot.upload_fields(
                            side_fields)
                        self._record_upload_deltas()
                        self.device_snapshot.begin_dispatch()
                    sides = assemble_sides(up_fields)
                    if self._last_step_compiled:
                        self._record_step_compile(
                            "fused",
                            self._step_meta(
                                (pods.padded_size, nodes.padded_size,
                                 fc_host.quota_runtime.shape[0]),
                                ng, ngroups, active, explain,
                                waves=int(k_waves),
                                sides_tag=list(res_ctx["tag"])),
                            (fc, sides, np.int32(len(nodes.names)))
                            if explain is not None else (fc, sides))
                    t_dispatch = time.perf_counter()
                    win.mark_dispatch(self._window_path("fused"))
                    n_shape = (len(nodes.names),
                               int(np.shape(fc.base.allocatable)[0]))
                    try:
                        if self.fault_injector is not None:
                            self.fault_injector("fused")
                        if explain is not None:
                            out, ex_out = step(fc, sides,
                                               np.int32(len(nodes.names)))
                        else:
                            out = step(fc, sides)  # async
                        compacted = (out.bind_pods, out.bind_nodes,
                                     out.bind_zones, out.bind_res,
                                     out.wave_counts)
                        if self.pipeline_mode:
                            self._flush_deferred_in_window()
                            self._prepack_in_window()
                            with self.tracer.span("overlap_wait"):
                                # the single designated sync point: the
                                # first readback blocks until the whole
                                # fused program (all K waves) finished;
                                # the compacted buffers merge together
                                # (mesh mode reads them from the
                                # per-shard replicas in one pass)
                                (bind_pods, bind_nodes, bind_zones,
                                 bind_res,
                                 wave_counts) = self._readback_sync(
                                     n_shape, *compacted, path="fused")
                        else:
                            (bind_pods, bind_nodes, bind_zones, bind_res,
                             wave_counts) = self._readback_sync(
                                 n_shape, *compacted, path="fused")
                        waves_run = int(out.waves_run)
                    finally:
                        if self.device_snapshot is not None:
                            self.device_snapshot.end_dispatch()
                    result.device_busy_seconds += (
                        time.perf_counter() - t_dispatch)
                    scheduler_metrics.WAVES_PER_DISPATCH.observe(
                        float(waves_run))
                    scheduler_metrics.READBACK_BYTES.inc(
                        int(bind_pods.nbytes + bind_nodes.nbytes
                            + bind_zones.nbytes + wave_counts.nbytes + 4))
                    explain_counts = None
                    if ex_out is not None:
                        # program complete at the bind_pods sync:
                        # materialized outputs, not fresh syncs
                        # koordlint: disable=blocking-readback-in-pipeline
                        explain_counts = np.asarray(ex_out.stage_counts)
                        ex_bytes = explain_counts.nbytes
                        if ex_out.terms is not None:
                            # koordlint: disable=blocking-readback-in-pipeline
                            terms_np = np.asarray(ex_out.terms)
                            ex_bytes += terms_np.nbytes
                            kept_mask = np.zeros(len(pods.keys), bool)
                            # nominated rows (res >= 0) carry no term
                            # rows — the serial twin's pre-pass binds
                            # never reach the kernel's attribution
                            kept_mask[bind_pods[(bind_pods >= 0)
                                                & (bind_res < 0)]] = True
                            self._stash_terms(pods.keys, kept_mask,
                                              terms_np)
                        scheduler_metrics.EXPLAIN_READBACK_BYTES.inc(
                            int(ex_bytes))
                    for w in range(waves_run):
                        # retrospective per-wave markers under the kernel
                        # span: how the dispatch's work split across the
                        # fused rounds
                        with self.tracer.span(
                                "wave", index=str(w),
                                bound=str(int(wave_counts[w]))):
                            pass
                break
            except _HostWriteFailure as hw:
                # deferred store writes died, not the device: the ladder
                # must not absorb this — re-raise the original error as
                # an unhandled cycle exception
                raise hw.__cause__
            except Exception as exc:
                attempts += 1
                if isinstance(exc, DispatchDeadlineExceeded):
                    had_deadline = True
                self._on_dispatch_failure("fused", exc)
                if self.ladder.level >= LEVEL_SERIAL_WAVES:
                    # demoted below fused waves: no binding was applied,
                    # the cycle driver re-runs this pass serially
                    raise FusedDispatchDemoted() from exc
        result.kernel_seconds += ksp.duration_seconds
        scheduler_metrics.KERNEL_SECONDS.observe(ksp.duration_seconds)
        if self._last_step_compiled:
            self._add_compile_wall(ksp.duration_seconds)
        self._close_window(win, attempts, had_deadline, level0)

        # ---- replay the waves as logical cycles. The state mirror is
        # LAZY: it only exists to diagnose unbound pods against wave-w
        # state, so the happy path (every wave binds cleanly) never pays
        # the array copies or the per-binding numpy replay — typed
        # mirror ops accumulate in a backlog that the first diagnosable
        # wave replays in order (pod/nominated/reservation commits,
        # succeed transitions, wave-boundary claim rebuilds).
        mirror: Optional[_WaveStateMirror] = None
        mirror_backlog: List[Tuple] = []

        def mirror_apply(ops) -> None:
            if mirror is None:
                mirror_backlog.extend(ops)
            else:
                for op in ops:
                    _apply_mirror_op(mirror, op)

        def mirror_state() -> _WaveStateMirror:
            nonlocal mirror
            if mirror is None:
                mirror = self._new_wave_mirror(fc_host, res_ctx)
                for op in mirror_backlog:
                    _apply_mirror_op(mirror, op)
                mirror_backlog.clear()
            return mirror

        index = {key: j for j, key in enumerate(pods.keys)}
        by_key = {p.meta.key: p for p in pending}
        keys = pods.keys
        bound_mask = np.zeros(len(keys), bool)
        gang_plugin = self.extender.plugin("Coscheduling")
        pos = 0
        pending_succ: List[Tuple] = []
        for w in range(k_waves):
            n_w = int(wave_counts[w]) if w < waves_run else 0
            seg_rows = [
                (int(bind_pods[b]), int(bind_nodes[b]),
                 int(bind_zones[b]), int(bind_res[b]))
                for b in range(pos, pos + n_w)]
            pos += n_w
            rejected_pods: List[Pod] = []
            failed_pods: List[Tuple[Pod, str]] = []
            kernel_ops: List[Tuple] = []
            # the reconcile's Succeeded transition from the previous
            # wave's allocate-once consumption applies at this wave's
            # start — before any of this wave's binds touch the mirror
            if explain_counts is None and pending_succ:
                mirror_apply(pending_succ)
            pending_succ = []
            with self.tracer.span("bind", wave=str(w)) as bsp:
                bound_before = len(result.bound)
                # nominated binds first (the serial pre-pass position) —
                # a migration-created Reservation bound in an earlier
                # wave is consumed HERE, inside the same dispatch
                (veto, nom_bound, nom_failed, nom_ops,
                 pending_succ) = self._replay_nominated_binds(
                    seg_rows, lambda row: by_key[keys[row]], nodes,
                    res_ctx, ctx, result, failed_pods)
                for row in nom_bound:
                    bound_mask[row] = True
                nominated = nom_bound | nom_failed
                if explain_counts is None and nom_ops:
                    # nominations are pre-pass state: serial cycle w
                    # packed its batch AFTER them, so this wave's
                    # diagnosis state includes them
                    mirror_apply(nom_ops)
                bind_of = {row: (node_idx, zone)
                           for row, node_idx, zone, slot in seg_rows
                           if slot < 0}
                # one walk in packed (queue) order, the serial bind-loop
                # contract: bind-or-classify each still-pending pod
                for i, key in enumerate(keys):
                    if bound_mask[i] or i in nominated:
                        continue  # bound earlier, or handled above
                    pod = by_key[key]
                    ent = bind_of.get(i)
                    if ent is not None:
                        node_idx, zone = ent
                        reservation = pending_reservations.get(key)
                        err = self._reserve_and_bind(
                            pod, nodes.names[node_idx], ctx, result,
                            reservation_cr=reservation)
                        if err:
                            failed_pods.append((pod, err))
                            veto = True
                        else:
                            bound_mask[i] = True
                            if reservation is not None:
                                kernel_ops.append(
                                    ("res",
                                     int(res_ctx["res_slot_of"][key]),
                                     node_idx))
                            else:
                                kernel_ops.append(("pod", i, node_idx,
                                                   zone))
                        continue
                    reason = pods.unschedulable_reasons.get(i)
                    if reason is not None:
                        failed_pods.append((pod, reason))
                    elif pod.gang_name or pod.quota_name:
                        rejected_pods.append(pod)
                    else:
                        failed_pods.append((pod, "no feasible node"))
                bsp.attributes["bound"] = str(
                    len(result.bound) - bound_before)
            # diagnosis for THIS logical cycle reads wave-w-START state
            # (serial cycle w packed its batch before its kernel ran);
            # the mirror still holds it — advance happens below. With
            # kernel counts the mirror is bypassed entirely: the dispatch
            # already attributed every wave at wave-start state.
            if any(r in DIAGNOSED_REASONS for _p, r in failed_pods) or (
                    rejected_pods):
                if explain_counts is not None:
                    # waves >= waves_run reuse the last EXECUTED wave's
                    # row: a zero-commit early exit proves the state (and
                    # hence the counts) is a fixpoint
                    counts_w = explain_counts[min(w, waves_run - 1)]
                    self._last_batch = (
                        None, index, len(nodes.names), counts_w)
                else:
                    self._last_batch = (
                        mirror_state().patched_fc(), index,
                        len(nodes.names), None)
            truncate = veto
            any_victims = self._post_filter_preempt(
                rejected_pods, failed_pods, result)
            if any_victims:
                # serial cycle w's in-cycle kernel rerun after evictions:
                # a fresh SINGLE-round pass over the still-unbound pods
                # (the device's later waves assumed no evictions — drop
                # them and let the next cycle continue the budget)
                retry = self.extender.transform_before_prefilter(
                    [
                        originals.get(p.meta.key, p)
                        for p in rejected_pods
                        + [p for p, _ in failed_pods]
                    ],
                    ctx,
                )
                rejected_pods, failed_pods = self._batch_pass(
                    retry, now, ctx, result, pending_reservations
                )
                truncate = True
            for b in result.bound:
                self._preempt_attempted.pop(b.pod_key, None)
            for pod in rejected_pods:
                result.rejected.append(pod.meta.key)
                self.extender.error_handlers.dispatch(
                    pod, "admission rejected")
            for pod, reason in failed_pods:
                result.failed.append(pod.meta.key)
                self.extender.error_handlers.dispatch(pod, reason)
            self._write_unschedulable_conditions(
                rejected_pods, failed_pods, now)
            result.waves += 1
            if gang_plugin is not None:
                gang_plugin.update_pod_group_status(self.store, now)
            if truncate:
                break
            # advance the mirror with the device's view of this wave's
            # kernel commits + the wave-boundary claim rebuild, so the
            # next logical cycle diagnoses against the state serial
            # cycle w+1 would have packed (kernel counts make the whole
            # mirror unnecessary — each wave carries its own attribution)
            if explain_counts is None:
                mirror_apply(kernel_ops + [("wave_end",)])
        self._last_batch = None

    # ------------------------------------------------------------------
    # overlapped wave replay (KOORD_TPU_REPLAY_OVERLAP, the default)
    # ------------------------------------------------------------------
    def _initial_chain_carry(self, fc, sides, explain):
        """Wave-0 carried state for the chained dispatch, from the same
        (device-resident when uploaded) arrays the fused init reads."""
        carry = initial_wave_carry(fc, sides, explain=explain)
        if self.mesh is not None:
            carry = self._place_chain_carry_on_mesh(carry, explain, sides)
        return carry

    def _place_chain_carry_on_mesh(self, carry, explain, sides):
        """Wave-0 carry placement for the mesh chain: node-axis slots
        that arrived sharded through the DeviceSnapshot upload pass
        through untouched; the host-created slots (the assigned mask,
        the aff_exists coercion, quota/gang/reservation state, the fresh
        claim counters, koordexplain term rows) are placed via
        put_on_mesh — node-axis zeros under the node sharding, the rest
        replicated — so the first chain dispatch never pays an implicit
        reshard."""
        from koordinator_tpu.parallel import (
            put_on_mesh,
            wave_carry_shardings,
        )

        shardings = wave_carry_shardings(
            self.mesh, explain=explain,
            prod=sides.prod is not None,
            claims=sides.claims is not None,
            res=sides.res is not None)
        # claim_new/vol_new are node-axis but HOST-CREATED zeros (the
        # other node slots arrive device-resident through the upload)
        host_node = {WAVE_STATE_FIELDS.index("claim_new"),
                     WAVE_STATE_FIELDS.index("vol_new")}
        out = []
        for i, (arr, sh) in enumerate(zip(carry, shardings)):
            if arr is None:
                out.append(None)
            elif i in WAVE_STATE_NODE_SLOTS and i not in host_node:
                out.append(arr)
            else:
                out.append(put_on_mesh(arr, sh))
        return tuple(out)

    def _dispatch_chain_wave(self, step, fc, carry, sides, n_real: int,
                             explain):
        """Dispatch ONE chained wave asynchronously. Returns (next
        carry, WaveChainOut, counts_row-or-None) — all device values,
        nothing synced: the caller decides when to block."""
        if explain is not None:
            return step(fc, carry, sides, np.int32(n_real))
        carry, rows = step(fc, carry, sides)
        return carry, rows, None

    def _sync_wave_rows(self, n_shape, rows, counts_row,
                        monitored: bool = True):
        """Materialize one wave's compacted readback — the per-wave
        designated sync point of the overlapped replay. Returns host
        arrays (pods, nodes, zones, res, count[, counts_row]).

        ``monitored=False`` (the replay phase, wave >= 2) runs the sync
        INLINE, outside the deadline watchdog: those syncs happen after
        binds applied, where a DispatchDeadlineExceeded could only
        escape as a cycle exception whose unwind closes the dispatch
        window under the still-running program — re-arming donation.
        The ladder's deadline window is wave 1's readback only; a
        genuinely slow device trips it there on the next cycle."""
        arrays = (rows.bind_pods, rows.bind_nodes, rows.bind_zones,
                  rows.bind_res, rows.count)
        if counts_row is not None:
            arrays = arrays + (counts_row,)
        if monitored:
            synced = self._readback_sync(n_shape, *arrays, path="fused")
        else:
            synced = self._readback_sync_now(n_shape, *arrays)
        scheduler_metrics.READBACK_BYTES.inc(
            int(sum(a.nbytes for a in synced[:5])))
        if counts_row is not None:
            scheduler_metrics.EXPLAIN_READBACK_BYTES.inc(
                int(synced[5].nbytes))
        return synced

    def _drain_abandoned_wave(self, rows) -> None:
        """A truncation (Reserve veto, preemption retry) dropped a
        dispatched-but-unconsumed wave: block until it completes before
        the dispatch window closes, so the DeviceSnapshot donation guard
        can never re-arm while the wave still holds the buffers. A
        deliberate sync of a result we discard."""
        import jax

        # the designated abandoned-wave drain: deliberately unmonitored —
        # it runs AFTER binds applied (truncation/unwind), where shedding
        # the wait would only trade a bounded block for a donation hazard
        # (deadline overruns never reach here: their abort path skips the
        # drain and rebuilds the mirror instead)
        # koordlint: disable=naked-device-sync-without-deadline
        jax.block_until_ready(rows.count)

    def _abort_chain_window(self, rows, window_open: bool) -> None:
        """Tear down the chain dispatch window on a host-side failure
        (store-write fault in the in-window flush, ladder retry): wave 1
        may still be executing, and end_dispatch must not re-arm the
        DeviceSnapshot donation guard while the program holds the
        buffers — the next upload (a ladder retry, or the next cycle
        after the re-raise) would donate them out from under it."""
        if rows is not None:
            try:
                self._drain_abandoned_wave(rows)
            except Exception:
                # the wave itself wrecked: it no longer holds buffers,
                # and the ORIGINAL failure is the evidence being raised
                logger.exception("abandoned chain wave failed while "
                                 "draining")
        if window_open:
            self.device_snapshot.end_dispatch()

    def _fused_wave_dispatch_overlap(
        self,
        pending: List[Pod],
        now: float,
        ctx: CycleContext,
        result: CycleResult,
        pending_reservations: Dict[str, Reservation],
        originals: Dict[str, Pod],
        k_waves: int,
    ) -> None:
        """The overlapped-replay fused dispatch: K waves as a CHAIN of
        per-wave device programs (models/fused_waves.py
        build_chained_wave_step — one compiled step serves every K),
        with wave w+1 dispatched BEFORE wave w's rows are read back, so
        the host-side replay of logical cycle w — bind/classify over the
        still-pending slice, PostFilter preemption, condition-write
        capture — drains while the device executes wave w+1.

        The degradation ladder's window closes at the FIRST wave's
        readback: beyond that point bindings are being applied, and a
        failure is evidence for the flight recorder (an unhandled
        cycle_exception), never a reason to shed device capability.

        Byte parity: the chain traces the SAME wave body as the fused
        while_loop and the replay applies the same logical-cycle
        sequence, so outcomes are byte-identical to
        KOORD_TPU_REPLAY_OVERLAP=0 and, transitively, to K sequential
        serial cycles (run_replay_overlap_parity + run_fused_wave_parity
        gate both)."""
        result.waves = 0
        # transform_score=False: registered ScoreTransformers run as
        # in-kernel passes on every wave's rebuilt inputs — the host
        # before_score must NOT also apply at encode (a non-rebuilt
        # field like the score weights would transform twice; the
        # transformer parity gate pins this)
        enc = self._encode_batch(pending, now, ctx, transform_score=False)
        if enc is None:
            self._fused_no_node_cycles(pending, now, result, k_waves)
            return
        fc, pods, nodes, ng, ngroups, active = enc
        fc_host = fc  # the pre-upload host arrays feed the wave mirror
        side_fields, assemble_sides, res_ctx = self._encode_wave_sides(
            fc_host, pods, nodes, pending, pending_reservations, active,
            now)

        # ---- ladder-wrapped dispatch window: step build, upload, the
        # wave-1 dispatch and its readback — strictly before any binding.
        self.ladder.begin_pass()
        win = self._open_window("chained")
        attempts = 0
        had_deadline = False
        level0 = self.ladder.level
        window_open = False
        rows0 = None  # wave 1 in flight: must drain before the window closes
        while True:
            explain = self._effective_explain()
            try:
                step = self._get_chain_step(
                    (pods.padded_size, nodes.padded_size,
                     fc_host.quota_runtime.shape[0]),
                    ng, ngroups, active, explain=explain,
                    sides_tag=res_ctx["tag"],
                )
                with self.tracer.span(
                        "kernel",
                        compiled="1" if self._last_step_compiled else "0",
                        waves=str(k_waves), overlap="1",
                        decision_id=win.decision_id):
                    fc = fc_host
                    up_fields = side_fields
                    if self.device_snapshot is not None:
                        fc = self.device_snapshot.upload(fc)
                        up_fields = self.device_snapshot.upload_fields(
                            side_fields)
                        self._record_upload_deltas()
                        self.device_snapshot.begin_dispatch()
                        window_open = True
                    sides = assemble_sides(up_fields)
                    chain_compiled = self._last_step_compiled
                    t_dispatch = time.perf_counter()
                    win.mark_dispatch(self._window_path("chained"))
                    n_real = len(nodes.names)
                    n_shape = (n_real,
                               int(np.shape(fc.base.allocatable)[0]))
                    if self.fault_injector is not None:
                        self.fault_injector("fused")
                    carry = self._initial_chain_carry(fc, sides, explain)
                    if chain_compiled:
                        self._record_step_compile(
                            "chain",
                            self._step_meta(
                                (pods.padded_size, nodes.padded_size,
                                 fc_host.quota_runtime.shape[0]),
                                ng, ngroups, active, explain,
                                sides_tag=list(res_ctx["tag"])),
                            (fc, carry, sides, np.int32(n_real))
                            if explain is not None
                            else (fc, carry, sides))
                    carry, rows0, crow0 = self._dispatch_chain_wave(
                        step, fc, carry, sides, n_real, explain)
                    if self.pipeline_mode:
                        # the previous cycle's deferred host work drains
                        # while the device runs wave 1
                        self._flush_deferred_in_window()
                    # pack overlap: the chained dispatch always has an
                    # in-window host phase (wave 1 in flight) — pre-pack
                    # the next cycle's rows before blocking on it
                    self._prepack_in_window()
                    with self.tracer.span("overlap_wait"):
                        synced = self._sync_wave_rows(n_shape, rows0,
                                                      crow0)
                break
            except _HostWriteFailure as hw:
                self._abort_chain_window(rows0, window_open)
                rows0, window_open = None, False
                raise hw.__cause__
            except DispatchDeadlineExceeded as exc:
                # the slow wave is exactly what we are escaping: never
                # drain it here (that blocks as long as the overrun) —
                # the window stays open on the old mirror (donation off
                # for good) and _on_dispatch_failure swaps in a fresh
                # one before the retry/demoted re-run
                rows0, window_open = None, False
                attempts += 1
                had_deadline = True
                self._on_dispatch_failure("fused", exc)
                if self.ladder.level >= LEVEL_SERIAL_WAVES:
                    raise FusedDispatchDemoted() from exc
            except Exception as exc:
                self._abort_chain_window(rows0, window_open)
                rows0, window_open = None, False
                attempts += 1
                self._on_dispatch_failure("fused", exc)
                if self.ladder.level >= LEVEL_SERIAL_WAVES:
                    raise FusedDispatchDemoted() from exc
        try:
            executed, t_last_sync = self._replay_wave_chain(
                step, fc, fc_host, carry, sides, res_ctx, synced,
                n_shape, n_real, pods, nodes, pending, now, ctx, result,
                pending_reservations, originals, k_waves, explain)
        finally:
            if window_open:
                self.device_snapshot.end_dispatch()
        # kernel time = the CHAIN's dispatch->last-sync window, the same
        # quantity the serial twin's single-program kernel span measures
        # — NOT just wave 1's window, or the metric would silently
        # shrink ~(K-1)/K when the overlap default flips on and every
        # KERNEL_SECONDS dashboard would read a phantom speedup
        window_seconds = t_last_sync - t_dispatch
        result.kernel_seconds += window_seconds
        scheduler_metrics.KERNEL_SECONDS.observe(window_seconds)
        if chain_compiled:
            self._add_compile_wall(window_seconds)
        result.device_busy_seconds += window_seconds
        scheduler_metrics.WAVES_PER_DISPATCH.observe(float(executed))
        # the timeline window closes at the chain's LAST device sync —
        # the same dispatch->last-sync quantity the kernel span measures
        self._close_window(win, attempts, had_deadline, level0,
                           end_mono=t_last_sync)
        self._last_batch = None

    def _replay_wave_chain(
        self,
        step,
        fc,
        fc_host,
        carry,
        sides,
        res_ctx,
        synced,
        n_shape,
        n_real: int,
        pods,
        nodes,
        pending: List[Pod],
        now: float,
        ctx: CycleContext,
        result: CycleResult,
        pending_reservations: Dict[str, Reservation],
        originals: Dict[str, Pod],
        k_waves: int,
        explain,
    ) -> Tuple[int, float]:
        """Consume the wave chain: one logical cycle per wave, the
        replay of wave w overlapping device execution of wave w+1.
        Returns (wave bodies consumed device-side, wall clock of the
        last device sync) — the device-busy window closes at the last
        sync; host replay past it is not device time.

        Packed-order work is amortized per DISPATCH: the classification
        of every pod (encoding-overflow reason, gang/quota membership,
        plain no-fit) is static for the dispatch, so each wave walks
        only the still-pending slice, and a fixpoint repeat — a wave
        the device early-exited, re-verdicting the same pods at the
        same wave-start state — reuses the previous wave's lists and
        attribution wholesale instead of re-deriving them. Store
        writes: the wave's bind patches land as ONE update_many
        transaction before preemption or gang status reads the store
        (span ``store_flush``); condition writes queue on the deferred
        machinery with byte-identical repeats deduped (their flush was
        already a proven no-op)."""
        keys = pods.keys
        by_key = {p.meta.key: p for p in pending}
        index = {key: j for j, key in enumerate(keys)}
        # per-dispatch precompute: the static (pod, verdict) partition
        _REJECT = object()
        pending_rows: List[Tuple[int, Pod, object]] = []
        for i, key in enumerate(keys):
            pod = by_key[key]
            reason = pods.unschedulable_reasons.get(i)
            if reason is None and (pod.gang_name or pod.quota_name):
                pending_rows.append((i, pod, _REJECT))
            else:
                pending_rows.append(
                    (i, pod, reason or "no feasible node"))
        gang_plugin = self.extender.plugin("Coscheduling")

        mirror: Optional[_WaveStateMirror] = None
        mirror_backlog: List[Tuple] = []

        def mirror_apply(ops) -> None:
            if mirror is None:
                mirror_backlog.extend(ops)
            else:
                for op in ops:
                    _apply_mirror_op(mirror, op)

        def mirror_state() -> _WaveStateMirror:
            nonlocal mirror
            if mirror is None:
                mirror = self._new_wave_mirror(fc_host, res_ctx)
                for op in mirror_backlog:
                    _apply_mirror_op(mirror, op)
                mirror_backlog.clear()
            return mirror

        executed = 1           # wave bodies whose readback we consumed
        t_last_sync = time.perf_counter()
        # fixpoint-repeat caches: valid while no wave commits/vetoes and
        # preemption stays victim-less (any of those invalidates)
        reuse_lists = None     # (rejected_pods, failed_pods)
        reuse_attrib = None    # [(pod key, /explain attribution entry)]
        in_flight = None       # (rows, counts_row) of wave w+1
        # explain=full bookkeeping mirroring the serial twin's masking:
        # term rows belong to DEVICE-kept pods (the chain's bind rows),
        # and a preemption-retry pass stashes its own kernel's rows for
        # the pods it re-ran — the end-of-chain stash must not clobber
        # those (the serial twin stashes chain rows BEFORE the replay,
        # so its retry stash wins by order)
        device_kept = (np.zeros(len(keys), bool)
                       if explain == "full" else None)
        retried_keys: set = set()
        pending_succ: List[Tuple] = []
        try:
            with self.tracer.span("replay_drain",
                                  waves=str(k_waves)) as dsp:
                for w in range(k_waves):
                    if synced is not None:
                        seg_rows = [
                            (int(synced[0][b]), int(synced[1][b]),
                             int(synced[2][b]), int(synced[3][b]))
                            for b in range(int(synced[4]))]
                        cnt_w = int(synced[4])
                        crow_w = synced[5] if explain is not None else None
                    else:
                        seg_rows = []
                        cnt_w = 0
                        crow_w = None
                    # one-ahead: launch wave w+1 BEFORE replaying wave w
                    # — the device works through it while the host
                    # replays (a known fixpoint dispatches nothing: the
                    # fused while_loop's early exit, saved host-side)
                    if (synced is not None and cnt_w > 0
                            and w + 1 < k_waves):
                        carry, rows_n, crow_n = self._dispatch_chain_wave(
                            step, fc, carry, sides, n_real, explain)
                        in_flight = (rows_n, crow_n)
                    else:
                        in_flight = None

                    if device_kept is not None and cnt_w:
                        # nominated rows (res >= 0) have no term rows —
                        # the serial twin's pre-pass binds never reach
                        # the kernel's attribution either
                        device_kept[[row for row, _n, _z, slot
                                     in seg_rows if slot < 0]] = True
                    replay_out: Dict[str, object] = {
                        "apply_succ": pending_succ}
                    truncate = self._replay_logical_cycle(
                        w, seg_rows, cnt_w, crow_w, pending_rows,
                        mirror_state, mirror_apply, res_ctx, index,
                        n_real, nodes, now, ctx, result,
                        pending_reservations, originals, explain,
                        reuse_lists, reuse_attrib, replay_out)
                    pending_succ = replay_out.get("pending_succ", [])
                    pending_rows = replay_out["pending_rows"]
                    reuse_lists = replay_out["reuse_lists"]
                    reuse_attrib = replay_out["reuse_attrib"]
                    retried_keys.update(replay_out.get("retried_keys",
                                                       ()))
                    result.waves += 1
                    if gang_plugin is not None:
                        gang_plugin.update_pod_group_status(self.store,
                                                            now)
                    if truncate:
                        break
                    # advance the mirror with the device's kernel-
                    # committed rows + the wave-boundary claim rebuild,
                    # so the next logical cycle diagnoses at
                    # wave-(w+1)-start state (kernel counts make the
                    # mirror unnecessary; nominated/succeed ops were
                    # applied pre-diagnosis inside the replay)
                    if explain is None:
                        ops = replay_out.get("kernel_mirror_ops", [])
                        if cnt_w or ops:
                            mirror_apply(list(ops) + [("wave_end",)])
                    if in_flight is not None:
                        rows_n, crow_n = in_flight
                        in_flight = None
                        with self.tracer.span("overlap_wait",
                                              wave=str(w + 1)):
                            # post-bind: inline, unmonitored (see
                            # _sync_wave_rows)
                            synced = self._sync_wave_rows(
                                n_shape, rows_n, crow_n, monitored=False)
                        t_last_sync = time.perf_counter()
                        executed += 1
                    else:
                        synced = None
                dsp.attributes["cycles"] = str(result.waves)
        finally:
            if in_flight is not None:
                # a truncation (or a replay wreck mid-flight) left wave
                # w+1 dispatched and unread: block it before the
                # dispatch window can close behind us. Guarded — a
                # device fault in the DISCARDED wave must not replace
                # the in-flight replay exception (or wreck a truncated
                # cycle whose binds already applied) during unwind.
                try:
                    self._drain_abandoned_wave(in_flight[0])
                    # the discarded wave DID execute on device — count
                    # it. (On truncation the overlap world still
                    # executes FEWER device waves than the serial
                    # twin's run-to-fixpoint program: that gap in the
                    # waves-per-dispatch histogram is the overlap's
                    # saved device work, not an accounting artifact.)
                    executed += 1
                except Exception:
                    logger.exception("abandoned chain wave failed "
                                     "while draining")
                t_last_sync = time.perf_counter()
        if explain == "full":
            # decision-time score terms ride the carried state; the last
            # dispatched wave's carry holds the kept-wave-wins rows. The
            # chain completed at the syncs/drain above — this transfer
            # materializes a finished output. The mask is the serial
            # twin's: DEVICE-kept rows (not result.bound — a preemption
            # retry's host rebind has no chain row), minus the pods a
            # retry pass re-ran (its kernel already stashed their rows;
            # in the serial twin that stash comes after the chain's and
            # wins by order).
            terms_np = np.asarray(carry[-1])
            scheduler_metrics.EXPLAIN_READBACK_BYTES.inc(
                int(terms_np.nbytes))
            kept_mask = device_kept
            for key in retried_keys:
                j = index.get(key)
                if j is not None:
                    kept_mask[j] = False
            self._stash_terms(keys, kept_mask, terms_np)
        return executed, t_last_sync

    def _replay_logical_cycle(
        self,
        w: int,
        seg_rows,
        cnt_w: int,
        crow_w,
        pending_rows,
        mirror_state,
        mirror_apply,
        res_ctx,
        index,
        n_real: int,
        nodes,
        now: float,
        ctx: CycleContext,
        result: CycleResult,
        pending_reservations: Dict[str, Reservation],
        originals: Dict[str, Pod],
        explain,
        reuse_lists,
        reuse_attrib,
        out: dict,
    ) -> bool:
        """Replay ONE logical cycle of the overlapped chain (nominated
        via-reservation binds first — the serial pre-pass position —
        then bind/classify in packed order, PostFilter preemption,
        failure records, condition capture). Returns whether the
        dispatch truncates; the updated pending slice, the fixpoint-
        reuse caches, the kernel mirror ops and the delayed Succeeded
        transitions ride ``out``. A pending row's verdict is a string
        (the static failure reason) or the chain's reject sentinel (any
        non-string: gang/quota admission rejection)."""
        rejected_pods: List[Pod] = []
        failed_pods: List[Tuple[Pod, str]] = []
        kernel_ops: List[Tuple] = []
        veto = False
        fresh = True
        txn: List[tuple] = []  # (patched, live pod, annotations, node)
        with self.tracer.span("wave_replay", index=str(w)) as wsp:
            bound_before = len(result.bound)
            # the previous wave's allocate-once consumption lands its
            # Succeeded transition at THIS wave's start (device pass 0a)
            if explain is None and out.get("apply_succ"):
                mirror_apply(out["apply_succ"])
            if cnt_w == 0 and reuse_lists is not None:
                # fixpoint repeat: same pending slice, same wave-start
                # state — the previous wave's partition IS this wave's
                rejected_pods, failed_pods = reuse_lists
                fresh = False
            else:
                pod_of_row = {i: pod for i, pod, _v in pending_rows}
                bound_mask: Dict[int, bool] = {}
                nom_failed: set = set()
                if any(slot >= 0 for _i, _n, _z, slot in seg_rows):
                    # the SAME nomination replay the fused path runs
                    # (serial pre-pass position, via-reservation binds)
                    (nveto, nom_bound, nom_failed, nom_ops,
                     succ_ops) = self._replay_nominated_binds(
                        seg_rows, pod_of_row.__getitem__, nodes,
                        res_ctx, ctx, result, failed_pods, txn=txn)
                    veto |= nveto
                    for row in nom_bound:
                        bound_mask[row] = True
                    if explain is None and nom_ops:
                        mirror_apply(nom_ops)
                    if succ_ops:
                        out.setdefault("pending_succ", []).extend(
                            succ_ops)
                bind_of = {row: (node_idx, zone)
                           for row, node_idx, zone, slot in seg_rows
                           if slot < 0}
                still: List[Tuple[int, Pod, object]] = []
                for ent in pending_rows:
                    i, pod, verdict = ent
                    if bound_mask.get(i):
                        continue  # nominated above: bound, not pending
                    if i in nom_failed:
                        still.append(ent)  # veto: stays pending
                        continue
                    bnd = bind_of.get(i) if cnt_w else None
                    if bnd is not None:
                        node_idx, zone = bnd
                        key = pod.meta.key
                        reservation = pending_reservations.get(key)
                        err = self._reserve_and_bind(
                            pod, nodes.names[node_idx], ctx, result,
                            reservation_cr=reservation, txn=txn)
                        if err:
                            failed_pods.append((pod, err))
                            veto = True
                            still.append(ent)
                        elif reservation is not None:
                            kernel_ops.append(
                                ("res", int(res_ctx["res_slot_of"][key]),
                                 node_idx))
                        else:
                            kernel_ops.append(("pod", i, node_idx, zone))
                        continue
                    still.append(ent)
                    if isinstance(verdict, str):
                        failed_pods.append((pod, verdict))
                    else:
                        rejected_pods.append(pod)
                pending_rows = still
            if txn:
                with self.tracer.span("store_flush",
                                      writes=str(len(txn))):
                    # the wave's bind patches: ONE store transaction,
                    # applied before preemption/gang status reads; the
                    # live queue objects turn coherent right after, as
                    # the serial per-pod write would have left them.
                    # THE designated batched flush site of the replay
                    # koordlint: disable=store-write-in-wave-replay-loop
                    self.store.update_many(KIND_POD,
                                           [t[0] for t in txn])
                for _patched, live, annotations, node_name in txn:
                    live.meta.annotations.update(annotations)
                    live.spec.node_name = node_name

            if fresh and (rejected_pods or any(
                    r in DIAGNOSED_REASONS for _p, r in failed_pods)):
                if explain is not None:
                    self._last_batch = (None, index, n_real, crow_w)
                else:
                    self._last_batch = (
                        mirror_state().patched_fc(), index, n_real, None)
            truncate = veto
            any_victims = self._post_filter_preempt(
                rejected_pods, failed_pods, result)
            if any_victims:
                retry = self.extender.transform_before_prefilter(
                    [
                        originals.get(p.meta.key, p)
                        for p in rejected_pods
                        + [p for p, _ in failed_pods]
                    ],
                    ctx,
                )
                rejected_pods, failed_pods = self._batch_pass(
                    retry, now, ctx, result, pending_reservations
                )
                out["retried_keys"] = [p.meta.key for p in retry]
                truncate = True
                fresh = True
            for b in result.bound[bound_before:]:
                self._preempt_attempted.pop(b.pod_key, None)
            for pod in rejected_pods:
                result.rejected.append(pod.meta.key)
                self.extender.error_handlers.dispatch(
                    pod, "admission rejected")
            for pod, reason in failed_pods:
                result.failed.append(pod.meta.key)
                self.extender.error_handlers.dispatch(pod, reason)
            if fresh:
                self._write_unschedulable_conditions(
                    rejected_pods, failed_pods, now)
            elif reuse_attrib:
                # the repeat's attribution is per logical cycle, exactly
                # like K serial cycles — re-apply the cached entries and
                # stage counters; the byte-identical deferred store
                # write is deduped (its flush was a proven no-op)
                for key, entry in reuse_attrib:
                    self._cycle_attrib[key] = entry
                    for stage_key, c in entry.get("stages", {}).items():
                        scheduler_metrics.FILTER_REJECTIONS.inc(
                            c, stage=stage_key)
            # set LAST so a preemption-retry pass's rebinds count toward
            # this logical cycle's replay span, as they are bound in it
            wsp.attributes["bound"] = str(len(result.bound) - bound_before)
        reuse_ok = cnt_w == 0 and not veto and not any_victims
        if reuse_ok and fresh:
            reuse_lists = (rejected_pods, failed_pods)
            if self.explain_spec is not None:
                reuse_attrib = [
                    (p.meta.key, self._cycle_attrib[p.meta.key])
                    for p in rejected_pods + [fp for fp, _r in failed_pods]
                    if p.meta.key in self._cycle_attrib
                ]
            else:
                reuse_attrib = None
        elif not reuse_ok:
            reuse_lists = None
            reuse_attrib = None
        out["pending_rows"] = pending_rows
        out["reuse_lists"] = reuse_lists
        out["reuse_attrib"] = reuse_attrib
        out["kernel_mirror_ops"] = kernel_ops
        return truncate

    # ------------------------------------------------------------------
    def _reserve_and_bind(
        self,
        pod: Pod,
        node_name: str,
        ctx: CycleContext,
        result: CycleResult,
        via_reservation: Optional[Reservation] = None,
        reservation_cr: Optional[Reservation] = None,
        txn: Optional[list] = None,
    ) -> Optional[str]:
        """Reserve hooks -> PreBind -> Bind; returns error to leave pod
        pending. ``txn`` (overlapped wave replay) collects the bind's
        store patch instead of writing it immediately — the wave flushes
        the whole batch as one store transaction before anything
        (preemption dry-runs, gang status) reads the store."""
        if reservation_cr is not None:
            # binding a Reservation CR itself: no plugin reserve (it only holds
            # capacity), just set status (reservation plugin Bind, plugin.go:596).
            # Allocatable comes from the CR's own template, NOT the pseudo-pod,
            # which may be a cycle-local transformer view that must not persist
            reservation_cr.node_name = node_name
            reservation_cr.phase = "Available"
            reservation_cr.allocatable = reservation_cr.template.requests.copy()
            self.store.update(KIND_RESERVATION, reservation_cr)
            result.bound.append(
                BindResult(RESERVATION_POD_PREFIX + reservation_cr.meta.name,
                           node_name)
            )
            return None

        with self.tracer.span("bind_pod", pod=pod.meta.key,
                              node=node_name) as psp:
            with self.tracer.span("reserve"):
                done: List = []
                for plugin in self.extender.plugins:
                    err = plugin.reserve(pod, node_name, ctx)
                    if err:
                        for p in reversed(done):
                            p.unreserve(pod, node_name, ctx)
                        psp.attributes["veto"] = plugin.name
                        return f"{plugin.name}: {err}"
                    done.append(plugin)
                if via_reservation is not None:
                    res_plugin = self.extender.plugin("Reservation")
                    res_plugin.consume(pod, via_reservation, ctx)

            with self.tracer.span("prebind"):
                annotations: Dict[str, str] = {}
                for plugin in self.extender.plugins:
                    plugin.pre_bind(pod, node_name, ctx, annotations)
                prebind = self.extender.plugin("DefaultPreBind")
                prebind.apply_patch(pod, node_name, annotations, now=ctx.now,
                                    txn=txn)
        result.bound.append(BindResult(pod.meta.key, node_name, annotations))
        return None


# ---------------------------------------------------------------------------
# pipelined cycle driver
# ---------------------------------------------------------------------------

def pipeline_enabled_from_env() -> bool:
    """KOORD_TPU_PIPELINE=0 restores the strictly serial cycle."""
    import os

    return os.environ.get("KOORD_TPU_PIPELINE", "1") != "0"


class CyclePipeline:
    """Pipelined cycle driver: overlap host work with device execution.

    Wraps a Scheduler for a STREAM of cycles (the input-pipeline shape from
    the training world — keep the accelerator fed). Three hand-off points
    change relative to the serial path, none of which changes results:

      1. the kernel dispatch is non-blocking — no ``np.asarray`` readback
         until the bind loop actually needs the chosen vector;
      2. unschedulability diagnosis + PodScheduled condition writes for
         cycle N run inside cycle N+1's kernel window (``flush_deferred``),
         while the device executes — content is captured at cycle N (same
         packed batch, same ``now``), so the writes are byte-identical;
      3. ``flush()`` drains whatever the last cycle deferred.

    Bind order, CRD writes and unschedulable conditions end up byte-for-
    byte what the serial path produces (tests/test_cycle_pipeline.py runs
    both paths over the same fixture and diffs the store).
    ``KOORD_TPU_PIPELINE=0`` (or ``enabled=False``) falls back to the
    serial single-threaded path exactly.
    """

    def __init__(self, scheduler: Scheduler,
                 enabled: Optional[bool] = None) -> None:
        self.scheduler = scheduler
        self.enabled = (pipeline_enabled_from_env()
                        if enabled is None else bool(enabled))
        scheduler.pipeline_mode = self.enabled

    def run_cycle(self, now: Optional[float] = None,
                  waves=None) -> CycleResult:
        return self.scheduler.run_cycle(now=now, waves=waves)

    def flush(self) -> None:
        """Drain deferred condition writes (call at end of stream)."""
        self.scheduler.flush_deferred()

    def __enter__(self) -> "CyclePipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.flush()
