"""Serial-vs-pipelined cycle parity: same fixture, byte-identical outcomes.

The CyclePipeline (scheduler/cycle.py) reorders WHEN host work runs — the
kernel readback is deferred until bind needs it, and unschedulability
condition writes for cycle N run inside cycle N+1's kernel window. None of
that may change WHAT the scheduler produces: bind order, CRD writes, and
PodScheduled conditions must be byte-for-byte what the strictly serial
path produces. This module drives one store fixture through both paths
with identical arrival/metric churn and diffs everything observable.

Run as a gate (hack/lint.sh and tier-1 via tests/test_cycle_pipeline.py):

    JAX_PLATFORMS=cpu python -m koordinator_tpu.scheduler.pipeline_parity
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

GIB = 1024 ** 3


def build_store_from_state(state):
    from koordinator_tpu.client.store import (
        KIND_ELASTIC_QUOTA,
        KIND_NODE,
        KIND_NODE_METRIC,
        KIND_NODE_TOPOLOGY,
        KIND_POD,
        KIND_POD_GROUP,
        ObjectStore,
    )

    store = ObjectStore()
    for n in state.nodes:
        store.add(KIND_NODE, n)
    for nm in state.node_metrics.values():
        store.add(KIND_NODE_METRIC, nm)
    for p in state.pods_by_key.values():
        store.add(KIND_POD, p)
    for p in state.pending_pods:
        store.add(KIND_POD, p)
    for pg in state.pod_groups:
        store.add(KIND_POD_GROUP, pg)
    for q in state.quotas:
        store.add(KIND_ELASTIC_QUOTA, q)
    for t in state.topologies.values():
        store.add(KIND_NODE_TOPOLOGY, t)
    return store


def apply_round_delta(store, round_idx: int, now: float, arrivals: int,
                      metric_touches: Optional[int] = None,
                      prefix: str = "pp", namespace: str = "parity") -> None:
    """Deterministic per-round churn: fresh pending pods + metric touches.
    Twin worlds receive byte-identical objects (independent instances).
    Shared by the parity gate and bench.run_steady_state so both exercise
    the same delta shape; ``metric_touches`` defaults to ~1/7 of the
    metrics (the parity fixture's historical cadence)."""
    from koordinator_tpu.api.objects import (
        NodeMetricInfo,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_NODE_METRIC, KIND_POD

    for i in range(arrivals):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"{prefix}-{round_idx}-{i}",
                            namespace=namespace,
                            uid=f"{prefix}-{round_idx}-{i}",
                            creation_timestamp=now + round_idx),
            spec=PodSpec(priority=5000 + (i % 4) * 1000,
                         requests=ResourceList.of(
                             cpu=250 * (1 + i % 6),
                             memory=(1 + i % 3) * GIB, pods=1)),
        ))
    metrics = store.list(KIND_NODE_METRIC)
    stride = (7 if metric_touches is None
              else max(1, len(metrics) // metric_touches))
    for nm in metrics[round_idx % min(3, stride)::stride]:
        nm.update_time = now + round_idx
        nm.node_metric = NodeMetricInfo(
            node_usage=ResourceList.of(
                cpu=4000 + 500 * round_idx, memory=(8 + round_idx) * GIB))
        store.update(KIND_NODE_METRIC, nm)


def _conditions(store) -> Dict[str, tuple]:
    """Every pod's PodScheduled condition, keyed by pod key."""
    from koordinator_tpu.client.store import KIND_POD

    out = {}
    for pod in store.list(KIND_POD):
        cond = pod.get_condition("PodScheduled")
        if cond is not None:
            out[pod.meta.key] = (cond.status, cond.reason, cond.message,
                                 cond.last_transition_time)
    return out


def _dump_on_mismatch(mismatches, *scheds) -> None:
    """Flight-recorder trigger: a parity mismatch dumps each world's ring
    (files land in KOORD_TPU_FLIGHT_DIR when set; the dump counter always
    increments so the trigger is observable either way)."""
    if not mismatches:
        return
    for sched in scheds:
        sched.flight.dump("parity_mismatch")


def run_pipeline_parity(num_nodes: int = 24, num_pods: int = 70,
                        rounds: int = 4, seed: int = 11,
                        arrivals: int = 9, explain: str = "off") -> dict:
    """Drive identical twin stores through the serial and pipelined paths.

    Returns a report dict; report["ok"] is the gate. Diffs per round:
    bound (pod, node) sequences in order, failed/rejected/victim sets —
    and at end of stream (after flush): every pod's PodScheduled
    condition tuple and node assignment. ``explain`` runs BOTH worlds at
    that koordexplain level (the PR 5 acceptance gate: the pipeline stays
    byte-identical with attribution enabled)."""
    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
    from koordinator_tpu.testing import synth_full_cluster

    def make_world():
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
            topology_fraction=0.5, lsr_fraction=0.2)
        return state, build_store_from_state(state)

    state_s, store_serial = make_world()
    _state_p, store_pipe = make_world()
    # waves pinned to 1: this gate isolates pipelining; the fused-wave
    # gate (run_fused_wave_parity) owns the K > 1 dimension
    sched_serial = Scheduler(store_serial, waves=1, explain=explain)
    sched_pipe = Scheduler(store_pipe, waves=1, explain=explain)
    pipeline = CyclePipeline(sched_pipe, enabled=True)
    assert sched_serial.pipeline_mode is False

    now = state_s.now
    mismatches: List[str] = []
    for r in range(rounds + 1):
        if r > 0:
            apply_round_delta(store_serial, r, now, arrivals)
            apply_round_delta(store_pipe, r, now, arrivals)
        t = now + 2 * r
        res_s = sched_serial.run_cycle(now=t)
        res_p = pipeline.run_cycle(now=t)
        if ([(b.pod_key, b.node_name, b.annotations) for b in res_s.bound]
                != [(b.pod_key, b.node_name, b.annotations)
                    for b in res_p.bound]):
            mismatches.append(f"round {r}: bound sequence differs")
        for field in ("failed", "rejected", "preempted_victims",
                      "resized", "resize_pending"):
            if sorted(getattr(res_s, field)) != sorted(getattr(res_p, field)):
                mismatches.append(f"round {r}: {field} differs")
    pipeline.flush()

    cond_s, cond_p = _conditions(store_serial), _conditions(store_pipe)
    if cond_s != cond_p:
        keys = {k for k in set(cond_s) | set(cond_p)
                if cond_s.get(k) != cond_p.get(k)}
        mismatches.append(
            f"PodScheduled conditions differ for {len(keys)} pods "
            f"(e.g. {sorted(keys)[:3]})")
    assign_s = {p.meta.key: p.spec.node_name
                for p in store_serial.list(KIND_POD)}
    assign_p = {p.meta.key: p.spec.node_name
                for p in store_pipe.list(KIND_POD)}
    if assign_s != assign_p:
        mismatches.append("final pod->node assignments differ")
    _dump_on_mismatch(mismatches, sched_serial, sched_pipe)

    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "rounds": rounds + 1,
        "pods": len(assign_s),
        "conditions_checked": len(cond_s),
        "explain": explain,
    }


def run_explain_parity(num_nodes: int = 24, num_pods: int = 70,
                       rounds: int = 4, seed: int = 11,
                       arrivals: int = 9, waves: int = 1) -> dict:
    """Formatter-over-kernel-counts vs the legacy host-numpy diagnosis:
    byte-identical stores on a churn workload.

    Twin worlds run the SAME cycle cadence, one with KOORD_TPU_EXPLAIN
    semantics pinned to "counts" (PodScheduled messages formatted from the
    kernel-emitted per-stage counts) and one pinned off (the legacy
    diagnose_unbound recompute). Every observable — bound sequences,
    failure sets, every PodScheduled condition tuple string-for-string,
    final assignments — must match, proving BOTH that attribution does not
    perturb decisions and that the kernel counts format to the exact
    legacy messages."""
    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.cycle import Scheduler
    from koordinator_tpu.testing import synth_full_cluster

    def make_world():
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
            topology_fraction=0.5, lsr_fraction=0.2)
        return state, build_store_from_state(state)

    state_l, store_legacy = make_world()
    _state_e, store_explain = make_world()
    sched_legacy = Scheduler(store_legacy, waves=waves, explain="off")
    sched_explain = Scheduler(store_explain, waves=waves, explain="counts")

    now = state_l.now
    mismatches: List[str] = []
    for r in range(rounds + 1):
        if r > 0:
            apply_round_delta(store_legacy, r, now, arrivals)
            apply_round_delta(store_explain, r, now, arrivals)
        t = now + 2 * r
        res_l = sched_legacy.run_cycle(now=t)
        res_e = sched_explain.run_cycle(now=t)
        if ([(b.pod_key, b.node_name) for b in res_l.bound]
                != [(b.pod_key, b.node_name) for b in res_e.bound]):
            mismatches.append(f"round {r}: bound sequence differs")
        for field in ("failed", "rejected", "preempted_victims"):
            if sorted(getattr(res_l, field)) != sorted(getattr(res_e, field)):
                mismatches.append(f"round {r}: {field} differs")

    cond_l, cond_e = _conditions(store_legacy), _conditions(store_explain)
    if cond_l != cond_e:
        keys = {k for k in set(cond_l) | set(cond_e)
                if cond_l.get(k) != cond_e.get(k)}
        mismatches.append(
            f"PodScheduled conditions differ for {len(keys)} pods "
            f"(e.g. {sorted(keys)[:3]})")
    assign_l = {p.meta.key: p.spec.node_name
                for p in store_legacy.list(KIND_POD)}
    assign_e = {p.meta.key: p.spec.node_name
                for p in store_explain.list(KIND_POD)}
    if assign_l != assign_e:
        mismatches.append("final pod->node assignments differ")
    _dump_on_mismatch(mismatches, sched_legacy, sched_explain)

    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "rounds": rounds + 1,
        "waves": waves,
        "pods": len(assign_l),
        "conditions_checked": len(cond_l),
    }


def run_fused_wave_parity(k_waves: int, num_nodes: int = 24,
                          num_pods: int = 70, rounds: int = 2,
                          seed: int = 11, arrivals: int = 9,
                          explain: str = "off") -> dict:
    """Fused-K vs K sequential single-round cycles: byte-identical state.

    The fused wave kernel (models/fused_waves.py) runs K dependent
    scheduling rounds in one dispatch; the driver replays them as logical
    cycles. This harness drives twin stores through identical churn: the
    serial world runs K plain single-round cycles per round, the fused
    world runs pipelined fused cycles until K logical cycles are consumed
    (``CycleResult.waves`` — a veto/preemption truncation hands the
    remaining budget to the next dispatch). Diffed per round: the
    CONCATENATED bound sequences and failed/rejected/victim lists across
    the K logical cycles; at end of stream: every pod's PodScheduled
    condition tuple and node assignment."""
    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
    from koordinator_tpu.testing import synth_full_cluster

    def make_world():
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
            topology_fraction=0.5, lsr_fraction=0.2)
        return state, build_store_from_state(state)

    state_s, store_serial = make_world()
    _state_f, store_fused = make_world()
    sched_serial = Scheduler(store_serial, waves=1, explain=explain)
    sched_fused = Scheduler(store_fused, waves=k_waves, explain=explain)
    pipeline = CyclePipeline(sched_fused, enabled=True)
    assert sched_serial.pipeline_mode is False

    now = state_s.now
    mismatches: List[str] = []
    fields = ("failed", "rejected", "preempted_victims", "resized",
              "resize_pending")
    for r in range(rounds + 1):
        if r > 0:
            apply_round_delta(store_serial, r, now, arrivals)
            apply_round_delta(store_fused, r, now, arrivals)
        t = now + 2 * r
        ser_bound: List[tuple] = []
        ser_lists = {f: [] for f in fields}
        for _c in range(k_waves):
            res = sched_serial.run_cycle(now=t)
            ser_bound.extend(
                (b.pod_key, b.node_name, b.annotations) for b in res.bound)
            for f in fields:
                ser_lists[f].extend(getattr(res, f))
        fused_bound: List[tuple] = []
        fused_lists = {f: [] for f in fields}
        consumed = 0
        while consumed < k_waves:
            res = pipeline.run_cycle(now=t, waves=k_waves - consumed)
            if res.waves <= 0:  # defensive: a cycle must consume >= 1
                mismatches.append(f"round {r}: fused cycle consumed 0")
                break
            consumed += res.waves
            fused_bound.extend(
                (b.pod_key, b.node_name, b.annotations) for b in res.bound)
            for f in fields:
                fused_lists[f].extend(getattr(res, f))
        if ser_bound != fused_bound:
            mismatches.append(
                f"round {r}: bound sequence differs "
                f"(serial {len(ser_bound)} vs fused {len(fused_bound)})")
        for f in fields:
            if ser_lists[f] != fused_lists[f]:
                mismatches.append(f"round {r}: {f} differs")
    pipeline.flush()

    cond_s, cond_f = _conditions(store_serial), _conditions(store_fused)
    if cond_s != cond_f:
        keys = {k for k in set(cond_s) | set(cond_f)
                if cond_s.get(k) != cond_f.get(k)}
        mismatches.append(
            f"PodScheduled conditions differ for {len(keys)} pods "
            f"(e.g. {sorted(keys)[:3]})")
    # plugin-side counters: the fused path increments gang assumed and
    # quota used via carried device state + per-wave binds — the host
    # plugin caches must land exactly where K serial cycles put them
    import numpy as np

    def plugin_counters(sched):
        gang = sched.extender.plugin("Coscheduling")
        quota = sched.extender.plugin("ElasticQuota")
        return (
            {g: n for g, n in (gang.assumed if gang else {}).items() if n},
            {q: tuple(np.asarray(v).tolist())
             for q, v in (quota.used if quota else {}).items()
             if np.asarray(v).any()},
        )

    gang_s, quota_s = plugin_counters(sched_serial)
    gang_f, quota_f = plugin_counters(sched_fused)
    if gang_s != gang_f:
        mismatches.append(f"gang assumed counters differ: "
                          f"{gang_s} vs {gang_f}")
    if quota_s != quota_f:
        mismatches.append("quota used counters differ")
    assign_s = {p.meta.key: p.spec.node_name
                for p in store_serial.list(KIND_POD)}
    assign_f = {p.meta.key: p.spec.node_name
                for p in store_fused.list(KIND_POD)}
    if assign_s != assign_f:
        diff = sorted(k for k in set(assign_s) | set(assign_f)
                      if assign_s.get(k) != assign_f.get(k))
        mismatches.append(
            f"final pod->node assignments differ for {len(diff)} pods "
            f"(e.g. {diff[:3]})")
    _dump_on_mismatch(mismatches, sched_serial, sched_fused)

    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "waves": k_waves,
        "rounds": rounds + 1,
        "pods": len(assign_s),
        "conditions_checked": len(cond_s),
        "explain": explain,
    }


def run_pack_overlap_parity(waves: int = 1, ndev: Optional[int] = None,
                            num_nodes: int = 24, num_pods: int = 70,
                            rounds: int = 3, seed: int = 11,
                            arrivals: int = 9) -> dict:
    """Pack/device overlap (PR 15) vs the gap-pack twin: byte-identical
    ScheduleInputs, decisions and conditions.

    The overlap world pre-packs the next cycle's candidate pod rows
    INSIDE the device window (cycle.py _prepack_in_window); the twin
    pins KOORD_TPU_PACK_OVERLAP=0 — the pack runs strictly in the
    inter-window gap, today's exact path. Both drive identical churn
    through the pipeline and BOTH register the encode observer: every
    post-reduce FullChainInputs array (the ScheduleInputs level) is
    byte-compared per encode — the overlap may move WHEN rows pack,
    never a single produced bit. ``waves`` selects the serial (1) or
    fused-chain path; ``ndev`` shards both worlds over a mesh."""
    import numpy as np

    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
    from koordinator_tpu.testing import synth_full_cluster

    def make_world():
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
            topology_fraction=0.5, lsr_fraction=0.2)
        return state, build_store_from_state(state)

    def snap_fc(fc):
        out = {}
        for name in fc._fields:
            value = getattr(fc, name)
            if name == "base":
                for f2 in value._fields:
                    out["base." + f2] = np.array(
                        np.asarray(getattr(value, f2)), copy=True)
            else:
                out[name] = np.array(np.asarray(value), copy=True)
        return out

    mesh = ndev if ndev is not None else "off"
    state_on, store_on = make_world()
    _state_off, store_off = make_world()
    sched_on = Scheduler(store_on, waves=waves, explain="off", mesh=mesh,
                         pack_overlap=True)
    sched_off = Scheduler(store_off, waves=waves, explain="off", mesh=mesh,
                          pack_overlap=False)
    encodes = {True: [], False: []}
    sched_on.encode_observer = lambda fc: encodes[True].append(snap_fc(fc))
    sched_off.encode_observer = lambda fc: encodes[False].append(
        snap_fc(fc))
    pipe_on = CyclePipeline(sched_on, enabled=True)
    pipe_off = CyclePipeline(sched_off, enabled=True)

    now = state_on.now
    mismatches: List[str] = []
    for r in range(rounds + 1):
        if r > 0:
            apply_round_delta(store_on, r, now, arrivals)
            apply_round_delta(store_off, r, now, arrivals)
        t = now + 2 * r
        res_on = pipe_on.run_cycle(now=t)
        res_off = pipe_off.run_cycle(now=t)
        if ([(b.pod_key, b.node_name, b.annotations)
             for b in res_on.bound]
                != [(b.pod_key, b.node_name, b.annotations)
                    for b in res_off.bound]):
            mismatches.append(f"round {r}: bound sequence differs")
        for f in ("failed", "rejected", "preempted_victims"):
            if sorted(getattr(res_on, f)) != sorted(getattr(res_off, f)):
                mismatches.append(f"round {r}: {f} differs")
    pipe_on.flush()
    pipe_off.flush()

    if len(encodes[True]) != len(encodes[False]):
        mismatches.append(
            f"encode counts differ ({len(encodes[True])} vs "
            f"{len(encodes[False])})")
    else:
        for i, (a, b) in enumerate(zip(encodes[True], encodes[False])):
            bad = [k for k in a
                   if a[k].shape != b[k].shape
                   or not np.array_equal(a[k], b[k])]
            if bad:
                mismatches.append(
                    f"encode {i}: ScheduleInputs fields differ {bad[:4]}")
    cond_on, cond_off = _conditions(store_on), _conditions(store_off)
    if cond_on != cond_off:
        mismatches.append("PodScheduled conditions differ")
    assign_on = {p.meta.key: p.spec.node_name
                 for p in store_on.list(KIND_POD)}
    assign_off = {p.meta.key: p.spec.node_name
                  for p in store_off.list(KIND_POD)}
    if assign_on != assign_off:
        mismatches.append("final pod->node assignments differ")
    _dump_on_mismatch(mismatches, sched_on, sched_off)

    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "rounds": rounds + 1,
        "pods": len(assign_on),
        "conditions_checked": len(cond_on),
        "encodes_compared": len(encodes[True]),
    }


def run_replay_overlap_parity(k_waves: int, num_nodes: int = 24,
                              num_pods: int = 70, rounds: int = 2,
                              seed: int = 11, arrivals: int = 9,
                              explain: str = "off") -> dict:
    """Overlapped wave replay vs the serial-replay fused dispatch:
    byte-identical state.

    The overlap world (KOORD_TPU_REPLAY_OVERLAP=1 semantics pinned) runs
    the fused dispatch as a chain of per-wave device programs with the
    host replay of wave w draining while wave w+1 executes, batched bind
    transactions and deduped condition repeats; the twin pins overlap
    OFF — the single fused program with strictly serial post-readback
    replay, i.e. today's exact path. Both drive identical churn at the
    same wave depth through the pipeline. Diffed per round: bound
    (pod, node, annotations) sequences, the failure/victim/resize lists;
    at end of stream: every PodScheduled condition tuple, gang/quota
    plugin counters, and final assignments."""
    import numpy as np

    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
    from koordinator_tpu.testing import synth_full_cluster

    def make_world():
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
            topology_fraction=0.5, lsr_fraction=0.2)
        return state, build_store_from_state(state)

    state_s, store_serial = make_world()
    _state_o, store_overlap = make_world()
    sched_serial = Scheduler(store_serial, waves=k_waves, explain=explain,
                             replay_overlap=False)
    sched_overlap = Scheduler(store_overlap, waves=k_waves,
                              explain=explain, replay_overlap=True)
    pipe_serial = CyclePipeline(sched_serial, enabled=True)
    pipe_overlap = CyclePipeline(sched_overlap, enabled=True)

    now = state_s.now
    mismatches: List[str] = []
    fields = ("failed", "rejected", "preempted_victims", "resized",
              "resize_pending")
    for r in range(rounds + 1):
        if r > 0:
            apply_round_delta(store_serial, r, now, arrivals)
            apply_round_delta(store_overlap, r, now, arrivals)
        t = now + 2 * r
        res_s = pipe_serial.run_cycle(now=t)
        res_o = pipe_overlap.run_cycle(now=t)
        if ([(b.pod_key, b.node_name, b.annotations) for b in res_s.bound]
                != [(b.pod_key, b.node_name, b.annotations)
                    for b in res_o.bound]):
            mismatches.append(f"round {r}: bound sequence differs")
        if res_s.waves != res_o.waves:
            mismatches.append(f"round {r}: waves consumed differ "
                              f"({res_s.waves} vs {res_o.waves})")
        for f in fields:
            if sorted(getattr(res_s, f)) != sorted(getattr(res_o, f)):
                mismatches.append(f"round {r}: {f} differs")
    pipe_serial.flush()
    pipe_overlap.flush()

    cond_s, cond_o = _conditions(store_serial), _conditions(store_overlap)
    if cond_s != cond_o:
        keys = {k for k in set(cond_s) | set(cond_o)
                if cond_s.get(k) != cond_o.get(k)}
        mismatches.append(
            f"PodScheduled conditions differ for {len(keys)} pods "
            f"(e.g. {sorted(keys)[:3]})")

    def plugin_counters(sched):
        gang = sched.extender.plugin("Coscheduling")
        quota = sched.extender.plugin("ElasticQuota")
        return (
            {g: n for g, n in (gang.assumed if gang else {}).items() if n},
            {q: tuple(np.asarray(v).tolist())
             for q, v in (quota.used if quota else {}).items()
             if np.asarray(v).any()},
        )

    gang_s, quota_s = plugin_counters(sched_serial)
    gang_o, quota_o = plugin_counters(sched_overlap)
    if gang_s != gang_o:
        mismatches.append(f"gang assumed counters differ: "
                          f"{gang_s} vs {gang_o}")
    if quota_s != quota_o:
        mismatches.append("quota used counters differ")
    assign_s = {p.meta.key: p.spec.node_name
                for p in store_serial.list(KIND_POD)}
    assign_o = {p.meta.key: p.spec.node_name
                for p in store_overlap.list(KIND_POD)}
    if assign_s != assign_o:
        diff = sorted(k for k in set(assign_s) | set(assign_o)
                      if assign_s.get(k) != assign_o.get(k))
        mismatches.append(
            f"final pod->node assignments differ for {len(diff)} pods "
            f"(e.g. {diff[:3]})")
    if explain == "full":
        # the per-pod score-term rows ride the chain's carried state —
        # the one koordexplain mode with NEW state threading in the
        # overlap world. The /explain surface (verdict, node, terms,
        # margin for bound pods; stages/message for unbound) must be
        # identical record-for-record.
        rec_s = {k: sched_serial.explain_record(k) for k in assign_s}
        rec_o = {k: sched_overlap.explain_record(k) for k in assign_o}
        if rec_s != rec_o:
            keys = sorted(k for k in set(rec_s) | set(rec_o)
                          if rec_s.get(k) != rec_o.get(k))
            mismatches.append(
                f"explain=full records differ for {len(keys)} pods "
                f"(e.g. {keys[:3]})")
    _dump_on_mismatch(mismatches, sched_serial, sched_overlap)

    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "waves": k_waves,
        "rounds": rounds + 1,
        "pods": len(assign_s),
        "conditions_checked": len(cond_s),
        "explain": explain,
    }


def run_mesh_parity(ndev: int, waves: int = 1, num_nodes: int = 24,
                    num_pods: int = 70, rounds: int = 2, seed: int = 11,
                    arrivals: int = 9, explain: str = "off") -> dict:
    """Mesh-backed dispatch vs the single-device path: byte-identical.

    The mesh world runs the production cycle with KOORD_TPU_MESH=ndev
    semantics pinned (node-state tensors sharded over an ndev-device mesh,
    sharded upload + shard-aware scatter, per-shard readback merge —
    scheduler/cycle.py + parallel/mesh.py); the twin runs the exact
    single-device path. Both worlds use the SAME wave depth and explain
    level, so this gate isolates the mesh dimension; composition with
    pipelining and K-fusion is covered transitively by the PR 3/4 gates.
    Diffed per round: bound (pod, node, annotations) sequences in order
    and the failure/victim/resize lists; at end of stream: every
    PodScheduled condition tuple, gang/quota plugin counters, and final
    assignments."""
    import numpy as np

    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.cycle import Scheduler
    from koordinator_tpu.testing import synth_full_cluster

    def make_world():
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
            topology_fraction=0.5, lsr_fraction=0.2)
        return state, build_store_from_state(state)

    state_s, store_single = make_world()
    _state_m, store_mesh = make_world()
    sched_single = Scheduler(store_single, waves=waves, explain=explain,
                             mesh="off")
    sched_mesh = Scheduler(store_mesh, waves=waves, explain=explain,
                           mesh=ndev)
    assert sched_mesh.mesh is not None and (
        sched_mesh.mesh.devices.size == ndev)

    now = state_s.now
    mismatches: List[str] = []
    fields = ("failed", "rejected", "preempted_victims", "resized",
              "resize_pending")
    for r in range(rounds + 1):
        if r > 0:
            apply_round_delta(store_single, r, now, arrivals)
            apply_round_delta(store_mesh, r, now, arrivals)
        t = now + 2 * r
        res_s = sched_single.run_cycle(now=t)
        res_m = sched_mesh.run_cycle(now=t)
        if ([(b.pod_key, b.node_name, b.annotations) for b in res_s.bound]
                != [(b.pod_key, b.node_name, b.annotations)
                    for b in res_m.bound]):
            mismatches.append(f"round {r}: bound sequence differs")
        if res_s.waves != res_m.waves:
            mismatches.append(f"round {r}: waves consumed differ "
                              f"({res_s.waves} vs {res_m.waves})")
        for f in fields:
            if sorted(getattr(res_s, f)) != sorted(getattr(res_m, f)):
                mismatches.append(f"round {r}: {f} differs")

    cond_s, cond_m = _conditions(store_single), _conditions(store_mesh)
    if cond_s != cond_m:
        keys = {k for k in set(cond_s) | set(cond_m)
                if cond_s.get(k) != cond_m.get(k)}
        mismatches.append(
            f"PodScheduled conditions differ for {len(keys)} pods "
            f"(e.g. {sorted(keys)[:3]})")

    def plugin_counters(sched):
        gang = sched.extender.plugin("Coscheduling")
        quota = sched.extender.plugin("ElasticQuota")
        return (
            {g: n for g, n in (gang.assumed if gang else {}).items() if n},
            {q: tuple(np.asarray(v).tolist())
             for q, v in (quota.used if quota else {}).items()
             if np.asarray(v).any()},
        )

    gang_s, quota_s = plugin_counters(sched_single)
    gang_m, quota_m = plugin_counters(sched_mesh)
    if gang_s != gang_m:
        mismatches.append(f"gang assumed counters differ: "
                          f"{gang_s} vs {gang_m}")
    if quota_s != quota_m:
        mismatches.append("quota used counters differ")
    assign_s = {p.meta.key: p.spec.node_name
                for p in store_single.list(KIND_POD)}
    assign_m = {p.meta.key: p.spec.node_name
                for p in store_mesh.list(KIND_POD)}
    if assign_s != assign_m:
        diff = sorted(k for k in set(assign_s) | set(assign_m)
                      if assign_s.get(k) != assign_m.get(k))
        mismatches.append(
            f"final pod->node assignments differ for {len(diff)} pods "
            f"(e.g. {diff[:3]})")
    _dump_on_mismatch(mismatches, sched_single, sched_mesh)

    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "ndev": ndev,
        "waves": waves,
        "rounds": rounds + 1,
        "pods": len(assign_s),
        "conditions_checked": len(cond_s),
        "explain": explain,
    }


def run_rebalance_parity(ndev: Optional[int] = None, num_nodes: int = 16,
                         rounds: int = 4, seed: int = 11,
                         arrivals: int = 18) -> dict:
    """Device rebalance pass vs the host LowNodeLoad oracle:
    decision-identical on seeded churn, with the pack-memo-shared
    snapshot (koordbalance acceptance gate).

    ONE world runs the production Scheduler (mesh pinned to ``ndev``
    when given) plus a Descheduler wired as the second snapshot
    consumer (``Descheduler(scheduler=...)``: the LowNodeLoad view
    comes from the scheduler's SnapshotCache subscription chain and the
    device pass uploads through the scheduler's DeviceSnapshot). Every
    round applies seeded churn, runs a scheduling cycle, then runs BOTH
    engines over the SAME packed view and diffs:

      * the victim list (order included — the migration-job creation
        order is the arbitrator's input),
      * node classification (is_low / is_high) against a host
        ``classify_nodes`` recompute,
      * the migration-job list the descheduler actually writes vs the
        jobs the host victim set implies.

    The device engine must actually run (``stats["engine"] ==
    "device"``) — a silent host demotion would compare host to host."""
    import numpy as np

    from koordinator_tpu.api.objects import (
        Node,
        NodeMetric,
        NodeMetricInfo,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_NODE,
        KIND_NODE_METRIC,
        KIND_POD,
        KIND_POD_MIGRATION_JOB,
        ObjectStore,
    )
    from koordinator_tpu.descheduler.descheduler import Descheduler
    from koordinator_tpu.descheduler.lownodeload import classify_nodes
    from koordinator_tpu.scheduler.cycle import Scheduler

    import random

    rng = random.Random(seed)
    now = 1_000_000.0
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"rb-n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=32_000, memory=128 * GIB,
                                        pods=128)))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"rb-n{i}", namespace=""),
            update_time=now - 5,
            node_metric=NodeMetricInfo(node_usage=ResourceList.of(
                cpu=6_000, memory=16 * GIB))))
    sched = Scheduler(store, mesh=("off" if ndev is None else ndev))
    desch = Descheduler(store, scheduler=sched, rebalance="on")
    plugin = None
    for profile in desch.profiles:
        for p in profile.balance_plugins:
            if p.name == "LowNodeLoad":
                plugin = p.inner
    assert plugin is not None and plugin.device is desch.rebalancer

    mismatches: List[str] = []
    uid = 0
    for r in range(rounds + 1):
        now += 10.0
        # seeded churn: arrivals (the scheduler binds them), departures,
        # and a rotating metric skew that flips which nodes read high/low
        for _ in range(arrivals):
            uid += 1
            store.add(KIND_POD, Pod(
                meta=ObjectMeta(name=f"rb-p{uid}", namespace="parity",
                                uid=f"rb-p{uid}", creation_timestamp=now,
                                owner_kind="ReplicaSet",
                                owner_name=f"rs-{uid % 13}"),
                spec=PodSpec(
                    priority=rng.choice([100, 5500, 9000]),
                    requests=ResourceList.of(
                        cpu=rng.choice([300, 700, 1100, 1500]),
                        memory=rng.choice([1, 2, 3]) * GIB))))
        running = [p for p in store.list(KIND_POD)
                   if p.is_assigned and not p.is_terminated]
        for p in rng.sample(running, min(3, len(running))):
            store.delete(KIND_POD, p.meta.key)
        for i, nm in enumerate(store.list(KIND_NODE_METRIC)):
            band = 0.85 if (i + r) % 3 == 0 else (
                0.15 if (i + r) % 3 == 1 else 0.55)
            nm.update_time = now - 5
            nm.node_metric = NodeMetricInfo(node_usage=ResourceList.of(
                cpu=int(32_000 * band), memory=int(128 * GIB * band)))
            store.update(KIND_NODE_METRIC, nm)
        res = sched.run_cycle(now=now)
        for b in res.bound:
            pod = store.get(KIND_POD, b.pod_key)
            if pod is not None and not pod.is_terminated:
                pod.phase = "Running"
                store.update(KIND_POD, pod)

        # ---- both engines over the SAME packed view
        picked_dev, _src, v = plugin.select_victims(now=now)
        stats = dict(plugin.last_pass_stats)
        if stats.get("engine") != "device":
            mismatches.append(
                f"round {r}: device engine did not run "
                f"(engine={stats.get('engine')!r})")
            break
        picked_host = plugin.select_victims_host(v)
        if list(picked_dev) != list(picked_host):
            mismatches.append(
                f"round {r}: victim lists differ "
                f"({len(picked_dev)} device vs {len(picked_host)} host)")
        is_low_h, is_high_h = classify_nodes(
            v["usage_pct"], v["has_metric"],
            plugin._thr_vec(plugin.args.low_thresholds),
            plugin._thr_vec(plugin.args.high_thresholds))
        if (list(stats["is_low"]) != list(is_low_h)
                or list(stats["is_high"]) != list(is_high_h)):
            mismatches.append(f"round {r}: node classification differs")

        # ---- the migration-job list the descheduler writes must be
        # exactly what the host victim set implies
        before = {j.meta.key for j in store.list(KIND_POD_MIGRATION_JOB)}
        expected = before | {
            f"koordinator-system/migrate-"
            f"{_src[k].meta.namespace}-{_src[k].meta.name}"
            for k in picked_host}
        desch.run_once(now=now)
        after = {j.meta.key for j in store.list(KIND_POD_MIGRATION_JOB)}
        if after != expected:
            mismatches.append(
                f"round {r}: migration-job list differs "
                f"(+{sorted(after - expected)[:3]} "
                f"-{sorted(expected - after)[:3]})")
    if mismatches and desch.rebalancer is not None:
        desch.rebalancer.flight.dump("rebalance_parity_mismatch")
    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "ndev": ndev or 0,
        "rounds": rounds + 1,
        "pods": len(store.list(KIND_POD)),
        "conditions_checked": len(store.list(KIND_POD_MIGRATION_JOB)),
    }


def run_colo_parity(ndev: Optional[int] = None, num_nodes: int = 12,
                    rounds: int = 4, seed: int = 29,
                    arrivals: int = 10) -> dict:
    """Device colo pass vs the retained host oracles: decision-identical
    on seeded churn, with the pack fed from the SnapshotCache's existing
    subscriptions (koordcolo acceptance gate).

    TWO worlds run the identical seeded sequence — production Scheduler
    (mesh pinned to ``ndev`` when given) + a co-located Manager — with
    only the colo engine differing (``colo="on"`` vs ``colo="host"``).
    Every round applies churn (arrivals incl. quota-labeled and
    batch-class pods, departures, metric skews + staleness flips, a
    reservation-annotation rewrite, a mid-run slo-config hot reload, a
    quota max shift), ticks the manager, revokes, and runs a scheduling
    cycle; the gate diffs:

      * batch/mid allocatable on every node (the writeback vectors),
      * the staleness-degraded node set (against a fresh host gather),
      * the runtime-quota matrix (device fold vs compute_runtime_quotas),
      * the revoke-victim lists (order included) from the overuse loop,
      * the binding logs of the scheduling cycles (the closed loop).

    The device engine must actually run (``engine == "device"``) and the
    revoke loop must consume the device runtime at least once — a silent
    host demotion would compare host to host."""
    import random

    import numpy as np

    from koordinator_tpu.api.objects import (
        ConfigMap,
        ElasticQuota,
        LABEL_QUOTA_IS_PARENT,
        LABEL_QUOTA_NAME,
        LABEL_QUOTA_PARENT,
        Node,
        NodeMetric,
        NodeMetricInfo,
        ObjectMeta,
        Pod,
        PodMetricInfo,
        PodSpec,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_CONFIG_MAP,
        KIND_ELASTIC_QUOTA,
        KIND_NODE,
        KIND_NODE_METRIC,
        KIND_POD,
        ObjectStore,
    )
    from koordinator_tpu.manager import Manager
    from koordinator_tpu.scheduler.config import SchedulerConfiguration
    from koordinator_tpu.scheduler.cycle import Scheduler

    import json

    def build_world(colo: str):
        rng = random.Random(seed)
        now = 1_000_000.0
        store = ObjectStore()
        for i in range(num_nodes):
            node = Node(
                meta=ObjectMeta(name=f"co-n{i}", namespace=""),
                allocatable=ResourceList.of(cpu=32_000, memory=128 * GIB,
                                            pods=128))
            if i % 3 == 0:
                node.meta.annotations[
                    "node.koordinator.sh/reservation"] = json.dumps(
                        {"resources": {"cpu": "2", "memory": "4Gi"},
                         "systemResources": {"cpu": "1"}})
            if i % 4 == 0:
                node.meta.labels["pool"] = "batchy"
            store.add(KIND_NODE, node)
            store.add(KIND_NODE_METRIC, NodeMetric(
                meta=ObjectMeta(name=f"co-n{i}", namespace=""),
                update_time=now - 5,
                node_metric=NodeMetricInfo(node_usage=ResourceList.of(
                    cpu=4_000 + 1_000 * (i % 3), memory=16 * GIB)),
                prod_reclaimable=ResourceList.of(cpu=2_000,
                                                 memory=8 * GIB)))
        # slo-config: cluster strategy + a node-pool override (the
        # per-node strategy scalars must reach the device pass)
        store.add(KIND_CONFIG_MAP, ConfigMap(
            meta=ObjectMeta(name="slo-controller-config",
                            namespace="koordinator-system"),
            data={"colocation-config": json.dumps({
                "cpuReclaimThresholdPercent": 65,
                "memoryReclaimThresholdPercent": 70,
                "nodeConfigs": [{"nodeSelector": {"pool": "batchy"},
                                 "cpuReclaimThresholdPercent": 80}],
            })}))
        # quota tree: root capped tight enough that the children's mins
        # force AutoScaleMin, one child not lending — the fold's corners
        store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
            meta=ObjectMeta(name="co-root", namespace="parity",
                            labels={LABEL_QUOTA_IS_PARENT: "true"}),
            min=ResourceList.of(cpu=12_000, memory=48 * GIB),
            max=ResourceList.of(cpu=20_000, memory=64 * GIB)))
        for qname, lent in (("co-qa", "true"), ("co-qb", "false")):
            store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
                meta=ObjectMeta(
                    name=qname, namespace="parity",
                    labels={
                        LABEL_QUOTA_PARENT: "co-root",
                        "quota.scheduling.koordinator.sh/"
                        "allow-lent-resource": lent}),
                min=ResourceList.of(cpu=6_000, memory=16 * GIB),
                max=ResourceList.of(cpu=18_000, memory=56 * GIB)))
        cfg = SchedulerConfiguration()
        sched = Scheduler(store, config=cfg,
                          mesh=("off" if ndev is None else ndev))
        mgr = Manager(store, identity=f"mgr-{colo}", scheduler=sched,
                      colo=colo)
        plugin = sched.extender.plugin("ElasticQuota")
        import dataclasses as _dc

        revoke_args = _dc.replace(cfg.elastic_quota,
                                  monitor_all_quotas=True,
                                  delay_evict_time_seconds=5.0,
                                  revoke_pod_interval_seconds=1.0)
        revoker = plugin.revoke_controller(store, revoke_args)
        return rng, now, store, sched, mgr, plugin, revoker

    worlds = {name: build_world(name) for name in ("on", "host")}
    mismatches: List[str] = []
    device_runtime_consumed = 0
    victims_seen = 0
    uid = 0
    for r in range(rounds + 1):
        state = {}
        for name in ("on", "host"):
            rng, now, store, sched, mgr, plugin, revoker = worlds[name]
            now += 10.0
            wuid = uid
            # ---- seeded churn (identical draws per world)
            for _ in range(arrivals):
                wuid += 1
                flavor = rng.random()
                spec = PodSpec(
                    priority=rng.choice([9500, 9200, 5500]),
                    requests=ResourceList.of(
                        cpu=rng.choice([500, 1000, 2000]),
                        memory=rng.choice([1, 2, 4]) * GIB))
                labels = {}
                if flavor < 0.3:
                    labels[LABEL_QUOTA_NAME] = rng.choice(
                        ["co-qa", "co-qb"])
                elif flavor < 0.45:
                    # batch-class pod consuming the overcommit the colo
                    # pass publishes — the closed loop's consumer
                    spec = PodSpec(
                        priority=5500,
                        requests=ResourceList.of(
                            batch_cpu=rng.choice([1000, 2000]),
                            batch_memory=rng.choice([1, 2]) * GIB))
                store.add(KIND_POD, Pod(
                    meta=ObjectMeta(name=f"co-p{wuid}",
                                    namespace="parity",
                                    uid=f"co-p{wuid}",
                                    creation_timestamp=now,
                                    labels=labels,
                                    owner_kind="ReplicaSet",
                                    owner_name=f"rs-{wuid % 7}"),
                    spec=spec))
            running = [p for p in store.list(KIND_POD)
                       if p.is_assigned and not p.is_terminated]
            for p in rng.sample(running, min(2, len(running))):
                store.delete(KIND_POD, p.meta.key)
            for i, nm in enumerate(store.list(KIND_NODE_METRIC)):
                stale = (i + r) % 5 == 0
                nm.update_time = (now - 10_000.0) if stale else (now - 5)
                band = 0.25 + 0.15 * ((i + r) % 4)
                usage = {}
                for p in store.list(KIND_POD):
                    if (p.is_assigned and not p.is_terminated
                            and p.spec.node_name == nm.meta.name):
                        usage[p.meta.key] = ResourceList.of(
                            cpu=(p.spec.requests["cpu"] * 3) // 4,
                            memory=(p.spec.requests["memory"] // GIB)
                            * GIB // 2)
                nm.pods_metric = [
                    PodMetricInfo(namespace=k.split("/")[0],
                                  name=k.split("/")[1], pod_usage=v)
                    for k, v in usage.items()]
                nm.node_metric = NodeMetricInfo(
                    node_usage=ResourceList.of(
                        cpu=int(32_000 * band), memory=int(128 * GIB * band)))
                store.update(KIND_NODE_METRIC, nm)
            if r == 1:
                # reservation-annotation rewrite on one node
                node = store.get(KIND_NODE, "/co-n0")
                node.meta.annotations[
                    "node.koordinator.sh/reservation"] = json.dumps(
                        {"resources": {"cpu": "4", "memory": "8Gi"}})
                store.update(KIND_NODE, node)
            if r == 2:
                # slo-config hot reload: the policy scalars must move
                cm = store.get(KIND_CONFIG_MAP,
                               "koordinator-system/slo-controller-config")
                cm.data["colocation-config"] = json.dumps({
                    "cpuReclaimThresholdPercent": 55,
                    "memoryReclaimThresholdPercent": 60,
                    "midCPUThresholdPercent": 15,
                })
                store.update(KIND_CONFIG_MAP, cm)
            if r == 3:
                # quota shrink: runtime collapses under the live used,
                # arming the overuse revoke path
                q = store.get(KIND_ELASTIC_QUOTA, "parity/co-qa")
                q.min = ResourceList.of(cpu=500, memory=GIB)
                q.max = ResourceList.of(cpu=1_000, memory=2 * GIB)
                store.update(KIND_ELASTIC_QUOTA, q)

            # ---- manager tick (the engines under test), then revoke,
            # then the scheduling cycle that consumes the overcommit
            assert mgr.tick(now=now)
            consumed = plugin.fresh_device_runtime() is not None
            victims = revoker.reconcile(now)
            res = sched.run_cycle(now=now)
            for b in res.bound:
                pod = store.get(KIND_POD, b.pod_key)
                if pod is not None and not pod.is_terminated:
                    pod.phase = "Running"
                    store.update(KIND_POD, pod)
            snap = plugin.tree_snapshot(store)
            state[name] = {
                "now": now,
                "uid": wuid,
                "alloc": {n.meta.name: dict(n.allocatable.quantities)
                          for n in store.list(KIND_NODE)},
                "victims": list(victims),
                "bound": [(b.pod_key, b.node_name) for b in res.bound],
                "runtime": (None if snap is None else snap[1]),
                "consumed": consumed,
                "stats": (dict(mgr.colo.last_pass_stats)
                          if mgr.colo is not None else {}),
            }
            worlds[name] = (rng, now, store, sched, mgr, plugin, revoker)
        uid = state["on"]["uid"]
        a, b = state["on"], state["host"]
        if a["stats"].get("engine") != "device":
            mismatches.append(
                f"round {r}: device engine did not run "
                f"(engine={a['stats'].get('engine')!r})")
            break
        if a["consumed"]:
            device_runtime_consumed += 1
        if a["alloc"] != b["alloc"]:
            diff = [n for n in a["alloc"]
                    if a["alloc"][n] != b["alloc"].get(n)]
            mismatches.append(
                f"round {r}: batch/mid allocatable differs on "
                f"{diff[:3]}")
        # degraded set: device stats vs a fresh host gather
        rngh, nowh, storeh, schedh, mgrh, _p, _rv = worlds["host"]
        ctl = mgrh.controllers["noderesource"]
        nodes_h = storeh.list(KIND_NODE)
        degraded_h = ctl._gather(nodes_h, b["now"])[-1]
        degraded_d = np.asarray(a["stats"]["degraded"])
        if list(degraded_d) != list(degraded_h):
            mismatches.append(f"round {r}: degraded-node set differs")
        if (a["runtime"] is None) != (b["runtime"] is None) or (
                a["runtime"] is not None
                and not np.array_equal(a["runtime"], b["runtime"])):
            mismatches.append(f"round {r}: runtime-quota matrix differs")
        dev_rt = a["stats"].get("runtime")
        if dev_rt is not None and a["runtime"] is not None:
            # the device fold's published matrix itself, against the
            # host oracle fold over the same post-writeback store
            if not np.array_equal(np.asarray(dev_rt),
                                  np.asarray(b["runtime"])):
                mismatches.append(
                    f"round {r}: device runtime matrix differs from "
                    f"the host fold")
        if a["victims"] != b["victims"]:
            mismatches.append(
                f"round {r}: revoke-victim lists differ "
                f"({a['victims'][:3]} vs {b['victims'][:3]})")
        victims_seen += len(a["victims"])
        if a["bound"] != b["bound"]:
            mismatches.append(f"round {r}: binding logs differ")
    if not mismatches and device_runtime_consumed == 0:
        mismatches.append(
            "the revoke loop never consumed the device runtime")
    if not mismatches and victims_seen == 0:
        # the victim-list comparison must not be vacuous: the round-3
        # quota shrink is designed to arm the overuse revoke
        mismatches.append("the revoke loop never selected a victim")
    mgr_on = worlds["on"][4]
    if mismatches and mgr_on.colo is not None:
        mgr_on.colo.flight.dump("colo_parity_mismatch")
    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "ndev": ndev or 0,
        "rounds": rounds + 1,
        "pods": len(worlds["on"][2].list(KIND_POD)),
        "conditions_checked": device_runtime_consumed,
    }


def _make_parity_transformer():
    """Device-expressible ScoreTransformer for the transformer parity
    gate. Two exact elementwise rewrites, chosen to cover BOTH rewrite
    classes: the LoadAware nonprod term (a field the wave body REBUILDS
    from carried state each wave — the pass must re-apply on top) and
    the score weights (a field the wave body does NOT rebuild — a pass
    applied both host-side at encode and in-kernel would compound to
    9x instead of 3x, so this gate catches a double application)."""
    from koordinator_tpu.scheduler.frameworkext import (
        DeviceScoreTransformer,
    )

    class ParityHalver(DeviceScoreTransformer):
        name = "parity-halver"

        def device_pass(self, inputs):
            import jax.numpy as jnp

            base = inputs.base
            w = base.weights
            w = w * jnp.where(
                jnp.arange(w.shape[0], dtype=jnp.int32) == 0,
                jnp.float32(3.0), jnp.float32(1.0))
            return inputs._replace(base=base._replace(
                la_term_nonprod=base.la_term_nonprod * jnp.float32(0.5),
                weights=w))

    return ParityHalver()


def _reservation_world():
    """A store whose fused dispatch MUST carry reservation rows: Pending
    Reservation CRs bind in wave 1, selector-blocked owner pods consume
    them via the wave-2 in-kernel nomination (allocate-once + shared
    multi-consumer), and the consumed allocate-once row's Succeeded
    transition lands at the wave-3 boundary — exactly what K serial
    cycles do through the host pre-pass + reconcile."""
    from koordinator_tpu.api.objects import (
        Node,
        ObjectMeta,
        Pod,
        PodSpec,
        Reservation,
        ReservationOwner,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_NODE,
        KIND_POD,
        KIND_RESERVATION,
        ObjectStore,
    )

    now = 1_000_000.0
    store = ObjectStore()
    for name, used in (("n0", 3000), ("n1", 9000)):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=name, namespace=""),
            allocatable=ResourceList.of(cpu=10000, memory=64 * GIB,
                                        pods=60)))
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"pre-{name}", uid=f"pre-{name}",
                            creation_timestamp=now - 100),
            spec=PodSpec(node_name=name,
                         requests=ResourceList.of(cpu=used, memory=GIB,
                                                  pods=1))))

    def pend(name, cpu, labels=None, blocked=True, ts=now):
        pod = Pod(
            meta=ObjectMeta(name=name, uid=name, creation_timestamp=ts,
                            labels=dict(labels or {})),
            spec=PodSpec(requests=ResourceList.of(cpu=cpu, memory=GIB,
                                                  pods=1)))
        if blocked:
            # owner pods ride ONLY the reserved capacity: the selector
            # matches no node, so open-capacity scheduling always fails
            # and the nomination pre-pass is the single bind channel
            pod.spec.node_selector = {"reserved-only": "true"}
        store.add(KIND_POD, pod)
        return pod

    pend("big-f", 7500, blocked=False)       # fails every round: no fit
    pend("own-a", 2000, labels={"app": "a"})
    pend("own-b1", 400, labels={"app": "b"})
    pend("own-b2", 400, labels={"app": "b"})
    pend("small", 800, blocked=False)        # binds wave 1
    store.add(KIND_RESERVATION, Reservation(
        meta=ObjectMeta(name="resv-a", namespace="",
                        creation_timestamp=now - 10),
        template=PodSpec(requests=ResourceList.of(cpu=6000, memory=2 * GIB,
                                                   pods=4)),
        owners=[ReservationOwner(label_selector={"app": "a"})],
        allocate_once=True))
    store.add(KIND_RESERVATION, Reservation(
        meta=ObjectMeta(name="resv-b", namespace="",
                        creation_timestamp=now - 5),
        template=PodSpec(requests=ResourceList.of(cpu=1000, memory=2 * GIB,
                                                   pods=2)),
        owners=[ReservationOwner(label_selector={"app": "b"})],
        allocate_once=False))
    return now, store


def _reservation_round_delta(store, round_idx: int, now: float) -> None:
    """Per-round churn for the reservation world: a fresh Pending
    reservation + its selector-blocked owner + an open filler — the
    PR 9 closed-loop cadence (every migration creates a Reservation)."""
    from koordinator_tpu.api.objects import (
        ObjectMeta,
        Pod,
        PodSpec,
        Reservation,
        ReservationOwner,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_POD, KIND_RESERVATION

    t = now + round_idx
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name=f"own-r{round_idx}", uid=f"own-r{round_idx}",
                        creation_timestamp=t,
                        labels={"app": f"r{round_idx}"}),
        spec=PodSpec(node_selector={"reserved-only": "true"},
                     requests=ResourceList.of(cpu=300, memory=GIB,
                                              pods=1))))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name=f"fill-r{round_idx}", uid=f"fill-r{round_idx}",
                        creation_timestamp=t),
        spec=PodSpec(requests=ResourceList.of(cpu=200, memory=GIB,
                                              pods=1))))
    store.add(KIND_RESERVATION, Reservation(
        meta=ObjectMeta(name=f"resv-r{round_idx}", namespace="",
                        creation_timestamp=t),
        template=PodSpec(requests=ResourceList.of(cpu=500, memory=GIB,
                                                   pods=1)),
        owners=[ReservationOwner(
            label_selector={"app": f"r{round_idx}"})],
        allocate_once=True))


def _claims_world():
    """A store whose fused dispatch MUST carry claim state: hot claims
    (shared between pending pods AND already attached on nodes), tight
    attachable-volume limits, and a pod whose bind becomes feasible only
    after another pod's in-dispatch attachment grants it the
    already-attached exemption (the wave-2 regrouping)."""
    from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore

    now = 1_000_000.0
    store = ObjectStore()
    for i in range(4):
        node = Node(
            meta=ObjectMeta(name=f"n{i}", namespace="",
                            labels={"vg": str(i)}),
            allocatable=ResourceList.of(cpu=32000, memory=64 * GIB,
                                        pods=80))
        node.attachable_volume_limit = 3
        store.add(KIND_NODE, node)

    def pod(name, cpu, pvcs=(), node_name="", selector=None, ts=now):
        p = Pod(
            meta=ObjectMeta(name=name, uid=name, creation_timestamp=ts),
            spec=PodSpec(requests=ResourceList.of(cpu=cpu, memory=GIB,
                                                  pods=1),
                         pvc_names=list(pvcs)))
        if node_name:
            p.spec.node_name = node_name
        if selector:
            p.spec.node_selector = dict(selector)
        store.add(KIND_POD, p)
        return p

    # attached sets: shared-x lives on n0 AND n1 (distinct volume groups)
    pod("b0", 1000, pvcs=["shared-x", "a0"], node_name="n0", ts=now - 100)
    pod("b1", 1000, pvcs=["shared-x"], node_name="n1", ts=now - 100)
    # pending: the exemption consumer (shared-x already attached), a
    # shared pair, the wave-2 exemption flip (q3 pinned to n2 binds only
    # after q2's attachment covers its claim), and unique-claim pods
    pod("q1", 500, pvcs=["shared-x", "new-1"])
    pod("q2", 500, pvcs=["shared-y", "y-extra", "y-extra2"],
        selector={"vg": "2"})
    pod("q3", 500, pvcs=["shared-y"], selector={"vg": "2"})
    pod("q4", 500, pvcs=["u1", "u2"])
    pod("plain", 700)
    return now, store


def _claims_round_delta(store, round_idx: int, now: float) -> None:
    """Per-round claim churn: fresh pods re-sharing earlier claims (some
    now attached — exemptions), plus a new shared pair."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_POD

    t = now + round_idx
    for i, pvcs in enumerate((["shared-x"],
                              [f"r{round_idx}-s"],
                              [f"r{round_idx}-s", "u-extra"])):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"cl-{round_idx}-{i}",
                            uid=f"cl-{round_idx}-{i}",
                            creation_timestamp=t),
            spec=PodSpec(requests=ResourceList.of(cpu=400, memory=GIB,
                                                  pods=1),
                         pvc_names=pvcs)))


def run_carried_state_parity(feature: str, k_waves: int = 4,
                             ndev: Optional[int] = None,
                             explain: str = "off", overlap: bool = True,
                             rounds: int = 2, seed: int = 11) -> dict:
    """One byte-parity gate per retired fused-wave demotion (PR 14).

    ``feature`` selects the carried state under test:

      * ``reservations`` — Pending Reservation CRs ride the batch, turn
        Available in wave 1, get consumed by the wave-2 in-kernel
        nomination (allocate-once Succeeded transition at wave 3).
      * ``claims`` — hot-claim columns: shared/attached claims, volume
        limits, the wave-2 already-attached exemption flip.
      * ``prod`` — scoreAccordingProdUsage with the carried est/adj prod
        term split, over the full synth cluster.
      * ``transformer`` — a device-expressible ScoreTransformer applied
        as an in-kernel tensor pass each wave vs the serial twin's host
        before_score.

    The fused world runs K waves per dispatch (overlap on — the default
    production shape), the serial twin runs K single-round cycles, both
    under the same mesh placement; diffed per round: bound (pod, node,
    annotations) sequences and the failure/rejection/victim lists; at
    end of stream: every PodScheduled condition tuple, gang/quota plugin
    counters and final assignments. A regression that re-demotes (the
    fused world silently running serial) fails the ``fused_engaged``
    assertion — this gate can never pass vacuously."""
    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
    from koordinator_tpu.testing import synth_full_cluster

    args = None
    round_delta = None
    transformer_factory = None
    if feature == "reservations":
        def make_world():
            return _reservation_world()

        round_delta = _reservation_round_delta
    elif feature == "claims":
        def make_world():
            return _claims_world()

        round_delta = _claims_round_delta
    elif feature in ("prod", "transformer"):
        if feature == "prod":
            args = LoadAwareArgs(score_according_prod_usage=True)
        else:
            transformer_factory = _make_parity_transformer

        def make_world():
            _cluster, state = synth_full_cluster(
                20, 60, seed=seed, num_quotas=2, num_gangs=3,
                topology_fraction=0.5, lsr_fraction=0.2)
            return state.now, build_store_from_state(state)

        def round_delta(store, r, now):
            apply_round_delta(store, r, now, arrivals=7)
    else:
        raise ValueError(f"unknown feature {feature!r}")

    now, store_serial = make_world()
    _now, store_fused = make_world()
    mesh = ndev if ndev is not None else "off"
    sched_serial = Scheduler(store_serial, args=args, waves=1,
                             explain=explain, mesh=mesh)
    sched_fused = Scheduler(store_fused, args=args, waves=k_waves,
                            explain=explain, mesh=mesh,
                            replay_overlap=overlap)
    if transformer_factory is not None:
        sched_serial.extender.register_transformer(transformer_factory())
        sched_fused.extender.register_transformer(transformer_factory())
    pipeline = CyclePipeline(sched_fused, enabled=True)

    mismatches: List[str] = []
    fields = ("failed", "rejected", "preempted_victims", "resized",
              "resize_pending")
    fused_engaged = 0
    for r in range(rounds + 1):
        if r > 0:
            round_delta(store_serial, r, now)
            round_delta(store_fused, r, now)
        t = now + 2 * r
        ser_bound: List[tuple] = []
        ser_lists = {f: [] for f in fields}
        for _c in range(k_waves):
            res = sched_serial.run_cycle(now=t)
            ser_bound.extend(
                (b.pod_key, b.node_name, b.annotations) for b in res.bound)
            for f in fields:
                ser_lists[f].extend(getattr(res, f))
        fused_bound: List[tuple] = []
        fused_lists = {f: [] for f in fields}
        consumed = 0
        while consumed < k_waves:
            res = pipeline.run_cycle(now=t, waves=k_waves - consumed)
            if res.waves <= 0:
                mismatches.append(f"round {r}: fused cycle consumed 0")
                break
            # the burn-down's whole point: none of the retired reasons
            # may fire, and the dispatch must actually run multi-wave
            if res.demotions:
                mismatches.append(
                    f"round {r}: fused cycle demoted ({res.demotions})")
            if res.waves > 1:
                fused_engaged += 1
            consumed += res.waves
            fused_bound.extend(
                (b.pod_key, b.node_name, b.annotations) for b in res.bound)
            for f in fields:
                fused_lists[f].extend(getattr(res, f))
        if ser_bound != fused_bound:
            mismatches.append(
                f"round {r}: bound sequence differs "
                f"(serial {len(ser_bound)} vs fused {len(fused_bound)}): "
                f"{ser_bound} != {fused_bound}")
        for f in fields:
            if ser_lists[f] != fused_lists[f]:
                mismatches.append(f"round {r}: {f} differs")
    pipeline.flush()
    if not fused_engaged:
        mismatches.append("fused path never ran multi-wave: the gate "
                          "would be vacuous (did a demotion sneak back?)")

    cond_s, cond_f = _conditions(store_serial), _conditions(store_fused)
    if cond_s != cond_f:
        keys = {k for k in set(cond_s) | set(cond_f)
                if cond_s.get(k) != cond_f.get(k)}
        mismatches.append(
            f"PodScheduled conditions differ for {len(keys)} pods "
            f"(e.g. {sorted(keys)[:3]})")
    import numpy as np

    def plugin_counters(sched):
        gang = sched.extender.plugin("Coscheduling")
        quota = sched.extender.plugin("ElasticQuota")
        return (
            {g: n for g, n in (gang.assumed if gang else {}).items() if n},
            {q: tuple(np.asarray(v).tolist())
             for q, v in (quota.used if quota else {}).items()
             if np.asarray(v).any()},
        )

    if plugin_counters(sched_serial) != plugin_counters(sched_fused):
        mismatches.append("gang/quota plugin counters differ")
    assign_s = {p.meta.key: p.spec.node_name
                for p in store_serial.list(KIND_POD)}
    assign_f = {p.meta.key: p.spec.node_name
                for p in store_fused.list(KIND_POD)}
    if assign_s != assign_f:
        diff = sorted(k for k in set(assign_s) | set(assign_f)
                      if assign_s.get(k) != assign_f.get(k))
        mismatches.append(
            f"final pod->node assignments differ for {len(diff)} pods "
            f"(e.g. {diff[:3]})")
    _dump_on_mismatch(mismatches, sched_serial, sched_fused)

    return {
        "ok": not mismatches,
        "mismatches": mismatches,
        "feature": feature,
        "waves": k_waves,
        "ndev": ndev,
        "rounds": rounds + 1,
        "pods": len(assign_s),
        "conditions_checked": len(cond_s),
        "explain": explain,
        "overlap": overlap,
    }


def _force_virtual_devices() -> None:
    """The mesh parity gates need >= 8 devices; on the CPU backend force
    the 8-way virtual split (same shape tests/conftest.py pins) BEFORE the
    first jax import of this process."""
    import os
    import sys

    if "jax" in sys.modules:
        return  # too late to change the platform flags; use what exists
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv: List[str]) -> int:
    def show(name: str, rep: dict) -> bool:
        line = (f"{name}: rounds={rep['rounds']} pods={rep['pods']} "
                f"conditions={rep['conditions_checked']} -> "
                f"{'OK' if rep['ok'] else 'MISMATCH'}")
        print(line, file=sys.stderr)
        for m in rep["mismatches"]:
            print(f"  {m}", file=sys.stderr)
        return rep["ok"]

    _force_virtual_devices()
    ok = show("pipeline parity", run_pipeline_parity())
    # pack/device overlap (PR 15): the in-window pre-pack must be a pure
    # latency lever — ScheduleInputs byte-identical at the encode level,
    # serial + fused-chain + mesh-sharded (the other gates below run
    # with the overlap DEFAULT-ON on top, so every parity property also
    # holds under the overlap architecture)
    ok = show("pack-overlap parity (serial)",
              run_pack_overlap_parity(waves=1)) and ok
    ok = show("pack-overlap parity (fused K=4)",
              run_pack_overlap_parity(waves=4)) and ok
    for k in (1, 2, 4, 8):
        ok = show(f"fused-wave parity K={k}", run_fused_wave_parity(k)) and ok
    # overlapped wave replay (KOORD_TPU_REPLAY_OVERLAP): the chain-of-
    # per-wave-programs dispatch with in-flight replay must be byte-
    # identical to the single-program serial-replay twin at every depth
    for k in (1, 2, 4, 8):
        ok = show(f"replay-overlap parity K={k}",
                  run_replay_overlap_parity(k)) and ok
    ok = show("replay-overlap parity K=4 (explain=counts)",
              run_replay_overlap_parity(4, explain="counts")) and ok
    # "full" is the one explain mode whose kept-wave-wins term rows ride
    # the NEW chain carry (slot 12) — gate its surface record-for-record
    ok = show("replay-overlap parity K=4 (explain=full)",
              run_replay_overlap_parity(4, explain="full")) and ok
    # mesh-backed dispatch (KOORD_TPU_MESH): the production sharded path
    # must be byte-identical to single-device at every mesh size, serial
    # and fused, and with koordexplain attribution enabled on top
    import jax

    max_dev = len(jax.devices())
    for nd in (1, 2, 4, 8):
        if nd > max_dev:
            print(f"mesh parity ndev={nd}: SKIPPED "
                  f"(only {max_dev} devices)", file=sys.stderr)
            continue
        ok = show(f"mesh parity ndev={nd} (serial)",
                  run_mesh_parity(nd)) and ok
        ok = show(f"mesh parity ndev={nd} (fused K=4)",
                  run_mesh_parity(nd, waves=4)) and ok
    if max_dev >= 2:
        ok = show("pack-overlap parity (mesh ndev=2, fused K=4)",
                  run_pack_overlap_parity(waves=4, ndev=2)) and ok
    if max_dev >= 8:
        ok = show("mesh parity ndev=8 (serial, explain=counts)",
                  run_mesh_parity(8, explain="counts")) and ok
        ok = show("mesh parity ndev=8 (fused K=4, explain=counts)",
                  run_mesh_parity(8, waves=4, explain="counts")) and ok
    # koordexplain gates (PR 5): kernel-counts formatter vs the legacy
    # host diagnosis must be string-for-string on churn, and the PR 3/4
    # parity properties must survive with attribution enabled
    # koordbalance (balance/): the device rebalance pass must be
    # decision-identical to the host LowNodeLoad oracle — victim lists,
    # node classification, migration jobs — single-device and sharded
    # over 1/2/4/8-device meshes, with the pack-memo-shared snapshot
    ok = show("rebalance parity (single-device)",
              run_rebalance_parity()) and ok
    for nd in (1, 2, 4, 8):
        if nd > max_dev:
            print(f"rebalance parity ndev={nd}: SKIPPED "
                  f"(only {max_dev} devices)", file=sys.stderr)
            continue
        ok = show(f"rebalance parity ndev={nd}",
                  run_rebalance_parity(nd)) and ok
    # koordcolo (colo/): the device control-plane pass must be
    # decision-identical to the retained host oracles — batch/mid
    # allocatable, degraded-node sets, runtime-quota matrices,
    # revoke-victim lists, binding logs — single-device and sharded
    # over 1/2/4/8-device meshes, with the SnapshotCache-fed pack
    ok = show("colo parity (single-device)", run_colo_parity()) and ok
    for nd in (1, 2, 4, 8):
        if nd > max_dev:
            print(f"colo parity ndev={nd}: SKIPPED "
                  f"(only {max_dev} devices)", file=sys.stderr)
            continue
        ok = show(f"colo parity ndev={nd}", run_colo_parity(nd)) and ok
    ok = show("explain parity (counts vs legacy, serial)",
              run_explain_parity()) and ok
    ok = show("explain parity (counts vs legacy, fused K=4)",
              run_explain_parity(waves=4, rounds=2)) and ok
    ok = show("pipeline parity (explain=counts)",
              run_pipeline_parity(explain="counts")) and ok
    ok = show("fused-wave parity K=4 (explain=counts)",
              run_fused_wave_parity(4, explain="counts")) and ok
    # PR 14 demotion burn-down: one byte-parity gate per retired
    # fused-wave demotion (claims / reservations / prod scoring /
    # score transformers as carried device state), each vs K sequential
    # serial cycles at K in {2,4,8}, plus explain=counts and the
    # mesh-sharded placement at 1/4 devices
    for feat in ("claims", "reservations", "prod", "transformer"):
        for k in (2, 4, 8):
            ok = show(f"carried-state parity [{feat}] K={k}",
                      run_carried_state_parity(feat, k_waves=k)) and ok
        ok = show(f"carried-state parity [{feat}] K=4 (explain=counts)",
                  run_carried_state_parity(
                      feat, k_waves=4, explain="counts")) and ok
        # the serial-replay twin (KOORD_TPU_REPLAY_OVERLAP=0): the
        # non-overlap fused dispatch replays carried state too
        ok = show(f"carried-state parity [{feat}] K=4 (overlap off)",
                  run_carried_state_parity(
                      feat, k_waves=4, overlap=False)) and ok
        for nd in (1, 4):
            if nd > max_dev:
                print(f"carried-state parity [{feat}] ndev={nd}: SKIPPED",
                      file=sys.stderr)
                continue
            ok = show(
                f"carried-state parity [{feat}] K=4 ndev={nd} "
                f"(explain=counts)",
                run_carried_state_parity(feat, k_waves=4, ndev=nd,
                                         explain="counts")) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
