"""Unschedulability diagnosis: why did the kernel leave a pod unbound?

The reference surfaces every filter failure in pod status through the
scheduler framework (the '0/N nodes are available: X Insufficient cpu...'
message kube-scheduler writes to the PodScheduled condition, and
frameworkext's debug plumbing /root/reference/pkg/scheduler/frameworkext/
debug.go:31-46). The batched kernel returns only `chosen[i] == -1`, so
this module re-runs the SAME per-stage predicates in numpy against the
batch's packed arrays — pre-batch state, one pod at a time — and
aggregates per-stage failure counts into the upstream-style message.

Cost: O(N x R) per diagnosed pod, run host-side only for pods that END a
cycle unbound (typically few); the kernel pass itself is untouched.

Caveat, documented: the breakdown is computed against the CYCLE-START
state (before in-batch placements), so a pod starved by earlier pods in
the same batch reports the stage that failed at batch start — the same
approximation upstream makes when it diagnoses against the informer
snapshot rather than the in-flight assume cache.

koordexplain split (PR 5): the module is now counts + formatter. The
kernel emits the same per-stage counts on device in the scheduling
dispatch (models/full_chain.explain_stage_counts, KOORD_TPU_EXPLAIN);
``format_stage_counts`` renders EITHER source into the identical message,
and ``host_stage_counts`` (this module's numpy recompute) stays as the
parity oracle tier-1 diffs the kernel counts against.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from koordinator_tpu.models.full_chain import (
    EXPLAIN_STAGE_GANG,
    EXPLAIN_STAGE_QUOTA,
    EXPLAIN_STAGES,
    NUM_EXPLAIN_STAGES,
)

GANG_MESSAGE = ("gang minMember not satisfied: sibling pods missing or the "
                "gang timed out (Coscheduling PreFilter)")
QUOTA_MESSAGE = ("quota group exhausted: request exceeds runtime "
                 "quota along the ancestor chain (ElasticQuota "
                 "PreFilter)")


def _count(mask) -> int:
    return int(np.asarray(mask).sum())


def format_stage_counts(counts, num_nodes: int) -> str:
    """The upstream-style message for one pod's stage-count vector
    (NUM_EXPLAIN_STAGES long, kernel- or host-computed — the SAME formatter
    renders both, so parity between them reduces to count equality).
    Reproduces the legacy diagnose_unbound byte-for-byte: PreFilter
    verdicts short-circuit (gang before quota, the legacy early returns),
    then non-zero per-node stages sort by descending count with the
    taxonomy order breaking ties (Python's stable sort + EXPLAIN_STAGES
    insertion order)."""
    counts = np.asarray(counts)
    if int(counts[EXPLAIN_STAGE_GANG]):
        return GANG_MESSAGE
    if int(counts[EXPLAIN_STAGE_QUOTA]):
        return QUOTA_MESSAGE
    parts: List[str] = [
        f"{int(c)} {label}"
        for label, c in zip(EXPLAIN_STAGES, counts)
        if int(c)
    ]
    parts.sort(key=lambda s: -int(s.split(" ", 1)[0]))
    if not parts:
        # every stage we model passes on some node at cycle-start state:
        # the pod lost to in-batch contention (capacity taken by earlier
        # queue positions this cycle)
        return (f"0/{num_nodes} nodes available after in-batch placements: "
                "capacity consumed by earlier pods this cycle")
    return f"0/{num_nodes} nodes are available: " + ", ".join(parts) + "."


def shared_state(fc, num_nodes: int) -> dict:
    """Node-level inputs every diagnosis of this batch shares, pulled to
    host ONCE: the LoadAware reject rows are a compiled-op call whose
    result readback costs a full device round-trip — paying it per unbound
    pod made a many-unbound cycle quadratically expensive."""
    from koordinator_tpu.ops import loadaware as la_ops

    inputs = fc.base
    n = num_nodes
    rej_np, rej_pr = la_ops.loadaware_node_reject(
        inputs.allocatable, inputs.la_filter_usage,
        inputs.la_has_filter_usage, inputs.la_filter_thresholds,
        inputs.la_prod_thresholds, inputs.la_prod_pod_usage,
        inputs.la_filter_skip)
    return {
        "alloc": np.asarray(inputs.allocatable, np.float32)[:n],
        "requested": np.asarray(inputs.requested, np.float32)[:n],
        "node_ok": np.asarray(inputs.node_ok, bool)[:n],
        "rej_np": np.asarray(rej_np, bool)[:n],
        "rej_pr": np.asarray(rej_pr, bool)[:n],
    }


def _stage_verdicts(fc, i: int, num_nodes: int, shared: dict = None):
    """The per-stage verdicts behind one pod's diagnosis: a pod-level
    PreFilter flag pair (gang invalid, quota exhausted) plus the per-node
    reject masks keyed by EXPLAIN_STAGES label. Shared by the counts
    oracle (host_stage_counts) and the feasibility view
    (host_feasible_mask) so the two can never drift."""
    inputs = fc.base
    n = num_nodes
    if shared is None:
        shared = shared_state(fc, n)
    alloc = shared["alloc"]
    requested = shared["requested"]
    node_ok = shared["node_ok"]
    fit_req = np.asarray(inputs.fit_requests, np.float32)[i]
    raw_req = np.asarray(fc.requests, np.float32)[i]

    # ---- PreFilter stage (pod-level verdict flags; no node breakdown)
    gang_bad = False
    quota_bad = False
    gang_id = int(np.asarray(fc.gang_id)[i])
    if gang_id >= 0 and not bool(np.asarray(fc.gang_valid)[gang_id]):
        gang_bad = True
    qid = int(np.asarray(fc.quota_id)[i])
    if qid >= 0:
        used = np.asarray(fc.quota_used, np.float32)
        runtime = np.asarray(fc.quota_runtime, np.float32)
        chain = np.asarray(fc.quota_ancestors)[qid]
        for g in chain:
            if g < 0:
                continue
            bad = (raw_req > 0) & (used[g] + raw_req > runtime[g])
            if bad.any():
                quota_bad = True
                break

    # ---- Filter stages, counted per node
    reasons: Dict[str, np.ndarray] = {}
    reasons["node not schedulable"] = ~node_ok
    # admission bitmask: taints + nodeSelector/affinity + volume topology
    mask = int(np.asarray(fc.pod_taint_mask)[i])
    group = np.asarray(fc.node_taint_group)[:n]
    reasons["taint/selector/volume-topology mismatch"] = (
        ((mask >> group) & 1) == 0)
    # NodeResourcesFit
    reasons["insufficient resources"] = (
        (fit_req[None, :] > 0) & (requested + fit_req[None, :] > alloc)
    ).any(axis=1)
    # LoadAware thresholds (node rows precomputed in shared_state)
    is_prod = bool(np.asarray(inputs.is_prod)[i])
    is_ds = bool(np.asarray(inputs.is_daemonset)[i])
    la_rej = shared["rej_pr"] if is_prod else shared["rej_np"]
    reasons["node load over threshold"] = (
        la_rej if not is_ds else np.zeros(n, bool))
    # NodePorts
    wants = np.asarray(fc.pod_port_wants, bool)[i]
    if wants.any():
        used_ports = np.asarray(fc.port_used, np.float32)[:n]
        reasons["hostPort in use"] = (
            used_ports[:, wants] > 0).any(axis=1)
    # CSI volume limits (volume-group row selects new attachments)
    vn_row = np.asarray(fc.vol_needed, np.float32)[i]
    if (vn_row > 0).any():
        vg = np.asarray(fc.node_vol_group)[:n]
        vn = vn_row[vg]
        reasons["CSI volume limit exceeded"] = (
            (vn > 0) & (np.asarray(fc.vol_free, np.float32)[:n] < vn))
    # cpuset capacity
    if bool(np.asarray(fc.needs_bind)[i]):
        cores = float(np.asarray(fc.cores_needed)[i])
        bind_free = np.asarray(fc.bind_free, np.float32)[:n]
        has_topo = np.asarray(fc.has_topology, bool)[:n]
        cpc = np.maximum(np.asarray(fc.cpus_per_core, np.float32)[:n], 1.0)
        bad = ~has_topo | (cores > bind_free)
        if bool(np.asarray(fc.full_pcpus)[i]):
            bad |= np.remainder(cores, cpc) != 0
        reasons["insufficient bindable CPUs"] = bad
    # NUMA topology
    if bool(np.asarray(fc.needs_numa)[i]):
        numa_free = np.asarray(fc.numa_free, np.float32)[:n]
        policy = np.asarray(fc.numa_policy)[:n]
        per_zone_fit = (
            (raw_req[None, None, :] <= 0)
            | (raw_req[None, None, :] <= numa_free)).all(axis=2).any(axis=1)
        total_fit = (
            (raw_req[None, :] <= 0)
            | (raw_req[None, :] <= numa_free.sum(axis=1))).all(axis=1)
        reasons["NUMA topology cannot fit"] = np.where(
            policy == 1, ~per_zone_fit, (policy != 0) & ~total_fit)
    # inter-pod affinity / anti-affinity / spread (aggregate), mirroring
    # the kernel predicates in models/full_chain.py make_pod_evaluator
    T = fc.aff_dom.shape[1]
    if T:
        aff_bad = np.zeros(n, bool)
        dom = np.asarray(fc.aff_dom, np.float32)[:n]
        count = np.asarray(fc.aff_count, np.float32)[:n]
        cover = np.asarray(fc.anti_cover, np.float32)[:n]
        exists = np.asarray(fc.aff_exists, bool)
        taint_ok = ~reasons["taint/selector/volume-topology mismatch"]
        skew_row = np.asarray(fc.pod_spread_skew, np.float32)[i]
        for t in range(T):
            match_t = bool(np.asarray(fc.pod_aff_match)[i, t])
            if bool(np.asarray(fc.pod_anti_req)[i, t]):
                aff_bad |= count[:, t] > 0
            if match_t:
                aff_bad |= cover[:, t] > 0
            if bool(np.asarray(fc.pod_aff_req)[i, t]):
                # bootstrap admits a self-matching first replica only when
                # NO matching pod exists anywhere; otherwise the node needs
                # a matching pod in a valid domain
                bootstrap = match_t and not exists[t]
                if not bootstrap:
                    aff_bad |= ~((dom[:, t] >= 0) & (count[:, t] > 0))
            skew = float(skew_row[t])
            if skew > 0:
                dom_valid = dom[:, t] >= 0
                eligible = dom_valid & taint_ok
                min_count = (count[eligible, t].min()
                             if eligible.any() else np.inf)
                self_m = 1.0 if match_t else 0.0
                aff_bad |= ~(dom_valid
                             & (count[:, t] + self_m - min_count <= skew))
        reasons["affinity/anti-affinity/spread mismatch"] = aff_bad

    return gang_bad, quota_bad, reasons


def host_stage_counts(fc, i: int, num_nodes: int,
                      shared: dict = None) -> np.ndarray:
    """[NUM_EXPLAIN_STAGES] uint32 for pod row ``i`` of FullChainInputs
    ``fc``: per-stage rejected-node counts over the first ``num_nodes``
    real (unpadded) nodes plus the gang/quota PreFilter verdict flags —
    the host-numpy oracle the kernel's on-device attribution is diffed
    against. Pass ``shared`` (shared_state) when diagnosing many pods of
    one batch."""
    gang_bad, quota_bad, reasons = _stage_verdicts(fc, i, num_nodes,
                                                  shared=shared)
    counts = np.zeros(NUM_EXPLAIN_STAGES, np.uint32)
    if gang_bad:
        counts[EXPLAIN_STAGE_GANG] = 1
    if quota_bad:
        counts[EXPLAIN_STAGE_QUOTA] = 1
    for s, label in enumerate(EXPLAIN_STAGES):
        bad = reasons.get(label)
        if bad is not None:
            counts[s] = _count(bad)
    return counts


def host_feasible_mask(fc, i: int, num_nodes: int,
                       shared: dict = None) -> np.ndarray:
    """bool[num_nodes]: the nodes on which pod row ``i`` passes every
    modeled PreFilter/Filter predicate at ``fc``'s state — the
    complement union of the same per-stage verdicts the counts oracle
    reports. The degradation ladder's host-fallback pass
    (scheduler/degrade.host_fallback_schedule) schedules against this
    view when the device dispatch is down."""
    gang_bad, quota_bad, reasons = _stage_verdicts(fc, i, num_nodes,
                                                  shared=shared)
    if gang_bad or quota_bad:
        return np.zeros(num_nodes, bool)
    feasible = np.ones(num_nodes, bool)
    for bad in reasons.values():
        feasible &= ~np.asarray(bad, bool)
    return feasible


def diagnose_unbound(fc, i: int, num_nodes: int,
                     shared: dict = None) -> str:
    """Upstream-style message for pod row ``i`` of FullChainInputs ``fc``:
    the legacy host-numpy recompute path — host counts through the shared
    formatter. The explain-enabled cycle driver formats KERNEL counts with
    the same formatter instead; tier-1 pins the two string-for-string."""
    return format_stage_counts(
        host_stage_counts(fc, i, num_nodes, shared=shared), num_nodes)
