"""Batched scheduling sidecar: gRPC service around the fused kernel.

Server side runs next to the TPU; the host scheduler (the reference's Go event
loop, or our Python cycle driver on another machine) packs its caches into
tensors and calls ScheduleBatch. Step functions are cached by (shapes, gangs,
flags) exactly like the in-process cycle driver."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from koordinator_tpu.models.full_chain import FullChainInputs, build_full_chain_step
from koordinator_tpu.models.scheduler_model import ScheduleInputs
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler import sidecar_pb2

SERVICE_NAME = "koordinator.scheduler.v1.BatchedScheduler"

_DTYPES = {"float32": np.float32, "int32": np.int32, "bool": np.bool_}


def tensor_to_np(t: sidecar_pb2.Tensor) -> np.ndarray:
    arr = np.frombuffer(t.data, dtype=_DTYPES[t.dtype])
    return arr.reshape(tuple(t.shape)).copy()


def np_to_tensor(a: np.ndarray) -> sidecar_pb2.Tensor:
    a = np.asarray(a)
    dtype = {"float32": "float32", "int32": "int32", "bool": "bool"}[str(a.dtype)]
    return sidecar_pb2.Tensor(shape=list(a.shape), dtype=dtype, data=a.tobytes())


def pack_request(fc: FullChainInputs, num_gangs: int, num_groups: int,
                 args: LoadAwareArgs, active_axes=None,
                 snapshot_version: int = 0) -> sidecar_pb2.ScheduleBatchRequest:
    req = sidecar_pb2.ScheduleBatchRequest(
        num_gangs=num_gangs,
        num_groups=num_groups,
        score_according_prod_usage=args.score_according_prod_usage,
        snapshot_version=snapshot_version,
    )
    if active_axes is not None:
        req.active_axes.extend(int(a) for a in active_axes)
    # args.resource_weights feed the compiled step's score weights — they
    # must ride the wire or the server would silently score with defaults.
    # The dense vector alone can't distinguish "axis unset" from "axis set
    # to 0", and consumers iterate resource_weights keys — so the set-axes
    # mask rides alongside and the server rebuilds the key set verbatim.
    from koordinator_tpu.api.resources import NUM_RESOURCES, RESOURCE_INDEX

    req.inputs["args.weights"].CopyFrom(
        np_to_tensor(np.asarray(args.weight_vector(), np.float32)))
    weights_set = np.zeros(NUM_RESOURCES, np.bool_)
    for name in args.resource_weights:
        weights_set[RESOURCE_INDEX[name]] = True
    req.inputs["args.weights_set"].CopyFrom(np_to_tensor(weights_set))
    for name, value in fc.base._asdict().items():
        req.inputs[f"base.{name}"].CopyFrom(np_to_tensor(np.asarray(value)))
    for name, value in fc._asdict().items():
        if name == "base":
            continue
        req.inputs[name].CopyFrom(np_to_tensor(np.asarray(value)))
    return req


def unpack_request(req: sidecar_pb2.ScheduleBatchRequest) -> Tuple[FullChainInputs, LoadAwareArgs]:
    import jax.numpy as jnp

    base_kwargs = {}
    fc_kwargs = {}
    weights_vec = None
    weights_set = None
    for name, tensor in req.inputs.items():
        if name == "args.weights":
            weights_vec = tensor_to_np(tensor)
            continue
        if name == "args.weights_set":
            weights_set = tensor_to_np(tensor)
            continue
        arr = jnp.asarray(tensor_to_np(tensor))
        if name.startswith("base."):
            base_kwargs[name[5:]] = arr
        else:
            fc_kwargs[name] = arr
    # wire compat: clients predating the volume-group encoding send a 1-D
    # vol_needed and no node_vol_group — normalize to the VG == 1 form
    # (identical semantics)
    vn = fc_kwargs.get("vol_needed")
    if vn is not None and vn.ndim == 1:
        fc_kwargs["vol_needed"] = vn[:, None]
    if "node_vol_group" not in fc_kwargs and "vol_free" in fc_kwargs:
        fc_kwargs["node_vol_group"] = jnp.zeros(
            fc_kwargs["vol_free"].shape[0], jnp.int32)
    fc = FullChainInputs(base=ScheduleInputs(**base_kwargs), **fc_kwargs)
    args = LoadAwareArgs(score_according_prod_usage=req.score_according_prod_usage)
    if weights_vec is not None:
        from koordinator_tpu.api.resources import RESOURCE_AXES

        # rebuild exactly the key set the client configured: the set-axes
        # mask keeps explicitly-zero weights (an older client without the
        # mask falls back to nonzero-only, the previous behavior)
        if weights_set is not None:
            args.resource_weights = {
                RESOURCE_AXES[i]: float(weights_vec[i])
                for i in range(len(weights_vec)) if weights_set[i]
            }
        else:
            args.resource_weights = {
                RESOURCE_AXES[i]: float(v)
                for i, v in enumerate(weights_vec) if v
            }
    return fc, args


class SidecarServer:
    """Request handler; transport added by serve_sidecar."""

    def __init__(self) -> None:
        self._steps: Dict[Tuple, object] = {}

    def _get_sidecar_step(self, args, request, active):
        """The server's keyed step build (koordlint rule 20: every step
        compile in a driver module routes through a _get_*step
        chokepoint — the caller owns the self._steps keying)."""
        return build_full_chain_step(
            args, int(request.num_gangs), int(request.num_groups),
            active_axes=list(active) if active else None,
        )

    def ScheduleBatch(self, request: sidecar_pb2.ScheduleBatchRequest):
        import time

        fc, args = unpack_request(request)
        active = tuple(request.active_axes) or None
        key = (
            fc.base.fit_requests.shape,
            fc.numa_free.shape,
            fc.quota_runtime.shape,
            int(request.num_gangs),
            int(request.num_groups),
            request.score_according_prod_usage,
            active,
        )
        if key not in self._steps:
            self._steps[key] = self._get_sidecar_step(args, request,
                                                      active)
        t0 = time.perf_counter()
        chosen, requested, quota_used = self._steps[key](fc)
        chosen = np.asarray(chosen)
        dt = time.perf_counter() - t0
        return sidecar_pb2.ScheduleBatchResponse(
            chosen=np_to_tensor(chosen),
            requested=np_to_tensor(np.asarray(requested)),
            quota_used=np_to_tensor(np.asarray(quota_used)),
            snapshot_version=request.snapshot_version,
            kernel_seconds=dt,
        )


def serve_sidecar(address: str, server_impl: Optional[SidecarServer] = None):
    """Start the gRPC server; address like 'unix:///tmp/x.sock' or '[::]:50051'."""
    import grpc
    from concurrent import futures

    impl = server_impl or SidecarServer()
    handler = grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            "ScheduleBatch": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: impl.ScheduleBatch(req),
                request_deserializer=sidecar_pb2.ScheduleBatchRequest.FromString,
                response_serializer=sidecar_pb2.ScheduleBatchResponse.SerializeToString,
            )
        },
    )
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=2),
        options=[("grpc.max_receive_message_length", 1 << 30),
                 ("grpc.max_send_message_length", 1 << 30)],
    )
    server.add_generic_rpc_handlers((handler,))
    server.add_insecure_port(address)
    server.start()
    return server


def schedule_batch_or_fallback(client, fc, num_gangs: int, num_groups: int,
                               args: LoadAwareArgs, active_axes=None,
                               local_step=None):
    """Call the sidecar; on ANY transport failure (dead socket, timeout,
    server crash) degrade to the in-process step instead of wedging the
    scheduling cycle — the same stance the reference takes for a missing
    NodeMetric dependency (load_aware.go:144-147: degrade, don't block).

    Returns (chosen, requested, quota_used, used_fallback). ``local_step``
    lets the caller inject its cached compiled step; otherwise one is built
    on first use (and NOT cached here — cycle drivers own step caches)."""
    import grpc

    # pack OUTSIDE the try: a client-side encoding bug is a programming
    # error that must surface, not silently degrade every cycle
    req = pack_request(fc, num_gangs, num_groups, args,
                       active_axes=active_axes)

    def _local_fallback():
        # transport-failure fallback: the Scheduler passes local_step
        # from ITS keyed cache; the bare build only runs for standalone
        # client use, where no step cache exists to route through
        # koordlint: disable=compile-in-steady-state
        step = local_step or build_full_chain_step(
            args, num_gangs, num_groups,
            active_axes=list(active_axes) if active_axes else None)
        chosen, requested, quota_used = step(fc)
        return (np.asarray(chosen), np.asarray(requested),
                np.asarray(quota_used), True)

    try:
        resp = client.schedule_batch(req)
        return (tensor_to_np(resp.chosen), tensor_to_np(resp.requested),
                tensor_to_np(resp.quota_used), False)
    except grpc.RpcError as e:
        # TRANSPORT failures degrade; server-side application errors
        # (INVALID_ARGUMENT/INTERNAL: a schema or kernel bug) must surface,
        # not silently burn an RPC round-trip every cycle forever
        transport_codes = (
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
            grpc.StatusCode.CANCELLED,
        )
        if e.code() not in transport_codes:
            raise
        return _local_fallback()
    except (ConnectionError, OSError):  # channel-level transport failure
        return _local_fallback()


class SidecarClient:
    def __init__(self, address: str, timeout_seconds: float = 120.0):
        import grpc

        self._channel = grpc.insecure_channel(
            address,
            options=[("grpc.max_receive_message_length", 1 << 30),
                     ("grpc.max_send_message_length", 1 << 30)],
        )
        self._timeout = timeout_seconds
        self._call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/ScheduleBatch",
            request_serializer=sidecar_pb2.ScheduleBatchRequest.SerializeToString,
            response_deserializer=sidecar_pb2.ScheduleBatchResponse.FromString,
        )

    def schedule_batch(self, request) -> sidecar_pb2.ScheduleBatchResponse:
        return self._call(request, timeout=self._timeout)

    def close(self) -> None:
        self._channel.close()
