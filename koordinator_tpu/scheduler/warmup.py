"""Persistent compile cache + the AOT warm-up ladder (kill the host tail).

Cold start is a production outage in miniature: a restarted scheduler
pays the full XLA compile ladder before its first bind (43.2s cold cycle
at 100k x 50k, MULTICHIP_r06; 1.53s restart-to-first-bind wall even at
sim scale, CHURN_r03). Two layers close it:

  1. **Persistent XLA compilation cache** — ``KOORD_TPU_COMPILE_CACHE_DIR``
     arms jax's on-disk executable cache (``jax_compilation_cache_dir``)
     so a re-traced program whose HLO already compiled in ANY prior
     process deserializes instead of recompiling. The thresholds are
     pinned to cache-everything: the scheduler's programs are exactly the
     multi-second compiles the cache exists for, and on the CPU backend
     the default min-compile-time threshold would skip the small rungs.

  2. **Warm-up ladder** (:class:`WarmupRunner`) — every step compile the
     cycle driver (and the rebalance/colo passes) performs is recorded in
     a tiny JSON index next to the XLA entries: the builder metadata
     (padded-shape signature, mesh device-id tuple, explain mode, wave
     depth, side tags) plus the call arguments' shape/dtype spec and the
     **program fingerprint**. A restarted scheduler replays the index at
     startup — rebuilding each rung through the SAME keyed step caches
     (``Scheduler._get_step`` / ``_get_fused_step`` / ``_get_chain_step``)
     and triggering its compile against zero-filled bucket-shaped inputs
     — so the first real cycle's step lookup is an in-memory HIT and the
     XLA work was disk-served during warm-up, in the background (or
     synchronously, for the deterministic gates) instead of on the first
     pod's critical path.

Fingerprint discipline: index entries are keyed by
:func:`program_fingerprint` (a hash over the kernel/model sources;
``KOORD_TPU_PROGRAM_FINGERPRINT`` overrides it for deploy pipelines that
version artifacts themselves). A fingerprint change invalidates every
recorded rung — warm-up skips them (counted ``invalidated``) and the
next write purges them — so a code-version bump can never replay stale
shapes against new programs. A corrupted/truncated index (or XLA cache
entry: jax already recovers with a warning) degrades to an empty index
and a clean compile; warm-up must never crash the scheduler.

Observability: ``koord_scheduler_warmup_*`` metrics (rungs by outcome,
wall seconds, the completion gauge) and a ``warmup`` span tree with one
``rung`` child per replayed entry. After warm-up completes the owner
flips into *steady state*: any further step-cache miss in the hot path is
flagged (``koord_scheduler_steady_state_compiles_total`` + the owner's
``compile_miss_hook``) — the runtime half of koordlint rule 20
(``compile-in-steady-state``); the AST half pins that step builders are
only ever called through the keyed ``_get_*step`` chokepoints.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

INDEX_VERSION = 1
INDEX_NAME = "koord_warmup_index.json"

# warm-up replay call-arg reconstruction: the namedtuple classes a
# recorded aval spec may reference. Lazy import targets — the registry
# stays import-light so configure_compile_cache can run before jax does.
_NT_REGISTRY = {
    "FullChainInputs": ("koordinator_tpu.models.full_chain",
                        "FullChainInputs"),
    "ScheduleInputs": ("koordinator_tpu.models.scheduler_model",
                       "ScheduleInputs"),
    "WaveSideInputs": ("koordinator_tpu.models.fused_waves",
                       "WaveSideInputs"),
    "ProdSides": ("koordinator_tpu.models.fused_waves", "ProdSides"),
    "ClaimSides": ("koordinator_tpu.models.fused_waves", "ClaimSides"),
    "ResSides": ("koordinator_tpu.models.fused_waves", "ResSides"),
}

# sources the default fingerprint hashes: the compiled programs' shape
# is fully determined by these packages (kernel bodies, wave state
# layout, sharding rules) plus the shape metadata the index records
_FINGERPRINT_PACKAGES = ("models", "ops", "parallel", "balance", "colo")


def compile_cache_dir_from_env() -> Optional[str]:
    """KOORD_TPU_COMPILE_CACHE_DIR=<dir> arms the persistent compile
    cache + the warm-up index; unset/empty keeps both off (the
    pre-PR-15 behavior, and the deterministic default for tests)."""
    raw = os.environ.get("KOORD_TPU_COMPILE_CACHE_DIR", "").strip()
    return raw or None


def warmup_mode_from_env() -> str:
    """KOORD_TPU_WARMUP=off|sync|background ("auto" = background when a
    compile-cache dir is configured, else off). sync runs the ladder
    inside Scheduler construction — what the crash-restart gates use, so
    restart-to-first-bind includes the whole warm-up and the steady-state
    guard arms deterministically."""
    raw = os.environ.get("KOORD_TPU_WARMUP", "auto").strip().lower()
    if raw in ("auto", ""):
        return "auto"
    if raw in ("0", "off", "false", "no"):
        return "off"
    if raw in ("sync", "1", "on", "true"):
        return "sync" if raw == "sync" else "background"
    if raw == "background":
        return "background"
    logger.warning("KOORD_TPU_WARMUP=%r unknown; warm-up stays off", raw)
    return "off"


_configured_dir: Optional[str] = None


def configure_compile_cache(dir_path: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at ``dir_path`` (default:
    the env knob). Idempotent and process-global — jax's cache config is
    global, so the first caller wins and later calls with the same dir
    are no-ops (a different dir logs and keeps the first: two schedulers
    in one process must share one cache). Returns the effective dir, or
    None when the cache stays off."""
    global _configured_dir
    want = dir_path if dir_path is not None else compile_cache_dir_from_env()
    if want is None:
        return _configured_dir
    if _configured_dir is not None:
        if _configured_dir != want:
            logger.warning(
                "compile cache already configured at %s; ignoring %s "
                "(jax's cache config is process-global)",
                _configured_dir, want)
        return _configured_dir
    os.makedirs(want, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", want)
    # threshold discipline: every STEP program (>= hundreds of ms even at
    # sim scale) must cache, so the default 1s min-compile-time is
    # lowered — but NOT to zero. Empirically (PR 15), persisting the
    # sub-100ms utility jits (the donated row scatters and friends) made
    # scheduler DECISIONS diverge run-to-run on the CPU backend once
    # their deserialized executables served the hot path; a 0.1s floor
    # keeps every rung the coldstart gate measures while leaving the
    # tiny jits to compile fresh — the determinism gates (lint parity +
    # sim --check-determinism) run with the cache armed to pin this.
    for flag, value in (("jax_persistent_cache_min_compile_time_secs", 0.1),
                        ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(flag, value)
        except Exception:  # older jax without the knob: dir alone works
            logger.debug("jax flag %s unavailable", flag)
    _configured_dir = want
    return want


_fingerprint_cache: Optional[str] = None


def program_fingerprint() -> str:
    """The code-version key for persistent-cache entries.
    ``KOORD_TPU_PROGRAM_FINGERPRINT`` pins it (deploy pipelines, and the
    invalidation tests' simulated version bump); the default hashes the
    kernel/model/parallel sources, so editing a wave body invalidates
    every recorded rung without any manual bump."""
    env = os.environ.get("KOORD_TPU_PROGRAM_FINGERPRINT", "").strip()
    if env:
        return env
    global _fingerprint_cache
    if _fingerprint_cache is not None:
        return _fingerprint_cache
    h = hashlib.sha256()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for pkg in _FINGERPRINT_PACKAGES:
        pkg_dir = os.path.join(root, pkg)
        if not os.path.isdir(pkg_dir):
            continue
        for name in sorted(os.listdir(pkg_dir)):
            if not name.endswith(".py"):
                continue
            path = os.path.join(pkg_dir, name)
            h.update(name.encode())
            try:
                with open(path, "rb") as f:
                    h.update(f.read())
            except OSError:
                continue
    _fingerprint_cache = h.hexdigest()[:32]
    return _fingerprint_cache


# ---------------------------------------------------------------------------
# call-argument shape specs (recorded at compile time, replayed as zeros)
# ---------------------------------------------------------------------------

def aval_spec(obj):
    """JSON-able (shape, dtype) tree of one call argument. Handles the
    pytrees the dispatch sites actually pass: namedtuples (registered in
    ``_NT_REGISTRY``), plain tuples/lists (the wave carry), ``None``
    slots (feature-absent leafless subtrees), arrays (host or device)
    and numpy scalars. Small Python ints/floats are recorded BY VALUE —
    ``np.int32(n_real)``-style operands must replay with a concrete
    value, not a zero aval, in case the builder treats them statically."""
    if obj is None:
        return {"t": "none"}
    if isinstance(obj, bool):
        return {"t": "v", "v": bool(obj)}
    if isinstance(obj, (int, float)):
        return {"t": "v", "v": obj}
    if isinstance(obj, np.generic):
        # numpy scalars (the np.int32(n_real) operand): by value, typed
        return {"t": "np", "v": obj.item(), "d": str(obj.dtype)}
    fields = getattr(obj, "_fields", None)
    if fields is not None:
        name = type(obj).__name__
        if name not in _NT_REGISTRY:
            raise TypeError(f"unregistered namedtuple {name!r} in aval spec")
        return {"t": "nt", "c": name,
                "f": [aval_spec(getattr(obj, f)) for f in fields]}
    if isinstance(obj, (tuple, list)):
        return {"t": "tuple", "i": [aval_spec(v) for v in obj]}
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None and dtype is not None:
        return {"t": "a", "s": [int(d) for d in shape], "d": str(dtype)}
    raise TypeError(f"unsupported aval-spec value {type(obj).__name__}")


def zeros_from_spec(spec):
    """Rebuild one call argument from its spec as zero-filled host
    arrays (padding-row semantics: every kernel masks invalid rows, so a
    zero world traces the exact program and converges immediately)."""
    t = spec["t"]
    if t == "none":
        return None
    if t == "v":
        return spec["v"]
    if t == "np":
        return np.dtype(spec["d"]).type(spec["v"])
    if t == "a":
        return np.zeros(tuple(spec["s"]), np.dtype(spec["d"]))
    if t == "tuple":
        return tuple(zeros_from_spec(s) for s in spec["i"])
    if t == "nt":
        import importlib

        mod_name, cls_name = _NT_REGISTRY[spec["c"]]
        cls = getattr(importlib.import_module(mod_name), cls_name)
        return cls(*(zeros_from_spec(s) for s in spec["f"]))
    raise ValueError(f"bad aval spec {t!r}")


# ---------------------------------------------------------------------------
# the persistent rung index
# ---------------------------------------------------------------------------


# process-wide: CompileCacheIndex instances are constructed per record
# call, so a per-instance lock would never exclude anyone — recorders
# in one process serialize here (cross-process writers are last-writer-
# wins on the atomic rename, which can drop a concurrent rung but can
# never corrupt the file: every writer renames its OWN unique tmp)
_index_lock = threading.Lock()  # koordlint: guards(rung-index-file)


class CompileCacheIndex:
    """The warm-up rung index living next to the XLA cache entries.

    One JSON file, atomically rewritten (unique tmp + rename) on every
    ``record``; entries dedupe on (kind, meta) and carry the recording
    fingerprint. A corrupted/truncated/absent file loads as EMPTY — the
    cache layer must degrade to a clean compile, never crash the
    ladder (pinned by tests)."""

    def __init__(self, dir_path: str) -> None:
        # immutable after construction; only the index FILE needs the lock
        self.path = os.path.join(dir_path, INDEX_NAME)  # koordlint: guarded-by(none)
        self._lock = _index_lock

    def load(self) -> Dict[str, dict]:
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {}
        if not isinstance(data, dict) or data.get("v") != INDEX_VERSION:
            return {}
        entries = data.get("entries")
        return entries if isinstance(entries, dict) else {}

    @staticmethod
    def entry_key(kind: str, meta: dict) -> str:
        return hashlib.sha256(
            json.dumps([kind, meta], sort_keys=True).encode()
        ).hexdigest()[:24]

    def record(self, kind: str, meta: dict, args_spec: List[dict]) -> None:
        """Merge one rung; stale-fingerprint entries are purged on the
        same write (the invalidation discipline: a version bump leaves
        no replayable residue behind)."""
        fp = program_fingerprint()
        with self._lock:
            entries = self.load()
            entries = {k: e for k, e in entries.items()
                       if isinstance(e, dict) and e.get("fp") == fp}
            entries[self.entry_key(kind, meta)] = {
                "kind": kind, "meta": meta, "args": args_spec, "fp": fp,
            }
            import tempfile

            fd, tmp = tempfile.mkstemp(
                prefix=INDEX_NAME + ".", suffix=".tmp",
                dir=os.path.dirname(self.path))
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump({"v": INDEX_VERSION, "entries": entries},
                              f, sort_keys=True)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise


def record_step_compile(kind: str, meta: dict, args: Tuple) -> bool:
    """Record one freshly-compiled step rung into the configured cache
    dir's index (no-op when the persistent cache is off). Never raises:
    recording is pure observability for the NEXT process — a bad entry
    must not cost this one its dispatch."""
    dir_path = _configured_dir
    if dir_path is None:
        return False
    try:
        CompileCacheIndex(dir_path).record(
            kind, meta, [aval_spec(a) for a in args])
        return True
    except Exception:
        logger.exception("compile-cache index record failed (kind=%s)",
                         kind)
        return False


# ---------------------------------------------------------------------------
# the warm-up ladder
# ---------------------------------------------------------------------------

# background ladders serialize process-wide: two schedulers warming at
# once would just contend for the same XLA compile threads, and the
# atexit join below must have a bounded set to wait on
_ladder_lock = threading.Lock()
# koordlint: guarded-by(_ladder_lock)
_live_threads: List[threading.Thread] = []
_atexit_registered = False


def _join_live_ladders() -> None:
    """Interpreter-exit guard: a daemon ladder thread killed MID-XLA-
    COMPILE aborts the process in native teardown ("terminate called
    without an active exception") — give outstanding ladders a bounded
    window to finish before the runtime unwinds. The ladder lock is
    held for a whole run(), so taking it here would turn the bounded
    join into an unbounded wait — the bare snapshot is a list() copy
    (atomic under the GIL) of threads only ever appended before start;
    the pragma below records that deliberate exception."""
    for t in list(_live_threads):  # koordlint: disable=unguarded-shared-field
        t.join(timeout=30.0)


class WarmupRunner:
    """Replay the recorded rung index against a fresh Scheduler.

    Scheduler rungs (serial/fused/chain) rebuild through the SAME keyed
    ``_get_*step`` chokepoints — populating the in-memory step cache
    under the exact production keys — then trigger the XLA compile (disk
    hit on a warm dir) with zero-filled inputs of the recorded shapes.
    Rebalance/colo rungs replay through their module builders: the colo
    reconciler and rebalancer own separate step caches, so the value
    there is the warmed XLA disk entry, not an in-memory hit.

    Mesh discipline: a rung recorded under a mesh device-id tuple only
    replays when the scheduler's CURRENT placement matches (koordguard:
    two same-size submeshes never share a step); mismatches count as
    ``skipped``. Every rung runs inside try/except — a corrupted entry
    or a failed zero-call counts as ``failed`` and warm-up continues."""

    def __init__(self, scheduler, background: bool = False) -> None:
        from koordinator_tpu.obs import Tracer

        self.scheduler = scheduler
        self.background = background
        # own tracer: the background ladder must not interleave spans
        # into the cycle thread's ring mid-cycle
        self.tracer = Tracer()
        self.stats = {"rungs": 0, "warmed": 0, "built": 0, "skipped": 0,
                      "failed": 0, "invalidated": 0, "seconds": 0.0,
                      "complete": False}
        self._thread: Optional[threading.Thread] = None

    # -- rung replay ----------------------------------------------------
    def _replay_scheduler_rung(self, entry: dict):
        sched = self.scheduler
        meta = entry["meta"]
        if tuple(meta.get("mesh_tag", ())) != sched._mesh_tag():
            return "skipped"
        # config the program structure bakes in must match THIS
        # scheduler, or the recorded avals describe a different carry
        # pytree (a co-resident scheduler with another prod/transformer
        # config recorded the rung): skip, never trip
        if "prod" in meta and meta["prod"] != bool(
                sched.args.score_according_prod_usage):
            return "skipped"
        if "score_tag" in meta and [
                [name, int(epoch)]
                for name, epoch in sched._score_pass_tag()
        ] != meta["score_tag"]:
            return "skipped"
        kind = entry["kind"]
        mesh_rung = bool(meta.get("mesh_tag"))
        signature = tuple(meta["signature"])
        active = list(meta["active"])
        explain = meta.get("explain")
        if kind == "serial":
            step = sched._get_step(signature, meta["ng"], meta["ngroups"],
                                   active, explain=explain)
        elif kind == "fused":
            step = sched._get_fused_step(
                signature, meta["ng"], meta["ngroups"], active,
                meta["waves"], explain=explain,
                sides_tag=tuple(meta["sides_tag"]))
        elif kind == "chain":
            step = sched._get_chain_step(
                signature, meta["ng"], meta["ngroups"], active,
                explain=explain, sides_tag=tuple(meta["sides_tag"]))
        else:
            return "skipped"
        if mesh_rung:
            # mesh rungs are BUILD-ONLY: a zero-call with host operands
            # commits different input shardings than the production
            # upload path, which hashes to a DIFFERENT program — the
            # zero-call would compile fresh instead of hitting the disk
            # entry the real dispatch wrote. Building through the keyed
            # chokepoint still pre-populates the in-memory step cache;
            # the first real dispatch re-traces the recorded HLO and
            # ITS XLA compile is the disk hit.
            return "built"
        self._zero_call(step, entry)
        return "warmed"

    def _replay_standalone_rung(self, entry: dict):
        """Rebalance/colo rungs: module builders, single-device only —
        a mesh build needs the live Mesh object, which belongs to the
        process that recorded it."""
        meta = entry["meta"]
        if tuple(meta.get("mesh_tag", ())):
            return "skipped"
        if entry["kind"] == "rebalance":
            from koordinator_tpu.balance.step import build_rebalance_step

            step = build_rebalance_step(meta["cap"])
        elif entry["kind"] == "colo":
            from koordinator_tpu.colo.step import build_colo_step

            step = build_colo_step(meta["policies"][0], meta["policies"][1])
        else:
            return "skipped"
        self._zero_call(step, entry)
        return "warmed"

    def _zero_call(self, step, entry: dict) -> None:
        import jax

        args = tuple(zeros_from_spec(s) for s in entry["args"])
        t0 = time.perf_counter()
        out = step(*args)
        # startup-time ladder, not a dispatch window: a hung device
        # surfaces at process start instead of wedging a cycle, and the
        # background mode keeps it off the bind path entirely
        # koordlint: disable=naked-device-sync-without-deadline
        jax.block_until_ready(
            [leaf for leaf in jax.tree_util.tree_leaves(out)])
        # the ladder's XLA work is compile wall: the restart report's
        # compile/pack split must attribute warm-up to compile
        # (lock-guarded — the background ladder adds from its thread)
        self.scheduler._add_compile_wall(time.perf_counter() - t0)

    # -- the ladder -----------------------------------------------------
    def run(self) -> dict:
        from koordinator_tpu.scheduler import metrics as scheduler_metrics

        sched = self.scheduler
        t0 = time.perf_counter()
        fp = program_fingerprint()
        entries: Dict[str, dict] = {}
        if _configured_dir is not None:
            entries = CompileCacheIndex(_configured_dir).load()
        with self.tracer.span("warmup", rungs=str(len(entries))):
            for key in sorted(entries):
                entry = entries[key]
                self.stats["rungs"] += 1
                if not isinstance(entry, dict) or entry.get("fp") != fp:
                    # fingerprint mismatch (or a mangled entry): the
                    # recorded shapes belong to another code version —
                    # never replay them; the next record purges them
                    self.stats["invalidated"] += 1
                    scheduler_metrics.WARMUP_RUNGS.inc(outcome="invalidated")
                    continue
                kind = entry.get("kind", "")
                with self.tracer.span("rung", kind=kind, key=key):
                    try:
                        if kind in ("serial", "fused", "chain"):
                            outcome = self._replay_scheduler_rung(entry)
                        else:
                            outcome = self._replay_standalone_rung(entry)
                    except Exception:
                        # a wrecked rung (stale spec, corrupted XLA
                        # entry jax could not recover) falls back to the
                        # on-demand compile — warm-up NEVER crashes
                        logger.exception("warm-up rung failed (%s)", kind)
                        outcome = "failed"
                self.stats[outcome] += 1
                scheduler_metrics.WARMUP_RUNGS.inc(outcome=outcome)
        self.stats["seconds"] = time.perf_counter() - t0
        self.stats["complete"] = True
        scheduler_metrics.WARMUP_SECONDS.set(self.stats["seconds"])
        sched.note_warmup_complete(self.stats)
        return self.stats

    def start(self) -> None:
        if not self.background:
            with _ladder_lock:
                self.run()
            return
        global _atexit_registered
        if not _atexit_registered:
            import atexit

            atexit.register(_join_live_ladders)
            _atexit_registered = True
        self._thread = threading.Thread(
            target=self._run_guarded, name="koord-warmup", daemon=True)
        with _ladder_lock:
            _live_threads.append(self._thread)
        self._thread.start()

    def _run_guarded(self) -> None:
        try:
            with _ladder_lock:
                self.run()
        except Exception:  # the ladder is best-effort by contract
            logger.exception("warm-up ladder failed")
        finally:
            try:
                with _ladder_lock:
                    _live_threads.remove(self._thread)
            except ValueError:  # pragma: no cover - defensive
                pass

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
