"""Reservation plugin host side.

Reference `plugins/reservation/`: Reservation CRs are scheduled like pods
(eventhandlers sync them into the cache as fake reservation-pods); Available
reservations pre-claim node resources; pods matching an owner consume reserved
resources (nominator.go picks which one); expired reservations are garbage
collected (controller/controller.go).

TPU rebuild v1: the cycle driver schedules Reservation CRs through the same
batched kernel (their template requests ride the pod batch); matching pods are
nominated to their reservation's node host-side BEFORE the kernel pass (the
reference nominator also prefers reservations over open capacity), consuming
from the reservation's free resources. A matched pod bypasses Filter thresholds
the way the reference's reservation-restore transformer returns reserved
resources to the node snapshot."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.api.objects import (
    ANNOTATION_RESERVATION_ALLOCATED,
    Pod,
    Reservation,
)
from koordinator_tpu.client.store import (
    KIND_RESERVATION,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.frameworkext import (
    CycleContext,
    FilterTransformer,
    Plugin,
)


class ReservationRestoreTransformer(FilterTransformer):
    """Reservation restore through the declared before-Filter extension point
    (reference plugins/reservation/transformer.go BeforeFilter: expand the
    nodeInfo view with reserved resources so owner pods fit).

    Batched form: the base snapshot counts every assigned pod; this transform
    (a) adds each Available reservation's held capacity to its node's
    assigned_requests, and (b) subtracts pods allocated FROM a counted
    reservation, since their usage lives inside the reservation's allocatable
    (double-count restore). Expired/failed reservations are skipped, so their
    consumers fall back to direct accounting and the node never overcommits."""

    name = "ReservationRestore"

    def __init__(self, store: ObjectStore):
        self.store = store

    def before_filter(self, state, ctx: CycleContext) -> None:
        out = state.assigned_requests

        def add(node: str, vec: np.ndarray) -> None:
            if node in out:
                out[node] = out[node] + vec
            else:
                out[node] = vec.astype(np.float32)

        counted = set()
        for res in self.store.list(KIND_RESERVATION):
            if res.is_available and not res.is_expired(ctx.now):
                counted.add(res.meta.name)
                add(res.node_name, res.allocatable.to_vector())
        # pod-backed reservations (operating-mode pods) already occupy their
        # node AS assigned pods — no capacity to add — but their consumers'
        # usage lives inside that footprint, so they join the subtract pass
        for pod in state.pods_by_key.values():
            if (pod.is_reservation_operating_mode and pod.is_assigned
                    and not pod.is_terminated):
                counted.add(f"pod:{pod.meta.key}")
        if not counted:
            return
        from koordinator_tpu.ops.fit import with_pod_count

        for pod in state.pods_by_key.values():
            if not pod.is_assigned or pod.is_terminated:
                continue
            res_name = pod.meta.annotations.get(ANNOTATION_RESERVATION_ALLOCATED)
            if res_name and res_name in counted:
                add(pod.spec.node_name,
                    -with_pod_count(pod.spec.requests.to_vector()[None])[0])


class ReservationPlugin(Plugin):
    name = "Reservation"

    def __init__(self) -> None:
        self.by_name: Dict[str, Reservation] = {}
        self.by_node: Dict[str, List[str]] = {}
        self._store: Optional[ObjectStore] = None
        # (store rv, {reservation name -> [(owner key, requests)]}) — one
        # O(P) pass serves every cold rebuild of a subscriber replay
        self._consumer_index = (-1, {})

    def register(self, store: ObjectStore) -> None:
        self._store = store
        store.subscribe(KIND_RESERVATION, self._on_reservation)
        from koordinator_tpu.client.store import KIND_POD

        store.subscribe(KIND_POD, self._on_pod)

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        """Operating-mode pods (operating_pod.go ReservationPodOperatingMode)
        mirror into the reservation cache once assigned: the pod schedules
        like any pod, then its resources are reserved for its declared
        owners. The pod's lifecycle governs the entry — termination or
        deletion removes it."""
        if not pod.is_reservation_operating_mode:
            return
        key = f"pod:{pod.meta.key}"
        if (ev is EventType.DELETED or pod.is_terminated
                or not pod.is_assigned):
            prev = self.by_name.pop(key, None)
            if prev and prev.node_name:
                nodes = self.by_node.get(prev.node_name, [])
                if key in nodes:
                    nodes.remove(key)
            return
        from dataclasses import replace

        from koordinator_tpu.api.resources import ResourceList

        prev = self.by_name.get(key)
        if prev is not None:
            allocated, owners_now = prev.allocated, prev.current_owners
        else:
            # cold rebuild (subscriber replay / scheduler restart): the
            # consumed amount lives on the CONSUMER pods' annotations —
            # without this, a restarted scheduler would see the full
            # footprint free and over-consume the reservation
            allocated, owners_now = ResourceList(), []
            for owner_key, req in self._consumers_of(key):
                allocated = allocated.add(req)
                owners_now.append(owner_key)
        res = Reservation(
            meta=(prev.meta if prev
                  else replace(pod.meta, name=key, namespace="")),
            owners=pod.reservation_owners(),
            allocate_once=False,
            phase="Available",
            node_name=pod.spec.node_name,
            allocatable=pod.spec.requests.copy(),
            allocated=allocated,
            current_owners=owners_now,
            from_pod_key=pod.meta.key,
        )
        self.by_name[key] = res
        nodes = self.by_node.setdefault(pod.spec.node_name, [])
        if key not in nodes:
            nodes.append(key)

    def _consumers_of(self, res_name: str):
        """Consumers grouped by reservation annotation, indexed once per
        store state (an O(P) scan per operating-mode pod would make
        subscriber replay O(N*P))."""
        if self._store is None:
            return []
        rv = self._store.resource_version
        if self._consumer_index[0] != rv:
            from koordinator_tpu.client.store import KIND_POD

            index: Dict[str, list] = {}
            for other in self._store.list(KIND_POD):
                target = other.meta.annotations.get(
                    ANNOTATION_RESERVATION_ALLOCATED)
                if (target and other.is_assigned
                        and not other.is_terminated):
                    index.setdefault(target, []).append(
                        (other.meta.key, other.spec.requests))
            self._consumer_index = (rv, index)
        return self._consumer_index[1].get(res_name, [])

    def _persist_pod_backed_owners(self, res: Reservation) -> None:
        """Write the owner list onto the BACKING pod
        (operating_pod.go AnnotationReservationCurrentOwner) — the single
        persistence site consume() and unreserve() share."""
        if not res.from_pod_key or self._store is None:
            return
        import json

        from koordinator_tpu.api.objects import (
            ANNOTATION_RESERVATION_CURRENT_OWNER,
        )
        from koordinator_tpu.client.store import KIND_POD

        backing = self._store.get(KIND_POD, res.from_pod_key)
        if backing is not None:
            backing.meta.annotations[
                ANNOTATION_RESERVATION_CURRENT_OWNER
            ] = json.dumps(res.current_owners)
            self._store.update(KIND_POD, backing)

    def _on_reservation(self, ev: EventType, res: Reservation, old) -> None:
        key = res.meta.name
        if ev is EventType.DELETED:
            prev = self.by_name.pop(key, None)
            if prev and prev.node_name:
                nodes = self.by_node.get(prev.node_name, [])
                if key in nodes:
                    nodes.remove(key)
            return
        prev = self.by_name.get(key)
        if prev and prev.node_name and prev.node_name != res.node_name:
            nodes = self.by_node.get(prev.node_name, [])
            if key in nodes:
                nodes.remove(key)
        self.by_name[key] = res
        if res.node_name:
            nodes = self.by_node.setdefault(res.node_name, [])
            if key not in nodes:
                nodes.append(key)

    # -- nomination (nominator.go analog) -----------------------------------
    def nominate(self, pod: Pod, now: float) -> Optional[Reservation]:
        """Pick the matching Available reservation with enough free resources;
        earliest-created wins (deterministic)."""
        candidates = []
        req = pod.spec.requests
        for res in self.by_name.values():
            if not res.is_available or res.is_expired(now):
                continue
            if res.allocate_once and res.current_owners:
                continue
            if not res.matches(pod):
                continue
            free = res.allocatable.sub(res.allocated)
            if any(req[r] > free[r] for r in req):
                continue
            candidates.append(res)
        if not candidates:
            return None
        candidates.sort(key=lambda r: (r.meta.creation_timestamp, r.meta.name))
        return candidates[0]

    def consume(self, pod: Pod, res: Reservation, ctx: CycleContext) -> None:
        res.allocated = res.allocated.add(pod.spec.requests)
        res.current_owners.append(pod.meta.key)
        ctx.data.setdefault("reservation_of", {})[pod.meta.key] = res.meta.name
        if self._store is None:
            return
        if res.from_pod_key:
            self._persist_pod_backed_owners(res)
        else:
            self._store.update(KIND_RESERVATION, res)

    def unreserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> None:
        res_name = ctx.data.get("reservation_of", {}).pop(pod.meta.key, None)
        if res_name and res_name in self.by_name:
            res = self.by_name[res_name]
            res.allocated = res.allocated.sub(pod.spec.requests)
            if pod.meta.key in res.current_owners:
                res.current_owners.remove(pod.meta.key)
            self._persist_pod_backed_owners(res)

    def pre_bind(self, pod: Pod, node_name: str, ctx: CycleContext,
                 annotations: Dict[str, str]) -> None:
        res_name = ctx.data.get("reservation_of", {}).get(pod.meta.key)
        if res_name:
            annotations[ANNOTATION_RESERVATION_ALLOCATED] = res_name

    # -- GC controller (controller/controller.go analog) --------------------
    def expire_reservations(self, now: Optional[float] = None) -> List[str]:
        """Mark expired reservations Failed; returns expired names."""
        now = time.time() if now is None else now
        expired = []
        for res in self.by_name.values():
            if res.from_pod_key:
                continue  # the backing pod's lifecycle governs, never a TTL
            if res.phase in ("Pending", "Available") and res.is_expired(now):
                res.phase = "Failed"
                expired.append(res.meta.name)
                if self._store is not None:
                    self._store.update(KIND_RESERVATION, res)
        return expired


class ReservationController:
    """Expiry + GC controller (plugins/reservation/controller/controller.go):
    each reconcile pass expires overdue Pending/Available reservations (via
    the plugin, which owns the cache), marks fully-allocated allocate-once
    reservations Succeeded, and deletes terminal (Failed/Succeeded)
    reservations once they have been terminal for gc_duration_seconds."""

    def __init__(self, plugin: ReservationPlugin, store: ObjectStore,
                 gc_duration_seconds: float = 24 * 3600.0):
        self.plugin = plugin
        self.store = store
        self.gc_duration = gc_duration_seconds
        self._terminal_since: Dict[str, float] = {}

    def reconcile(self, now: Optional[float] = None) -> Dict[str, List[str]]:
        now = time.time() if now is None else now
        expired = self.plugin.expire_reservations(now)
        succeeded: List[str] = []
        deleted: List[str] = []
        for res in list(self.plugin.by_name.values()):
            # allocate-once reservations that have been consumed are done
            if (res.phase == "Available" and res.allocate_once
                    and res.current_owners):
                res.phase = "Succeeded"
                succeeded.append(res.meta.name)
                self.store.update(KIND_RESERVATION, res)
            if res.phase in ("Failed", "Succeeded"):
                since = self._terminal_since.setdefault(res.meta.name, now)
                if now - since >= self.gc_duration:
                    self.store.delete(KIND_RESERVATION, res.meta.key)
                    self._terminal_since.pop(res.meta.name, None)
                    deleted.append(res.meta.name)
            else:
                self._terminal_since.pop(res.meta.name, None)
        return {"expired": expired, "succeeded": succeeded, "deleted": deleted}
