"""LoadAware plugin host side: the podAssignCache.

Reference `plugins/loadaware/pod_assign_cache.go`: tracks pods Reserved on each
node with their assign timestamp, so Score can estimate usage of pods not yet
visible in NodeMetric. Maintained from store events (Reserve adds, terminal
phase/delete removes)."""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

from koordinator_tpu.api.objects import Pod
from koordinator_tpu.client.store import KIND_POD, EventType, ObjectStore
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin


class LoadAwarePlugin(Plugin):
    name = "LoadAwareScheduling"

    def __init__(self) -> None:
        self.assign_cache: Dict[str, Dict[str, Tuple[Pod, float]]] = {}
        # per-node change counter: bumped on EVERY mutation of the node's
        # assign-cache entry set, so the incremental snapshot builder
        # (scheduler/snapshot_cache.py) can key its per-node LoadAware rows
        self.node_epoch: Dict[str, int] = {}
        # names bumped since the snapshot cache last drained: lets the
        # cache find changed nodes without scanning every epoch per build
        self.epoch_dirty: set = set()

    def _bump(self, node_name: str) -> None:
        self.node_epoch[node_name] = self.node_epoch.get(node_name, 0) + 1
        self.epoch_dirty.add(node_name)

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_POD, self._on_pod)

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        if ev in (EventType.ADDED, EventType.MODIFIED):
            if pod.is_assigned and not pod.is_terminated:
                node = self.assign_cache.setdefault(pod.spec.node_name, {})
                if pod.meta.key not in node:
                    node[pod.meta.key] = (pod, time.time())
                else:
                    node[pod.meta.key] = (pod, node[pod.meta.key][1])
                self._bump(pod.spec.node_name)
            elif pod.is_terminated:
                self._drop(pod)
        elif ev is EventType.DELETED:
            self._drop(pod)

    def _drop(self, pod: Pod) -> None:
        node = self.assign_cache.get(pod.spec.node_name)
        if node:
            node.pop(pod.meta.key, None)
            self._bump(pod.spec.node_name)

    def reserve(self, pod: Pod, node_name: str, ctx: CycleContext):
        self.assign_cache.setdefault(node_name, {})[pod.meta.key] = (pod, ctx.now)
        self._bump(node_name)
        return None

    def unreserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> None:
        node = self.assign_cache.get(node_name)
        if node:
            node.pop(pod.meta.key, None)
            self._bump(node_name)

    def assigned_view(self) -> Dict[str, List[Tuple[Pod, float]]]:
        return {
            node: list(items.values()) for node, items in self.assign_cache.items()
        }
