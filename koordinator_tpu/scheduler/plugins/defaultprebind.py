"""DefaultPreBind: apply accumulated object patches once.

Reference `plugins/defaultprebind/plugin.go` implementing PreBindExtensions
(frameworkext/interface.go:194-197): every plugin contributes annotations during
PreBind; this plugin merges them into ONE store update per pod (one apiserver
patch in the reference) together with the binding itself."""

from __future__ import annotations


from typing import Dict

from koordinator_tpu.api.objects import Pod
from koordinator_tpu.client.store import KIND_POD, ObjectStore
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin


class DefaultPreBindPlugin(Plugin):
    name = "DefaultPreBind"

    def __init__(self) -> None:
        self._store: ObjectStore = None  # type: ignore[assignment]

    def register(self, store: ObjectStore) -> None:
        self._store = store

    def apply_patch(self, pod: Pod, node_name: str,
                    annotations: Dict[str, str], now: float = 0.0,
                    txn=None) -> None:
        # patch a COPY of the STORED object: watch subscribers diff old vs new,
        # and `pod` may be a cycle-local transformer view (BeforePreFilter
        # semantics) whose rewrites must not persist — the reference patches
        # nodeName/annotations via the apiserver against the server's copy
        stored = self._store.get(KIND_POD, pod.meta.key)
        patched = (stored if stored is not None else pod).patch_copy()
        patched.meta.annotations.update(annotations)
        patched.spec.node_name = node_name
        # PodScheduled=True rides the same single patch (upstream sets the
        # condition through the bind API call)
        patched.set_condition("PodScheduled", "True", "", "", now)
        if txn is not None:
            # overlapped wave replay: the cycle driver lands the whole
            # wave's patches as ONE store.update_many transaction. The
            # live-object mutation is deferred with it — `pod` may BE the
            # stored object, and mutating it before the batched event
            # fires would make the MODIFIED old-side already assigned,
            # hiding the bind transition from the gang/quota event
            # handlers the plugin counters hang off.
            txn.append((patched, pod, annotations, node_name))
            return
        self._store.update(KIND_POD, patched)
        # keep the caller's object coherent for later hooks in this cycle
        pod.meta.annotations.update(annotations)
        pod.spec.node_name = node_name
