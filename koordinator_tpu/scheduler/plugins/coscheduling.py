"""Coscheduling plugin host side: the gang cache.

Reference `plugins/coscheduling/core/gang_cache.go` + `gang.go`: gangs come from
PodGroup CRs or pod annotations; track member counts, assumed (bound) members,
schedule-cycle state, and gang-groups (annotation listing gangs that must be
co-admitted). The Permit barrier itself is the device-side post-pass
(ops/gang.py); this cache feeds it."""

from __future__ import annotations

import json
from typing import Dict, List

from koordinator_tpu.api.objects import Pod, PodGroup
from koordinator_tpu.client.store import (
    KIND_POD,
    KIND_POD_GROUP,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin

ANNOTATION_GANG_GROUPS = "gang.scheduling.koordinator.sh/groups"


class CoschedulingPlugin(Plugin):
    name = "Coscheduling"

    def __init__(self) -> None:
        self.pod_groups: Dict[str, PodGroup] = {}
        self.assumed: Dict[str, int] = {}     # gang -> bound member count
        self.members: Dict[str, int] = {}     # gang -> known member count

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_POD_GROUP, self._on_pod_group)
        store.subscribe(KIND_POD, self._on_pod)

    def services(self):
        """frameworkext services endpoints (/apis/v1/plugins/Coscheduling/...)."""
        return {
            "gangs": lambda: {
                name: {
                    "min_member": pg.min_member,
                    "members": self.members.get(name, 0),
                    "assumed": self.assumed.get(name, 0),
                }
                for name, pg in sorted(self.pod_groups.items())
            }
        }

    def _on_pod_group(self, ev: EventType, pg: PodGroup, old) -> None:
        if ev is EventType.DELETED:
            self.pod_groups.pop(pg.meta.name, None)
        else:
            self.pod_groups[pg.meta.name] = pg

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        gang = pod.gang_name
        if not gang:
            return
        if ev is EventType.ADDED:
            self.members[gang] = self.members.get(gang, 0) + 1
            if pod.is_assigned and not pod.is_terminated:
                self.assumed[gang] = self.assumed.get(gang, 0) + 1
        elif ev is EventType.MODIFIED:
            was = old is not None and old.is_assigned and not old.is_terminated
            now = pod.is_assigned and not pod.is_terminated
            if now and not was:
                self.assumed[gang] = self.assumed.get(gang, 0) + 1
            elif was and not now:
                self.assumed[gang] = max(0, self.assumed.get(gang, 0) - 1)
        elif ev is EventType.DELETED:
            self.members[gang] = max(0, self.members.get(gang, 0) - 1)
            if pod.is_assigned and not pod.is_terminated:
                self.assumed[gang] = max(0, self.assumed.get(gang, 0) - 1)

    def gang_groups(self, gang_name: str) -> List[str]:
        """Gangs co-admitted with this one (annotation on the PodGroup)."""
        pg = self.pod_groups.get(gang_name)
        if pg is None:
            return [gang_name]
        raw = pg.meta.annotations.get(ANNOTATION_GANG_GROUPS)
        if not raw:
            return [gang_name]
        try:
            groups = json.loads(raw)
            return list(groups) if groups else [gang_name]
        except (ValueError, TypeError):
            return [gang_name]

    def update_pod_group_status(self, store: ObjectStore) -> None:
        """PodGroup status controller analog (controller/podgroup.go:55-313)."""
        for pg in self.pod_groups.values():
            scheduled = self.assumed.get(pg.meta.name, 0)
            phase = (
                "Scheduled"
                if scheduled >= pg.min_member
                else ("Scheduling" if scheduled else "Pending")
            )
            if pg.scheduled != scheduled or pg.phase != phase:
                pg.scheduled, pg.phase = scheduled, phase
                store.update(KIND_POD_GROUP, pg)
