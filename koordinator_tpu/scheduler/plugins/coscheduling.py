"""Coscheduling plugin host side: the gang cache.

Reference `plugins/coscheduling/core/gang_cache.go` + `gang.go`: gangs come from
PodGroup CRs or pod annotations; track member counts, assumed (bound) members,
schedule-cycle state, and gang-groups (annotation listing gangs that must be
co-admitted). The Permit barrier itself is the device-side post-pass
(ops/gang.py); this cache feeds it."""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import Pod, PodGroup
from koordinator_tpu.client.store import (
    KIND_POD,
    KIND_POD_GROUP,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin

ANNOTATION_GANG_GROUPS = "gang.scheduling.koordinator.sh/groups"


class CoschedulingPlugin(Plugin):
    name = "Coscheduling"

    def __init__(self, default_timeout_seconds: float = 600.0) -> None:
        self.pod_groups: Dict[str, PodGroup] = {}
        self.assumed: Dict[str, int] = {}     # gang -> bound member count
        self.members: Dict[str, int] = {}     # gang -> known member count
        # CoschedulingArgs.defaultTimeout: used when the PodGroup doesn't set
        # its own scheduleTimeoutSeconds
        self.default_timeout_seconds = default_timeout_seconds
        # gangs that reached min-member at least once: a running gang that
        # loses a member must NOT be timeout-failed (it is rescheduling, not
        # stuck); rebuilt from observed Scheduled phase after restart
        self._ever_scheduled: set = set()

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_POD_GROUP, self._on_pod_group)
        store.subscribe(KIND_POD, self._on_pod)

    def services(self):
        """frameworkext services endpoints (/apis/v1/plugins/Coscheduling/...)."""
        return {
            "gangs": lambda: {
                name: {
                    "min_member": pg.min_member,
                    "members": self.members.get(name, 0),
                    "assumed": self.assumed.get(name, 0),
                }
                for name, pg in sorted(self.pod_groups.items())
            }
        }

    def _on_pod_group(self, ev: EventType, pg: PodGroup, old) -> None:
        # keyed by the namespaced gang identity (core.go GetGangFullName):
        # same-named gangs in different namespaces are distinct gangs
        if ev is EventType.DELETED:
            self.pod_groups.pop(pg.meta.key, None)
            # a recreated gang with the same name is a fresh gang: it must be
            # timeout-eligible again (also bounds the latch set's growth)
            self._ever_scheduled.discard(pg.meta.key)
        else:
            self.pod_groups[pg.meta.key] = pg

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        gang = pod.gang_key
        if not gang:
            return
        if ev is EventType.ADDED:
            self.members[gang] = self.members.get(gang, 0) + 1
            if pod.is_assigned and not pod.is_terminated:
                self.assumed[gang] = self.assumed.get(gang, 0) + 1
        elif ev is EventType.MODIFIED:
            was = old is not None and old.is_assigned and not old.is_terminated
            now = pod.is_assigned and not pod.is_terminated
            if now and not was:
                self.assumed[gang] = self.assumed.get(gang, 0) + 1
            elif was and not now:
                self.assumed[gang] = max(0, self.assumed.get(gang, 0) - 1)
        elif ev is EventType.DELETED:
            self.members[gang] = max(0, self.members.get(gang, 0) - 1)
            if pod.is_assigned and not pod.is_terminated:
                self.assumed[gang] = max(0, self.assumed.get(gang, 0) - 1)

    def gang_groups(self, gang_name: str) -> List[str]:
        """Gangs co-admitted with this one (annotation on the PodGroup)."""
        pg = self.pod_groups.get(gang_name)
        if pg is None:
            return [gang_name]
        raw = pg.meta.annotations.get(ANNOTATION_GANG_GROUPS)
        if not raw:
            return [gang_name]
        try:
            groups = json.loads(raw)
            return list(groups) if groups else [gang_name]
        except (ValueError, TypeError):
            return [gang_name]

    def update_pod_group_status(self, store: ObjectStore,
                                now: Optional[float] = None) -> None:
        """PodGroup status controller analog (controller/podgroup.go:55-313):
        phase progression Pending -> Scheduling -> Scheduled, plus timeout —
        a gang that hasn't reached min-member within its schedule timeout
        (from creation) is marked Failed, and stays Failed (terminal)."""
        import time as _time

        now = _time.time() if now is None else now
        for pg in self.pod_groups.values():
            name = pg.meta.key
            scheduled = self.assumed.get(name, 0)
            if pg.phase == "Scheduled":  # restart recovery of the latch
                self._ever_scheduled.add(name)
            timeout = pg.schedule_timeout_seconds or self.default_timeout_seconds
            if scheduled >= pg.min_member:
                phase = "Scheduled"
                self._ever_scheduled.add(name)
            elif name in self._ever_scheduled:
                # once-scheduled gangs are rescheduling, never timeout-failed
                phase = "Scheduling" if scheduled else "Pending"
            elif pg.phase == "Failed":
                phase = "Failed"
            elif (timeout > 0 and pg.meta.creation_timestamp
                  and now - pg.meta.creation_timestamp > timeout):
                phase = "Failed"
            elif scheduled:
                phase = "Scheduling"
            else:
                phase = "Pending"
            if pg.scheduled != scheduled or pg.phase != phase:
                pg.scheduled, pg.phase = scheduled, phase
                store.update(KIND_POD_GROUP, pg)

    def timed_out_gangs(self) -> List[str]:
        """Gangs whose PodGroup is terminally Failed — the cycle driver
        excludes their pods from admission (permit timeout rejection)."""
        return [name for name, pg in self.pod_groups.items()
                if pg.phase == "Failed"]
