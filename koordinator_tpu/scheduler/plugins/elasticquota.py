"""ElasticQuota plugin host side: the group quota manager cache.

Reference `plugins/elasticquota/core/group_quota_manager.go`: maintains the
quota tree from ElasticQuota CRs, tracks request/used deltas as pods come and
go, and exposes the packed tree to the admission kernel (ops/quota.py). Also
hosts the overuse revoke walk (quota_overuse_revoke.go)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.api.objects import ElasticQuota, Pod
from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_POD,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin


class ElasticQuotaPlugin(Plugin):
    name = "ElasticQuota"

    def __init__(self) -> None:
        self.quotas: Dict[str, ElasticQuota] = {}
        self.used: Dict[str, np.ndarray] = {}     # leaf quota -> used vector
        self.pending: Dict[str, np.ndarray] = {}  # leaf quota -> pending requests

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_ELASTIC_QUOTA, self._on_quota)
        store.subscribe(KIND_POD, self._on_pod)

    def services(self):
        """frameworkext services endpoints (/apis/v1/plugins/ElasticQuota/...)."""
        return {
            "quotas": lambda: {
                name: {
                    "min": dict(q.min.quantities),
                    "max": dict(q.max.quantities),
                    "used": self.used.get(name, np.zeros(NUM_RESOURCES)).tolist(),
                }
                for name, q in sorted(self.quotas.items())
            }
        }

    def _on_quota(self, ev: EventType, q: ElasticQuota, old) -> None:
        if ev is EventType.DELETED:
            self.quotas.pop(q.meta.name, None)
        else:
            self.quotas[q.meta.name] = q

    def _vec(self, cache: Dict[str, np.ndarray], name: str) -> np.ndarray:
        if name not in cache:
            cache[name] = np.zeros(NUM_RESOURCES, np.float32)
        return cache[name]

    @staticmethod
    def _bucket(pod: Pod) -> Optional[str]:
        """Which cache a pod contributes to: pending (unassigned, live), used
        (assigned, live), or none (terminated)."""
        if pod.is_terminated:
            return None
        return "used" if pod.is_assigned else "pending"

    def _apply(self, name: str, bucket: Optional[str], vec: np.ndarray,
               sign: float) -> None:
        if bucket is None:
            return
        cache = self.used if bucket == "used" else self.pending
        self._vec(cache, name)
        cache[name] = np.maximum(cache[name] + sign * vec, 0.0)

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        name = pod.quota_name
        if not name:
            return
        vec = pod.spec.requests.to_vector()
        if ev is EventType.ADDED:
            self._apply(name, self._bucket(pod), vec, +1.0)
        elif ev is EventType.MODIFIED:
            old_bucket = self._bucket(old) if old is not None else None
            new_bucket = self._bucket(pod)
            if old_bucket != new_bucket:
                self._apply(name, old_bucket, vec, -1.0)
                self._apply(name, new_bucket, vec, +1.0)
        elif ev is EventType.DELETED:
            self._apply(name, self._bucket(pod), vec, -1.0)

    def quota_list(self) -> List[ElasticQuota]:
        return list(self.quotas.values())

    def request_by_quota(self) -> Dict[str, np.ndarray]:
        """Group demand from the live caches (see ops.quota.merge_group_request)."""
        from koordinator_tpu.ops.quota import merge_group_request

        return merge_group_request(self.pending, self.used)

    def tree_snapshot(self, store: ObjectStore):
        """(tree, runtime[G, R]) from the live caches + node totals — the one
        shared snapshot the revoke controller and the preemptor both derive
        runtime quotas from. Returns None when no quotas exist."""
        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.client.store import KIND_NODE
        from koordinator_tpu.ops.quota import (
            build_quota_tree,
            compute_runtime_quotas,
        )

        quotas = self.quota_list()
        if not quotas:
            return None
        total = ResourceList()
        for node in store.list(KIND_NODE):
            total = total.add(node.allocatable)
        tree = build_quota_tree(
            quotas,
            pod_requests_by_quota=self.request_by_quota(),
            used_by_quota=self.used,
        )
        runtime = compute_runtime_quotas(tree, total.to_vector())
        return tree, runtime

    def revoke_controller(self, store: ObjectStore, args) -> "QuotaOveruseRevokeController":
        return QuotaOveruseRevokeController(self, store, args)

    @staticmethod
    def victim_order(name: str, pods: List[Pod]) -> List[Pod]:
        """The overuse victim ordering (quota_overuse_revoke.go): live assigned
        members of the group, lowest priority first, youngest first within a
        priority. Single home for the policy — the revoke controller walks it."""
        return sorted(
            (p for p in pods
             if p.quota_name == name and p.is_assigned and not p.is_terminated),
            key=lambda p: (p.spec.priority or 0, -p.meta.creation_timestamp),
        )


class QuotaOveruseRevokeController:
    """Overuse revocation loop (quota_overuse_revoke.go): every
    revokePodInterval, recompute runtime quotas from the live tree and evict
    members of groups whose used exceeds runtime — but only after the group
    has been continuously over-quota for delayEvictTime (grace for transient
    overshoot after a min shrink). Gated by ElasticQuotaArgs.monitorAllQuotas."""

    def __init__(self, plugin: ElasticQuotaPlugin, store: ObjectStore, args,
                 evictor=None):
        from koordinator_tpu.descheduler.evictions import EvictionAPIEvictor

        self.plugin = plugin
        self.store = store
        self.args = args
        # evictions route through the shared PDB/evictability machinery
        self.evictor = evictor or EvictionAPIEvictor(store)
        self._last_run: float = 0.0
        self._over_since: Dict[str, float] = {}

    def _runtime_by_name(self) -> Dict[str, np.ndarray]:
        snap = self.plugin.tree_snapshot(self.store)
        if snap is None:
            return {}
        tree, runtime = snap
        return {name: runtime[i] for i, name in enumerate(tree.names)}

    def reconcile(self, now: float) -> List[str]:
        """Returns keys of evicted pods."""
        if not self.args.monitor_all_quotas:
            return []
        if now - self._last_run < self.args.revoke_pod_interval_seconds:
            return []
        self._last_run = now
        runtime = self._runtime_by_name()
        if not runtime:
            return []
        # grace tracking: a group only becomes revocable after delayEvictTime
        revocable: Dict[str, np.ndarray] = {}
        for name, used in self.plugin.used.items():
            rt = runtime.get(name)
            if rt is None:
                continue
            if (np.maximum(used - rt, 0.0) > 0).any():
                since = self._over_since.setdefault(name, now)
                if now - since >= self.args.delay_evict_time_seconds:
                    revocable[name] = rt
            else:
                self._over_since.pop(name, None)
        if not revocable:
            return []
        from koordinator_tpu.descheduler.evictions import EvictionBlocked

        pods = self.store.list(KIND_POD)
        evicted = []
        # walk EVERY member of each over-quota group in victim order, not just
        # the minimal victim set: a blocked member (PDB / non-evictable) must
        # not shield the group from reclamation — the next member is tried
        for name, rt in revocable.items():
            over = np.maximum(self.plugin.used.get(name, 0.0) - rt, 0.0)
            for pod in self.plugin.victim_order(name, pods):
                if not (over > 0).any():
                    break
                try:
                    self.evictor.evict(pod, "quota-overused")
                except EvictionBlocked:
                    continue  # spared; try the next member
                evicted.append(pod.meta.key)
                over = over - pod.spec.requests.to_vector()
        return evicted
