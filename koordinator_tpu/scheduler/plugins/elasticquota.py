"""ElasticQuota plugin host side: the group quota manager cache.

Reference `plugins/elasticquota/core/group_quota_manager.go`: maintains the
quota tree from ElasticQuota CRs, tracks request/used deltas as pods come and
go, and exposes the packed tree to the admission kernel (ops/quota.py). Also
hosts the overuse revoke walk (quota_overuse_revoke.go)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.api.objects import ElasticQuota, Pod
from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_POD,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin


class ElasticQuotaPlugin(Plugin):
    name = "ElasticQuota"

    def __init__(self) -> None:
        self.quotas: Dict[str, ElasticQuota] = {}
        self.used: Dict[str, np.ndarray] = {}     # leaf quota -> used vector
        self.pending: Dict[str, np.ndarray] = {}  # leaf quota -> pending requests

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_ELASTIC_QUOTA, self._on_quota)
        store.subscribe(KIND_POD, self._on_pod)

    def _on_quota(self, ev: EventType, q: ElasticQuota, old) -> None:
        if ev is EventType.DELETED:
            self.quotas.pop(q.meta.name, None)
        else:
            self.quotas[q.meta.name] = q

    def _vec(self, cache: Dict[str, np.ndarray], name: str) -> np.ndarray:
        if name not in cache:
            cache[name] = np.zeros(NUM_RESOURCES, np.float32)
        return cache[name]

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        name = pod.quota_name
        if not name:
            return
        vec = pod.spec.requests.to_vector()
        if ev is EventType.ADDED:
            if pod.is_assigned and not pod.is_terminated:
                self._vec(self.used, name)
                self.used[name] += vec
            elif not pod.is_terminated:
                self._vec(self.pending, name)
                self.pending[name] += vec
        elif ev is EventType.MODIFIED and old is not None:
            was = old.is_assigned and not old.is_terminated
            now = pod.is_assigned and not pod.is_terminated
            if now and not was:
                self._vec(self.used, name)
                self.used[name] += vec
                self._vec(self.pending, name)
                self.pending[name] = np.maximum(self.pending[name] - vec, 0.0)
            elif was and not now:
                self._vec(self.used, name)
                self.used[name] = np.maximum(self.used[name] - vec, 0.0)
        elif ev is EventType.DELETED:
            cache = self.used if (pod.is_assigned and not pod.is_terminated) else self.pending
            self._vec(cache, name)
            cache[name] = np.maximum(cache[name] - vec, 0.0)

    def quota_list(self) -> List[ElasticQuota]:
        return list(self.quotas.values())

    # quota_overuse_revoke.go analog: pods to evict when a group exceeds runtime
    def find_overuse_victims(
        self, runtime_by_name: Dict[str, np.ndarray], pods: List[Pod]
    ) -> List[Pod]:
        victims: List[Pod] = []
        for name, used in self.used.items():
            runtime = runtime_by_name.get(name)
            if runtime is None:
                continue
            over = np.maximum(used - runtime, 0.0)
            if not (over > 0).any():
                continue
            members = sorted(
                (
                    p
                    for p in pods
                    if p.quota_name == name and p.is_assigned and not p.is_terminated
                ),
                key=lambda p: (p.spec.priority or 0, -p.meta.creation_timestamp),
            )
            for pod in members:
                if not (over > 0).any():
                    break
                victims.append(pod)
                over = over - pod.spec.requests.to_vector()
        return victims
