"""ElasticQuota plugin host side: the group quota manager cache.

Reference `plugins/elasticquota/core/group_quota_manager.go`: maintains the
quota tree from ElasticQuota CRs, tracks request/used deltas as pods come and
go, and exposes the packed tree to the admission kernel (ops/quota.py). Also
hosts the overuse revoke walk (quota_overuse_revoke.go)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.api.objects import ElasticQuota, Pod
from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_POD,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin


class ElasticQuotaPlugin(Plugin):
    name = "ElasticQuota"

    def __init__(self) -> None:
        self.quotas: Dict[str, ElasticQuota] = {}
        self.used: Dict[str, np.ndarray] = {}     # leaf quota -> used vector
        self.pending: Dict[str, np.ndarray] = {}  # leaf quota -> pending requests
        # epochs for the tree/runtime memos (koordcolo): the tree epoch
        # moves on quota CR events, the state epoch on any used/pending
        # mutation, the node epoch on node events (cluster total)
        self.tree_epoch = 0
        self.state_epoch = 0
        self.nodes_epoch = 0
        self._tree_memo: Optional[tuple] = None     # (key, tree)
        self._runtime_memo: Optional[tuple] = None  # (key, runtime)
        # the device colo pass's published runtime/revoke decisions:
        # (epoch key, names, runtime[G,R], over[G,R], mask[G]) — consumed
        # by the revoke controller while the key matches the live epochs
        self.device_runtime: Optional[tuple] = None

    def register(self, store: ObjectStore) -> None:
        from koordinator_tpu.client.store import KIND_NODE

        store.subscribe(KIND_ELASTIC_QUOTA, self._on_quota)
        store.subscribe(KIND_POD, self._on_pod)
        # cluster total (and hence every runtime quota) moves with node
        # allocatable — including the batch/mid axes the colo pass
        # itself publishes; the epoch keeps the runtime memo honest
        store.subscribe(KIND_NODE, self._on_node, replay=False)

    def _on_node(self, ev: EventType, node, old) -> None:
        self.nodes_epoch += 1

    @property
    def epoch_key(self) -> tuple:
        return (self.tree_epoch, self.state_epoch, self.nodes_epoch)

    def services(self):
        """frameworkext services endpoints (/apis/v1/plugins/ElasticQuota/...)."""
        return {
            "quotas": lambda: {
                name: {
                    "min": dict(q.min.quantities),
                    "max": dict(q.max.quantities),
                    "used": self.used.get(name, np.zeros(NUM_RESOURCES)).tolist(),
                }
                for name, q in sorted(self.quotas.items())
            }
        }

    def _on_quota(self, ev: EventType, q: ElasticQuota, old) -> None:
        if ev is EventType.DELETED:
            self.quotas.pop(q.meta.name, None)
        else:
            self.quotas[q.meta.name] = q
        self.tree_epoch += 1

    def _vec(self, cache: Dict[str, np.ndarray], name: str) -> np.ndarray:
        if name not in cache:
            cache[name] = np.zeros(NUM_RESOURCES, np.float32)
        return cache[name]

    @staticmethod
    def _bucket(pod: Pod) -> Optional[str]:
        """Which cache a pod contributes to: pending (unassigned, live), used
        (assigned, live), or none (terminated)."""
        if pod.is_terminated:
            return None
        return "used" if pod.is_assigned else "pending"

    def _apply(self, name: str, bucket: Optional[str], vec: np.ndarray,
               sign: float) -> None:
        if bucket is None:
            return
        cache = self.used if bucket == "used" else self.pending
        self._vec(cache, name)
        cache[name] = np.maximum(cache[name] + sign * vec, 0.0)
        self.state_epoch += 1

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        name = pod.quota_name
        if not name:
            return
        vec = pod.spec.requests.to_vector()
        if ev is EventType.ADDED:
            self._apply(name, self._bucket(pod), vec, +1.0)
        elif ev is EventType.MODIFIED:
            old_bucket = self._bucket(old) if old is not None else None
            new_bucket = self._bucket(pod)
            if old_bucket != new_bucket:
                self._apply(name, old_bucket, vec, -1.0)
                self._apply(name, new_bucket, vec, +1.0)
        elif ev is EventType.DELETED:
            self._apply(name, self._bucket(pod), vec, -1.0)

    def quota_list(self) -> List[ElasticQuota]:
        return list(self.quotas.values())

    def request_by_quota(self) -> Dict[str, np.ndarray]:
        """Group demand from the live caches (see ops.quota.merge_group_request)."""
        from koordinator_tpu.ops.quota import merge_group_request

        return merge_group_request(self.pending, self.used)

    def packed_tree(self):
        """The packed QuotaTreeArrays from the live caches, memoized on
        (tree_epoch, state_epoch) — a reconcile tick on an unchanged
        cluster reuses the previous build instead of re-walking every
        quota. Returns None when no quotas exist."""
        from koordinator_tpu.ops.quota import build_quota_tree

        key = (self.tree_epoch, self.state_epoch)
        hit = self._tree_memo
        if hit is not None and hit[0] == key:
            return hit[1]
        quotas = self.quota_list()
        tree = None
        if quotas:
            tree = build_quota_tree(
                quotas,
                pod_requests_by_quota=self.request_by_quota(),
                used_by_quota=self.used,
            )
        self._tree_memo = (key, tree)
        return tree

    @staticmethod
    def cluster_total_vec(store: ObjectStore) -> np.ndarray:
        """Cluster allocatable total as the packed [R] f32 vector — the
        exact value the runtime fold divides (single home: the host
        oracle, the revoke controller, and the colo pack all ship this
        vector, so the device fold's input is bit-identical)."""
        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.client.store import KIND_NODE

        total = ResourceList()
        for node in store.list(KIND_NODE):
            total = total.add(node.allocatable)
        return total.to_vector()

    def leaf_used_matrix(self, names) -> np.ndarray:
        """Per-group LEAF used rows aligned to ``names`` — what the
        overuse revoke loop checks against runtime (the aggregated tree
        ``used`` rolls children into parents; revocation is leaf-level,
        quota_overuse_revoke.go walks direct members only)."""
        out = np.zeros((len(names), NUM_RESOURCES), np.float32)
        for i, name in enumerate(names):
            vec = self.used.get(name)
            if vec is not None:
                out[i] = vec
        return out

    def tree_snapshot(self, store: ObjectStore):
        """(tree, runtime[G, R]) from the live caches + node totals — the one
        shared snapshot the revoke controller and the preemptor both derive
        runtime quotas from. Returns None when no quotas exist. Memoized on
        (tree_epoch, state_epoch, nodes_epoch): nothing changed -> the
        previous runtime matrix is returned without recomputing the fold."""
        from koordinator_tpu.ops.quota import compute_runtime_quotas

        tree = self.packed_tree()
        if tree is None:
            return None
        key = self.epoch_key
        hit = self._runtime_memo
        if hit is not None and hit[0] == key:
            return tree, hit[1]
        runtime = compute_runtime_quotas(tree, self.cluster_total_vec(store))
        self._runtime_memo = (key, runtime)
        return tree, runtime

    # ---- koordcolo: the device pass's published quota decisions ----------
    def set_device_runtime(self, names, runtime, over, mask, key) -> None:
        """The colo reconciler lands the device fold's outputs here;
        they stay authoritative while ``key`` matches the live epochs
        (any quota/pod/node event invalidates them until the next colo
        pass re-publishes)."""
        self.device_runtime = (tuple(key), list(names), runtime, over, mask)

    def fresh_device_runtime(self) -> Optional[tuple]:
        hit = self.device_runtime
        if hit is None or hit[0] != self.epoch_key:
            return None
        return hit

    def revoke_controller(self, store: ObjectStore, args) -> "QuotaOveruseRevokeController":
        return QuotaOveruseRevokeController(self, store, args)

    @staticmethod
    def victim_order(name: str, pods: List[Pod]) -> List[Pod]:
        """The overuse victim ordering (quota_overuse_revoke.go): live assigned
        members of the group, lowest priority first, youngest first within a
        priority. Single home for the policy — the revoke controller walks it."""
        return sorted(
            (p for p in pods
             if p.quota_name == name and p.is_assigned and not p.is_terminated),
            key=lambda p: (p.spec.priority or 0, -p.meta.creation_timestamp),
        )


class QuotaOveruseRevokeController:
    """Overuse revocation loop (quota_overuse_revoke.go): every
    revokePodInterval, recompute runtime quotas from the live tree and evict
    members of groups whose used exceeds runtime — but only after the group
    has been continuously over-quota for delayEvictTime (grace for transient
    overshoot after a min shrink). Gated by ElasticQuotaArgs.monitorAllQuotas."""

    def __init__(self, plugin: ElasticQuotaPlugin, store: ObjectStore, args,
                 evictor=None):
        from koordinator_tpu.descheduler.evictions import EvictionAPIEvictor

        self.plugin = plugin
        self.store = store
        self.args = args
        # evictions route through the shared PDB/evictability machinery
        self.evictor = evictor or EvictionAPIEvictor(store)
        self._last_run: float = 0.0
        self._over_since: Dict[str, float] = {}

    def _runtime_by_name(self, device=None) -> Dict[str, np.ndarray]:
        """Runtime quota per group. With a FRESH device colo pass
        published on the plugin (koordcolo), its runtime matrix is
        authoritative — decision-identical to the host fold by the
        run_colo_parity gate; otherwise the (epoch-memoized) host
        snapshot computes it. ``device`` is the caller's single
        fresh_device_runtime() read, so one pass cannot mix a device
        runtime with a host-path mask decision."""
        if device is not None:
            _key, names, runtime, _over, _mask = device
            return {name: runtime[i] for i, name in enumerate(names)}
        snap = self.plugin.tree_snapshot(self.store)
        if snap is None:
            return {}
        tree, runtime = snap
        return {name: runtime[i] for i, name in enumerate(tree.names)}

    def reconcile(self, now: float) -> List[str]:
        """Returns keys of evicted pods."""
        if not self.args.monitor_all_quotas:
            return []
        if now - self._last_run < self.args.revoke_pod_interval_seconds:
            return []
        self._last_run = now
        device = self.plugin.fresh_device_runtime()
        runtime = self._runtime_by_name(device)
        if not runtime:
            return []
        # grace tracking: a group only becomes revocable after delayEvictTime.
        # With a fresh device pass the over-runtime candidate detection
        # consumes the kernel's revoke mask (the host compare retained below
        # as the oracle path and for host/off modes).
        revocable: Dict[str, np.ndarray] = {}
        device_idx = ({n: i for i, n in enumerate(device[1])}
                      if device is not None else None)
        for name, used in self.plugin.used.items():
            rt = runtime.get(name)
            if rt is None:
                continue
            if device_idx is not None and name in device_idx:
                over_now = bool(device[4][device_idx[name]])
            else:
                over_now = bool((np.maximum(used - rt, 0.0) > 0).any())
            if over_now:
                since = self._over_since.setdefault(name, now)
                if now - since >= self.args.delay_evict_time_seconds:
                    revocable[name] = rt
            else:
                self._over_since.pop(name, None)
        if not revocable:
            return []
        from koordinator_tpu.descheduler.evictions import EvictionBlocked

        pods = self.store.list(KIND_POD)
        evicted = []
        # walk EVERY member of each over-quota group in victim order, not just
        # the minimal victim set: a blocked member (PDB / non-evictable) must
        # not shield the group from reclamation — the next member is tried
        for name, rt in revocable.items():
            over = np.maximum(self.plugin.used.get(name, 0.0) - rt, 0.0)
            for pod in self.plugin.victim_order(name, pods):
                if not (over > 0).any():
                    break
                try:
                    self.evictor.evict(pod, "quota-overused")
                except EvictionBlocked:
                    continue  # spared; try the next member
                evicted.append(pod.meta.key)
                over = over - pod.spec.requests.to_vector()
        if evicted:
            from koordinator_tpu import manager_metrics

            manager_metrics.QUOTA_REVOKES_TOTAL.inc(len(evicted))
        return evicted
