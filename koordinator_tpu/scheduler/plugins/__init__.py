"""The seven koordinator scheduler plugins (SURVEY.md section 2.2), host side.

Filter/Score math lives in `ops/` and is fused by `models/full_chain.py`; these
classes maintain the event-driven caches and perform per-binding effects
(Reserve/Unreserve/PreBind), mirroring the reference's split between
"incremental cache on events" and "pure function at schedule time".
"""

from koordinator_tpu.scheduler.plugins.loadaware import LoadAwarePlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.nodenumaresource import (  # noqa: F401
    NodeNUMAResourcePlugin,
)
from koordinator_tpu.scheduler.plugins.reservation import ReservationPlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.coscheduling import CoschedulingPlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.elasticquota import ElasticQuotaPlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.deviceshare import DeviceSharePlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.defaultprebind import DefaultPreBindPlugin  # noqa: F401
from koordinator_tpu.scheduler.volumebinding import VolumeBindingPlugin  # noqa: F401

DEFAULT_PLUGINS = (
    LoadAwarePlugin,
    NodeNUMAResourcePlugin,
    ReservationPlugin,
    CoschedulingPlugin,
    ElasticQuotaPlugin,
    DeviceSharePlugin,
    VolumeBindingPlugin,
    DefaultPreBindPlugin,
)
