"""DeviceShare plugin host side: device cache + concrete allocation.

Reference `plugins/deviceshare/` (device_allocator.go:1-522, numa_topology.go,
topology_hint.go:33-130, devicehandler_gpu.go): Device CRs describe per-node
GPU/RDMA/FPGA inventory with per-device NUMA affinity; fractional GPU requests
(gpu-core percent, gpu-memory[-ratio], device_share.go:38-46); Filter checks
aggregate device capacity (covered by the GPU/RDMA/FPGA resource axes in the
batched Fit); Reserve picks concrete device minors (device_allocator.go)
honoring the topologymanager's merged NUMA affinity; PreBind writes the
allocation annotation (plugin.go:475).

Redesign notes vs the reference:
  * The reference walks PCIe switches inside a NUMA node
    (deviceTopologyGuide); the Device CR here reports per-device numa_node, so
    joint allocation (GPU+RDMA, jointAllocate in device_allocator.go:278-331)
    prefers secondary devices on the SAME NUMA nodes as the primary GPUs —
    the NUMA level of the same preference ladder.
  * RDMA/FPGA are whole-device grants (the reference's VF selection collapses
    to device granularity; the device minor is the grant unit).
  * NUMA hints (topology_hint.go GetPodTopologyHints) are generated per device
    type and merged by the shared TopologyManager with the CPU hints from
    NodeNUMAResource — the scheduling-time kubelet-style admit.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import (
    ANNOTATION_DEVICE_ALLOCATED,
    Device,
    DeviceInfo,
    Pod,
)
from koordinator_tpu.api.resources import ResourceName
from koordinator_tpu.client.store import KIND_DEVICE, EventType, ObjectStore
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin
from koordinator_tpu.scheduler.topologymanager import (
    BitMask,
    NUMATopologyHint,
)

# secondary (whole-device) types allocated after the primary GPU pick
SECONDARY_TYPES = ("rdma", "fpga")


def pod_gpu_request(pod: Pod) -> Dict[str, int]:
    """Normalize the GPU request forms (apis/extension/device_share.go):
    nvidia.com/gpu: N  ->  core N*100, memory-ratio N*100
    gpu-core/gpu-memory-ratio/gpu-memory given directly otherwise."""
    req = pod.spec.requests
    whole = req[ResourceName.GPU]
    if whole:
        return {"core": int(whole) * 100, "memory_ratio": int(whole) * 100}
    out: Dict[str, int] = {}
    if req[ResourceName.GPU_CORE]:
        out["core"] = int(req[ResourceName.GPU_CORE])
    if req[ResourceName.GPU_MEMORY_RATIO]:
        out["memory_ratio"] = int(req[ResourceName.GPU_MEMORY_RATIO])
    if req[ResourceName.GPU_MEMORY]:
        out["memory"] = int(req[ResourceName.GPU_MEMORY])
    return out


def pod_device_requests(pod: Pod) -> Dict[str, dict]:
    """Per-type device demand: {"gpu": {...}, "rdma": {"count": n}, ...}."""
    out: Dict[str, dict] = {}
    gpu = pod_gpu_request(pod)
    if gpu:
        out["gpu"] = gpu
    rdma = pod.spec.requests[ResourceName.RDMA]
    if rdma:
        out["rdma"] = {"count": int(rdma)}
    fpga = pod.spec.requests[ResourceName.FPGA]
    if fpga:
        out["fpga"] = {"count": int(fpga)}
    return out


def _gpu_device_need(want: dict) -> int:
    """How many distinct GPUs the request spans (1 for fractional/memory-only,
    core//100 for whole-GPU)."""
    core = want.get("core", 0)
    if core > 100:
        return core // 100
    return 1


class DeviceSharePlugin(Plugin):
    name = "DeviceShare"

    def __init__(self, scoring_strategy: str = "MostAllocated") -> None:
        self.devices: Dict[str, Device] = {}          # node -> Device CR
        # node -> type -> minor -> {"core": used, "memory_ratio": ..., ...}
        self.allocated: Dict[str, Dict[str, Dict[int, Dict[str, int]]]] = {}
        self.by_pod: Dict[str, Dict[str, List[dict]]] = {}
        self.scoring_strategy = scoring_strategy
        # keyed by (pod key, node name): the merged affinity is node-specific,
        # and a leaked entry from a vetoed attempt on another node must never
        # mask a later node's devices
        self._pending_affinity: Dict[tuple, NUMATopologyHint] = {}

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_DEVICE, self._on_device)

    def _on_device(self, ev: EventType, dev: Device, old) -> None:
        if ev is EventType.DELETED:
            self.devices.pop(dev.meta.name, None)
        else:
            self.devices[dev.meta.name] = dev

    # -- inventory helpers ---------------------------------------------
    def _infos(self, node: str, dtype: str) -> List[DeviceInfo]:
        dev = self.devices.get(node)
        if dev is None:
            return []
        return [d for d in dev.devices if d.type == dtype and d.health]

    def _used(self, node: str, dtype: str, minor: int) -> Dict[str, int]:
        return (
            self.allocated.setdefault(node, {})
            .setdefault(dtype, {})
            .setdefault(minor, {"core": 0, "memory_ratio": 0, "memory": 0,
                                "count": 0})
        )

    def _gpu_free(self, node: str, g: DeviceInfo) -> Dict[str, int]:
        used = self._used(node, "gpu", g.minor)
        cap_mem = int(g.resources[ResourceName.GPU_MEMORY]) or 0
        return {
            "core": 100 - used["core"],
            "memory_ratio": 100 - used["memory_ratio"],
            "memory": (cap_mem - used["memory"]) if cap_mem else -1,  # -1 = unreported
        }

    @staticmethod
    def _gpu_demand(g: DeviceInfo, want: dict, core: int) -> Dict[str, int]:
        """Per-device demand with the memory<->ratio axes kept in sync: ratio
        and bytes are two views of one capacity
        (apis/extension/device_share.go memoryRatio conversion), so a grant on
        either axis books BOTH — otherwise a memory-only pod and a ratio pod
        double-book the same HBM."""
        cap_mem = int(g.resources[ResourceName.GPU_MEMORY]) or 0
        ratio = want.get("memory_ratio", core)
        mem = want.get("memory", 0)
        if cap_mem:
            if mem and not want.get("memory_ratio"):
                ratio = max(ratio, -(-mem * 100 // cap_mem))  # ceil
            if ratio and not mem:
                mem = ratio * cap_mem // 100
        return {"core": core, "memory_ratio": ratio, "memory": mem}

    def _gpu_can_serve(self, node: str, g: DeviceInfo, want: dict) -> bool:
        """One device can serve one slice of the request, every axis checked.
        Shared between hint counting and the reserve chooser so the hints the
        topologymanager admits are exactly what reserve can satisfy."""
        core = want.get("core", 0)
        per_dev_core = 100 if core > 100 else core
        if per_dev_core == 100:
            # whole-GPU slices need an untouched device (any fractional
            # core/ratio/memory grant disqualifies it)
            used = self._used(node, "gpu", g.minor)
            return used["core"] == 0 and used["memory_ratio"] == 0 and \
                used["memory"] == 0
        need = self._gpu_demand(g, want, per_dev_core)
        free = self._gpu_free(node, g)
        if free["core"] < need["core"]:
            return False
        if free["memory_ratio"] < need["memory_ratio"]:
            return False
        if need["memory"] and free["memory"] >= 0 and \
                free["memory"] < need["memory"]:
            return False
        return True

    # -- NUMA topology hints (topology_hint.go) ------------------------
    def _restrict(self, infos: List[DeviceInfo],
                  affinity: Optional[NUMATopologyHint]) -> List[DeviceInfo]:
        """Devices usable under an affinity mask; numa_node -1 (unreported)
        devices are never excluded (calcTotalDevicesByNUMA counts them
        everywhere)."""
        if affinity is None or affinity.affinity is None:
            return infos
        allowed = set(affinity.affinity.get_bits())
        return [d for d in infos if d.numa_node < 0 or d.numa_node in allowed]

    def get_pod_topology_hints(self, pod: Pod, node_name: str):
        """Per-device-type hints: every NUMA-node subset whose free devices
        cover the request is a candidate; preferred iff minimal width
        (generateTopologyHints, topology_hint.go:108-214)."""
        import itertools

        wants = pod_device_requests(pod)
        if not wants:
            return None
        hints: Dict[str, Optional[List[NUMATopologyHint]]] = {}
        for dtype, want in wants.items():
            infos = self._infos(node_name, dtype)
            numa_ids = sorted({d.numa_node for d in infos if d.numa_node >= 0})
            if not numa_ids:
                hints[f"device/{dtype}"] = None  # no topology -> don't care
                continue
            need = (_gpu_device_need(want) if dtype == "gpu"
                    else want.get("count", 1))
            fitting: List[BitMask] = []
            min_width = len(numa_ids) + 1
            for width in range(1, len(numa_ids) + 1):
                for combo in itertools.combinations(numa_ids, width):
                    mask = BitMask(combo)
                    usable = self._restrict(
                        infos, NUMATopologyHint(mask, True))
                    if self._count_allocatable(
                            node_name, dtype, want, usable) >= need:
                        fitting.append(mask)
                        min_width = min(min_width, width)
            hints[f"device/{dtype}"] = [
                NUMATopologyHint(m, m.count() == min_width)
                for m in fitting
            ]
        return hints

    def _count_allocatable(self, node: str, dtype: str, want: dict,
                           infos: List[DeviceInfo]) -> int:
        """How many of `infos` could serve one slice of the request."""
        n = 0
        for d in infos:
            if dtype == "gpu":
                if self._gpu_can_serve(node, d, want):
                    n += 1
            else:
                if self._used(node, dtype, d.minor)["count"] == 0:
                    n += 1
        return n

    def allocate(self, pod: Pod, node_name: str,
                 affinity: NUMATopologyHint) -> Optional[str]:
        """TopologyManager fan-out: remember the merged affinity for reserve."""
        self._pending_affinity[(pod.meta.key, node_name)] = affinity
        return None

    # -- Reserve (device_allocator.go) ---------------------------------
    def reserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> Optional[str]:
        wants = pod_device_requests(pod)
        if not wants:
            return None
        affinity = self._pending_affinity.pop((pod.meta.key, node_name), None)
        allocations: Dict[str, List[dict]] = {}

        err = None
        if "gpu" in wants:
            err = self._reserve_gpu(pod, node_name, wants["gpu"], affinity,
                                    allocations)
        if err is None:
            # joint allocation: secondary devices prefer the primary GPUs'
            # NUMA nodes (jointAllocate, device_allocator.go:278-331)
            gpu_numas = self._numas_of(node_name, "gpu",
                                       allocations.get("gpu", []))
            for dtype in SECONDARY_TYPES:
                if dtype in wants:
                    err = self._reserve_count(
                        pod, node_name, dtype, wants[dtype]["count"],
                        affinity, gpu_numas, allocations)
                    if err:
                        break
        if err:
            self._rollback(node_name, allocations)
            return err
        self.by_pod[pod.meta.key] = allocations
        return None

    def _numas_of(self, node: str, dtype: str, picks: List[dict]) -> set:
        by_minor = {d.minor: d for d in self._infos(node, dtype)}
        return {
            by_minor[p["minor"]].numa_node
            for p in picks
            if p["minor"] in by_minor and by_minor[p["minor"]].numa_node >= 0
        }

    def _reserve_gpu(self, pod: Pod, node: str, want: dict,
                     affinity: Optional[NUMATopologyHint],
                     allocations: Dict[str, List[dict]]) -> Optional[str]:
        gpus = self._restrict(self._infos(node, "gpu"), affinity)
        if not gpus:
            return "no healthy gpu on node"
        core = want.get("core", 0)
        if core > 100 and core % 100 != 0:
            # multi-GPU requests must be whole GPUs (validation in
            # apis/extension/device_share.go ValidatePercentageResource)
            return "gpu-core above 100 must be a multiple of 100"

        # DeviceShareArgs.scoringStrategy: MostAllocated packs fractional
        # requests onto fuller GPUs (keeps whole GPUs free for whole-GPU
        # pods); LeastAllocated spreads
        sign = -1 if self.scoring_strategy == "MostAllocated" else 1
        order = sorted(
            gpus,
            key=lambda g: (sign * self._used(node, "gpu", g.minor)["core"],
                           g.minor),
        )
        picks: List[dict] = []
        if core > 100:
            n = core // 100
            free_gpus = [g for g in order if self._gpu_can_serve(node, g, want)]
            if len(free_gpus) < n:
                return "insufficient whole gpus"
            per_dev = {**want, "core": 100}
            if "memory_ratio" in want:
                per_dev["memory_ratio"] = want["memory_ratio"] // n
            if "memory" in want:
                per_dev["memory"] = want["memory"] // n
            for g in free_gpus[:n]:
                picks.append({"minor": g.minor,
                              **self._gpu_demand(g, per_dev, 100)})
        else:
            # fractional or memory-only: one GPU that covers every dimension
            chosen = None
            for g in order:
                if self._gpu_can_serve(node, g, want):
                    chosen = g
                    break
            if chosen is None:
                return "insufficient gpu capacity"
            picks.append({"minor": chosen.minor,
                          **self._gpu_demand(chosen, want, core)})
        for p in picks:
            used = self._used(node, "gpu", p["minor"])
            used["core"] += p["core"]
            used["memory_ratio"] += p["memory_ratio"]
            used["memory"] += p["memory"]
        allocations["gpu"] = picks
        return None

    def _reserve_count(self, pod: Pod, node: str, dtype: str, count: int,
                       affinity: Optional[NUMATopologyHint],
                       preferred_numas: set,
                       allocations: Dict[str, List[dict]]) -> Optional[str]:
        infos = self._restrict(self._infos(node, dtype), affinity)
        free = [d for d in infos if self._used(node, dtype, d.minor)["count"] == 0]
        if len(free) < count:
            return f"insufficient {dtype} devices"
        # joint preference: same NUMA node as the primary GPUs first
        free.sort(key=lambda d: (
            0 if (preferred_numas and d.numa_node in preferred_numas) else 1,
            d.minor,
        ))
        picks = []
        for d in free[:count]:
            self._used(node, dtype, d.minor)["count"] = 1
            picks.append({"minor": d.minor})
        allocations[dtype] = picks
        return None

    # -- rollback / unreserve ------------------------------------------
    def _rollback(self, node: str, allocations: Dict[str, List[dict]]) -> None:
        for dtype, picks in allocations.items():
            for p in picks:
                used = self._used(node, dtype, p["minor"])
                if dtype == "gpu":
                    used["core"] -= p["core"]
                    used["memory_ratio"] -= p["memory_ratio"]
                    used["memory"] -= p["memory"]
                else:
                    used["count"] = 0

    def unreserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> None:
        allocations = self.by_pod.pop(pod.meta.key, None)
        if allocations:
            self._rollback(node_name, allocations)
        self._pending_affinity.pop((pod.meta.key, node_name), None)

    def pre_bind(self, pod: Pod, node_name: str, ctx: CycleContext,
                 annotations: Dict[str, str]) -> None:
        allocations = self.by_pod.get(pod.meta.key)
        if allocations:
            annotations[ANNOTATION_DEVICE_ALLOCATED] = json.dumps(allocations)
