"""DeviceShare plugin host side: device cache + concrete allocation.

Reference `plugins/deviceshare/`: Device CRs describe per-node GPU/RDMA/FPGA
inventory; fractional GPU requests (gpu-core percent, gpu-memory[-ratio],
device_share.go:38-46); Filter checks aggregate device capacity (covered by the
GPU resource axes in the batched Fit); Reserve picks concrete device minors
(device_allocator.go) honoring NUMA affinity when present; PreBind writes the
allocation annotation (plugin.go:475)."""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import (
    ANNOTATION_DEVICE_ALLOCATED,
    Device,
    DeviceInfo,
    Pod,
)
from koordinator_tpu.api.resources import ResourceName
from koordinator_tpu.client.store import KIND_DEVICE, EventType, ObjectStore
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin


def pod_gpu_request(pod: Pod) -> Dict[str, int]:
    """Normalize the GPU request forms (apis/extension/device_share.go):
    nvidia.com/gpu: N  ->  core N*100, memory-ratio N*100
    gpu-core/gpu-memory-ratio/gpu-memory given directly otherwise."""
    req = pod.spec.requests
    whole = req[ResourceName.GPU]
    if whole:
        return {"core": whole * 100, "memory_ratio": whole * 100}
    out: Dict[str, int] = {}
    if req[ResourceName.GPU_CORE]:
        out["core"] = req[ResourceName.GPU_CORE]
    if req[ResourceName.GPU_MEMORY_RATIO]:
        out["memory_ratio"] = req[ResourceName.GPU_MEMORY_RATIO]
    if req[ResourceName.GPU_MEMORY]:
        out["memory"] = req[ResourceName.GPU_MEMORY]
    return out


class DeviceSharePlugin(Plugin):
    name = "DeviceShare"

    def __init__(self, scoring_strategy: str = "MostAllocated") -> None:
        self.devices: Dict[str, Device] = {}          # node -> Device CR
        # node -> minor -> {"core": used, "memory_ratio": used, "memory": used}
        self.allocated: Dict[str, Dict[int, Dict[str, int]]] = {}
        self.by_pod: Dict[str, List[dict]] = {}
        self.scoring_strategy = scoring_strategy

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_DEVICE, self._on_device)

    def _on_device(self, ev: EventType, dev: Device, old) -> None:
        if ev is EventType.DELETED:
            self.devices.pop(dev.meta.name, None)
        else:
            self.devices[dev.meta.name] = dev

    def _gpu_infos(self, node: str) -> List[DeviceInfo]:
        dev = self.devices.get(node)
        if dev is None:
            return []
        return [d for d in dev.devices if d.type == "gpu" and d.health]

    def reserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> Optional[str]:
        want = pod_gpu_request(pod)
        if not want:
            return None
        gpus = self._gpu_infos(node_name)
        if not gpus:
            return "no healthy gpu on node"
        node_alloc = self.allocated.setdefault(node_name, {})
        remaining_core = want.get("core", 0)
        picks: List[dict] = []
        # DeviceShareArgs.scoringStrategy: MostAllocated packs fractional
        # requests onto fuller GPUs (keeps whole GPUs free for whole-GPU
        # pods, device_allocator.go preference); LeastAllocated spreads
        sign = -1 if self.scoring_strategy == "MostAllocated" else 1
        order = sorted(
            gpus,
            key=lambda g: (
                sign * node_alloc.get(g.minor, {}).get("core", 0),
                g.minor,
            ),
        )
        total_core = max(want.get("core", 0), 1)
        for g in order:
            if remaining_core <= 0:
                break
            used = node_alloc.setdefault(
                g.minor, {"core": 0, "memory_ratio": 0, "memory": 0}
            )
            free_core = 100 - used["core"]
            if free_core <= 0:
                continue
            take = min(free_core, remaining_core)
            if remaining_core > 100 and take < 100:
                continue  # whole-gpu requests need whole gpus
            # memory/ratio are split across picks in proportion to core take
            # (the implicit ratio default follows the core request: total_core,
            # NOT take — proportional split then yields `take` per pick)
            ratio_share = int(
                want.get("memory_ratio", total_core) * take / total_core
            )
            mem_share = int(want.get("memory", 0) * take / total_core)
            used["core"] += take
            used["memory_ratio"] += ratio_share
            used["memory"] += mem_share
            picks.append(
                {"minor": g.minor, "core": take, "memory": mem_share,
                 "memory_ratio": ratio_share}
            )
            remaining_core -= take
        if remaining_core > 0:
            for p in picks:
                self._release(node_alloc, p)
            return "insufficient gpu capacity"
        self.by_pod[pod.meta.key] = picks
        return None

    @staticmethod
    def _release(node_alloc: Dict[int, Dict[str, int]], pick: dict) -> None:
        used = node_alloc.get(pick["minor"])
        if used:
            used["core"] -= pick["core"]
            used["memory"] -= pick["memory"]
            used["memory_ratio"] -= pick.get("memory_ratio", 0)

    def unreserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> None:
        picks = self.by_pod.pop(pod.meta.key, None)
        if not picks:
            return
        node_alloc = self.allocated.get(node_name, {})
        for p in picks:
            self._release(node_alloc, p)

    def pre_bind(self, pod: Pod, node_name: str, ctx: CycleContext,
                 annotations: Dict[str, str]) -> None:
        picks = self.by_pod.get(pod.meta.key)
        if picks:
            annotations[ANNOTATION_DEVICE_ALLOCATED] = json.dumps({"gpu": picks})
