"""NodeNUMAResource plugin host side: topology options + cpuset allocation.

Reference `plugins/nodenumaresource/`: TopologyOptionsManager ingests
NodeResourceTopology CRs (reported by koordlet); Reserve allocates concrete cpus
via the accumulator; PreBind writes the allocation into the pod annotation
(`scheduling.koordinator.sh/resource-status`, plugin.go:431-479) which koordlet's
cpuset runtime hook applies to the container cgroup."""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from koordinator_tpu.api.objects import (
    ANNOTATION_RESOURCE_STATUS,
    NodeResourceTopology,
    Pod,
)
from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.client.store import (
    KIND_NODE_TOPOLOGY,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.cpu_topology import (
    EXCLUSIVE_NONE,
    FULL_PCPUS,
    SPREAD_BY_PCPUS,
    CPUAllocationState,
    CPUTopology,
    take_cpus,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin
from koordinator_tpu.scheduler.snapshot import _pod_cpuset_flags


class NodeNUMAResourcePlugin(Plugin):
    name = "NodeNUMAResource"

    def __init__(self, max_ref_count: int = 1) -> None:
        self.max_ref_count = max_ref_count
        self.cpu_states: Dict[str, CPUAllocationState] = {}
        self.topologies: Dict[str, NodeResourceTopology] = {}
        self.numa_allocated: Dict[str, np.ndarray] = {}

    def register(self, store: ObjectStore) -> None:
        store.subscribe(KIND_NODE_TOPOLOGY, self._on_topology)

    def _on_topology(self, ev: EventType, cr: NodeResourceTopology, old) -> None:
        name = cr.meta.name
        if ev is EventType.DELETED:
            self.topologies.pop(name, None)
            self.cpu_states.pop(name, None)
            return
        self.topologies[name] = cr
        if name not in self.cpu_states and cr.cpus:
            topo = CPUTopology(cr.cpus)
            state = CPUAllocationState(topo, self.max_ref_count)
            self.cpu_states[name] = state
            if cr.kubelet_reserved_cpus:
                # kubelet static cpu-manager claims are unavailable to koordinator
                from koordinator_tpu.utils.cpuset import CPUSet

                state.add(
                    "kubelet-reserved",
                    CPUSet(cr.kubelet_reserved_cpus),
                    EXCLUSIVE_NONE,
                )

    def reserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> Optional[str]:
        needs_bind, cores, full_pcpus = _pod_cpuset_flags(pod)
        if not needs_bind:
            self._track_numa(pod, node_name, add=True)
            return None
        state = self.cpu_states.get(node_name)
        if state is None:
            return "node has no CPU topology"
        got = take_cpus(
            state,
            int(cores),
            bind_policy=FULL_PCPUS if full_pcpus else SPREAD_BY_PCPUS,
        )
        if got is None:
            return "insufficient bindable cpus"
        state.add(pod.meta.key, got, EXCLUSIVE_NONE)
        ctx.data.setdefault("cpusets", {})[pod.meta.key] = got
        self._track_numa(pod, node_name, add=True)
        return None

    def unreserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> None:
        state = self.cpu_states.get(node_name)
        if state is not None:
            state.remove(pod.meta.key)
        ctx.data.get("cpusets", {}).pop(pod.meta.key, None)
        self._track_numa(pod, node_name, add=False)

    def _track_numa(self, pod: Pod, node_name: str, add: bool) -> None:
        """Zone-level accounting feeding snapshot numa_free (spread fill, same
        deterministic rule as the kernel)."""
        if node_name not in self.topologies:
            return
        vec = pod.spec.requests.to_vector()
        alloc = self.numa_allocated.setdefault(
            node_name,
            np.zeros((8, NUM_RESOURCES), np.float32),
        )
        if add:
            alloc[0] += vec  # refined per-zone tracking comes with zone reporting
        else:
            alloc[0] = np.maximum(alloc[0] - vec, 0.0)

    def pre_bind(self, pod: Pod, node_name: str, ctx: CycleContext,
                 annotations: Dict[str, str]) -> None:
        got = ctx.data.get("cpusets", {}).get(pod.meta.key)
        if got is not None:
            annotations[ANNOTATION_RESOURCE_STATUS] = json.dumps(
                {"cpuset": got.format()}
            )
