"""NodeNUMAResource plugin host side: topology options + cpuset allocation.

Reference `plugins/nodenumaresource/`: TopologyOptionsManager ingests
NodeResourceTopology CRs (reported by koordlet); Reserve allocates concrete cpus
via the accumulator; PreBind writes the allocation into the pod annotation
(`scheduling.koordinator.sh/resource-status`, plugin.go:431-479) which koordlet's
cpuset runtime hook applies to the container cgroup."""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

import numpy as np

from koordinator_tpu.api.objects import (
    ANNOTATION_RESOURCE_STATUS,
    NodeResourceTopology,
    Pod,
)
from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.client.store import (
    KIND_NODE_TOPOLOGY,
    KIND_POD,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler.cpu_topology import (
    EXCLUSIVE_NONE,
    FULL_PCPUS,
    SPREAD_BY_PCPUS,
    CPUAllocationState,
    CPUTopology,
    take_cpus,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin
from koordinator_tpu.scheduler.snapshot import _pod_cpuset_flags
from koordinator_tpu.scheduler.topologymanager import (
    POLICY_NONE,
    NUMATopologyHint,
    TopologyManager,
    generate_fit_hints,
    resolve_numa_policy,
)


class NodeNUMAResourcePlugin(Plugin):
    name = "NodeNUMAResource"

    def __init__(self, max_ref_count: int = 1,
                 default_cpu_bind_policy: str = FULL_PCPUS,
                 numa_allocate_strategy: str = "MostAllocated") -> None:
        self.max_ref_count = max_ref_count
        self.default_cpu_bind_policy = default_cpu_bind_policy
        self.numa_allocate_strategy = numa_allocate_strategy
        self.cpu_states: Dict[str, CPUAllocationState] = {}
        self.topologies: Dict[str, NodeResourceTopology] = {}
        self.numa_allocated: Dict[str, np.ndarray] = {}
        self.store: Optional[ObjectStore] = None
        # the plugin is itself a hint provider (resource_manager.go:418-532);
        # DeviceShare registers alongside it in the scheduler wiring
        self.topology_manager = TopologyManager([self])
        self._pending_affinity: Dict[str, NUMATopologyHint] = {}
        # exact per-pod zone placement, so release reverses what add placed
        self._pod_zone_alloc: Dict[Tuple[str, str], np.ndarray] = {}
        # per-node change counter over (topologies, cpu_states,
        # numa_allocated) — keys the incremental snapshot builder's NUMA rows
        self.node_epoch: Dict[str, int] = {}
        # names bumped since the snapshot cache last drained (see
        # scheduler/snapshot_cache.py numa_arrays)
        self.epoch_dirty: set = set()

    def _bump(self, node_name: str) -> None:
        self.node_epoch[node_name] = self.node_epoch.get(node_name, 0) + 1
        self.epoch_dirty.add(node_name)

    def register(self, store: ObjectStore) -> None:
        self.store = store
        store.subscribe(KIND_NODE_TOPOLOGY, self._on_topology)
        store.subscribe(KIND_POD, self._on_pod)
        from koordinator_tpu.client.store import KIND_NODE

        store.subscribe(KIND_NODE, self._on_node)

    def _on_pod(self, ev: EventType, pod: Pod, old) -> None:
        """Release zone + cpuset accounting when an assigned pod dies (the
        reference frees allocations on pod delete events via its resource
        manager cache)."""
        if ev is EventType.DELETED or pod.is_terminated:
            node = pod.spec.node_name
            if node:
                self._release_zone_alloc(node, pod.meta.key)
                state = self.cpu_states.get(node)
                if state is not None:
                    state.remove(pod.meta.key)
                    self._bump(node)

    def _on_topology(self, ev: EventType, cr: NodeResourceTopology, old) -> None:
        name = cr.meta.name
        self._bump(name)
        if ev is EventType.DELETED:
            self.topologies.pop(name, None)
            self.cpu_states.pop(name, None)
            return
        self.topologies[name] = cr
        if name not in self.cpu_states and cr.cpus:
            topo = CPUTopology(cr.cpus)
            state = CPUAllocationState(topo, self.max_ref_count)
            self.cpu_states[name] = state
            if cr.kubelet_reserved_cpus:
                # kubelet static cpu-manager claims are unavailable to koordinator
                from koordinator_tpu.utils.cpuset import CPUSet

                state.add(
                    "kubelet-reserved",
                    CPUSet(cr.kubelet_reserved_cpus),
                    EXCLUSIVE_NONE,
                )
            self._sync_node_reservation(name)

    def _on_node(self, ev: EventType, node, old) -> None:
        """Re-sync the node-reservation cpuset claim whenever the Node object
        changes — the annotation may appear, change, or vanish after the
        topology CR created the allocation state (or arrive before the Node
        existed at all)."""
        if ev is not EventType.DELETED and node.meta.name in self.cpu_states:
            self._sync_node_reservation(node.meta.name)

    def _sync_node_reservation(self, name: str) -> None:
        """node-reservation reservedCPUs (both apply policies) and EXCLUSIVE
        system-QoS cores are unavailable to cpuset allocation
        (nodenumaresource/reservation.go + topology_options.go via
        apis/extension)."""
        state = self.cpu_states.get(name)
        if state is None or self.store is None:
            return
        self._bump(name)
        from koordinator_tpu.client.store import KIND_NODE
        from koordinator_tpu.utils.cpuset import CPUSet

        node = self.store.get(KIND_NODE, f"/{name}")
        cpus = node.node_reservation()[1] if node is not None else ""
        state.remove("node-reservation")
        if cpus:
            state.add("node-reservation", CPUSet.parse(cpus), EXCLUSIVE_NONE)
        sys_cpus, exclusive = (node.system_qos_resource()
                               if node is not None else ("", True))
        state.remove("system-qos")
        if sys_cpus and exclusive:
            state.add("system-qos", CPUSet.parse(sys_cpus), EXCLUSIVE_NONE)

    # -- NUMATopologyHintProvider (topologymanager.py) -----------------
    def node_policy(self, node_name: str) -> str:
        """Policy from the node label, falling back to the reported kubelet
        cpu-manager policy (shared precedence helper with the snapshot packer)."""
        topo = self.topologies.get(node_name)
        labels = {}
        if self.store is not None:
            from koordinator_tpu.client.store import KIND_NODE

            node = self.store.get(KIND_NODE, f"/{node_name}")
            if node is not None:
                labels = node.meta.labels
        kubelet_policy = topo.kubelet_cpu_manager_policy if topo else ""
        return resolve_numa_policy(labels, kubelet_policy)

    def _numa_ids(self, topo: NodeResourceTopology) -> list:
        # zones beyond MAX_NUMA are dropped, matching the snapshot packer
        return sorted(z.numa_id for z in topo.zones if 0 <= z.numa_id < 8)

    def _zone_free(self, node_name: str) -> Optional[np.ndarray]:
        """[8, R] free per numa_id row (rows without a zone stay zero)."""
        topo = self.topologies.get(node_name)
        if topo is None or not topo.zones:
            return None
        cap = np.zeros((8, NUM_RESOURCES), np.float32)
        for z in topo.zones:
            if 0 <= z.numa_id < 8:
                cap[z.numa_id] = z.allocatable.to_vector()
        alloc = self.numa_allocated.get(node_name)
        if alloc is not None:
            cap = cap - alloc
        return cap

    def get_pod_topology_hints(self, pod: Pod, node_name: str):
        zone_free = self._zone_free(node_name)
        if zone_free is None:
            return None
        numa_ids = self._numa_ids(self.topologies[node_name])
        if not numa_ids:
            return None
        req = pod.spec.requests.to_vector()
        # row i of the slice corresponds to numa_ids[i]
        return {"resources": generate_fit_hints(req, zone_free[numa_ids], numa_ids)}

    def allocate(self, pod: Pod, node_name: str,
                 affinity: NUMATopologyHint) -> Optional[str]:
        self._pending_affinity[pod.meta.key] = affinity
        return None

    # ------------------------------------------------------------------
    def reserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> Optional[str]:
        topo = self.topologies.get(node_name)
        # the device coarse cut (snapshot.py) only arms numa_policy for nodes
        # reporting a CPU list; the host admit must gate identically or the
        # kernel keeps proposing nodes the host always vetoes
        if topo is not None and topo.cpus and topo.zones:
            policy = self.node_policy(node_name)
            if policy != POLICY_NONE:
                numa_ids = self._numa_ids(topo)
                if numa_ids:
                    err = self.topology_manager.admit(
                        pod, node_name, numa_ids, policy
                    )
                    if err:
                        self._pending_affinity.pop(pod.meta.key, None)
                        return err
        needs_bind, cores, full_pcpus = _pod_cpuset_flags(
            pod, self.default_cpu_bind_policy)
        if not needs_bind:
            self._track_numa(pod, node_name, add=True)
            return None
        state = self.cpu_states.get(node_name)
        if state is None:
            self._pending_affinity.pop(pod.meta.key, None)
            return "node has no CPU topology"
        got = take_cpus(
            state,
            int(cores),
            bind_policy=FULL_PCPUS if full_pcpus else SPREAD_BY_PCPUS,
            numa_strategy=self.numa_allocate_strategy,
        )
        if got is None:
            self._pending_affinity.pop(pod.meta.key, None)
            return "insufficient bindable cpus"
        state.add(pod.meta.key, got, EXCLUSIVE_NONE)
        ctx.data.setdefault("cpusets", {})[pod.meta.key] = got
        self._track_numa(pod, node_name, add=True)
        return None

    def unreserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> None:
        state = self.cpu_states.get(node_name)
        if state is not None:
            state.remove(pod.meta.key)
        ctx.data.get("cpusets", {}).pop(pod.meta.key, None)
        self._track_numa(pod, node_name, add=False)
        self._pending_affinity.pop(pod.meta.key, None)

    def _affinity_zones(self, pod: Pod, node_name: str) -> Optional[list]:
        hint = self._pending_affinity.get(pod.meta.key)
        if hint is not None and hint.affinity is not None:
            return hint.affinity.get_bits()
        return None

    def _release_zone_alloc(self, node_name: str, pod_key: str) -> None:
        placed = self._pod_zone_alloc.pop((node_name, pod_key), None)
        if placed is None:
            return
        self._bump(node_name)
        alloc = self.numa_allocated.get(node_name)
        if alloc is not None:
            np.maximum(alloc - placed, 0.0, out=alloc)

    def _track_numa(self, pod: Pod, node_name: str, add: bool) -> None:
        """Zone-level accounting feeding snapshot numa_free. Allocation follows
        the merged topology hint when one was admitted (all into a single zone
        for width-1 affinities, waterfall lowest-zone-first inside wider ones);
        without a hint it waterfalls over all zones. Waterfall take and
        dropped-overflow semantics match the kernel's numa_spread_fill
        (ops/numa.py) so host accounting and in-batch kernel state agree.
        The per-pod placement is recorded so release reverses it exactly."""
        if node_name not in self.topologies:
            return
        self._bump(node_name)
        if not add:
            self._release_zone_alloc(node_name, pod.meta.key)
            return
        vec = pod.spec.requests.to_vector()
        alloc = self.numa_allocated.setdefault(
            node_name,
            np.zeros((8, NUM_RESOURCES), np.float32),
        )
        zones = self._affinity_zones(pod, node_name)
        if zones is None:
            zones = list(range(alloc.shape[0]))
        zones = [z for z in zones if z < alloc.shape[0]]
        placed = np.zeros_like(alloc)
        if len(zones) == 1:
            # width-1 affinity: the whole request lands in the chosen zone,
            # as the kernel's single_case subtracts it wholesale
            placed[zones[0]] = vec
        else:
            free = self._zone_free(node_name)
            remaining = vec.astype(np.float32).copy()
            for z in zones:
                headroom = (
                    np.maximum(free[z], 0.0)
                    if free is not None
                    else remaining
                )
                take = np.minimum(headroom, remaining)
                placed[z] = take
                remaining = remaining - take
            # unplaceable remainder is dropped, as numa_spread_fill drops it
        alloc += placed
        self._pod_zone_alloc[(node_name, pod.meta.key)] = placed

    def pre_bind(self, pod: Pod, node_name: str, ctx: CycleContext,
                 annotations: Dict[str, str]) -> None:
        status: Dict[str, object] = {}
        got = ctx.data.get("cpusets", {}).get(pod.meta.key)
        if got is not None:
            status["cpuset"] = got.format()
        hint = self._pending_affinity.pop(pod.meta.key, None)
        if hint is not None and hint.affinity is not None:
            status["numaNodeResources"] = [
                {"node": z} for z in hint.affinity.get_bits()
            ]
        if status:
            annotations[ANNOTATION_RESOURCE_STATUS] = json.dumps(status)
