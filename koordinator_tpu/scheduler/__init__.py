"""Scheduler: frameworkext analog, plugin registry, batched cycle, parity harness.

Analog of reference `pkg/scheduler/` (SURVEY.md section 2.2): the extender engine
that wraps extension points, the plugins (LoadAware, NodeNUMAResource, Reservation,
Coscheduling, ElasticQuota, DeviceShare), and the scheduling cycle driver that feeds
the batched TPU kernels and applies bindings back to the object store.
"""
