"""Scheduler metrics registry (analog of reference pkg/scheduler/metrics/).

Reuses the shared Prometheus-style Registry (koordlet/metrics.py) the way
every reference binary reuses client_golang. The encoding-overflow signals
make conservative batch-encoding cuts (affinity-term / hostPort-slot
budgets, admission-signature degradation) first-class observables instead
of log lines: the reference surfaces every filter failure in pod status
and scheduler metrics, so an operator can see WHY a pod is pending."""

from __future__ import annotations

from koordinator_tpu.koordlet.metrics import Registry

REGISTRY = Registry()

# pods marked unschedulable this round because an encoding budget
# overflowed; kind = affinity_terms | port_slots
ENCODING_OVERFLOW_PODS = REGISTRY.counter(
    "koord_scheduler_encoding_overflow_unschedulable_total",
    "Pods marked unschedulable by a batch-encoding budget overflow",
)

# nodes degraded to their label-unknown admission bucket in the last
# snapshot (selector-carrying pods cannot schedule there)
ADMISSION_DEGRADED_NODES = REGISTRY.gauge(
    "koord_scheduler_admission_signature_degraded_nodes",
    "Nodes in a label-unknown admission bucket in the last snapshot",
)

# nodes whose attached-claim volume group overflowed MAX_VOL_GROUPS in the
# last snapshot: pods pay the full (unexempted) attachment count there
VOL_GROUP_DEGRADED_NODES = REGISTRY.gauge(
    "koord_scheduler_volume_group_degraded_nodes",
    "Nodes degraded to the conservative volume group in the last snapshot",
)

# cycle-latency histograms (koordtrace spans carry the per-stage split;
# these carry the distribution a scraper can alert on)
CYCLE_SECONDS = REGISTRY.histogram(
    "koord_scheduler_cycle_seconds",
    "End-to-end scheduling cycle latency",
)
KERNEL_SECONDS = REGISTRY.histogram(
    "koord_scheduler_kernel_seconds",
    "Batched kernel pass latency (compile+execute on a cache miss)",
)

# shape-signature step-cache traffic: a steady-state cluster should be
# all hits; misses are the XLA-recompile pathology the batched-tensor
# design introduces over the reference, and each one costs seconds
COMPILE_CACHE_HITS = REGISTRY.counter(
    "koord_scheduler_compile_cache_hits_total",
    "Kernel launches served by the shape-signature step cache",
)
COMPILE_CACHE_MISSES = REGISTRY.counter(
    "koord_scheduler_compile_cache_misses_total",
    "Kernel step builds forced by a shape-signature cache miss",
)

PODS_BOUND_TOTAL = REGISTRY.counter(
    "koord_scheduler_pods_bound_total",
    "Pods bound across all cycles",
)

# fused multi-wave dispatch (models/fused_waves.py): how many dependent
# scheduling rounds each device dispatch actually executed (early exit
# stops at the first zero-commit wave), and how many bytes every kernel
# readback shipped — the compacted binding buffer is the whole point, so
# a regression back to full-matrix readbacks must be visible
WAVES_PER_DISPATCH = REGISTRY.histogram(
    "koord_scheduler_waves_per_dispatch",
    "Scheduling waves executed per device dispatch",
    buckets=(1.0, 2.0, 4.0, 8.0),
)
READBACK_BYTES = REGISTRY.counter(
    "koord_scheduler_readback_bytes_total",
    "Bytes read back from the device across all kernel dispatches",
)

# pipeline occupancy: fraction of the last cycle's wall time the device
# had work in flight (device_busy_seconds / cycle duration). The whole
# point of the batched-tensor re-expression is that the DEVICE sets the
# cycle rate — this gauge makes the next host-side bottleneck visible in
# /metrics instead of only in bench JSON. Overlapped wave replay
# (KOORD_TPU_REPLAY_OVERLAP) raises it by draining the replay queue
# while later waves execute.
PIPELINE_OCCUPANCY = REGISTRY.gauge(
    "koord_scheduler_pipeline_occupancy",
    "Device-busy fraction of the last scheduling cycle's wall time",
)

# incremental-pack row traffic: steady state should be nearly all reused;
# a repack surge means the store is churning (or a cache regression)
PACK_ROWS_REUSED = REGISTRY.counter(
    "koord_scheduler_pack_rows_reused_total",
    "Packed pod rows gathered unchanged from the previous build",
)
PACK_ROWS_REPACKED = REGISTRY.counter(
    "koord_scheduler_pack_rows_repacked_total",
    "Packed pod rows rebuilt from the object (new/changed pods)",
)

# DeviceSnapshot upload traffic (scheduler/snapshot_cache.DeviceSnapshot
# stats, fed as per-cycle counter deltas by the cycle driver): an upload
# regression — reuse collapsing into full puts — shows up in /metrics,
# not just bench runs. Counters, so rate()/increase() behave across
# process restarts.
UPLOAD_FIELDS_REUSED = REGISTRY.counter(
    "koord_scheduler_upload_fields_reused_total",
    "Device-snapshot fields reused without any transfer",
)
UPLOAD_FIELDS_SCATTERED = REGISTRY.counter(
    "koord_scheduler_upload_fields_scattered_total",
    "Device-snapshot fields updated by donated row scatters",
)
UPLOAD_FIELDS_PUT = REGISTRY.counter(
    "koord_scheduler_upload_fields_put_total",
    "Device-snapshot fields re-uploaded in full",
)
UPLOAD_BYTES_SCATTERED = REGISTRY.counter(
    "koord_scheduler_upload_bytes_scattered_total",
    "Bytes shipped by device-snapshot row scatters",
)
UPLOAD_BYTES_PUT = REGISTRY.counter(
    "koord_scheduler_upload_bytes_put_total",
    "Bytes shipped by full device-snapshot puts",
)

# koordexplain (PR 5): per-stage filter rejections, attributed on device by
# the scheduling dispatch itself (models/full_chain.explain_stage_counts).
# Labeled by stage key (EXPLAIN_STAGE_KEYS); counted once per pod ending a
# logical cycle unbound, over the nodes each stage rejected — the
# aggregate view of what /explain answers per pod. Only populated when
# KOORD_TPU_EXPLAIN is on (the legacy host recompute does not feed it).
FILTER_REJECTIONS = REGISTRY.counter(
    "koord_scheduler_filter_rejections_total",
    "Node rejections per filter stage for pods left unbound, "
    "labeled by stage",
)
# explain attribution rides the kernel readback; its extra bytes must be
# visible so the counts-level overhead stays an explicit trade
EXPLAIN_READBACK_BYTES = REGISTRY.counter(
    "koord_scheduler_explain_readback_bytes_total",
    "Bytes of koordexplain attribution read back from the device",
)
# cycle flight recorder (obs/flight.py): every bundle dump, labeled by the
# trigger (deadline_overrun | cycle_exception | parity_mismatch |
# degradation | invariant_breach | slo_overrun | http)
FLIGHT_DUMPS = REGISTRY.counter(
    "koord_flight_recorder_dumps_total",
    "Flight-recorder bundle dumps, labeled by trigger reason",
)

# dispatch degradation ladder (scheduler/degrade.py): the current rung
# (0=full, 1=partial-mesh, 2=no-mesh, 3=serial-waves, 4=no-explain,
# 5=host-fallback) and every failed dispatch attempt the ladder absorbed
# instead of letting it kill the scheduler, labeled by the dispatch
# stage that failed
DEGRADED_LEVEL = REGISTRY.gauge(
    "koord_scheduler_degraded_level",
    "Dispatch degradation-ladder level "
    "(0=full 1=partial-mesh 2=no-mesh 3=serial-waves 4=no-explain "
    "5=host-fallback)",
)
DISPATCH_RETRIES = REGISTRY.counter(
    "koord_scheduler_dispatch_retries_total",
    "Failed device-dispatch attempts absorbed by the degradation "
    "ladder, labeled by stage",
)

# koordguard dispatch deadline (scheduler/deadline.py,
# KOORD_TPU_DISPATCH_DEADLINE_MS): monitored device syncs that overran
# and were abandoned — a slow-not-dead device demoting the ladder
# instead of wedging the cycle. Labeled by the dispatch path
# (serial | fused | rebalance).
DISPATCH_DEADLINE_OVERRUNS = REGISTRY.counter(
    "koord_scheduler_dispatch_deadline_overruns_total",
    "Device syncs abandoned after overrunning the dispatch deadline, "
    "labeled by path",
)

# mesh-backed dispatch (KOORD_TPU_MESH, parallel/mesh.py): how many
# devices the production cycle shards over (0 = single-device path), how
# the node rows and the compacted readback split across shards. The
# imbalance gauge is max/mean REAL (unpadded) rows per shard — 1.0 is a
# perfectly level mesh; trailing shards holding only pad rows push it up
# and that capacity is simply wasted.
MESH_DEVICES = REGISTRY.gauge(
    "koord_scheduler_mesh_devices",
    "Devices in the production dispatch mesh (0 = single-device)",
)
MESH_SHARD_READBACK_BYTES = REGISTRY.gauge(
    "koord_scheduler_mesh_readback_bytes",
    "Bytes of the last kernel readback held per mesh shard, "
    "labeled by shard",
)
MESH_SHARD_IMBALANCE = REGISTRY.gauge(
    "koord_scheduler_mesh_shard_imbalance",
    "Max/mean real node rows per mesh shard in the last dispatch",
)

# koordwatch (PR 13) demotion accounting: every silent fused-wave /
# explain / mesh demotion routes through the Scheduler._note_demotion
# chokepoint and lands here, labeled by the structured reason
# (ladder-serial-waves | sidecar | pending-reservations |
# prod-usage-score | claim-pods | score-transformer | explain-sidecar |
# explain-ladder | mesh-off | partial-mesh). Counted once per cycle per
# reason, so the counter reads as "cycles demoted for this reason" —
# the real-traffic data the ROADMAP demotion burn-down starts from.
WAVE_DEMOTIONS = REGISTRY.counter(
    "koord_scheduler_wave_demotions_total",
    "Scheduling cycles demoted below their configured wave/explain/mesh "
    "level, labeled by structured reason",
)

# SURVEY 7 step 6 sidecar path: kernel passes that fell back to the
# in-process step after a sidecar RPC failure (previously a loose
# Scheduler attribute invisible to /metrics)
SIDECAR_FALLBACKS = REGISTRY.counter(
    "koord_scheduler_sidecar_fallbacks_total",
    "Kernel passes served by the in-process step after a sidecar "
    "RPC transport failure",
)

# pending-queue visibility (pre-work for the ROADMAP admission/queueing
# item): the queue depth each cycle drained and the enqueue-to-dispatch
# age of every pod observed in it — the front-door latency signal the
# device-resident queueing work will have to improve
PENDING_QUEUE_DEPTH = REGISTRY.gauge(
    "koord_scheduler_pending_queue_depth",
    "Pods (and pending reservations) in the queue at cycle start",
)
QUEUE_WAIT_SECONDS = REGISTRY.histogram(
    "koord_scheduler_queue_wait_seconds",
    "Enqueue-to-dispatch age of each queued pod, observed per cycle",
    buckets=(1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0),
)

# koordwatch device timeline (obs/timeline.py): every device window —
# scheduler dispatch, koordbalance rebalance pass, koordcolo pass —
# records its dispatch->last-sync interval and the idle gap before it.
# The idle fraction is THE number the host-tail / rebalance-overlap
# ROADMAP items must drive down.
DEVICE_WINDOW_SECONDS = REGISTRY.histogram(
    "koord_device_window_seconds",
    "Device-window dispatch-to-last-sync interval, labeled by consumer "
    "(scheduler|rebalance|colo) and path (serial|fused|chained|mesh)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
)
DEVICE_IDLE_FRACTION = REGISTRY.gauge(
    "koord_device_idle_fraction",
    "Gap time between consecutive device windows over wall time",
)

# koordwatch SLO engine (obs/slo.py): per-objective burn rate
# (observed/target at the gating percentile; 1.0 = exactly on budget)
# and the met verdict, labeled by objective name
SLO_BURN_RATE = REGISTRY.gauge(
    "koord_slo_burn_rate",
    "SLO burn rate (observed/target at the gating percentile), "
    "labeled by objective",
)
SLO_MET = REGISTRY.gauge(
    "koord_slo_met",
    "Whether the SLO is currently met (1) or blown (0), "
    "labeled by objective",
)

# host-tail pack overlap (PR 15): pod rows pre-packed into the pack memo
# INSIDE a device window (serial/fused pipeline windows, the chained
# dispatch) — host work that used to run in the inter-window gap. A
# steady soak should show this tracking the repack counter; zero with
# KOORD_TPU_PACK_OVERLAP=0.
PREPACK_ROWS = REGISTRY.counter(
    "koord_scheduler_prepack_rows_total",
    "Pod rows pre-packed into the pack memo inside a device window",
)

# AOT warm-up ladder (scheduler/warmup.py, PR 15): rungs replayed from
# the persistent compile-cache index at startup, labeled by outcome
# (warmed | skipped | failed | invalidated — the last is the
# program-fingerprint discipline), the last ladder's wall seconds, and
# the completion gauge the steady-state compile guard arms on
WARMUP_RUNGS = REGISTRY.counter(
    "koord_scheduler_warmup_rungs_total",
    "Warm-up ladder rungs replayed from the persistent compile-cache "
    "index, labeled by outcome",
)
WARMUP_SECONDS = REGISTRY.gauge(
    "koord_scheduler_warmup_seconds",
    "Wall seconds the last warm-up ladder took",
)
WARMUP_COMPLETE = REGISTRY.gauge(
    "koord_scheduler_warmup_complete",
    "Whether the warm-up ladder has completed (1) for this scheduler",
)
# koordlint rule 20 (compile-in-steady-state), the runtime half: a
# step-cache MISS in the hot path AFTER warm-up completed — outside the
# warmup/ladder-transition/restart contexts every legitimate compile
# belongs to. A warm-cache restart must keep this flat through its
# first bind (the crash-restart coldstart gate asserts it).
STEADY_STATE_COMPILES = REGISTRY.counter(
    "koord_scheduler_steady_state_compiles_total",
    "Step-cache misses flagged in steady state (after warm-up, outside "
    "ladder transitions)",
)

# pipeline deferred-diagnose backlog: depth of the queue carrying cycle
# N's unschedulability writes into cycle N+1's kernel window, plus the
# total items ever deferred — a growing depth means kernel windows (or
# flush()) are not draining the backlog
DIAGNOSE_DEFERRED_TOTAL = REGISTRY.counter(
    "koord_scheduler_diagnose_deferred_total",
    "Unschedulability diagnose/condition writes deferred by the pipeline",
)
DIAGNOSE_DEFERRED_DEPTH = REGISTRY.gauge(
    "koord_scheduler_diagnose_deferred_depth",
    "Deferred diagnose entries currently queued",
)
