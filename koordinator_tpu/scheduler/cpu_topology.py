"""CPU topology model + cpuset accumulator.

Analog of reference `pkg/scheduler/plugins/nodenumaresource/cpu_topology.go:25-270`
and the sorted free-core take algorithm of `cpu_accumulator.go:234-810`. This is
deliberately HOST code (SURVEY.md section 7 hard parts: "cpuset/bitmask
combinatorics on accelerator vs host: keep exact semantics ... candidate for host
callback"): it runs once per actual assignment (Reserve), not per pod x node, so it
is off the hot path. The device-side NUMA *fit* check lives in ops/numa.py.

Semantics kept from the reference:
  * FullPCPUs: allocate whole physical cores (SMT siblings together); request must
    be a multiple of cpus-per-core (SMT alignment, plugin.go Filter).
  * SpreadByPCPUs: allocate one logical cpu per core, spreading across cores.
  * Exclusivity: PCPULevel (no sharing a core with other exclusive pods) and
    NUMANodeLevel (no sharing a NUMA node); previously allocated exclusive
    cores/nodes are avoided.
  * maxRefCount: logical cpus may be shared by up to maxRefCount LSR pods.
  * NUMA allocate strategy: MostAllocated prefers fuller NUMA nodes (bin-packing),
    LeastAllocated prefers emptier ones.
  * Deterministic ordering: candidates sorted by (free-cpus-in-unit, ref-count,
    id) so repeated runs bind identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from koordinator_tpu.api.objects import CPUInfo
from koordinator_tpu.utils.cpuset import CPUSet

FULL_PCPUS = "FullPCPUs"
SPREAD_BY_PCPUS = "SpreadByPCPUs"
EXCLUSIVE_NONE = ""
EXCLUSIVE_PCPU = "PCPULevel"
EXCLUSIVE_NUMA = "NUMANodeLevel"
NUMA_MOST_ALLOCATED = "MostAllocated"
NUMA_LEAST_ALLOCATED = "LeastAllocated"


@dataclass
class CPUTopology:
    """cpu -> (core, socket, numa node) maps (cpu_topology.go CPUTopology)."""

    cpus: List[CPUInfo] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.by_id: Dict[int, CPUInfo] = {c.cpu_id: c for c in self.cpus}
        self._cores: Dict[int, List[int]] = {}
        self._numa_of_core: Dict[int, int] = {}
        for c in self.cpus:
            self._cores.setdefault(c.core_id, []).append(c.cpu_id)
            self._numa_of_core[c.core_id] = c.numa_node_id
        for lst in self._cores.values():
            lst.sort()

    @staticmethod
    def build(num_sockets: int, nodes_per_socket: int, cores_per_node: int,
              threads_per_core: int = 2) -> "CPUTopology":
        """Synthesize a regular topology (test/report helper)."""
        cpus = []
        num_nodes = num_sockets * nodes_per_socket
        num_cores = num_nodes * cores_per_node
        cpu_id = 0
        for t in range(threads_per_core):
            for core in range(num_cores):
                node = core // cores_per_node
                socket = node // nodes_per_socket
                cpus.append(
                    CPUInfo(cpu_id=cpu_id, core_id=core, socket_id=socket,
                            numa_node_id=node)
                )
                cpu_id += 1
        return CPUTopology(cpus)

    @property
    def num_cpus(self) -> int:
        return len(self.cpus)

    @property
    def cpus_per_core(self) -> int:
        return max((len(v) for v in self._cores.values()), default=1)

    @property
    def num_numa_nodes(self) -> int:
        return len({c.numa_node_id for c in self.cpus}) or 1

    def is_valid(self) -> bool:
        return self.num_cpus > 0

    def cpus_in_numa(self, numa_id: int) -> CPUSet:
        return CPUSet(c.cpu_id for c in self.cpus if c.numa_node_id == numa_id)

    def cores(self) -> Dict[int, List[int]]:
        return self._cores

    def numa_of_core(self, core_id: int) -> int:
        return self._numa_of_core[core_id]


@dataclass
class AllocatedCPUInfo:
    ref_count: int = 0
    exclusive_policy: str = EXCLUSIVE_NONE


class CPUAllocationState:
    """Per-node allocation book-keeping (resource_manager's allocation cache)."""

    def __init__(self, topology: CPUTopology, max_ref_count: int = 1):
        self.topology = topology
        self.max_ref_count = max_ref_count
        self.allocated: Dict[int, AllocatedCPUInfo] = {}
        self.by_pod: Dict[str, CPUSet] = {}

    def available_cpus(self) -> CPUSet:
        """CPUs with ref count below maxRefCount."""
        return CPUSet(
            c.cpu_id
            for c in self.topology.cpus
            if self.allocated.get(c.cpu_id, AllocatedCPUInfo()).ref_count
            < self.max_ref_count
        )

    def num_available(self) -> int:
        """len(available_cpus()) without materializing the set: O(allocated)
        instead of O(all cpus) — the snapshot builder calls this per node per
        cycle. Only cpu ids actually IN the topology count as saturated, so
        an inconsistent CR (reserved id outside cr.cpus) cannot undercount."""
        topo_ids = self.topology.by_id
        saturated = sum(
            1 for cpu_id, info in self.allocated.items()
            if info.ref_count >= self.max_ref_count and cpu_id in topo_ids
        )
        return len(self.topology.cpus) - saturated

    def add(self, pod_key: str, cpus: CPUSet, exclusive_policy: str) -> None:
        self.by_pod[pod_key] = cpus
        for cpu in cpus:
            info = self.allocated.setdefault(cpu, AllocatedCPUInfo())
            info.ref_count += 1
            if exclusive_policy != EXCLUSIVE_NONE:
                info.exclusive_policy = exclusive_policy

    def remove(self, pod_key: str) -> None:
        cpus = self.by_pod.pop(pod_key, None)
        if cpus is None:
            return
        for cpu in cpus:
            info = self.allocated.get(cpu)
            if info is None:
                continue
            info.ref_count -= 1
            if info.ref_count <= 0:
                del self.allocated[cpu]

    def exclusive_cores(self) -> set:
        return {
            self.topology.by_id[cpu].core_id
            for cpu, info in self.allocated.items()
            if info.exclusive_policy == EXCLUSIVE_PCPU
        }

    def exclusive_numa_nodes(self) -> set:
        return {
            self.topology.by_id[cpu].numa_node_id
            for cpu, info in self.allocated.items()
            if info.exclusive_policy == EXCLUSIVE_NUMA
        }


def take_cpus(
    state: CPUAllocationState,
    num_cpus: int,
    bind_policy: str = FULL_PCPUS,
    exclusive_policy: str = EXCLUSIVE_NONE,
    numa_strategy: str = NUMA_MOST_ALLOCATED,
    numa_affinity: Optional[Sequence[int]] = None,
) -> Optional[CPUSet]:
    """Pick num_cpus logical cpus honoring policy/exclusivity; None if impossible.

    The take order mirrors the accumulator: group free cpus by NUMA node (restricted
    to numa_affinity when the topology manager chose one), order NUMA nodes by the
    allocate strategy, within a node order cores by (free cpus desc, ref count asc,
    core id asc), then take full cores (FullPCPUs) or round-robin single cpus
    (SpreadByPCPUs).
    """
    topo = state.topology
    if num_cpus <= 0:
        return CPUSet()
    available = state.available_cpus()
    excl_cores = state.exclusive_cores() if exclusive_policy == EXCLUSIVE_PCPU else set()
    excl_nodes = (
        state.exclusive_numa_nodes() if exclusive_policy == EXCLUSIVE_NUMA else set()
    )

    # free cpus per core, filtered
    free_in_core: Dict[int, List[int]] = {}
    for cpu in available:
        info = topo.by_id[cpu]
        if info.core_id in excl_cores:
            continue
        if info.numa_node_id in excl_nodes:
            continue
        if numa_affinity is not None and info.numa_node_id not in numa_affinity:
            continue
        free_in_core.setdefault(info.core_id, []).append(cpu)

    # group cores by numa node
    cores_in_numa: Dict[int, List[int]] = {}
    for core_id in free_in_core:
        cores_in_numa.setdefault(topo.numa_of_core(core_id), []).append(core_id)

    def core_ref(core_id: int) -> int:
        return sum(
            state.allocated.get(c, AllocatedCPUInfo()).ref_count
            for c in topo.cores()[core_id]
        )

    def numa_free(numa_id: int) -> int:
        return sum(len(free_in_core[c]) for c in cores_in_numa[numa_id])

    numa_ids = sorted(
        cores_in_numa,
        key=lambda nid: (
            numa_free(nid) if numa_strategy == NUMA_MOST_ALLOCATED else -numa_free(nid),
            nid,
        ),
    )

    result: List[int] = []
    needed = num_cpus
    for nid in numa_ids:
        cores = sorted(
            cores_in_numa[nid],
            key=lambda c: (-len(free_in_core[c]), core_ref(c), c),
        )
        if bind_policy == FULL_PCPUS:
            taken_cores = set()
            # phase 1: whole free cores while a full core still fits
            for core_id in cores:
                cpus = free_in_core[core_id]
                if len(cpus) == topo.cpus_per_core and needed >= len(cpus):
                    result.extend(sorted(cpus))
                    taken_cores.add(core_id)
                    needed -= len(cpus)
                if needed <= 0:
                    break
            if needed > 0:
                # phase 2: leftover single cpus (reference falls back to takeCPUs),
                # partial cores first, then remaining full cores
                leftovers = [c for c in cores if c not in taken_cores]
                leftovers.sort(
                    key=lambda c: (len(free_in_core[c]) == topo.cpus_per_core, cores.index(c))
                )
                for core_id in leftovers:
                    for cpu in sorted(free_in_core[core_id]):
                        if needed <= 0:
                            break
                        result.append(cpu)
                        needed -= 1
                    if needed <= 0:
                        break
        else:  # SpreadByPCPUs: one cpu per core, round-robin
            round_idx = 0
            while needed > 0:
                progress = False
                for core_id in cores:
                    cpus = sorted(free_in_core[core_id])
                    if round_idx < len(cpus):
                        result.append(cpus[round_idx])
                        needed -= 1
                        progress = True
                        if needed <= 0:
                            break
                if not progress:
                    break
                round_idx += 1
        if needed <= 0:
            break

    if needed > 0:
        return None
    return CPUSet(result[:num_cpus])
