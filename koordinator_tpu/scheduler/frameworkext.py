"""Framework extension engine: the host-side orchestration around the kernels.

Analog of reference `pkg/scheduler/frameworkext/` (SURVEY.md section 2.2): the
extender owns the plugin registry, runs the scheduling cycle (snapshot -> fused
kernel -> host Reserve/PreBind/Bind), dispatches store events to plugin caches,
and provides the monitor/debug surfaces (scheduler_monitor.go, debug.go).

The kube-scheduler extension points map as:
  PreFilter/Filter/Score -> fused into the batched kernel (models/full_chain.py)
  Reserve/Unreserve      -> host plugin hooks (cpuset take, device pick,
                            reservation consume) run per actual binding
  PreBind                -> accumulated object patches applied once
                            (defaultprebind semantics, frameworkext/interface.go:194)
  Bind                   -> store update of pod.spec.node_name
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from koordinator_tpu.api.objects import Pod
from koordinator_tpu.client.store import ObjectStore


@dataclass
class BindResult:
    pod_key: str
    node_name: str
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class CycleResult:
    bound: List[BindResult] = field(default_factory=list)
    failed: List[str] = field(default_factory=list)      # pod keys left pending
    rejected: List[str] = field(default_factory=list)    # struck by permit/quota
    preempted_victims: List[str] = field(default_factory=list)  # quota PostFilter
    resized: List[str] = field(default_factory=list)     # in-place resizes applied
    resize_pending: List[str] = field(default_factory=list)  # resize didn't fit
    duration_seconds: float = 0.0
    kernel_seconds: float = 0.0
    # wall spent between kernel dispatch and readback completion (the
    # window where the device has work queued; upper bound when overlapped
    # host work outlasts the kernel) — feeds the pipeline occupancy number
    device_busy_seconds: float = 0.0
    skipped_not_leader: bool = False  # election-gated replica in standby
    # logical scheduling rounds this cycle consumed: 1 on the serial path,
    # up to K on a fused multi-wave dispatch (models/fused_waves.py). A
    # fused cycle truncated by a Reserve veto or a preemption retry
    # reports the rounds it actually completed, so a driver replaying a
    # K-round budget knows how much remains.
    waves: int = 1
    # koordwatch demotion accounting: the structured reasons this cycle
    # ran below its configured wave/explain/mesh level (deduped per
    # cycle, in first-hit order; empty = no demotion). Every entry also
    # incremented koord_scheduler_wave_demotions_total{reason} and rides
    # the cycle's flight record — the sim aggregates these into the
    # per-scenario demotion profile.
    demotions: List[str] = field(default_factory=list)
    # koordwatch decision correlation: the decision ids of the device
    # windows this cycle opened (obs/timeline.py), joinable against
    # kernel spans, timeline windows and flight records
    decision_ids: List[str] = field(default_factory=list)


class Plugin:
    """Host-side plugin base. Kernels consume arrays the plugins contribute via
    the snapshot builder; these hooks cover cache maintenance + per-binding
    effects."""

    name = "plugin"

    def register(self, store: ObjectStore) -> None:
        """Subscribe to store events to maintain caches."""

    def reserve(self, pod: Pod, node_name: str, ctx: "CycleContext") -> Optional[str]:
        """Claim host-side resources for a tentative binding. Return an error
        string to veto (triggers unreserve of earlier plugins)."""
        return None

    def unreserve(self, pod: Pod, node_name: str, ctx: "CycleContext") -> None:
        """Roll back reserve."""

    def pre_bind(self, pod: Pod, node_name: str, ctx: "CycleContext",
                 annotations: Dict[str, str]) -> None:
        """Contribute annotations/patches to the single PreBind patch."""


@dataclass
class CycleContext:
    """Per-cycle scratch shared by plugins (cycleState analog)."""

    now: float
    data: Dict[str, Any] = field(default_factory=dict)


class SchedulingTransformer:
    """Declared view-transform extension point (frameworkext/interface.go:78-97).

    The reference runs Before/After hooks per (pod, node) inside the Go
    framework; in the batched architecture the same power lives at the three
    places a view exists on host:

      * ``PreFilterTransformer.before_prefilter`` — rewrite one pending pod's
        view before it is packed (BeforePreFilter: return a replacement, never
        mutate the stored object)
      * ``PreFilterTransformer.after_prefilter`` / ``FilterTransformer.
        before_filter`` — rewrite the assembled ClusterState (the batched
        nodeInfo view) before packing
      * ``ScoreTransformer.before_score`` — rewrite the packed
        FullChainInputs before the kernel launches (BeforeScore over all
        nodes at once).
    """

    name = "transformer"


class PreFilterTransformer(SchedulingTransformer):
    def before_prefilter(self, pod: Pod, ctx: "CycleContext") -> Optional[Pod]:
        """Return a replacement pod view for this cycle, or None to keep."""
        return None

    def after_prefilter(self, state, ctx: "CycleContext") -> None:
        """Adjust the assembled ClusterState after per-pod transforms ran."""
        return None


class FilterTransformer(SchedulingTransformer):
    def before_filter(self, state, ctx: "CycleContext") -> None:
        """Rewrite node-side views (assigned_requests, topologies, ...)."""
        return None


class ScoreTransformer(SchedulingTransformer):
    def before_score(self, inputs, ctx: "CycleContext"):
        """Return replacement FullChainInputs, or None to keep."""
        return None

    # PR 14 device-expressible protocol: a ScoreTransformer whose rewrite
    # can run INSIDE the fused wave kernel sets ``device_pass`` (see
    # DeviceScoreTransformer); transformers without it force the fused
    # dispatch down to the exact serial path (the
    # ``non-expressible-transformer`` demotion).
    device_pass = None


class DeviceScoreTransformer(ScoreTransformer):
    """A ScoreTransformer expressible as a pure tensor pass — the shape
    the fused wave kernel can carry (models/fused_waves.py).

    Implement ``device_pass(inputs) -> inputs``: a jax-traceable, pure,
    cycle-independent rewrite of the packed FullChainInputs. Contract:

      * SCORE-side fields only (la_term_nonprod / la_term_prod,
        pref_scores, img_scores, ppref_w, base.weights ...): the kept-
        only replay commits through the UNtransformed inputs, so a
        filter/commit-side rewrite would desynchronize carried state.
      * pure + trace-stable: the pass is compiled INTO the wave program
        and re-applied to every wave's rebuilt inputs. Parameter changes
        must bump ``device_epoch`` (a step-cache key component) or a
        cached program keeps the old constants.
      * elementwise/gather jnp ops only for bit-stability: the host
        ``before_score`` (which the SERIAL path still runs) applies the
        SAME function, so the two paths produce identical floats.

    The default ``before_score`` routes through ``device_pass`` and
    materializes the result back to host numpy, keeping the serial
    path's packed batch a plain host array set."""

    device_epoch = 0

    def device_pass(self, inputs):  # pragma: no cover - interface
        raise NotImplementedError

    def before_score(self, inputs, ctx: "CycleContext"):
        out = self.device_pass(inputs)
        if out is None:
            return None
        import jax

        return jax.tree_util.tree_map(np.asarray, out)


class SchedulerMonitor:
    """Slow/stuck cycle watchdog (frameworkext/scheduler_monitor.go:44-108).
    History is a bounded window; totals are running counters so a long-running
    scheduler never grows unbounded."""

    def __init__(self, timeout_seconds: float = 10.0, history_size: int = 512):
        from collections import deque

        self.timeout = timeout_seconds
        self.history = deque(maxlen=history_size)
        self.total_cycles = 0
        self._slow_cycles = 0

    def record(self, result: CycleResult) -> None:
        slow = result.duration_seconds > self.timeout
        self.total_cycles += 1
        self._slow_cycles += int(slow)
        self.history.append(
            {
                "duration": result.duration_seconds,
                "kernel": result.kernel_seconds,
                "bound": float(len(result.bound)),
                "slow": float(slow),
            }
        )

    @property
    def slow_cycles(self) -> int:
        return self._slow_cycles


class ErrorHandlerDispatcher:
    """Scheduling-failure dispatch chain (frameworkext/errorhandler_dispatcher.go):
    pre-handlers run in registration order until one consumes the failure;
    unconsumed failures fall through to the default handler (requeue)."""

    def __init__(self, history_size: int = 1024) -> None:
        from collections import deque

        self._handlers: List[Callable[[Pod, str], bool]] = []
        self.default_handler: Optional[Callable[[Pod, str], None]] = None
        # bounded (pod_key, reason) audit trail: permanently-pending pods
        # dispatch every cycle, so an unbounded list would leak
        self.failures = deque(maxlen=history_size)

    def register(self, handler: Callable[[Pod, str], bool]) -> None:
        self._handlers.append(handler)

    def dispatch(self, pod: Pod, reason: str) -> None:
        self.failures.append((pod.meta.key, reason))
        for handler in self._handlers:
            if handler(pod, reason):
                return
        if self.default_handler is not None:
            self.default_handler(pod, reason)


class ServicesEngine:
    """Per-plugin debug/API endpoints (frameworkext/services/services.go:44-53):
    plugins expose callables under /apis/v1/plugins/<plugin>/<endpoint>, and
    /apis/v1/nodes/<name> dumps a node's scheduling view. `handle(path)` is the
    routing core; `serve()` wraps it in a ThreadingHTTPServer for live use."""

    def __init__(self, extender: "FrameworkExtender"):
        self.extender = extender

    def handle(self, path: str) -> Any:
        parts = [p for p in path.split("/") if p]
        if parts[:2] != ["apis", "v1"]:
            raise KeyError(f"unknown path {path!r}")
        if len(parts) == 4 and parts[2] == "nodes":
            return self._dump_node(parts[3])
        if len(parts) >= 5 and parts[2] == "plugins":
            plugin = self.extender.plugin(parts[3])
            if plugin is None:
                raise KeyError(f"unknown plugin {parts[3]!r}")
            services = getattr(plugin, "services", None)
            endpoints = services() if callable(services) else {}
            if parts[4] not in endpoints:
                raise KeyError(f"plugin {parts[3]!r} has no endpoint {parts[4]!r}")
            return endpoints[parts[4]]()
        raise KeyError(f"unknown path {path!r}")

    def _dump_node(self, name: str) -> Dict[str, Any]:
        from koordinator_tpu.client.store import KIND_NODE, KIND_POD

        node = self.extender.store.get(KIND_NODE, f"/{name}")
        if node is None:
            raise KeyError(f"unknown node {name!r}")
        pods = [
            p.meta.key
            for p in self.extender.store.list(KIND_POD)
            if p.spec.node_name == name and not p.is_terminated
        ]
        return {
            "name": name,
            "allocatable": dict(node.allocatable.quantities),
            "pods": sorted(pods),
        }

    def serve(self, port: int = 0):
        """Start an HTTP server exposing handle(); returns (server, thread)."""
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        engine = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                try:
                    route = self.path.split("?", 1)[0]
                    payload = json.dumps(engine.handle(route)).encode()
                    self.send_response(200)
                except KeyError as e:
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(404)
                except Exception as e:  # debug surface must answer, not drop
                    payload = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # silence per-request stderr noise
                pass

        server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server, thread


class FrameworkExtender:
    """Plugin registry + event fan-out (framework_extender_factory.go analog)."""

    def __init__(self, store: ObjectStore):
        self.store = store
        self.plugins: List[Plugin] = []
        self.transformers: List[SchedulingTransformer] = []
        self.monitor = SchedulerMonitor()
        self.error_handlers = ErrorHandlerDispatcher()
        self.services = ServicesEngine(self)
        self._debug_top_n = 0

    def register_plugin(self, plugin: Plugin) -> None:
        self.plugins.append(plugin)
        plugin.register(self.store)

    def register_transformer(self, transformer: SchedulingTransformer) -> None:
        """Transformers run in registration order at each stage
        (framework_extender.go runTransformers)."""
        self.transformers.append(transformer)

    # -- transformer dispatch (interface.go:78-97) ---------------------------
    def transform_before_prefilter(self, pods: List[Pod],
                                   ctx: CycleContext) -> List[Pod]:
        if not self.transformers:
            return pods
        out = []
        for pod in pods:
            for t in self.transformers:
                if isinstance(t, PreFilterTransformer):
                    replaced = t.before_prefilter(pod, ctx)
                    if replaced is not None:
                        pod = replaced
            out.append(pod)
        return out

    def transform_after_prefilter(self, state, ctx: CycleContext) -> None:
        for t in self.transformers:
            if isinstance(t, PreFilterTransformer):
                t.after_prefilter(state, ctx)

    def transform_before_filter(self, state, ctx: CycleContext) -> None:
        for t in self.transformers:
            if isinstance(t, FilterTransformer):
                t.before_filter(state, ctx)

    def transform_before_score(self, inputs, ctx: CycleContext):
        for t in self.transformers:
            if isinstance(t, ScoreTransformer):
                replaced = t.before_score(inputs, ctx)
                if replaced is not None:
                    inputs = replaced
        return inputs

    def plugin(self, name: str) -> Optional[Plugin]:
        for p in self.plugins:
            if p.name == name:
                return p
        return None

    # debug.go analog: runtime-settable top-N score dump
    def set_debug_top_n(self, n: int) -> None:
        self._debug_top_n = n

    def debug_scores(self, score_row: np.ndarray, node_names: List[str]) -> List[str]:
        if self._debug_top_n <= 0:
            return []
        order = np.argsort(-score_row)[: self._debug_top_n]
        return [
            f"{node_names[i]}={score_row[i]:.0f}"
            for i in order
            if i < len(node_names)
        ]
