"""Framework extension engine: the host-side orchestration around the kernels.

Analog of reference `pkg/scheduler/frameworkext/` (SURVEY.md section 2.2): the
extender owns the plugin registry, runs the scheduling cycle (snapshot -> fused
kernel -> host Reserve/PreBind/Bind), dispatches store events to plugin caches,
and provides the monitor/debug surfaces (scheduler_monitor.go, debug.go).

The kube-scheduler extension points map as:
  PreFilter/Filter/Score -> fused into the batched kernel (models/full_chain.py)
  Reserve/Unreserve      -> host plugin hooks (cpuset take, device pick,
                            reservation consume) run per actual binding
  PreBind                -> accumulated object patches applied once
                            (defaultprebind semantics, frameworkext/interface.go:194)
  Bind                   -> store update of pod.spec.node_name
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from koordinator_tpu.api.objects import Pod
from koordinator_tpu.client.store import ObjectStore


@dataclass
class BindResult:
    pod_key: str
    node_name: str
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class CycleResult:
    bound: List[BindResult] = field(default_factory=list)
    failed: List[str] = field(default_factory=list)      # pod keys left pending
    rejected: List[str] = field(default_factory=list)    # struck by permit/quota
    duration_seconds: float = 0.0
    kernel_seconds: float = 0.0


class Plugin:
    """Host-side plugin base. Kernels consume arrays the plugins contribute via
    the snapshot builder; these hooks cover cache maintenance + per-binding
    effects."""

    name = "plugin"

    def register(self, store: ObjectStore) -> None:
        """Subscribe to store events to maintain caches."""

    def reserve(self, pod: Pod, node_name: str, ctx: "CycleContext") -> Optional[str]:
        """Claim host-side resources for a tentative binding. Return an error
        string to veto (triggers unreserve of earlier plugins)."""
        return None

    def unreserve(self, pod: Pod, node_name: str, ctx: "CycleContext") -> None:
        """Roll back reserve."""

    def pre_bind(self, pod: Pod, node_name: str, ctx: "CycleContext",
                 annotations: Dict[str, str]) -> None:
        """Contribute annotations/patches to the single PreBind patch."""


@dataclass
class CycleContext:
    """Per-cycle scratch shared by plugins (cycleState analog)."""

    now: float
    data: Dict[str, Any] = field(default_factory=dict)


class SchedulerMonitor:
    """Slow/stuck cycle watchdog (frameworkext/scheduler_monitor.go:44-108).
    History is a bounded window; totals are running counters so a long-running
    scheduler never grows unbounded."""

    def __init__(self, timeout_seconds: float = 10.0, history_size: int = 512):
        from collections import deque

        self.timeout = timeout_seconds
        self.history = deque(maxlen=history_size)
        self.total_cycles = 0
        self._slow_cycles = 0

    def record(self, result: CycleResult) -> None:
        slow = result.duration_seconds > self.timeout
        self.total_cycles += 1
        self._slow_cycles += int(slow)
        self.history.append(
            {
                "duration": result.duration_seconds,
                "kernel": result.kernel_seconds,
                "bound": float(len(result.bound)),
                "slow": float(slow),
            }
        )

    @property
    def slow_cycles(self) -> int:
        return self._slow_cycles


class FrameworkExtender:
    """Plugin registry + event fan-out (framework_extender_factory.go analog)."""

    def __init__(self, store: ObjectStore):
        self.store = store
        self.plugins: List[Plugin] = []
        self.monitor = SchedulerMonitor()
        self._debug_top_n = 0

    def register_plugin(self, plugin: Plugin) -> None:
        self.plugins.append(plugin)
        plugin.register(self.store)

    def plugin(self, name: str) -> Optional[Plugin]:
        for p in self.plugins:
            if p.name == name:
                return p
        return None

    # debug.go analog: runtime-settable top-N score dump
    def set_debug_top_n(self, n: int) -> None:
        self._debug_top_n = n

    def debug_scores(self, score_row: np.ndarray, node_names: List[str]) -> List[str]:
        if self._debug_top_n <= 0:
            return []
        order = np.argsort(-score_row)[: self._debug_top_n]
        return [
            f"{node_names[i]}={score_row[i]:.0f}"
            for i in order
            if i < len(node_names)
        ]
