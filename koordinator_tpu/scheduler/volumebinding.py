"""VolumeBinding analog: schedule-time PVC->PV matching and binding.

The reference wraps the stock kube-scheduler, which vendors the upstream
VolumeBinding plugin (registered via the upstream app in
/root/reference/cmd/koord-scheduler/main.go:43-62): PreFilter classifies a
pod's claims, Filter checks each candidate node can satisfy the unbound
WaitForFirstConsumer (WFFC) claims, Reserve assumes a concrete PV per
claim, and PreBind writes the PV/PVC bind patches (or triggers dynamic
provisioning and waits).

TPU-first shape: per-(pod, node) PV matching does not batch, but volume
*topology* does. A WFFC claim is satisfiable on a node iff the node's
topology labels cover some candidate PV's topology (static binding) or
some provisioner-allowed topology term (dynamic). That predicate is pure
host metadata, so it rides the existing admission-signature bitmask
(ops/taints.py `any_of_sets`) — the kernel still runs ONE bit test per
(pod, node) and every backend (XLA, Pallas, wave, numpy oracle, C++
floor) inherits the filter through the packed arrays, parity by
construction. Concrete PV selection happens once per actual binding at
Reserve (smallest-fit, upstream volume_binding's sort order), and the
PVC/PV patches land at PreBind.

Divergence, documented: where upstream PreBind blocks awaiting an
external dynamic provisioner, this analog annotates the claim with the
selected node and vetoes the binding — the pod retries next cycle and
binds as soon as the PV exists. Functionally equivalent, deadline-free,
and it keeps the cycle driver non-blocking.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.api.objects import (
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    StorageClass,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_PV,
    KIND_PVC,
    KIND_STORAGECLASS,
    ObjectStore,
)
from koordinator_tpu.scheduler.frameworkext import CycleContext, Plugin

# upstream storage.k8s.io constants
NO_PROVISIONER = "kubernetes.io/no-provisioner"
WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"
IMMEDIATE = "Immediate"
SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"

# upstream unschedulable status messages (volume_binding.go ErrReason*)
REASON_PVC_NOT_FOUND = "persistentvolumeclaim not found"
REASON_SC_NOT_FOUND = "storageclass not found"
REASON_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
REASON_NO_MATCHING_PV = "no persistent volume matches the claim topology"


def _covers(capacity, request) -> bool:
    """PV capacity >= claim request on every requested quantity."""
    return all(capacity.get(k, 0) >= v for k, v in request.quantities.items())


def pv_available_for(pv: PersistentVolume, pvc_key: str) -> bool:
    """Static-binding candidate: unclaimed and Available, or already
    pre-bound to this very claim (upstream honors claimRef pre-binding)."""
    if pv.claim_ref:
        return pv.claim_ref == pvc_key
    return pv.phase == "Available"


def pv_matches_claim(pv: PersistentVolume, pvc: PersistentVolumeClaim) -> bool:
    return (pv.storage_class_name == pvc.storage_class_name
            and pv_available_for(pv, pvc.meta.key)
            and _covers(pv.capacity, pvc.capacity))


def _topology_alternatives(term) -> List[frozenset]:
    """Expand one allowedTopologies term — (key, values) requirements ANDed,
    values within a key ORed — into flat pair-set alternatives."""
    alts = [frozenset()]
    for key, values in term:
        alts = [alt | {(key, v)} for alt in alts for v in values]
    return alts


@dataclass
class PodVolumeClassification:
    """PreFilter output for one pod (upstream PodVolumes analog)."""

    # unbound WFFC claim keys needing a Reserve-time PV pick / provisioning
    wffc_claims: Tuple[str, ...] = ()
    # one element per TOPOLOGY-CONSTRAINED unbound claim: alternatives of
    # required (key, value) pair sets — rides admission_mask(any_of_sets=)
    any_of_sets: Tuple[frozenset, ...] = ()
    # hard PreFilter rejection (mask will be zero; this is the condition
    # reason surfaced on the pod)
    reason: Optional[str] = None


def index_pvs_by_class(
    pvs: Dict[str, PersistentVolume],
) -> Dict[str, List[PersistentVolume]]:
    """Per-storage-class candidate index, built once per snapshot so each
    classification scans only its class's volumes instead of every PV."""
    by_class: Dict[str, List[PersistentVolume]] = {}
    for pv in pvs.values():
        by_class.setdefault(pv.storage_class_name, []).append(pv)
    return by_class


def classify_pod_volumes(
    pod: Pod,
    pvcs: Dict[str, PersistentVolumeClaim],
    pvs: Dict[str, PersistentVolume],
    storage_classes: Dict[str, StorageClass],
    pvs_by_class: Optional[Dict[str, List[PersistentVolume]]] = None,
) -> PodVolumeClassification:
    """Classify the pod's claims the way upstream PreFilter does.

    Bound claims are out of scope here — their PV topology already rides
    the admission bitmask as required pairs (snapshot.volume_zone_pairs).
    """
    if pvs_by_class is None:
        pvs_by_class = index_pvs_by_class(pvs)
    wffc: List[str] = []
    any_of: List[frozenset] = []
    for claim in pod.spec.pvc_names:
        pvc_key = f"{pod.meta.namespace}/{claim}"
        pvc = pvcs.get(pvc_key)
        if pvc is None:
            return PodVolumeClassification(reason=REASON_PVC_NOT_FOUND)
        if pvc.is_bound:
            continue
        if not pvc.storage_class_name:
            # classless unbound claims belong to the async PV controller —
            # upstream treats them as unbound immediate
            return PodVolumeClassification(reason=REASON_UNBOUND_IMMEDIATE)
        sc = storage_classes.get(pvc.storage_class_name)
        if sc is None:
            return PodVolumeClassification(reason=REASON_SC_NOT_FOUND)
        if sc.volume_binding_mode != WAIT_FOR_FIRST_CONSUMER:
            return PodVolumeClassification(reason=REASON_UNBOUND_IMMEDIATE)
        wffc.append(claim)
        alternatives: set = set()
        unconstrained = False
        # static candidates: any matching Available PV's full topology
        # pair set is one alternative; a label-less PV fits every node
        for pv in pvs_by_class.get(pvc.storage_class_name, ()):
            if not pv_matches_claim(pv, pvc):
                continue
            zp = pv.zone_pairs()
            if not zp:
                unconstrained = True
                break
            alternatives.add(frozenset(zp))
        # dynamic provisioning: allowed everywhere (no term list) or on
        # nodes matching some allowedTopologies term
        if not unconstrained and sc.provisioner and sc.provisioner != NO_PROVISIONER:
            if not sc.allowed_topologies:
                unconstrained = True
            else:
                for term in sc.allowed_topologies:
                    alternatives.update(_topology_alternatives(term))
        if unconstrained:
            continue
        if not alternatives:
            # no PV anywhere and no provisioner: mask zeroes out and the
            # cycle surfaces this reason on the pod (upstream Filter fails
            # every node with the same message)
            return PodVolumeClassification(
                wffc_claims=tuple(wffc), reason=REASON_NO_MATCHING_PV)
        any_of.append(frozenset(alternatives))
    return PodVolumeClassification(
        wffc_claims=tuple(wffc), any_of_sets=tuple(any_of))


def any_of_pair_universe(any_of_sets: Sequence[frozenset]) -> frozenset:
    """All (key, value) pairs any alternative references — these must join
    the batch's selector pairs so node admission signatures encode them."""
    return frozenset(
        p for alts in any_of_sets for alt in alts for p in alt)


class VolumeBindingPlugin(Plugin):
    """Reserve/PreBind side of the analog (upstream Reserve assume-cache +
    PreBind BindPodVolumes). The per-cycle assumed set lives in the
    CycleContext so two pods in one batch never pick the same PV."""

    name = "VolumeBinding"

    def __init__(self) -> None:
        self._store: ObjectStore = None  # type: ignore[assignment]

    def register(self, store: ObjectStore) -> None:
        self._store = store

    # ------------------------------------------------------------------
    def _assumed(self, ctx: CycleContext) -> Dict[str, str]:
        return ctx.data.setdefault("volume_assumed", {})  # pv name -> pvc key

    def _decisions(self, ctx: CycleContext) -> Dict[str, List[Tuple[str, str]]]:
        return ctx.data.setdefault("volume_binds", {})  # pod key -> [(pvc, pv)]

    def reserve(self, pod: Pod, node_name: str,
                ctx: CycleContext) -> Optional[str]:
        if not pod.spec.pvc_names:
            return None
        # opaque-token mode (the SHARED volume-aware gate,
        # ops/volumes.py): pvc_names are CSI count tokens, nothing to
        # bind — Reserve must not veto them (pre-PR-14 it did, making
        # every sim claim pod an immortal queue resident). Cached per
        # cycle on the CycleContext: Reserve runs per binding.
        aware = ctx.data.get("volume_aware")
        if aware is None:
            from koordinator_tpu.ops.volumes import store_volume_aware

            aware = ctx.data["volume_aware"] = store_volume_aware(
                self._store)
        if not aware:
            return None
        node = self._store.get(KIND_NODE, f"/{node_name}")
        node_labels = node.meta.labels if node is not None else {}
        assumed = self._assumed(ctx)
        picks: List[Tuple[str, str]] = []
        provisioning: List[PersistentVolumeClaim] = []
        for claim in pod.spec.pvc_names:
            pvc_key = f"{pod.meta.namespace}/{claim}"
            pvc = self._store.get(KIND_PVC, pvc_key)
            if pvc is None:
                self._release(ctx, picks)
                return REASON_PVC_NOT_FOUND
            if pvc.is_bound:
                continue
            sc = self._class_of(pvc)
            if sc is None or sc.volume_binding_mode != WAIT_FOR_FIRST_CONSUMER:
                self._release(ctx, picks)
                return REASON_UNBOUND_IMMEDIATE
            pv = self._pick_pv(pvc, node_labels, assumed)
            if pv is not None:
                assumed[pv.meta.name] = pvc_key
                picks.append((pvc_key, pv.meta.name))
                continue
            if sc.provisioner and sc.provisioner != NO_PROVISIONER:
                provisioning.append(pvc)
                continue
            self._release(ctx, picks)
            return f"{REASON_NO_MATCHING_PV} on node"
        if provisioning:
            # upstream PreBind triggers the provisioner (selected-node
            # annotation) and blocks; the analog annotates and retries the
            # pod next cycle — see module docstring
            self._release(ctx, picks)
            for pvc in provisioning:
                if pvc.meta.annotations.get(SELECTED_NODE_ANNOTATION) != node_name:
                    # patch a COPY: watch subscribers diff old vs new
                    # (the DefaultPreBind discipline)
                    patched = copy.deepcopy(pvc)
                    patched.meta.annotations[SELECTED_NODE_ANNOTATION] = node_name
                    self._store.update(KIND_PVC, patched)
            return "waiting for volume provisioning"
        if picks:
            self._decisions(ctx)[pod.meta.key] = picks
        return None

    def unreserve(self, pod: Pod, node_name: str, ctx: CycleContext) -> None:
        picks = self._decisions(ctx).pop(pod.meta.key, None)
        if picks:
            self._release(ctx, picks)

    def pre_bind(self, pod: Pod, node_name: str, ctx: CycleContext,
                 annotations: Dict[str, str]) -> None:
        """Write the PV/PVC bind patches. The reference's volume binder
        issues its own PV/PVC API patches in PreBind, separate from the
        single pod patch — mirrored here as direct store updates."""
        picks = self._decisions(ctx).pop(pod.meta.key, None)
        if not picks:
            return
        for pvc_key, pv_name in picks:
            pvc = self._store.get(KIND_PVC, pvc_key)
            pv = self._pv_by_name(pv_name)
            if pvc is None or pv is None:
                continue
            pv = copy.deepcopy(pv)
            pv.claim_ref = pvc_key
            pv.phase = "Bound"
            self._store.update(KIND_PV, pv)
            pvc = copy.deepcopy(pvc)
            pvc.volume_name = pv_name
            pvc.phase = "Bound"
            self._store.update(KIND_PVC, pvc)
            self._assumed(ctx).pop(pv_name, None)

    # ------------------------------------------------------------------
    def _class_of(self, pvc: PersistentVolumeClaim) -> Optional[StorageClass]:
        if not pvc.storage_class_name:
            return None
        # cluster-scoped objects key as "/name" (namespace ""); fall back
        # to a scan for stores populated with a nonempty namespace
        sc = self._store.get(KIND_STORAGECLASS, f"/{pvc.storage_class_name}")
        if sc is not None:
            return sc
        for sc in self._store.list(KIND_STORAGECLASS):
            if sc.meta.name == pvc.storage_class_name:
                return sc
        return None

    def _pv_by_name(self, name: str) -> Optional[PersistentVolume]:
        pv = self._store.get(KIND_PV, f"/{name}")
        if pv is not None:
            return pv
        for pv in self._store.list(KIND_PV):
            if pv.meta.name == name:
                return pv
        return None

    def _pick_pv(self, pvc: PersistentVolumeClaim, node_labels: Dict[str, str],
                 assumed: Dict[str, str]) -> Optional[PersistentVolume]:
        """Smallest matching PV whose topology the node satisfies (upstream
        volume_binding FindMatchingVolume: smallest capacity, then name)."""
        best: Optional[PersistentVolume] = None
        best_key: Optional[Tuple[int, str]] = None
        for pv in self._store.list(KIND_PV):
            if pv.meta.name in assumed and assumed[pv.meta.name] != pvc.meta.key:
                continue
            if not pv_matches_claim(pv, pvc):
                continue
            if any(node_labels.get(k) != v for k, v in pv.zone_pairs()):
                continue
            key = (sum(pv.capacity.quantities.values()), pv.meta.name)
            if best_key is None or key < best_key:
                best, best_key = pv, key
        return best

    def _release(self, ctx: CycleContext, picks: List[Tuple[str, str]]) -> None:
        assumed = self._assumed(ctx)
        for _pvc_key, pv_name in picks:
            assumed.pop(pv_name, None)
