"""Graceful-degradation ladder for the device dispatch.

One transient XLA/mesh error used to kill the whole scheduler: the cycle
driver re-raised any dispatch failure (the flight recorder kept the
wreck, but the process was done binding pods). A shared-cluster
scheduler must instead *shed capability, not availability* — the same
stance the reference takes for a missing NodeMetric (degrade, don't
block) and the sidecar takes for a dead gRPC socket (fall back to the
in-process step).

The ladder orders the dispatch's optional machinery by how much it buys
vs how much surface it exposes, and walks DOWN one rung at a time when
dispatch attempts keep failing:

  level 0  full            — everything as configured
  level 1  partial-mesh    — mesh dispatch on the SURVIVING submesh: a
                             fault attributable to specific mesh devices
                             sheds only those devices (koordguard) — the
                             snapshot/step cache rebuild on the smaller
                             mesh instead of collapsing to single-device
  level 2  no-mesh         — mesh dispatch off, single-device buffers
  level 3  serial-waves    — fused multi-wave off, K pinned to 1
  level 4  no-explain      — koordexplain attribution off
  level 5  host-fallback   — no device dispatch at all: a pure-host
                             numpy scheduling pass built on the diagnose
                             oracle (scheduler/diagnose.py), the proof
                             that every modeled predicate evaluates on
                             host

The partial-mesh rung exists only for failures that NAME their dead
devices (``attributable_device_ids``): an anonymous dispatch fault
cannot pick survivors and skips straight past it. A further attributable
fault while already AT partial-mesh shrinks the submesh in place (a
same-level transition) instead of dropping the whole mesh; re-promotion
to ``full`` always probes the FULL configured mesh back — a still-dead
device re-records itself when the probe fails.

Policy (scheduler/cycle.py wires it around both the serial and fused
dispatch windows, strictly BEFORE any binding is applied, so a failed
attempt is always safe to re-run):

  * first failure in a scheduling pass: retry once at the same level;
  * further failures: demote to the next rung that actually changes
    behavior for this scheduler's configuration (a no-mesh rung is
    meaningless when no mesh was configured, so it is skipped);
  * every transition is observable: ``koord_scheduler_degraded_level``
    gauge, ``koord_scheduler_dispatch_retries_total{stage}`` counters,
    a loud log line and a flight-recorder dump;
  * after ``promote_after`` consecutive clean cycles the ladder probes
    one rung UP. A probe that fails (a demotion during the probation
    window that follows every promotion) doubles ``promote_after`` —
    exponential backoff, capped — and surviving probation resets it.

Rungs below host-fallback do not exist: if the host pass itself raises,
the failure propagates as an unhandled cycle exception (flight recorder
``cycle_exception`` trigger) — the ladder is exhausted and something is
wrong beyond the device.

The host fallback trades scoring fidelity for survival: it binds only
plain pods (gang and quota admission need the batched kernel's atomic
barriers, so those pods stay queued until re-promotion), picks the
feasible node with the lowest post-placement utilization, and advances
the same host state mirror the fused-wave replay uses — capacity,
hostPort, CSI-volume, NUMA and affinity invariants hold exactly
(tests/test_sim.py churns it against the store-level invariant checker).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

LEVEL_FULL = 0
LEVEL_PARTIAL_MESH = 1
LEVEL_NO_MESH = 2
LEVEL_SERIAL_WAVES = 3
LEVEL_NO_EXPLAIN = 4
LEVEL_HOST_FALLBACK = 5

LEVEL_NAMES = ("full", "partial-mesh", "no-mesh", "serial-waves",
               "no-explain", "host-fallback")


def attributable_device_ids(exc: BaseException) -> frozenset:
    """Mesh device ids a dispatch failure NAMES as failed, or an empty
    set. Read from the exception's ``failed_device_ids`` attribute — the
    sim's device-loss fault carries it, and a runtime integration can
    attach the same attribute after parsing an XLA/ICI error. Only an
    attributable failure can engage the partial-mesh rung: anonymous
    faults cannot pick survivors."""
    ids = getattr(exc, "failed_device_ids", None)
    if not ids:
        return frozenset()
    try:
        return frozenset(int(i) for i in ids)
    except (TypeError, ValueError):
        return frozenset()


class FusedDispatchDemoted(Exception):
    """Control flow, not an error: the fused dispatch window failed and
    the ladder demoted below fused waves — the cycle driver must re-run
    this scheduling pass through the serial path. Raised strictly before
    any binding of the failed dispatch was applied."""


def _rung_changes_behavior(level: int, features: Dict[str, bool]) -> bool:
    """Does demoting INTO ``level`` change anything for a scheduler with
    these configured features? Skipping no-op rungs keeps the ladder from
    burning retry budget on demotions that would fail identically."""
    if level == LEVEL_PARTIAL_MESH:
        # only meaningful when a mesh is configured AND the failure at
        # hand named dead devices with at least one survivor (the owner
        # sets this per failure — see Scheduler._on_dispatch_failure)
        return features.get("partial_mesh", False)
    if level == LEVEL_NO_MESH:
        return features.get("mesh", False)
    if level == LEVEL_SERIAL_WAVES:
        return features.get("waves", False)
    if level == LEVEL_NO_EXPLAIN:
        return features.get("explain", False)
    return True  # full and host-fallback always mean something


class DegradationLadder:
    """Demotion/re-promotion state machine for the dispatch path.

    Single-threaded by design: every method is called from the cycle
    thread only (the scheduler exposes read snapshots to other threads).
    ``observer`` (set by the owner) receives every transition record —
    the scheduler uses it to move the gauge, log, and dump the flight
    recorder.
    """

    def __init__(self, promote_after: int = 16,
                 max_promote_after: int = 512) -> None:
        if promote_after < 1:
            raise ValueError("promote_after must be >= 1")
        self.level = LEVEL_FULL
        self.promote_after = promote_after
        self._base_promote_after = promote_after
        self._max_promote_after = max(promote_after, max_promote_after)
        self.transitions: List[dict] = []
        self.observer: Optional[Callable[[dict], None]] = None
        self._clean = 0
        self._retried = False       # retry budget used this pass
        self._failed_this_cycle = False
        self._probation_left = 0    # cycles left in post-promotion probation
        self._seq = 0               # cycles observed (transition stamps)
        # features are only known at failure time (the owner passes
        # them); the promotion mirror reuses the last view. A ladder
        # that never failed never promotes, so {} is never consulted.
        self._features_seen: Dict[str, bool] = {}

    # ------------------------------------------------------------------
    @property
    def level_name(self) -> str:
        return LEVEL_NAMES[self.level]

    def snapshot(self) -> dict:
        """Read-only state for health/report surfaces. Read cross-thread
        (ObsServer /healthz) while the cycle thread transitions: the
        single read of ``level`` keeps level/level_name from tearing
        against a concurrent demotion; the remaining counters are
        monotonic and benign to race."""
        lvl = self.level
        return {
            "level": lvl,
            "level_name": LEVEL_NAMES[lvl],
            "clean_cycles": self._clean,
            "promote_after": self.promote_after,
            "transitions": len(self.transitions),
        }

    # ------------------------------------------------------------------
    def begin_pass(self) -> None:
        """Arm one retry for the scheduling pass starting now."""
        self._retried = False

    def on_failure(self, features: Dict[str, bool],
                   error: Optional[str] = None) -> str:
        """A dispatch attempt failed (before any binding was applied).
        Returns "retry" (re-run at the same level), "demoted" (settings
        changed — re-apply and re-run), or "exhausted" (already at the
        bottom rung; the caller re-raises)."""
        self._failed_this_cycle = True
        self._clean = 0
        self._features_seen = dict(features)
        if not self._retried:
            self._retried = True
            return "retry"
        target = None
        if (self.level == LEVEL_PARTIAL_MESH
                and features.get("partial_mesh_shrink", False)):
            # already on a submesh and the new failure named MORE dead
            # devices: shed those too (a same-level transition — the
            # observer re-applies settings and rebuilds the smaller
            # submesh) instead of dropping the whole mesh
            target = LEVEL_PARTIAL_MESH
        else:
            for lvl in range(self.level + 1, LEVEL_HOST_FALLBACK + 1):
                if _rung_changes_behavior(lvl, features):
                    target = lvl
                    break
        if target is None:
            return "exhausted"
        if self._probation_left > 0:
            # the re-promotion probe failed: back off exponentially
            self.promote_after = min(self.promote_after * 2,
                                     self._max_promote_after)
            self._probation_left = 0
        self._transition(target, f"dispatch failure: {error}")
        self._retried = False  # one fresh retry at the new level
        return "demoted"

    def note_cycle(self) -> None:
        """End of a completed cycle. Counts clean cycles toward the
        re-promotion probe and retires probation windows."""
        self._seq += 1
        if self._failed_this_cycle:
            self._failed_this_cycle = False
            return
        if self._probation_left > 0:
            self._probation_left -= 1
            if self._probation_left == 0:
                # the promoted level survived probation: forget the backoff
                self.promote_after = self._base_promote_after
        if self.level == LEVEL_FULL:
            return
        self._clean += 1
        if self._clean < self.promote_after:
            return
        # probe one rung up, skipping rungs that changed nothing on the
        # way down (their feature was never configured); features do not
        # change over a scheduler's lifetime, so the mirror of the
        # demotion skip is exact
        target = LEVEL_FULL
        for lvl in range(self.level - 1, LEVEL_FULL, -1):
            if _rung_changes_behavior(lvl, self._features_seen):
                target = lvl
                break
        self._transition(target, f"{self._clean} clean cycles")
        self._clean = 0
        self._probation_left = self._base_promote_after

    def _transition(self, to_level: int, reason: str) -> None:
        record = {
            "seq": self._seq,
            "from_level": self.level,
            "from": LEVEL_NAMES[self.level],
            "to_level": to_level,
            "to": LEVEL_NAMES[to_level],
            "reason": str(reason),
        }
        self.level = to_level
        self.transitions.append(record)
        if self.observer is not None:
            self.observer(record)


# ---------------------------------------------------------------------------
# host-fallback scheduling pass (the bottom rung)
# ---------------------------------------------------------------------------


def _fallback_shared_state(fc, n_real: int) -> dict:
    """shared_state for the host pass. The LoadAware reject rows are a
    compiled-op call — exactly the machinery that may be broken when the
    ladder reaches this rung — so a failure there degrades to "no
    load-aware filtering" (a softer placement policy, never an invariant:
    capacity/ports/volumes/NUMA all stay host-checked)."""
    from koordinator_tpu.scheduler.diagnose import shared_state

    try:
        return shared_state(fc, n_real)
    except Exception as exc:
        logger.warning("host fallback: load-aware reject rows unavailable "
                       "(%s: %s); skipping the load threshold stage",
                       type(exc).__name__, exc)
        inputs = fc.base
        return {
            "alloc": np.asarray(inputs.allocatable, np.float32)[:n_real],
            "requested": np.asarray(inputs.requested, np.float32)[:n_real],
            "node_ok": np.asarray(inputs.node_ok, bool)[:n_real],
            "rej_np": np.zeros(n_real, bool),
            "rej_pr": np.zeros(n_real, bool),
        }


def host_fallback_schedule(fc, pods, n_real: int) -> np.ndarray:
    """Pure-host numpy scheduling pass: the ladder's last rung.

    Greedy in packed (queue) order, the serial bind-loop contract. Each
    pod's feasibility is evaluated with the diagnose oracle's predicates
    (scheduler/diagnose.host_feasible_mask) against a host state mirror
    advanced after every placement (the fused-wave replay's
    _WaveStateMirror), so in-batch hostPort/capacity/volume/NUMA
    contention is respected. Node choice is the feasible node with the
    lowest post-placement utilization (max over requested axes) —
    survival-mode balance, NOT the kernel's score chain; re-promotion
    restores scoring fidelity.

    Gang and quota pods are left unchosen (-1): their all-or-nothing /
    runtime-quota admission lives in the batched kernel's atomic
    barriers, and binding them greedily could violate exactly the
    invariants this mode exists to protect. They stay queued and bind on
    re-promotion.

    Returns a chosen-node vector shaped like the kernel's readback
    (len(pods.keys), int32, -1 = unbound).
    """
    from koordinator_tpu.scheduler.cycle import _WaveStateMirror
    from koordinator_tpu.scheduler.diagnose import host_feasible_mask

    keys = pods.keys
    chosen = np.full(len(keys), -1, np.int32)
    if n_real <= 0 or not len(keys):
        return chosen
    mirror = _WaveStateMirror(fc)
    shared = _fallback_shared_state(fc, n_real)
    alloc = shared["alloc"]
    gang_id = np.asarray(fc.gang_id)
    quota_id = np.asarray(fc.quota_id)
    fit_requests = np.asarray(fc.base.fit_requests, np.float32)
    needs_numa = np.asarray(fc.needs_numa, bool)
    numa_policy = np.asarray(fc.numa_policy)
    requests = np.asarray(fc.requests, np.float32)
    # the patched view only changes when a placement commits; rebuilding
    # it lazily keeps the copy traffic O(commits), not O(pods) — most
    # iterations of a saturated queue commit nothing, and this is the
    # survival mode that must stay cheap
    fc_patched = None
    for i in range(len(keys)):
        if pods.unschedulable_reasons.get(i) is not None:
            continue  # encoding-budget overflow: no node can fix it
        if int(gang_id[i]) >= 0 or int(quota_id[i]) >= 0:
            continue  # kernel-only admission; stays pending
        if fc_patched is None:
            fc_patched = mirror.patched_fc()
        shared_i = dict(shared)
        shared_i["requested"] = mirror.requested[:n_real]
        feasible = host_feasible_mask(fc_patched, i, n_real,
                                      shared=shared_i)
        if not feasible.any():
            continue
        fit_req = fit_requests[i]
        after = mirror.requested[:n_real] + fit_req[None, :]
        with np.errstate(divide="ignore", invalid="ignore"):
            util = np.where(alloc > 0, after / alloc, 0.0)
        score = util.max(axis=1)
        score[~feasible] = np.inf
        node = int(np.argmin(score))
        zone = -1
        if needs_numa[i] and int(numa_policy[node]) == 1:
            # SingleNUMANode policy: the mirror must charge ONE zone, the
            # first that fits whole — what the plugin's Reserve will pick
            req = requests[i]
            for k in range(mirror.numa_free.shape[1]):
                if bool(((req <= 0)
                         | (req <= mirror.numa_free[node, k])).all()):
                    zone = k
                    break
            if zone < 0:
                continue  # per-zone fit raced away; leave pending
        chosen[i] = node
        mirror.commit(i, node, zone)
        fc_patched = None  # state advanced: rebuild before the next read
    return chosen
